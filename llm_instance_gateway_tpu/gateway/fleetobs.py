"""Fleet observability plane: cross-replica trace stitching + aggregation.

PR 10 made the control plane horizontally scalable (N gateway replicas x
M pools) but every observability surface stayed per-process: a request's
trace lives only on the replica that served it, event journals have no
fleet view, and SLO burn is computed per gateway.  This module is the
fleet layer the per-process surfaces report through:

- **Stitcher** (pure functions, the testable core): ``stitch_traces``
  merges ``/debug/traces`` payloads from any number of gateway replicas
  and model-server pods into per-trace-id timelines — every span tagged
  with its source, duplicates (a server span the gateway already merged
  from ``x-lig-spans``) folded, clock skew normalized PER HOP against
  the serving gateway's hop spans (clock domains follow span names, not
  shipping sources — the gateway's wire copies carry the pods' clocks),
  spans causally ordered.
  ``merge_events`` merges flight-recorder journals by ``(replica, seq)``;
  ``fleet_slo`` folds per-replica SLO payloads into fleet-wide
  compliance + worst burn per objective.
- **Collector** (``FleetCollector``): pulls ``/debug/traces?since=`` /
  ``/debug/events?since=`` (the incremental cursors — deltas, never the
  whole ring), ``/debug/slo`` and ``/debug/health`` from every peer
  gateway (the ``--statebus-peer`` list — the fleet topology is already
  wired) and every pool pod, folds them into bounded per-source caches,
  and serves the stitched fleet view as ``/debug/fleet`` on EVERY
  replica.  A dead source degrades to its cached data + an error marker
  (journaled ``fleet_peer_error``), never a failed page.

``tools/fleet_report.py`` renders the fleet view (per-phase fleet-wide
percentiles, slowest-trace exemplars, per-replica divergence);
``tools/trace_report.py --url a --url b`` runs multi-replica payloads
through the same stitcher.
"""

from __future__ import annotations

import asyncio
import collections
import time

from llm_instance_gateway_tpu.lockwitness import witness_lock
from llm_instance_gateway_tpu import events as events_mod
from llm_instance_gateway_tpu.tracing import (
    Histogram,
    escape_label,
    render_counter,
    render_histogram,
)

# Collect wall per source fetch is network-bound; second-scale buckets.
COLLECT_BUCKETS = (0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25,
                   0.5, 1.0, 2.5, 5.0)

# Which gateway hop span "covers" which downstream span names — the
# anchor pairs skew normalization aligns on.  A child source's earliest
# matching span must start inside its parent hop's window; when it
# doesn't, the whole source shifts by one offset (clocks skew per
# process, not per span).
HOP_CHILDREN = (
    ("gateway.prefill_hop", ("engine.queue_wait", "engine.prefill",
                             "handoff.serialize")),
    ("gateway.attach_hop", ("handoff.deserialize", "handoff.attach",
                            "engine.decode")),
    ("gateway.upstream", ("engine.queue_wait", "engine.prefill",
                          "engine.decode", "handoff.serialize")),
    ("gateway.stream", ("engine.queue_wait", "engine.prefill",
                        "engine.decode")),
)

# The span name that identifies the gateway that SERVED a trace — the
# reference clock skew normalization aligns everything else against.
REFERENCE_SPAN = "gateway.admission"


# ---------------------------------------------------------------------------
# Stitcher (pure)
# ---------------------------------------------------------------------------


def _span_key(span: dict) -> tuple:
    """Identity of a span independent of which replica shipped it: the
    gateway's merged copy of a server span (``x-lig-spans``) carries the
    same name and µs-rounded boundaries as the server's own record."""
    try:
        return (str(span.get("name", "")), round(float(span["start"]), 6),
                round(float(span["end"]), 6))
    except (KeyError, TypeError, ValueError):
        return (str(span.get("name", "")), None, None)


def _normalize_skew(spans: list[dict]) -> dict[str, float]:
    """Shift downstream spans onto the serving gateway's clock, IN PLACE;
    returns the applied offsets keyed by the anchoring hop span.

    The clock domain of a span is decided by its NAME, never by which
    replica shipped it: the gateway's ``/debug/traces`` already carries
    the pods' spans merged off ``x-lig-spans`` at the PODS' timestamps,
    so a source-keyed shift would leave exactly the skewed copies
    unshifted.  ``gateway.*`` spans are the reference clock; each hop's
    child span group (HOP_CHILDREN, claimed in order so e.g. a disagg
    trace's decode spans anchor on the attach hop, not the absent
    upstream span) shifts as ONE unit — clocks skew per process, and a
    hop's children all come from one process.  A group whose earliest
    span already starts inside its hop window stays put (synced clocks —
    the common case); groups with no matching hop stay unshifted (a
    partial trace is rendered honestly, not invented)."""
    ref_by_name: dict[str, dict] = {}
    for s in spans:
        if not s["name"].startswith("gateway."):
            continue
        # Earliest hop span of each name anchors (retries re-record hops).
        cur = ref_by_name.get(s["name"])
        if cur is None or s["start"] < cur["start"]:
            ref_by_name[s["name"]] = s
    skew: dict[str, float] = {}
    claimed: set[int] = set()
    for hop_name, child_names in HOP_CHILDREN:
        parent = ref_by_name.get(hop_name)
        if parent is None:
            continue
        children = [s for s in spans
                    if id(s) not in claimed and s["name"] in child_names]
        if not children:
            continue
        claimed.update(id(s) for s in children)
        child_start = min(s["start"] for s in children)
        if parent["start"] <= child_start <= parent["end"]:
            continue
        offset = parent["start"] - child_start
        skew[hop_name] = round(offset, 6)
        for s in children:
            s["start"] = round(s["start"] + offset, 6)
            s["end"] = round(s["end"] + offset, 6)
    return skew


def stitch_traces(sources: list[tuple[str, dict]],
                  limit: int = 256) -> list[dict]:
    """Merge ``/debug/traces`` payloads from many replicas into per-trace
    stitched timelines.

    ``sources`` is ``[(replica_name, payload), ...]`` where payload is
    the ``{"traces": [...]}`` shape both debug surfaces serve.  Returns
    stitched trace dicts, most recent first (by last span end), capped at
    ``limit``: trace_id, merged model/path/status, the sources that
    contributed, the per-hop skew offsets applied (``_normalize_skew``),
    and spans sorted causally (each span carries its ``source``).
    Hostile inputs degrade per-item: malformed spans are skipped,
    duplicate span names across replicas stay distinguishable by source,
    missing hops leave skew at zero.
    """
    traces: dict[str, dict] = {}
    for name, payload in sources:
        if not isinstance(payload, dict):
            continue
        for trace in payload.get("traces") or []:
            if not isinstance(trace, dict):
                continue
            tid = str(trace.get("trace_id") or "")
            if not tid:
                continue
            t = traces.setdefault(tid, {
                "trace_id": tid, "model": "", "path": "", "status": "",
                "sources": [], "_spans": {}})
            if name not in t["sources"]:
                t["sources"].append(name)
            for field in ("model", "path", "status"):
                v = trace.get(field)
                if v and not t[field]:
                    t[field] = str(v)
            for span in trace.get("spans") or []:
                if not isinstance(span, dict):
                    continue
                try:
                    clean = {"name": str(span.get("name", "?")),
                             "start": float(span["start"]),
                             "end": float(span["end"])}
                except (KeyError, TypeError, ValueError):
                    continue  # partial x-lig-spans rows degrade per-span
                if clean["end"] < clean["start"]:
                    clean["start"], clean["end"] = (clean["end"],
                                                    clean["start"])
                attrs = span.get("attrs")
                if isinstance(attrs, dict) and attrs:
                    clean["attrs"] = attrs
                key = _span_key(clean)
                if key in t["_spans"]:
                    continue  # the gateway's merged copy of this span
                clean["source"] = name
                t["_spans"][key] = clean

    out = []
    for t in traces.values():
        spans = list(t.pop("_spans").values())
        # Skew normalization needs the serving gateway's hop spans as the
        # reference clock; a pod-only view (no admission span) renders
        # unshifted.
        skew: dict[str, float] = {}
        if any(s["name"] == REFERENCE_SPAN for s in spans):
            skew = _normalize_skew(spans)
        spans.sort(key=lambda s: (s["start"], s["end"], s["name"]))
        t["skew"] = skew
        t["spans"] = spans
        t["t_created"] = spans[0]["start"] if spans else 0.0
        # Max end, not the last-sorted span's end: an enclosing span
        # (gateway.upstream around its engine children) ends last but
        # sorts by START — recency ordering must see the true last
        # activity or the limit cut drops the freshest trace.
        t["t_last"] = max((s["end"] for s in spans), default=0.0)
        out.append(t)
    out.sort(key=lambda t: -t["t_last"])
    return out[:max(0, limit)]


def merge_events(sources: list[tuple[str, dict]],
                 limit: int = 512) -> list[dict]:
    """Merge flight-recorder payloads by ``(replica, seq)``: each row
    gains a ``replica`` field, duplicates (re-polled pages) fold, and the
    result is one chronological fleet journal, newest ``limit`` rows.
    Rows without an int-able ``seq`` are skipped and non-numeric ``ts``
    sorts as 0 — a foreign/older peer's journal shape degrades per-row,
    never the merged page."""
    seen: set[tuple[str, int]] = set()
    rows: list[tuple[float, str, int, dict]] = []
    for name, payload in sources:
        if not isinstance(payload, dict):
            continue
        for event in payload.get("events") or []:
            if not isinstance(event, dict):
                continue
            try:
                seq = int(event.get("seq", 0))
            except (TypeError, ValueError):
                continue
            if (name, seq) in seen:
                continue
            seen.add((name, seq))
            try:
                ts = float(event.get("ts", 0.0))
            except (TypeError, ValueError):
                ts = 0.0
            rows.append((ts, name, seq, {**event, "replica": name}))
    rows.sort(key=lambda r: r[:3])
    return [r[3] for r in rows[-max(0, limit):]]


def fleet_slo(payloads: dict[str, dict]) -> dict:
    """Fold per-replica ``/debug/slo`` payloads into the fleet view:
    good/total SUM per (model, objective) — fleet compliance is the
    traffic-weighted truth, not an average of ratios — plus the worst
    burn rate and the per-replica burn states."""
    models: dict[str, dict] = {}
    for replica, payload in sorted(payloads.items()):
        if not isinstance(payload, dict):
            continue
        models_doc = payload.get("models")
        if not isinstance(models_doc, dict):
            continue
        for model, objectives in models_doc.items():
            if not isinstance(objectives, dict):
                continue
            for objective, o in objectives.items():
                if not isinstance(o, dict):
                    continue
                agg = models.setdefault(model, {}).setdefault(objective, {
                    "good": 0, "total": 0, "compliance": None,
                    "worst_burn": None, "worst_burn_replica": None,
                    "states": {}})
                try:
                    agg["good"] += int(o.get("good") or 0)
                    agg["total"] += int(o.get("total") or 0)
                except (TypeError, ValueError):
                    pass
                agg["states"][replica] = o.get("state")
                burns = [v for v in (o.get("burn_rates") or {}).values()
                         if isinstance(v, (int, float))]
                if burns:
                    worst = max(burns)
                    if agg["worst_burn"] is None or worst > agg["worst_burn"]:
                        agg["worst_burn"] = round(worst, 4)
                        agg["worst_burn_replica"] = replica
    for objectives in models.values():
        for agg in objectives.values():
            if agg["total"]:
                agg["compliance"] = round(agg["good"] / agg["total"], 6)
    return {"models": models, "replicas": sorted(payloads)}


def pick_steering_rollup(docs: list[dict]) -> dict:
    """Fold statebus docs' per-pool pick-ledger rollups
    (``gateway/pickledger.py`` via ``StateBus.snapshot``) into the fleet
    steering view — "which seam is steering traffic on which replica":
    per replica/pool the seam steering counts and decisive-seam
    histogram, plus fleet-wide seam totals.  Pure over ``all_docs()``;
    docs from pre-ledger peers (no ``picks`` key) are skipped."""
    replicas: dict[str, dict] = {}
    totals_steered: dict[str, int] = {}
    totals_decisive: dict[str, int] = {}
    for doc in docs or ():
        if not isinstance(doc, dict):
            continue
        replica = doc.get("replica")
        pools = doc.get("pools")
        if not isinstance(replica, str) or not isinstance(pools, dict):
            continue
        for pool, pool_doc in sorted(pools.items()):
            if not isinstance(pool_doc, dict):
                continue
            picks = pool_doc.get("picks")
            if not isinstance(picks, dict) or not picks.get("samples"):
                continue
            steered = {str(k): int(v) for k, v in
                       (picks.get("steered") or {}).items()
                       if isinstance(v, (int, float))}
            decisive = {str(k): int(v) for k, v in
                        (picks.get("decisive") or {}).items()
                        if isinstance(v, (int, float))}
            replicas.setdefault(replica, {})[pool] = {
                "samples": int(picks.get("samples") or 0),
                "picks": int(picks.get("picks") or 0),
                "steered": steered,
                "decisive": decisive,
                "escapes": dict(picks.get("escapes") or {}),
                "steered_away": dict(picks.get("steered_away") or {}),
            }
            for seam, n in steered.items():
                totals_steered[seam] = totals_steered.get(seam, 0) + n
            for tag, n in decisive.items():
                totals_decisive[tag] = totals_decisive.get(tag, 0) + n
    return {"replicas": replicas,
            "steered_total": totals_steered,
            "decisive_total": totals_decisive}


def collect_pod_payloads(pods: list[tuple[str, str]],
                         path: str = "/debug/profile",
                         timeout_s: float = 2.0,
                         thread_name: str = "blackbox-fetch") -> dict:
    """Best-effort JSON fetch of one debug ``path`` from every pool pod —
    the black-box dump's profiler and KV-economy sections (runs in the
    dump's executor thread, never on the event loop).  Fetches run
    CONCURRENTLY so a breach dump on a pool full of black-holed pods
    (exactly when dumps fire) is delayed by ~one timeout, not one per
    wedged pod; failures become error markers."""
    import concurrent.futures as futures
    import json as json_mod
    import urllib.request

    def fetch(address: str) -> dict:
        with urllib.request.urlopen(f"http://{address}{path}",
                                    timeout=timeout_s) as resp:
            return json_mod.loads(resp.read().decode())

    out: dict[str, dict] = {}
    if not pods:
        return out
    # No context manager: its exit is shutdown(wait=True), which would
    # block past the deadline on stragglers and discard what completed
    # meanwhile — the dump must pay at most the deadline, never a
    # per-wedged-pod wait.
    ex = futures.ThreadPoolExecutor(max_workers=min(16, len(pods)),
                                    thread_name_prefix=thread_name)
    futs = {ex.submit(fetch, address): name for name, address in pods}
    try:
        for fut in futures.as_completed(futs, timeout=timeout_s * 4):
            try:
                out[futs[fut]] = fut.result()
            except Exception as e:  # noqa: BLE001 — a failed pod is
                out[futs[fut]] = {"error": str(e)[:200]}  # a marker
    except futures.TimeoutError:
        # Sweep anything that finished between the deadline and here;
        # genuine stragglers get the fallback marker below.
        for fut, name in futs.items():
            if name not in out and fut.done():
                try:
                    out[name] = fut.result()
                except Exception as e:  # noqa: BLE001
                    out[name] = {"error": str(e)[:200]}
    ex.shutdown(wait=False, cancel_futures=True)
    for name, _address in pods:
        out.setdefault(name, {"error": "fetch did not complete"})
    return out


def collect_pod_profiles(pods: list[tuple[str, str]],
                         timeout_s: float = 2.0) -> dict:
    """Back-compat alias: the profiler-section fetch predates the
    path-parameterized ``collect_pod_payloads``."""
    return collect_pod_payloads(pods, "/debug/profile", timeout_s,
                                thread_name="blackbox-profile")


# ---------------------------------------------------------------------------
# Collector
# ---------------------------------------------------------------------------


class _SourceState:
    """Per-source incremental-poll state: cursors + bounded caches."""

    __slots__ = ("trace_since", "event_since", "traces", "events",
                 "last_ok", "last_error")

    def __init__(self):
        self.trace_since = 0
        self.event_since = 0
        # trace_id -> folded partial trace (bounded, LRU by activity).
        self.traces: "collections.OrderedDict[str, dict]" = (
            collections.OrderedDict())
        self.events: collections.deque = collections.deque(maxlen=2048)
        self.last_ok = False
        self.last_error = ""


class FleetCollector:
    """Pulls every replica's debug surfaces into one stitched fleet view.

    ``peer_urls`` are gateway base URLs (the ``--statebus-peer`` list);
    ``pods_fn`` returns the live ``[(pod_name, address), ...]`` pool
    membership; ``local_fn`` returns this replica's own payloads without
    HTTP (``{"traces": ..., "events": ..., "slo": ..., "health": ...}``).
    Thread-safe enough for its use: collect() runs on the event loop,
    render() on the scrape path — counters are guarded by a lock, caches
    are only touched from collect().
    """

    def __init__(self, replica: str, peer_urls: tuple = (),
                 pods_fn=None, local_fn=None,
                 journal: "events_mod.EventJournal | None" = None,
                 timeout_s: float = 2.0, trace_capacity: int = 256,
                 clock=time.time):
        self.replica = replica
        self.peer_urls = tuple(peer_urls)
        self.pods_fn = pods_fn or (lambda: [])
        self.local_fn = local_fn
        self.journal = journal
        self.timeout_s = timeout_s
        self.trace_capacity = max(1, trace_capacity)
        self._clock = clock
        self._sources: dict[str, _SourceState] = {}
        self._lock = witness_lock("FleetCollector._lock")
        # collect() is single-flight: two overlapping /debug/fleet pulls
        # would both read the same cursors and double-append events into
        # the bounded deques (evicting real history with duplicates).
        self._collect_lock = asyncio.Lock()
        self.collect_hist = Histogram(COLLECT_BUCKETS)
        self.errors_total: dict[str, int] = {}
        self.last_sources: dict[str, int] = {}  # kind -> fresh count
        self.last_stitched = 0

    # -- folding -------------------------------------------------------------
    def _state(self, name: str) -> _SourceState:
        st = self._sources.get(name)
        if st is None:
            st = self._sources[name] = _SourceState()
        return st

    def _fold_traces(self, st: _SourceState, payload: dict) -> None:
        for trace in payload.get("traces") or []:
            if not isinstance(trace, dict) or not trace.get("trace_id"):
                continue
            tid = str(trace["trace_id"])
            cur = st.traces.get(tid)
            if cur is None:
                cur = st.traces[tid] = {
                    "trace_id": tid, "model": "", "path": "", "status": "",
                    "spans": [], "_keys": set()}
                while len(st.traces) > self.trace_capacity:
                    st.traces.popitem(last=False)
            else:
                st.traces.move_to_end(tid)
            for field in ("model", "path", "status"):
                v = trace.get(field)
                if v:
                    cur[field] = str(v)
            for span in trace.get("spans") or []:
                if not isinstance(span, dict):
                    continue
                key = _span_key(span)
                if key in cur["_keys"]:
                    continue  # re-shipped row from a retreated cursor
                cur["_keys"].add(key)
                cur["spans"].append(span)
        if isinstance(payload.get("next_since"), int):
            st.trace_since = payload["next_since"]

    def _fold_events(self, st: _SourceState, payload: dict) -> None:
        for event in payload.get("events") or []:
            if isinstance(event, dict):
                st.events.append(event)
        if isinstance(payload.get("next_since"), int):
            st.event_since = payload["next_since"]

    def _trace_payload(self, st: _SourceState) -> dict:
        return {"traces": [
            {k: v for k, v in t.items() if k != "_keys"}
            for t in st.traces.values()]}

    # -- collection ----------------------------------------------------------
    async def _fetch_json(self, session, url: str):
        import aiohttp

        timeout = aiohttp.ClientTimeout(total=self.timeout_s)
        async with session.get(url, timeout=timeout) as resp:
            if resp.status != 200:
                raise RuntimeError(f"{url} -> {resp.status}")
            return await resp.json()

    async def _collect_source(self, session, name: str, base: str,
                              kind: str) -> dict | None:
        """One source's pull: traces+events deltas always; slo+health for
        gateway peers.  Returns the fetched slo/health payloads (or None
        on failure — the cached traces/events still contribute)."""
        st = self._state(name)
        try:
            traces = await self._fetch_json(
                session, f"{base}/debug/traces?since={st.trace_since}"
                         f"&limit=1024")
            events = await self._fetch_json(
                session, f"{base}/debug/events?since={st.event_since}"
                         f"&limit=2048")
            if not isinstance(traces, dict) or not isinstance(events, dict):
                # Valid JSON of the wrong shape (foreign peer, wrong URL)
                # is a source failure, not a page failure.
                raise RuntimeError(f"{base}: non-dict debug payload")
            extra = {}
            if kind == "gateway":
                extra["slo"] = await self._fetch_json(
                    session, f"{base}/debug/slo")
                extra["health"] = await self._fetch_json(
                    session, f"{base}/debug/health")
                if any(not isinstance(v, dict) for v in extra.values()):
                    raise RuntimeError(f"{base}: non-dict slo/health "
                                       f"payload")
        except Exception as e:  # noqa: BLE001 — every failure is a marker
            st.last_ok = False
            st.last_error = str(e)[:200]
            with self._lock:
                self.errors_total[name] = self.errors_total.get(name, 0) + 1
            if self.journal is not None:
                # ``kind`` is the journal's own positional — the source's
                # flavor rides as source_kind.
                self.journal.emit(events_mod.FLEET_PEER_ERROR, source=name,
                                  source_kind=kind, error=st.last_error)
            return None
        self._fold_traces(st, traces)
        self._fold_events(st, events)
        st.last_ok = True
        st.last_error = ""
        return extra

    async def collect(self, session, limit: int = 64) -> dict:
        """One fleet pull: every source concurrently, then stitch.
        Single-flight (overlapping callers queue on the lock — each
        still gets a complete, current payload)."""
        async with self._collect_lock:
            return await self._collect_locked(session, limit)

    async def _collect_locked(self, session, limit: int) -> dict:
        t0 = time.perf_counter()
        now = self._clock()
        gateways = [(f"gw:{u}", u, "gateway") for u in self.peer_urls]
        pods = [(f"pod:{name}", f"http://{addr}", "pod")
                for name, addr in self.pods_fn()]
        results = await asyncio.gather(*(
            self._collect_source(session, name, base, kind)
            for name, base, kind in gateways + pods))

        slo_payloads: dict[str, dict] = {}
        health_payloads: dict[str, dict] = {}
        trace_sources: list[tuple[str, dict]] = []
        event_sources: list[tuple[str, dict]] = []
        # This replica's own view rides along without HTTP.
        if self.local_fn is not None:
            local = self.local_fn()
            trace_sources.append((self.replica, local.get("traces") or {}))
            event_sources.append((self.replica, local.get("events") or {}))
            if local.get("slo") is not None:
                slo_payloads[self.replica] = local["slo"]
            if local.get("health") is not None:
                health_payloads[self.replica] = local["health"]
        for (name, _base, kind), extra in zip(gateways + pods, results):
            st = self._state(name)
            trace_sources.append((name, self._trace_payload(st)))
            event_sources.append((name, {"events": list(st.events)}))
            if extra:
                if "slo" in extra:
                    slo_payloads[name] = extra["slo"]
                if "health" in extra:
                    health_payloads[name] = extra["health"]

        stitched = stitch_traces(trace_sources, limit=limit)
        merged_events = merge_events(event_sources)
        ok_by_kind: dict[str, int] = {"gateway": 0, "pod": 0}
        source_rows = []
        if self.local_fn is not None:
            source_rows.append({"name": self.replica, "kind": "gateway",
                                "url": "", "ok": True, "error": ""})
            ok_by_kind["gateway"] += 1
        for name, base, kind in gateways + pods:
            st = self._state(name)
            if st.last_ok:
                ok_by_kind[kind] += 1
            source_rows.append({"name": name, "kind": kind, "url": base,
                               "ok": st.last_ok, "error": st.last_error})
        # Prune state for sources that left the fleet (pod churn mints
        # new names forever): a departed pod's cached deques/traces and
        # its errors_total series must not grow memory and Prometheus
        # cardinality monotonically (the statebus eviction precedent).
        live = {name for name, _base, _kind in gateways + pods}
        for name in [n for n in self._sources if n not in live]:
            del self._sources[name]
        with self._lock:
            for name in [n for n in self.errors_total if n not in live]:
                del self.errors_total[name]
            self.last_sources = ok_by_kind
            self.last_stitched = len(stitched)
        self.collect_hist.observe(time.perf_counter() - t0)
        return {
            "replica": self.replica,
            "collected_at": round(now, 6),
            "sources": source_rows,
            "traces": stitched,
            "events": merged_events,
            "slo": fleet_slo(slo_payloads),
            "health": health_payloads,
        }

    # -- export --------------------------------------------------------------
    def render(self) -> list[str]:
        """The ``gateway_fleet_*`` families."""
        with self._lock:
            sources = dict(self.last_sources)
            errors = dict(self.errors_total)
            stitched = self.last_stitched
        lines = ["# TYPE gateway_fleet_sources gauge"]
        for kind in sorted(sources):
            lines.append('gateway_fleet_sources{kind="%s"} %d'
                         % (escape_label(kind), sources[kind]))
        lines += ["# TYPE gateway_fleet_stitched_traces gauge",
                  f"gateway_fleet_stitched_traces {stitched}"]
        lines += render_counter("gateway_fleet_collect_errors_total",
                                errors, "source")
        lines += render_histogram("gateway_fleet_collect_seconds",
                                  self.collect_hist)
        return lines
