"""Per-pool advisor stack: one pool's full control-plane bundle.

Before this module the proxy inlined ONE health scorer + resilience plane
+ usage rollup + fairness policy + placement planner over its single
pool's provider and scheduler, and a multi-pool front
(``multipool.MultiPoolServer``) got none of it — PR 7 logged a loud
"enforcement INACTIVE" warning instead.  ``AdvisorStack`` extracts that
wiring so the proxy builds one stack **per pool**: each pool's scheduler
gets its own ``health_advisor`` / ``usage_advisor`` / ``placement_advisor``
seams (Python AND native paths — the advisors are the same objects both
marshal from), each pool's handler core gets its own fairness ``admit()``
gate, and the observability tick drives every stack.  The multi-pool
enforcement carve-out is gone.

The stack is also the unit the replicated state plane gossips
(``gateway/statebus.py``): each advisor exposes a *local* accessor (what
this replica derived itself — published) and a *remote overlay* setter
(the merged peer view — applied), so N gateways fronting the same pools
share one brain without any advisor growing a network dependency.
"""

from __future__ import annotations

from llm_instance_gateway_tpu import events as events_mod
from llm_instance_gateway_tpu.gateway import capacity as capacity_mod
from llm_instance_gateway_tpu.gateway import fairness as fairness_mod
from llm_instance_gateway_tpu.gateway import health as health_mod
from llm_instance_gateway_tpu.gateway import kvobs as kvobs_mod
from llm_instance_gateway_tpu.gateway import pickledger as pickledger_mod
from llm_instance_gateway_tpu.gateway import placement as placement_mod
from llm_instance_gateway_tpu.gateway import resilience as resilience_mod
from llm_instance_gateway_tpu.gateway import usage as usage_mod


class AdvisorStack:
    """One pool's advisors, built over that pool's provider and wired
    into that pool's scheduler + handler core.

    ``metrics`` is the (gateway-wide) GatewayMetrics the usage rollup
    reads admitted-traffic deltas from; ``request_filter`` scopes those
    deltas to this pool's models on multi-pool fronts.  ``journal`` is
    the shared flight recorder (one per gateway process — events carry
    pod/model attributes that disambiguate pools).
    """

    def __init__(self, pool_name: str, provider, scheduler=None,
                 server=None, metrics=None,
                 journal: "events_mod.EventJournal | None" = None,
                 resilience_cfg=None, health_cfg=None, usage_cfg=None,
                 fairness_cfg=None, placement_cfg=None,
                 pickledger_cfg=None, capacity_cfg=None,
                 request_filter=None):
        self.pool_name = pool_name
        self.provider = provider
        self.journal = journal if journal is not None \
            else events_mod.EventJournal()
        self.health = health_mod.HealthScorer(
            provider=provider, cfg=health_cfg, journal=self.journal)
        self.resilience = resilience_mod.ResiliencePlane(
            self.health, cfg=resilience_cfg, journal=self.journal)
        self.usage = usage_mod.UsageRollup(
            provider, metrics=metrics, cfg=usage_cfg, journal=self.journal,
            request_filter=request_filter)
        # KV economy rollup (gateway/kvobs.py): per-pod reuse efficiency /
        # parked share + the fleet prefix duplication index over the same
        # provider scrape.  Purely observational — no scheduler seam.
        self.kvobs = kvobs_mod.KvObsRollup(provider, journal=self.journal)
        # Routing decision ledger (gateway/pickledger.py): sampled
        # per-pick explanation records + counterfactual seam attribution.
        # Log-only by construction — the scheduler seam it wires never
        # alters routing (counter-modulus sampling, no RNG).
        self.pickledger = pickledger_mod.PickLedger(
            cfg=pickledger_cfg, journal=self.journal)
        # Capacity & saturation plane (gateway/capacity.py): saturation
        # indices + the sim-calibrated digital twin's headroom/
        # time-to-breach forecasts and drift alarms.  Purely
        # observational — no scheduler seam.
        self.capacity = capacity_mod.CapacityPlanner(
            provider, cfg=capacity_cfg, journal=self.journal)
        # Fairness config precedence, per FIELD: explicit CLI flags (a
        # dict of overrides from bootstrap.fairness_from_args — pinned,
        # re-applied on every hot reload) > THIS pool document's
        # schedulerConfig.fairnessPolicy section (already parsed into the
        # pool scheduler's live config) > defaults.  A full
        # FairnessConfig (programmatic callers/tests) is the initial
        # config, reloadable as a whole.
        fairness_overrides = None
        if isinstance(fairness_cfg, dict):
            fairness_overrides, fairness_cfg = fairness_cfg, None
        if fairness_cfg is None:
            sched_cfg = getattr(scheduler, "cfg", None)
            fairness_cfg = getattr(sched_cfg, "fairness", None)
        self.fairness = fairness_mod.FairnessPolicy(
            self.usage, cfg=fairness_cfg, journal=self.journal,
            provider=provider, cli_overrides=fairness_overrides)
        self.placement = placement_mod.PlacementPlanner(
            provider, usage=self.usage, cfg=placement_cfg,
            journal=self.journal)
        self.wire(scheduler, server)

    # -- seam wiring --------------------------------------------------------
    def wire(self, outer_scheduler, server) -> None:
        """Attach this stack's advisors to the pool's scheduler seams and
        handler core.  ``outer_scheduler`` may be the AdmissionController
        wrapping the real scheduler (reach through ``_scheduler``) or the
        scheduler itself; either may be None for partially-assembled test
        rigs."""
        sched = getattr(outer_scheduler, "_scheduler", outer_scheduler)
        if sched is not None and hasattr(sched, "health_advisor"):
            sched.health_advisor = self.resilience
        if sched is not None and hasattr(sched, "usage_advisor"):
            sched.usage_advisor = self.fairness
        if sched is not None and hasattr(sched, "placement_advisor"):
            sched.placement_advisor = self.placement
        if sched is not None and hasattr(sched, "pick_ledger"):
            sched.pick_ledger = self.pickledger
        # The AdmissionController feeds fairnessPolicy hot-reloads from
        # the pool document through this reference.
        if outer_scheduler is not None and hasattr(outer_scheduler,
                                                   "fairness"):
            outer_scheduler.fairness = self.fairness
        if server is not None and hasattr(server, "fairness"):
            server.fairness = self.fairness

    # -- lifecycle ----------------------------------------------------------
    def tick(self) -> None:
        """One observability pass for this pool, in dependency order:
        health/breaker first (cheap, feeds the journal), usage shares,
        then the planes that read them (fairness quotas, placement)."""
        self.resilience.tick()
        self.usage.tick()
        self.kvobs.tick()
        if self.capacity.cfg.enabled:
            self.capacity.tick()
        self.fairness.tick()
        self.placement.tick()
        self.pickledger.tick()

    def pod_names(self) -> set[str]:
        return {pm.pod.name for pm in self.provider.all_pod_metrics()}

    # -- export -------------------------------------------------------------
    def render(self) -> list[str]:
        """This pool's exposition lines (health + circuits + usage +
        fairness + placement).  Multi-pool fronts merge the per-stack
        blocks through ``merge_exposition_blocks``."""
        lines = (self.health.render() + self.resilience.render()
                 + self.usage.render() + self.kvobs.render())
        if self.capacity.cfg.enabled:
            lines += self.capacity.render()
        return (lines + self.fairness.render() + self.placement.render()
                + self.pickledger.render())


def merge_exposition_blocks(blocks: list[list[str]]) -> list[str]:
    """Merge several pools' exposition blocks into one valid page.

    Per-pool stacks render the SAME families (``gateway_pod_health_score``
    etc.) over disjoint label sets (pod names are unique across pools,
    model names are per-pool unambiguous), so labeled samples concatenate
    — but each family's ``# TYPE`` line must appear exactly once, and the
    unlabeled scalar samples the renderers emit (per-stack counters like
    ``gateway_placement_escapes_total``, and the empty-family ``0``
    fallbacks of ``render_keyed_family``) must SUM, not repeat: two
    unlabeled samples of one family is malformed exposition.

    Counter samples with identical name+labels sum; gauges keep the last
    value (pools never legitimately collide on a labeled gauge).  Order
    of first appearance is preserved.
    """
    types: dict[str, str] = {}
    order: list[tuple[str, str]] = []  # ("type"|"sample", key)
    seen: set[str] = set()
    values: dict[str, float] = {}
    int_valued: dict[str, bool] = {}

    def family_of(sample_key: str) -> str:
        name = sample_key.split("{", 1)[0]
        for suffix in ("_bucket", "_sum", "_count"):
            base = name[: -len(suffix)] if name.endswith(suffix) else None
            if base is not None and base in types:
                return base
        return name

    for block in blocks:
        for line in block:
            if line.startswith("# TYPE "):
                _, _, name, kind = line.split(" ", 3)
                if name not in types:
                    types[name] = kind
                    order.append(("type", name))
                continue
            if not line or line.startswith("#"):
                if line not in seen:
                    seen.add(line)
                    order.append(("raw", line))
                continue
            key, _, raw = line.rpartition(" ")
            try:
                value = float(raw)
            except ValueError:
                key, value = line, 0.0  # malformed: pass through verbatim
                raw = ""
            if key not in values:
                values[key] = value
                int_valued[key] = "." not in raw and "e" not in raw.lower()
                order.append(("sample", key))
            elif types.get(family_of(key)) == "counter":
                values[key] += value
                int_valued[key] = int_valued[key] and (
                    "." not in raw and "e" not in raw.lower())
            else:
                values[key] = value
    out: list[str] = []
    for kind, key in order:
        if kind == "type":
            out.append(f"# TYPE {key} {types[key]}")
        elif kind == "raw":
            out.append(key)
        else:
            v = values[key]
            out.append(f"{key} {int(v)}" if int_valued[key]
                       and float(v).is_integer() else f"{key} {v:g}")
    return out
