"""Core gateway data types: pods (TPU slice replicas) and their live metrics.

Parity: reference ``pkg/ext-proc/backend/types.go:8-53`` defines
``Pod{Name,Address}`` and ``Metrics{ActiveModels, RunningQueueSize,
WaitingQueueSize, KVCacheUsagePercent, ...}``.  The TPU-native schema differs
deliberately:

- The unit of placement is a **slice-backed replica** (a JetStream-style server
  owning one TPU slice), not a single-GPU pod (SURVEY.md §2.5).
- Queue depth is split into **prefill** and **decode** queues because TPU
  continuous batching disaggregates the two phases; the scheduler must route on
  the right one (SURVEY.md §7 "hard parts").
- KV headroom is token-denominated (``kv_tokens_free`` /
  ``kv_tokens_capacity``) in addition to the percent signal, enabling
  token-aware long-context routing (reference stubs this at
  ``backend/types.go:25`` but never uses it).
"""

from __future__ import annotations

import dataclasses
from dataclasses import dataclass, field

# Pool roles under cross-engine prefill/decode disaggregation
# (server/kv_transfer.py).  A pool mixing "prefill" and "decode" replicas
# gets two-stage routing (scheduler.schedule_disaggregated); "collocated"
# replicas serve whole requests single-hop (the reference topology).
ROLE_COLLOCATED = "collocated"
ROLE_PREFILL = "prefill"
ROLE_DECODE = "decode"
POOL_ROLES = (ROLE_COLLOCATED, ROLE_PREFILL, ROLE_DECODE)


def pod_role(pod) -> str:
    """A pod's disaggregation role, defaulting legacy objects to collocated."""
    return getattr(pod, "role", ROLE_COLLOCATED) or ROLE_COLLOCATED


@dataclass(frozen=True)
class Pod:
    """A routable model-server replica (one TPU-slice-backed server process).

    ``address`` is ``host:port`` of the replica's serving endpoint.  For a
    multi-host slice this is the slice leader (SURVEY.md §7: "the pod is
    actually the slice's leader host").  ``role`` marks prefill/decode
    specialization for disaggregated pools (collocated = serves both
    phases, the default and the reference behavior).
    """

    name: str
    address: str
    role: str = ROLE_COLLOCATED

    def __str__(self) -> str:  # parity: types.go Pod.String()
        return f"{self.name}({self.address})"


@dataclass
class Metrics:
    """Live scheduling signals scraped from one replica.

    Parity with ``backend/types.go:17-31`` plus the TPU prefill/decode split.
    ``active_adapters`` maps adapter id -> number of in-flight requests using
    it (reference: ``ActiveModels map[string]int``).
    """

    active_adapters: dict[str, int] = field(default_factory=dict)
    max_active_adapters: int = 0
    # Resident adapter -> LoRA rank (tpu:lora_requests_info adapter_ranks
    # label): the heterogeneity signal rank-aware fair-share weighting
    # consumes (gateway/fairness.py).  Empty for foreign servers.
    adapter_ranks: dict[str, int] = field(default_factory=dict)
    # Residency ladder (tpu:adapter_residency_info): adapter -> tier
    # ("slot" | "host").  Adapters absent are disk-tier (cold).  The
    # placement planner and the prefer_resident routing seam consume this;
    # empty for servers without the residency families.
    adapter_tiers: dict[str, str] = field(default_factory=dict)
    # The running/waiting split behind active_adapters (which stays the
    # UNION for the affinity filter): waiting adapters are the planner's
    # urgency signal — requests parked on an adapter not yet decodable.
    running_adapters: frozenset = frozenset()
    waiting_adapters: frozenset = frozenset()
    # Queue depths.  ``waiting_queue_size`` mirrors the reference's vLLM
    # num_requests_waiting; on TPU it is prefill_queue + decode_waiting.
    running_queue_size: int = 0
    waiting_queue_size: int = 0
    prefill_queue_size: int = 0
    decode_queue_size: int = 0
    # KV / HBM headroom.  ``kv_tokens_free`` already accounts for parked
    # (prefilled-but-unslotted) KV on the server side; ``kv_parked_tokens``
    # is exported separately for observability.
    kv_cache_usage_percent: float = 0.0
    kv_tokens_capacity: int = 0
    kv_tokens_free: int = 0
    kv_parked_tokens: int = 0
    # Serving rates (optional, for latency-aware policies and the simulator).
    decode_tokens_per_sec: float = 0.0
    # Cumulative prompt tokens served from the replica's prefix cache
    # (``tpu:prefix_reused_tokens``): the observable a future KV-affinity
    # routing policy needs — a replica already holding a shared prefix is
    # cheaper to prefill on (SURVEY §5 observability note).
    prefix_reused_tokens: int = 0
    # Phase-latency means derived from the replica's tpu:prefill_seconds /
    # tpu:decode_step_seconds histograms (_sum / _count): the per-replica
    # observables an SLO-aware routing policy ranks on.  0.0 = no samples
    # yet (or a foreign server without the families).
    prefill_seconds_mean: float = 0.0
    decode_step_seconds_mean: float = 0.0
    # CUMULATIVE phase-histogram sums/counts behind the means above, plus
    # the decode-batch occupancy histogram: the capacity plane
    # (gateway/capacity.py) differences these between scrape ticks to get
    # per-WINDOW means — the observation windows
    # sim/calibrate.calibrate_from_observables fits the twin from.
    prefill_seconds_sum: float = 0.0
    prefill_seconds_count: float = 0.0
    decode_step_seconds_sum: float = 0.0
    decode_step_seconds_count: float = 0.0
    decode_batch_occupancy_sum: float = 0.0
    decode_batch_occupancy_count: float = 0.0
    # Step-timeline profiler means (tpu:dispatch_wall_seconds /
    # tpu:dispatch_gap_seconds{kind="host"} _sum/_count): per-dispatch
    # device wall and the host-sync tax between dispatches — the
    # per-replica observables the dispatch-bound roadmap levers move.
    dispatch_wall_seconds_mean: float = 0.0
    dispatch_host_gap_seconds_mean: float = 0.0
    # Per-adapter capacity attribution scraped from the replica's
    # tpu:adapter_*_total families (server/usage.py).  Keys:
    # (model, adapter, phase) for step-seconds/tokens, (model, adapter)
    # for KV block-seconds; values are the replica's CUMULATIVE counters.
    # The gateway-wide rollup (gateway/usage.py) sums these across pods
    # and differences between scrape ticks.
    adapter_step_seconds: dict = field(default_factory=dict)
    adapter_tokens: dict = field(default_factory=dict)
    adapter_kv_block_seconds: dict = field(default_factory=dict)
    # Pool-waste counters (cumulative): slot-seconds decode dispatches ran
    # with empty rows, and prompt tokens prefilled as bucket/ring padding.
    idle_slot_seconds: float = 0.0
    prefill_padding_tokens: int = 0
    # KV economy ledger families (server/kv_ledger.py; all optional —
    # absent on foreign servers and with the ledger off).  kv_blocks maps
    # state -> blocks ("free"/"active"/"prefix_resident"/"parked", tiling
    # kv_blocks_total); kv_block_events maps lifecycle kind -> cumulative
    # count; the kv_prefix_* tables key on the content-addressed prefix
    # id, the join key of the fleet duplication index (gateway/kvobs.py).
    kv_blocks: dict = field(default_factory=dict)
    kv_blocks_total: int = 0
    kv_block_tokens: int = 0
    kv_block_events: dict = field(default_factory=dict)
    kv_prefix_hits: dict = field(default_factory=dict)
    kv_prefix_tokens_saved: dict = field(default_factory=dict)
    kv_prefix_resident_blocks: dict = field(default_factory=dict)

    def clone(self) -> "Metrics":
        m = dataclasses.replace(self)
        m.active_adapters = dict(self.active_adapters)
        m.adapter_ranks = dict(self.adapter_ranks)
        m.adapter_tiers = dict(self.adapter_tiers)
        m.adapter_step_seconds = dict(self.adapter_step_seconds)
        m.adapter_tokens = dict(self.adapter_tokens)
        m.adapter_kv_block_seconds = dict(self.adapter_kv_block_seconds)
        m.kv_blocks = dict(self.kv_blocks)
        m.kv_block_events = dict(self.kv_block_events)
        m.kv_prefix_hits = dict(self.kv_prefix_hits)
        m.kv_prefix_tokens_saved = dict(self.kv_prefix_tokens_saved)
        m.kv_prefix_resident_blocks = dict(self.kv_prefix_resident_blocks)
        return m

    @property
    def total_queue_size(self) -> int:
        """Combined pending work; used where the reference used WaitingQueueSize."""
        if self.waiting_queue_size:
            return self.waiting_queue_size
        return self.prefill_queue_size + self.decode_queue_size


@dataclass
class PodMetrics:
    """A pod together with its latest metrics snapshot (types.go:33-53)."""

    pod: Pod
    metrics: Metrics

    def clone(self) -> "PodMetrics":
        return PodMetrics(pod=self.pod, metrics=self.metrics.clone())

    def __str__(self) -> str:
        return f"Pod: {self.pod}; Metrics: {self.metrics}"
