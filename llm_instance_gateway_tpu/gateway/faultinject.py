"""Deterministic fault injection for the robustness plane.

``tests/test_resilience.py`` and ``tools/chaos.py`` need to *cause* the
failures the resilience plane (gateway/resilience.py) exists to absorb —
reproducibly, from a seed, against both the in-process test stack and the
real 3-process e2e stack.  This module is that harness:

- ``FaultSpec``/``FaultSchedule``: a declarative, time-windowed schedule of
  faults (replica blackhole, slow-TTFT brownout, injected error statuses,
  mid-stream disconnect, scrape flap, handoff failure).  Schedules are
  plain data — JSON-serializable for the e2e path — and ``arm()`` pins the
  schedule's t0, so a given schedule replays identically.
- ``aiohttp_middleware``: applied by the REAL model server (``api_http``)
  when the ``LIG_FAULTS`` env var names a schedule file — the e2e chaos
  stack injects faults into actual serving processes without forking the
  server code.
- ``make_chaos_app``: a minimal OpenAI-shaped fake upstream whose handlers
  consult the schedule — the in-process stack (no subprocesses, no model)
  that ``tools/chaos.py`` drives and the fast resilience tests use.
- ``ChaosProvider``: a StaticProvider whose ``scrape_health`` flaps per the
  schedule, for the scrape-flap scenario (that fault lives on the
  gateway's scrape plane, not the HTTP data path).

Fault kinds (``FaultSpec.kind``):

====================  ====================================================
``blackhole``         handler hangs (connect succeeds, no bytes follow)
``brownout``          handler sleeps ``delay_s`` before answering
``error``             handler answers ``status`` (default 500) immediately
``midstream_disconnect``  SSE stream cut after ``after_chunks`` chunks
``scrape_flap``       pod's metrics scrapes fail (ChaosProvider only)
``handoff_failure``   ``/v1/prefill`` / ``/v1/attach`` fail (``mode``:
                      ``error`` -> 500, ``disconnect`` -> transport cut)
====================  ====================================================
"""

from __future__ import annotations

import asyncio
import json
import time
from dataclasses import dataclass, field

from aiohttp import web

BLACKHOLE = "blackhole"
BROWNOUT = "brownout"
ERROR = "error"
MIDSTREAM_DISCONNECT = "midstream_disconnect"
SCRAPE_FLAP = "scrape_flap"
HANDOFF_FAILURE = "handoff_failure"
FAULT_KINDS = (BLACKHOLE, BROWNOUT, ERROR, MIDSTREAM_DISCONNECT,
               SCRAPE_FLAP, HANDOFF_FAILURE)

# Default path scope per kind (overridable via params["paths"]).
_COMPLETION_PATHS = ("/v1/completions", "/v1/chat/completions")
_KIND_PATHS = {
    HANDOFF_FAILURE: ("/v1/prefill", "/v1/attach"),
}
# How long a blackholed handler hangs per request before giving up with a
# 503 — long enough that every sane TTFT timeout fires first, short enough
# that a harness teardown never waits minutes on stragglers.
_BLACKHOLE_HANG_S = 30.0


@dataclass(frozen=True)
class FaultSpec:
    """One fault window.  ``pod=""`` matches every pod; times are seconds
    relative to ``FaultSchedule.arm()``."""

    kind: str
    pod: str = ""
    start_s: float = 0.0
    duration_s: float = 1e9
    params: dict = field(default_factory=dict)

    def __post_init__(self):
        if self.kind not in FAULT_KINDS:
            raise ValueError(f"unknown fault kind {self.kind!r} "
                             f"(expected one of {FAULT_KINDS})")

    def paths(self) -> tuple:
        return tuple(self.params.get(
            "paths", _KIND_PATHS.get(self.kind, _COMPLETION_PATHS)))

    def to_dict(self) -> dict:
        return {"kind": self.kind, "pod": self.pod, "start_s": self.start_s,
                "duration_s": self.duration_s, "params": dict(self.params)}


class FaultSchedule:
    """A set of fault windows on one clock.  ``arm()`` pins t0 (idempotent:
    the first arm wins, so middleware and harness share one origin)."""

    def __init__(self, faults: list[FaultSpec], seed: int = 0, clock=time.time):
        self.faults = list(faults)
        self.seed = seed
        self._clock = clock
        self._t0: float | None = None

    @classmethod
    def from_dict(cls, d: dict, clock=time.time) -> "FaultSchedule":
        faults = [FaultSpec(kind=f["kind"], pod=f.get("pod", ""),
                            start_s=float(f.get("start_s", 0.0)),
                            duration_s=float(f.get("duration_s", 1e9)),
                            params=dict(f.get("params", {})))
                  for f in d.get("faults", [])]
        return cls(faults, seed=int(d.get("seed", 0)), clock=clock)

    @classmethod
    def from_file(cls, path: str) -> "FaultSchedule":
        with open(path) as fh:
            return cls.from_dict(json.load(fh))

    def to_dict(self) -> dict:
        return {"seed": self.seed,
                "faults": [f.to_dict() for f in self.faults]}

    def arm(self, now: float | None = None) -> None:
        if self._t0 is None:
            self._t0 = self._clock() if now is None else now

    def elapsed(self, now: float | None = None) -> float:
        if self._t0 is None:
            self.arm(now)
        return (self._clock() if now is None else now) - self._t0

    def active(self, pod: str = "", path: str | None = None,
               kind: str | None = None,
               now: float | None = None) -> FaultSpec | None:
        """The first fault window covering (pod, path, kind) right now."""
        t = self.elapsed(now)
        for f in self.faults:
            if kind is not None and f.kind != kind:
                continue
            if f.pod and pod and f.pod != pod:
                continue
            if path is not None and path not in f.paths():
                continue
            if f.start_s <= t < f.start_s + f.duration_s:
                return f
        return None

    def inject_now(self, kind: str, pod: str = "", duration_s: float = 1e9,
                   **params) -> FaultSpec:
        """Append a fault window opening at the current schedule time —
        harness phases ('warm up clean, then break pod X') stay explicit
        instead of guessing wall-clock offsets."""
        spec = FaultSpec(kind, pod=pod, start_s=self.elapsed(),
                         duration_s=duration_s, params=params)
        self.faults.append(spec)
        return spec

    def remaining(self, spec: FaultSpec, now: float | None = None) -> float:
        return max(0.0, spec.start_s + spec.duration_s - self.elapsed(now))

    def describe(self) -> str:
        return "; ".join(
            f"{f.kind}(pod={f.pod or '*'}, t=[{f.start_s:g},"
            f"{f.start_s + min(f.duration_s, 9e8):g}))"
            for f in self.faults) or "empty"


async def _apply_http_fault(schedule: FaultSchedule, spec: FaultSpec,
                            request: web.Request, journal=None):
    """Apply one data-path fault inside an aiohttp handler/middleware.
    Returns a Response to short-circuit with, or None to proceed normally
    (brownout: after its delay)."""
    if journal is not None:
        journal.emit("fault_inject", fault=spec.kind, path=request.path,
                     pod=spec.pod)
    if spec.kind == BLACKHOLE:
        await asyncio.sleep(min(_BLACKHOLE_HANG_S,
                                schedule.remaining(spec) + 1.0))
        return web.Response(status=503, text="blackhole fault elapsed")
    if spec.kind == BROWNOUT:
        await asyncio.sleep(float(spec.params.get("delay_s", 1.0)))
        return None
    if spec.kind == ERROR:
        return web.Response(status=int(spec.params.get("status", 500)),
                            text="injected fault")
    if spec.kind == HANDOFF_FAILURE:
        if spec.params.get("mode", "error") == "disconnect":
            if request.transport is not None:
                request.transport.close()
            raise ConnectionResetError("injected handoff disconnect")
        return web.Response(status=int(spec.params.get("status", 500)),
                            text="injected handoff fault")
    # MIDSTREAM_DISCONNECT is applied inside the streaming handler (the
    # middleware can't truncate a live SSE relay) — pass through here.
    return None


def aiohttp_middleware(schedule: FaultSchedule, journal=None):
    """Middleware for the REAL model server: consult the schedule before
    every ``/v1/*`` handler.  Mid-stream disconnects are approximated by
    closing the transport ``after_s`` seconds into the request."""
    schedule.arm()

    @web.middleware
    async def fault_middleware(request: web.Request, handler):
        if not request.path.startswith("/v1/"):
            return await handler(request)
        spec = schedule.active(path=request.path)
        if spec is None:
            return await handler(request)
        if spec.kind == MIDSTREAM_DISCONNECT:
            loop = asyncio.get_running_loop()
            transport = request.transport

            def cut():
                if transport is not None:
                    transport.close()

            loop.call_later(float(spec.params.get("after_s", 0.2)), cut)
            if journal is not None:
                journal.emit("fault_inject", fault=spec.kind,
                             path=request.path)
            return await handler(request)
        short = await _apply_http_fault(schedule, spec, request, journal)
        return short if short is not None else await handler(request)

    return fault_middleware


def make_chaos_app(name: str, schedule: FaultSchedule,
                   state: dict | None = None) -> web.Application:
    """A minimal OpenAI-shaped fake upstream for the in-process chaos
    stack: echoes which pod served, supports SSE streaming, the
    disaggregation hops, and the release endpoint — every handler gated by
    the fault schedule.  ``state`` (optional) collects observations the
    harness asserts on (served counts, release calls)."""
    state = state if state is not None else {}
    state.setdefault("served", 0)
    state.setdefault("released", [])

    def _note_served():
        state["served"] += 1

    async def completions(request: web.Request) -> web.StreamResponse:
        spec = schedule.active(pod=name, path=request.path)
        if spec is not None and spec.kind != MIDSTREAM_DISCONNECT:
            short = await _apply_http_fault(schedule, spec, request)
            if short is not None:
                return short
        body = await request.json()
        stream = bool(body.get("stream"))
        usage = {"prompt_tokens": 4, "completion_tokens": 4,
                 "total_tokens": 8}
        if not stream:
            _note_served()
            return web.json_response({
                "id": "cmpl-1", "object": "text_completion",
                "model": body.get("model", "m"), "served_by": name,
                "choices": [{"index": 0, "text": "ok",
                             "finish_reason": "stop"}],
                "usage": usage, "ttft_ms": 1.0,
            })
        resp = web.StreamResponse(
            status=200, headers={"Content-Type": "text/event-stream"})
        await resp.prepare(request)
        cut_after = None
        if spec is not None and spec.kind == MIDSTREAM_DISCONNECT:
            cut_after = int(spec.params.get("after_chunks", 2))
        for i in range(4):
            if cut_after is not None and i >= cut_after:
                request.transport.close()
                return resp
            chunk = {"choices": [{"index": 0, "text": f"t{i}"}]}
            if i == 3:
                chunk["usage"] = usage
            await resp.write(b"data: " + json.dumps(chunk).encode() + b"\n\n")
            await asyncio.sleep(0.01)
        await resp.write(b"data: [DONE]\n\n")
        _note_served()
        return resp

    async def prefill(request: web.Request) -> web.Response:
        spec = schedule.active(pod=name, path=request.path)
        if spec is not None:
            short = await _apply_http_fault(schedule, spec, request)
            if short is not None:
                return short
        await request.read()
        return web.Response(
            body=b"FAKE-HANDOFF",
            content_type="application/octet-stream",
            headers={"x-request-id": f"eng-{name}-{state['served']}"})

    async def attach(request: web.Request) -> web.Response:
        spec = schedule.active(pod=name, path=request.path)
        if spec is not None:
            short = await _apply_http_fault(schedule, spec, request)
            if short is not None:
                return short
        await request.read()
        _note_served()
        return web.json_response({
            "id": "cmpl-a", "object": "text_completion", "served_by": name,
            "choices": [{"index": 0, "text": "ok", "finish_reason": "stop"}],
            "usage": {"prompt_tokens": 4, "completion_tokens": 4,
                      "total_tokens": 8},
            "ttft_ms": 1.0,
        })

    async def release(request: web.Request) -> web.Response:
        body = await request.json()
        state["released"].append(body.get("request_id"))
        return web.json_response({"request_id": body.get("request_id"),
                                  "released": True})

    app = web.Application()
    app.router.add_post("/v1/completions", completions)
    app.router.add_post("/v1/chat/completions", completions)
    app.router.add_post("/v1/prefill", prefill)
    app.router.add_post("/v1/attach", attach)
    app.router.add_post("/v1/prefill/release", release)
    return app


class ChaosProvider:
    """StaticProvider shape whose ``scrape_health`` flaps per the
    schedule — the scrape-flap fault lives on the gateway's metrics plane,
    not the HTTP data path."""

    def __init__(self, pod_metrics: list, schedule: FaultSchedule,
                 clock=time.time, flap_step: int = 5):
        self._pm = list(pod_metrics)
        self.schedule = schedule
        self._clock = clock
        # Failure-streak growth per scrape_health call: the real scrape
        # loop runs ~100x faster than the health tick, so one health-tick
        # observation of a flapping pod sees a multi-failure streak.
        self.flap_step = flap_step
        self._last_ok: dict[str, float] = {}
        self._streak: dict[str, int] = {}

    def all_pod_metrics(self) -> list:
        return list(self._pm)

    def get_pod_metrics(self, pod_name: str):
        for pm in self._pm:
            if pm.pod.name == pod_name:
                return pm
        return None

    def scrape_health(self) -> dict:
        """Each call is one scrape round: flapped pods extend their failure
        streak, clean pods stamp fresh success."""
        now = self._clock()
        out = {}
        for pm in self._pm:
            name = pm.pod.name
            if self.schedule.active(pod=name, kind=SCRAPE_FLAP,
                                    path=None) is not None:
                self._streak[name] = (self._streak.get(name, 0)
                                      + self.flap_step)
            else:
                self._streak[name] = 0
                self._last_ok[name] = now
            out[name] = (self._last_ok.get(name), self._streak.get(name, 0))
        return out
