"""SLO engine: multi-window burn-rate evaluation over the gateway's own
request-phase histograms and shed/error counters.

PR 2 made the gateway *record* TTFT/TPOT/e2e per model and path
(``gateway_*_seconds`` histograms) and count sheds/errors; nothing yet
*evaluated* them, so "are we in SLO, and how fast are we burning budget?"
had no machine answer.  This module is that answer, following the
multi-window, multi-burn-rate alerting shape managed LLM fleets converge on
(MinT's aggregation layer; Google SRE workbook alerting):

- An **objective** is "fraction ``target`` of requests must satisfy X" —
  latency objectives (``ttft``/``tpot``/``e2e`` under ``threshold_s``) and
  an ``error_rate`` objective (non-shed, non-error completion).  The error
  *budget* is ``1 - target``.
- The engine snapshots the cumulative good/total counts each tick and
  derives **windowed burn rates**: ``burn(w) = bad_fraction(w) / budget``.
  Burn 1.0 = exactly consuming budget at the sustainable rate; 14.4 over
  the fast window pair = the classic "2% of a 30-day budget in an hour"
  page condition, scaled here to whatever windows the config carries (tests
  shrink them to seconds).
- **State machine** per (model, objective): ``ok`` -> ``slow_burn`` ->
  ``fast_burn``.  Escalation is immediate (both windows of the pair over
  threshold); de-escalation needs ``clear_ticks`` consecutive clear ticks —
  hysteresis so a breach doesn't flap at the boundary.  Transitions emit
  ``slo_transition`` events into the flight recorder, and entering
  ``fast_burn`` fires ``on_fast_burn`` (the proxy wires the black-box dump
  there).

Counting from histograms: "good" for a latency objective is the cumulative
count in buckets whose upper edge is <= ``threshold_s`` — thresholds
therefore snap DOWN to the nearest bucket boundary (the default thresholds
align with ``tracing.LATENCY_BUCKETS`` exactly).  Observations beyond the
largest bucket are bad by construction.
"""

from __future__ import annotations

import collections
import json
import logging
import os
import re
import time
from dataclasses import dataclass, field

from llm_instance_gateway_tpu.lockwitness import witness_lock
from llm_instance_gateway_tpu import events as events_mod
from llm_instance_gateway_tpu.tracing import escape_label

logger = logging.getLogger(__name__)


@dataclass(frozen=True)
class Window:
    name: str       # label value, e.g. "1m"
    seconds: float


@dataclass(frozen=True)
class Objective:
    name: str                    # "ttft" | "tpot" | "e2e" | "error_rate"
    target: float                # required compliance ratio, e.g. 0.95
    threshold_s: float | None = None  # latency objectives only

    @property
    def budget(self) -> float:
        """Error budget (fraction of requests allowed to miss)."""
        return max(1e-9, 1.0 - self.target)


# Defaults: thresholds all sit ON LATENCY_BUCKETS edges (1.0 / 0.1 / 10.0)
# so histogram counting is exact, targets are deliberately loose for a
# framework default — operators override per model via SLOConfig.per_model.
DEFAULT_OBJECTIVES = (
    Objective("ttft", target=0.95, threshold_s=1.0),
    Objective("tpot", target=0.95, threshold_s=0.1),
    Objective("e2e", target=0.95, threshold_s=10.0),
    Objective("error_rate", target=0.99),
)

# Fast pair (page-grade) = first two; slow pair (ticket-grade) = last two.
DEFAULT_WINDOWS = (
    Window("1m", 60.0),
    Window("5m", 300.0),
    Window("30m", 1800.0),
    Window("6h", 21600.0),
)


@dataclass
class SLOConfig:
    objectives: tuple = DEFAULT_OBJECTIVES
    # model -> tuple[Objective, ...] overrides (absent models get defaults).
    per_model: dict = field(default_factory=dict)
    windows: tuple = DEFAULT_WINDOWS  # ascending duration; first two = fast
    fast_burn_threshold: float = 14.4
    slow_burn_threshold: float = 6.0
    # De-escalation hysteresis: consecutive clear ticks required to step
    # DOWN a state (escalation is immediate — a page must not wait).
    clear_ticks: int = 3
    # Windows spanning fewer than this many requests don't judge (a single
    # slow request in an idle window must not page anyone).
    min_window_total: int = 10

    def objectives_for(self, model: str) -> tuple:
        return self.per_model.get(model, self.objectives)


def _good_total(hist_state: dict, threshold_s: float) -> tuple[int, int]:
    """(good, total) from a ``Histogram.state()`` dict: good = observations
    in buckets with upper edge <= threshold (threshold snaps DOWN)."""
    good = 0
    for edge, count in zip(hist_state["buckets"], hist_state["counts"]):
        if edge <= threshold_s + 1e-12:
            good += count
        else:
            break
    return good, hist_state["count"]


class SLOEngine:
    """Evaluates objectives over a ``GatewayMetrics`` instance.

    ``tick()`` is driven by the proxy's observability loop (and lazily by
    ``/debug/slo``); tests drive it with explicit ``now`` values against
    second-scale windows.  All reads go through
    ``GatewayMetrics.slo_snapshot()`` so lock discipline stays in
    telemetry.py.
    """

    OK, SLOW_BURN, FAST_BURN = "ok", "slow_burn", "fast_burn"
    _RANK = {OK: 0, SLOW_BURN: 1, FAST_BURN: 2}

    def __init__(self, metrics, cfg: SLOConfig | None = None,
                 journal: events_mod.EventJournal | None = None,
                 on_fast_burn=None, clock=time.time):
        self.metrics = metrics
        self.cfg = cfg or SLOConfig()
        self.journal = journal
        self.on_fast_burn = on_fast_burn  # (model, objective, burns) -> None
        self._clock = clock
        self._lock = witness_lock("SLOEngine._lock")
        # (model, objective) -> deque[(ts, good, total)] pruned to the
        # longest window; one sample per tick, so memory is O(models *
        # objectives * horizon/tick).
        self._samples: dict[tuple, collections.deque] = {}
        self._state: dict[tuple, str] = {}
        self._clear_streak: dict[tuple, int] = {}
        self._last_burns: dict[tuple, dict] = {}
        self.last_tick = 0.0

    # -- counting ------------------------------------------------------------
    @staticmethod
    def _models(snap: dict) -> set[str]:
        models = set(snap["requests"])
        for table in snap["phase"].values():
            models.update(m for (m, _path) in table)
        return models

    @staticmethod
    def _counts_for(snap: dict, model: str, obj: Objective) -> tuple[int, int]:
        if obj.name == "error_rate":
            # Denominator = admitted requests + pre-admission errors (the
            # latter never reach record_request, so without the widening a
            # burst of admission failures alongside healthy traffic would
            # overstate the bad fraction).  max() is a final safety clamp.
            total = (snap["requests"].get(model, 0)
                     + snap.get("errors_pre", {}).get(model, 0))
            bad = snap["shed"].get(model, 0) + snap["errors"].get(model, 0)
            total = max(total, bad)
            return total - bad, total
        good = total = 0
        for (m, _path), state in snap["phase"].get(obj.name, {}).items():
            if m != model:
                continue
            g, t = _good_total(state, obj.threshold_s)
            good += g
            total += t
        return good, total

    def _burns(self, ring, now: float, obj: Objective) -> dict:
        """window name -> burn rate (None = window spans too few requests)."""
        _, cur_good, cur_total = ring[-1]
        out = {}
        for w in self.cfg.windows:
            start = now - w.seconds
            # Baseline = the newest sample at or before the window start;
            # a ring not yet spanning the window uses its oldest sample
            # (the standard startup approximation — the window judges
            # whatever history exists).
            base = None
            for t, g, tot in ring:
                if t <= start:
                    base = (t, g, tot)
                else:
                    break
            if base is None:
                base = ring[0]
            d_total = cur_total - base[2]
            d_good = cur_good - base[1]
            if d_total < self.cfg.min_window_total:
                out[w.name] = None
            else:
                bad_frac = max(0, d_total - d_good) / d_total
                out[w.name] = bad_frac / obj.budget
        return out

    # -- evaluation ----------------------------------------------------------
    def maybe_tick(self, min_interval_s: float = 1.0) -> None:
        """On-demand evaluation with a floor between passes.  The debug
        endpoint calls this per request: each real tick appends one ring
        sample per (model, objective) retained for the full slow-window
        horizon, so an unthrottled 10 Hz dashboard poll would grow the
        rings (and the per-tick burn scans) with poll rate instead of with
        the configured cadence."""
        if self._clock() - self.last_tick >= min_interval_s:
            self.tick()

    def tick(self, now: float | None = None) -> None:
        """One evaluation pass: snapshot counts, update burns and states.
        Fast-burn hooks fire AFTER the internal lock is released (they
        re-enter via debug_payload for the dump)."""
        now = self._clock() if now is None else now
        snap = self.metrics.slo_snapshot()
        horizon = self.cfg.windows[-1].seconds
        fired: list[tuple[str, str, dict]] = []
        with self._lock:
            for model in sorted(self._models(snap)):
                for obj in self.cfg.objectives_for(model):
                    key = (model, obj.name)
                    ring = self._samples.get(key)
                    if ring is None:
                        ring = self._samples[key] = collections.deque()
                        # Cold-start baseline: counts present at a model's
                        # FIRST tick accrued within roughly one tick
                        # interval (an earlier tick would have seen the
                        # model otherwise), so a zero sample lets this
                        # tick judge them instead of blinding the engine
                        # to a burst that predates it.
                        ring.append((now, 0, 0))
                    good, total = self._counts_for(snap, model, obj)
                    ring.append((now, good, total))
                    while ring and ring[0][0] < now - horizon - 1.0:
                        ring.popleft()
                    burns = self._burns(ring, now, obj)
                    self._last_burns[key] = burns
                    if self._advance(key, model, obj, burns):
                        fired.append((model, obj.name, burns))
            self.last_tick = now
        for model, objective, burns in fired:
            if self.on_fast_burn is not None:
                try:
                    self.on_fast_burn(model, objective, burns)
                except Exception:
                    logger.exception("fast-burn hook failed")

    def _advance(self, key, model: str, obj: Objective, burns: dict) -> bool:
        """State machine step; returns True when FAST_BURN was entered."""
        ws = self.cfg.windows
        fast_ws = ws[:2] if len(ws) >= 2 else ws
        slow_ws = ws[2:] if len(ws) > 2 else ws

        def exceeded(group, threshold: float) -> bool:
            vals = [burns.get(w.name) for w in group]
            return bool(vals) and all(
                v is not None and v >= threshold for v in vals)

        want = self.OK
        if exceeded(slow_ws, self.cfg.slow_burn_threshold):
            want = self.SLOW_BURN
        if exceeded(fast_ws, self.cfg.fast_burn_threshold):
            want = self.FAST_BURN
        cur = self._state.get(key, self.OK)
        if want == cur:
            self._clear_streak[key] = 0
            return False
        if self._RANK[want] > self._RANK[cur]:
            # Escalation is immediate.
            self._transition(key, model, obj, cur, want, burns)
            self._clear_streak[key] = 0
            return want == self.FAST_BURN
        # De-escalation waits out the hysteresis streak.
        streak = self._clear_streak.get(key, 0) + 1
        if streak >= self.cfg.clear_ticks:
            self._transition(key, model, obj, cur, want, burns)
            self._clear_streak[key] = 0
        else:
            self._clear_streak[key] = streak
        return False

    def _transition(self, key, model: str, obj: Objective,
                    frm: str, to: str, burns: dict) -> None:
        self._state[key] = to
        rounded = {k: (round(v, 3) if v is not None else None)
                   for k, v in burns.items()}
        log = (logger.warning if self._RANK[to] > self._RANK[frm]
               else logger.info)
        log("SLO %s/%s: %s -> %s (burn rates %s)",
            model, obj.name, frm, to, rounded)
        if self.journal is not None:
            self.journal.emit(events_mod.SLO_TRANSITION, model=model,
                              objective=obj.name, frm=frm, to=to,
                              burns=rounded)

    # -- export --------------------------------------------------------------
    def state(self, model: str, objective: str) -> str:
        with self._lock:
            return self._state.get((model, objective), self.OK)

    def render(self) -> list[str]:
        """``gateway_slo_compliance_ratio{model,objective}`` (cumulative
        good/total) and ``gateway_slo_burn_rate{model,objective,window}``
        gauges; empty when no tick has seen traffic."""
        with self._lock:
            samples = {k: ring[-1] for k, ring in self._samples.items()
                       if ring}
            burns = dict(self._last_burns)
        compliance, burn_lines = [], []
        for (model, objective) in sorted(samples):
            _, good, total = samples[(model, objective)]
            if total <= 0:
                continue
            labels = (f'model="{escape_label(model)}",'
                      f'objective="{escape_label(objective)}"')
            compliance.append(
                "gateway_slo_compliance_ratio{%s} %.6f"
                % (labels, good / total))
            for w in self.cfg.windows:
                v = burns.get((model, objective), {}).get(w.name)
                if v is None:
                    continue
                burn_lines.append(
                    'gateway_slo_burn_rate{%s,window="%s"} %.6f'
                    % (labels, escape_label(w.name), v))
        lines = []
        if compliance:
            lines.append("# TYPE gateway_slo_compliance_ratio gauge")
            lines += compliance
        if burn_lines:
            lines.append("# TYPE gateway_slo_burn_rate gauge")
            lines += burn_lines
        return lines

    def debug_payload(self) -> dict:
        """The ``/debug/slo`` JSON body."""
        with self._lock:
            keys = sorted(self._samples)
            out: dict = {}
            for (model, objective) in keys:
                ring = self._samples[(model, objective)]
                if not ring:
                    continue
                _, good, total = ring[-1]
                obj = next((o for o in self.cfg.objectives_for(model)
                            if o.name == objective), None)
                out.setdefault(model, {})[objective] = {
                    "threshold_s": obj.threshold_s if obj else None,
                    "target": obj.target if obj else None,
                    "good": good,
                    "total": total,
                    "compliance": round(good / total, 6) if total else None,
                    "state": self._state.get((model, objective), self.OK),
                    "burn_rates": {
                        k: (round(v, 4) if v is not None else None)
                        for k, v in self._last_burns.get(
                            (model, objective), {}).items()},
                }
            return {
                "models": out,
                "windows": {w.name: w.seconds for w in self.cfg.windows},
                "fast_burn_threshold": self.cfg.fast_burn_threshold,
                "slow_burn_threshold": self.cfg.slow_burn_threshold,
                "last_tick": self.last_tick,
            }


# ---------------------------------------------------------------------------
# Black-box dump (snapshot-on-breach)
# ---------------------------------------------------------------------------


def write_blackbox(dir_path: str, reason: dict, journal=None, tracer=None,
                   metrics_text: str = "", slo_payload: dict | None = None,
                   health_payload: dict | None = None,
                   usage_payload: dict | None = None,
                   statebus_payload: dict | None = None,
                   profile_payload: dict | None = None,
                   kv_payload: dict | None = None,
                   picks_payload: dict | None = None,
                   capacity_payload: dict | None = None,
                   clock=time.time) -> str:
    """Write the black-box dump for one breach; returns the file path.

    The dump is everything a post-mortem needs in ONE file: the flight
    recorder's journal, the trace ring, the SLO/health debug payloads,
    the replicated-state-bus view (merged vs local snapshots, peer ages,
    quota scale — was this replica enforcing alone when it burned?), the
    pool pods' step-profiler snapshots (was the engine dispatch-bound or
    host-bound at the breach?), and the raw /metrics text at the moment
    of the breach.  ``tools/blackbox_report.py`` renders it into a
    timeline with statebus + profiler sections.
    """
    os.makedirs(dir_path, exist_ok=True)
    ts = clock()
    stamp = time.strftime("%Y%m%dT%H%M%S", time.gmtime(ts))
    slug = re.sub(r"[^A-Za-z0-9._-]+", "_",
                  f"{reason.get('model', '')}-{reason.get('objective', '')}")
    path = os.path.join(
        dir_path, f"blackbox-{stamp}-{int(ts * 1000) % 1000:03d}-{slug}.json")
    payload = {
        "format": "lig-blackbox/1",
        "written_at": round(ts, 3),
        "reason": reason,
        "events": journal.snapshot() if journal is not None else None,
        "traces": tracer.recent(64) if tracer is not None else [],
        "slo": slo_payload,
        "health": health_payload,
        # Who was consuming the pool at the moment of the breach — the
        # first question a fast-burn post-mortem asks (gateway/usage.py).
        "usage": usage_payload,
        # Fleet context: the statebus divergence view and the pods' step
        # profiler snapshots (gateway/statebus.py, server/profiler.py).
        "statebus": statebus_payload,
        "profile": profile_payload,
        # KV economy at dump time (gateway/kvobs.py + per-pod /debug/kv):
        # was the pool burning because its KV budget was parked or
        # duplicated?  ``tools/blackbox_report.py`` renders the section.
        "kv": kv_payload,
        # Routing decisions near the breach (gateway/pickledger.py):
        # where WERE requests landing, and which advisor seam steered
        # them there?  Per-pool cursor payloads with sampled records.
        "picks": picks_payload,
        # Twin state at dump time (gateway/capacity.py): saturation,
        # headroom/time-to-breach forecasts and the drift trust flag —
        # was the breach forecast, and was the forecast trusted?
        "capacity": capacity_payload,
        "metrics_text": metrics_text,
    }
    tmp = path + ".tmp"
    with open(tmp, "w", encoding="utf-8") as f:
        json.dump(payload, f, indent=1)
    os.replace(tmp, path)  # readers never see a half-written dump
    return path
