"""Endpoint-picker gateway: metrics plane, scheduler, handlers, transports."""
