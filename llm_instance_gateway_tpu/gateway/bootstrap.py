"""Gateway assembly shared by both transports (HTTP proxy, gRPC ext-proc).

Builds datastore + reconcilers + membership sources + provider + scheduler +
handler core from a pool/model YAML and CLI-ish options.  Pod membership
sources, in precedence order:

- ``--pod name=host[:port][,zone]`` static entries (port defaults to the
  pool's targetPortNumber);
- ``--discover-dns <hostname>``: periodic A-record resolution of a headless
  Service — the k8s-API-free way the EPP tracks per-pod endpoints on GKE
  (the reference used an EndpointSlice informer; DNS gives the same set for
  a headless Service without RBAC);
- with ``--probe-endpoints``, entries from either source are health-probed
  and only Ready ones become schedulable (EndpointSlice Ready parity).
"""

from __future__ import annotations

import logging
from dataclasses import dataclass, field
from typing import TYPE_CHECKING

if TYPE_CHECKING:
    from llm_instance_gateway_tpu.gateway.multipool import MultiPoolComponents

import yaml

from llm_instance_gateway_tpu.api import v1alpha1
from llm_instance_gateway_tpu.gateway.controllers import (
    EndpointsReconciler,
    InferenceModelReconciler,
    InferencePoolReconciler,
)
from llm_instance_gateway_tpu.gateway.controllers.filewatch import (
    ConfigWatcher,
    DNSDiscoverer,
    EndpointProber,
    MembershipAggregator,
    StaticEndpoint,
)
from llm_instance_gateway_tpu.gateway.controllers.reconcilers import Endpoint
from llm_instance_gateway_tpu.gateway.datastore import Datastore
from llm_instance_gateway_tpu.gateway.handlers.server import Server
from llm_instance_gateway_tpu.gateway.metrics_client import PodMetricsClient
from llm_instance_gateway_tpu.gateway.provider import Provider
from llm_instance_gateway_tpu.gateway.scheduling.native import make_scheduler

logger = logging.getLogger(__name__)


@dataclass
class GatewayComponents:
    datastore: Datastore
    provider: Provider
    scheduler: object  # Scheduler or NativeScheduler (same .schedule interface)
    handler_server: Server
    watchers: list = field(default_factory=list)
    pool_reconciler: InferencePoolReconciler | None = None
    model_reconciler: InferenceModelReconciler | None = None

    def start_provider(self, pods_interval_s: float = 10.0,
                       metrics_interval_s: float = 0.05) -> None:
        self.provider.init(
            refresh_pods_interval_s=pods_interval_s,
            refresh_metrics_interval_s=metrics_interval_s,
        )

    def stop(self) -> None:
        self.provider.stop()
        for w in self.watchers:
            w.stop()


def _check_models_unambiguous(models: list, default_pool: str) -> None:
    """A modelName bound to two pools would route first-wins by iteration
    order — reject the ambiguity (at build time AND on hot reload)."""
    model_pool: dict[str, str] = {}
    for m in models:
        ref = m.spec.pool_ref.name if m.spec.pool_ref else default_pool
        prev = model_pool.setdefault(m.spec.model_name, ref)
        if prev != ref:
            raise ValueError(
                f"model {m.spec.model_name!r} is bound to two pools "
                f"({prev!r} and {ref!r})")


def _scope_by_pool(entries: list[str], pool_names: list[str]) -> dict[str, list[str]]:
    """Split ``pool/value`` entries per pool; unprefixed values go to the
    first (default) pool — single-pool invocations never need prefixes.

    A prefix that names no pool is a hard error: pod names, DNS hostnames,
    and service names never legitimately contain ``/``, so a slash always
    signals scoping intent and a typo'd pool would otherwise bind a
    foreign backend to the default pool silently.
    """
    out: dict[str, list[str]] = {n: [] for n in pool_names}
    for e in entries:
        head, sep, rest = e.partition("/")
        if sep:
            if head not in out:
                raise ValueError(
                    f"membership entry {e!r} scopes to unknown pool "
                    f"{head!r} (pools: {pool_names})")
            out[head].append(rest)
        else:
            out[pool_names[0]].append(e)
    return out


def build_gateway(
    config_path: str,
    static_pods: list[str] | None = None,
    discover_dns: str | list[str] | None = None,
    watch_config: bool = False,
    probe_endpoints: bool = False,
    probe_interval_s: float = 5.0,
    zone: str = "",
    kube_watch: bool = False,
    kube_api: str = "",
    kube_namespace: str = "",
    kube_service: str = "",
    kube_token_file: str = "",
    kube_ca_file: str = "",
) -> "GatewayComponents | MultiPoolComponents":
    """Build the gateway from a pool/model YAML.

    One InferencePool document -> ``GatewayComponents`` (the reference
    topology).  Several pools -> ``multipool.MultiPoolComponents``: one
    process, N independent pool stacks, requests routed per model (membership
    flags scope per pool with a ``pool/`` prefix; unprefixed entries bind to
    the first pool).
    """
    with open(config_path) as f:
        docs = list(yaml.safe_load_all(f))
    pools, models = v1alpha1.from_documents(docs)
    if not pools:
        raise ValueError(f"no InferencePool document in {config_path}")
    pool_names = [p.name for p in pools]
    if len(pool_names) != len(set(pool_names)):
        raise ValueError(f"duplicate InferencePool names in {config_path}")
    _check_models_unambiguous(models, pool_names[0])

    # Resolve the watch namespace FIRST: the reconcilers must be pinned to
    # the namespace the informers actually watch, or every apiserver event
    # from a non-default namespace would be silently dropped.
    kcfg = None
    if kube_watch:
        from llm_instance_gateway_tpu.gateway.controllers.k8swatch import (
            KubeConfig,
        )

        if kube_api:
            token = ""
            if kube_token_file:
                with open(kube_token_file) as f:
                    token = f.read().strip()
            kcfg = KubeConfig(
                base_url=kube_api, token=token,
                ca_file=kube_ca_file or None,
                namespace=kube_namespace or "default",
            )
        else:
            kcfg = KubeConfig.in_cluster()
            if kube_namespace:
                kcfg.namespace = kube_namespace
    namespace = kcfg.namespace if kcfg else "default"

    if isinstance(discover_dns, str):
        discover_dns = [discover_dns] if discover_dns else []
    scoped_pods = _scope_by_pool(static_pods or [], pool_names)
    scoped_dns = _scope_by_pool(discover_dns or [], pool_names)
    scoped_svc = _scope_by_pool(
        [s for s in kube_service.split(",") if s] if kube_service else [],
        pool_names)

    multi = len(pool_names) > 1
    built: dict[str, GatewayComponents] = {}
    try:
        for name in pool_names:
            if len(scoped_svc[name]) > 1:
                # Silently taking [0] would drop svc2's pods from membership
                # with nothing to see — same class of misbinding the
                # unknown-prefix check rejects.
                raise ValueError(
                    f"pool {name}: multiple --kube-service entries "
                    f"{scoped_svc[name]} (one service per pool)")
            svc = scoped_svc[name][0] if scoped_svc[name] else ""
            # An unscoped slice informer would watch EVERY EndpointSlice in
            # the namespace — in a multi-pool process that cross-pollutes
            # pool membership with other pools' pods.  Slice membership is
            # therefore opt-in per pool via a scoped service name.
            watch_slices = not multi or bool(svc)
            if multi and kcfg is not None and not svc:
                logger.warning(
                    "pool %s: no %s/<service> entry in --kube-service; "
                    "EndpointSlice membership disabled for this pool "
                    "(CRD watches stay on)", name, name)
            built[name] = _build_for_pool(
                name, pools, models,
                namespace=namespace,
                static_pods=scoped_pods[name],
                discover_dns=scoped_dns[name],
                probe_endpoints=probe_endpoints,
                probe_interval_s=probe_interval_s,
                zone=zone,
                kcfg=kcfg,
                kube_service=svc,
                watch_slices=watch_slices,
            )
    except Exception:
        # A half-built gateway must not leak running refresh loops, probers,
        # or watch streams from the pools that DID build.
        for comps in built.values():
            comps.stop()
        raise

    if watch_config:
        # ONE file poller feeds every pool's reconcilers (they self-filter
        # by pool name) instead of N pollers re-parsing the same file.
        watcher = ConfigWatcher(
            config_path,
            _FanoutReconcilers([c.pool_reconciler for c in built.values()]),
            _FanoutReconcilers(
                [c.model_reconciler for c in built.values()],
                validate=lambda ms: _check_models_unambiguous(
                    ms, pool_names[0]),
            ),
        )
        watcher.start()
        built[pool_names[0]].watchers.append(watcher)

    if not multi:
        return built[pool_names[0]]
    from llm_instance_gateway_tpu.gateway.multipool import MultiPoolComponents

    logger.info("multi-pool gateway: %s (default %s)",
                pool_names, pool_names[0])
    return MultiPoolComponents(built, default=pool_names[0])


class _FanoutReconcilers:
    """Broadcast reconcile/resync to per-pool reconcilers (each self-filters
    by pool name / poolRef, so every pool sees only its own objects).

    ``validate`` vets a full resync before any pool applies it; a rejected
    document set keeps the last good state (loudly) — the same posture as
    the scheduler-config hot-reload hook."""

    def __init__(self, reconcilers: list, validate=None):
        self._reconcilers = reconcilers
        self._validate = validate

    def reconcile(self, obj, **kwargs):
        for r in self._reconcilers:
            r.reconcile(obj, **kwargs)

    def resync(self, objs):
        if self._validate is not None:
            try:
                self._validate(objs)
            except ValueError as e:
                logger.error("rejected reloaded documents (keeping last "
                             "good state): %s", e)
                return
        for r in self._reconcilers:
            r.resync(objs)


def _build_for_pool(
    pool_name: str,
    pools: list,
    models: list,
    *,
    namespace: str,
    static_pods: list[str],
    discover_dns: list[str],
    probe_endpoints: bool,
    probe_interval_s: float,
    zone: str,
    kcfg,
    kube_service: str,
    watch_slices: bool = True,
) -> GatewayComponents:
    datastore = Datastore()
    watchers: list = []
    scheduler_holder: list = []  # filled below; hook needs a forward ref

    def on_pool_update(pool) -> None:
        """Hot-reload hook: re-validate and push thresholds into the live
        scheduler.  A bad reloaded doc keeps the last good config (loudly)."""
        if not scheduler_holder:
            return
        from llm_instance_gateway_tpu.gateway.scheduling.config import from_pool_spec

        try:
            scheduler_holder[0].update_config(from_pool_spec(pool.spec.scheduler))
            logger.info("scheduler thresholds reloaded from pool %s", pool.name)
        except ValueError as e:
            logger.error("rejected reloaded schedulerConfig (keeping last "
                         "good thresholds): %s", e)

    pool_rec = InferencePoolReconciler(
        datastore, pool_name, namespace=namespace, on_update=on_pool_update)
    model_rec = InferenceModelReconciler(
        datastore, pool_name, namespace=namespace,
        # poolRef-less models bind to the deployment's default (first)
        # pool — matching _check_models_unambiguous's build-time semantics
        # on every path (seed, file resync, k8s watch events).
        default_pool=pools[0].name)
    # YAML-seeded documents adopt the watch namespace: the file is local
    # bootstrap state, not an apiserver object — its metadata.namespace
    # (usually "default") must not fight the reconciler pinning.
    import dataclasses as _dc

    for pool in pools:
        if pool.namespace != namespace:
            pool = _dc.replace(pool, namespace=namespace)
        pool_rec.reconcile(pool)
    model_rec.resync([
        m if m.namespace == namespace else _dc.replace(m, namespace=namespace)
        for m in models
    ])
    target_port = datastore.get_pool().spec.target_port_number

    try:
        return _start_pool_sources(
            pool_name=pool_name, datastore=datastore, watchers=watchers,
            scheduler_holder=scheduler_holder, pool_rec=pool_rec,
            model_rec=model_rec, target_port=target_port,
            static_pods=static_pods, discover_dns=discover_dns,
            probe_endpoints=probe_endpoints,
            probe_interval_s=probe_interval_s, zone=zone, kcfg=kcfg,
            kube_service=kube_service, watch_slices=watch_slices,
        )
    except Exception:
        # This pool's own partially-started sources (probers, DNS loops,
        # watch streams, the admission drain thread) must not outlive the
        # failed build — the caller only sees fully-built pools.
        for w in watchers:
            w.stop()
        raise


def _start_pool_sources(
    *,
    pool_name: str,
    datastore: Datastore,
    watchers: list,
    scheduler_holder: list,
    pool_rec,
    model_rec,
    target_port: int,
    static_pods: list[str],
    discover_dns: list[str],
    probe_endpoints: bool,
    probe_interval_s: float,
    zone: str,
    kcfg,
    kube_service: str,
    watch_slices: bool,
) -> GatewayComponents:
    # Parse the scheduler config FIRST: it is the most likely document error
    # and failing here keeps the window with live threads minimal.
    from llm_instance_gateway_tpu.gateway.scheduling.config import from_pool_spec

    scheduler_cfg = from_pool_spec(datastore.get_pool().spec.scheduler)

    endpoints: list[StaticEndpoint] = []
    for spec in static_pods or []:
        name, _, rest = spec.partition("=")
        addr, *opts = rest.split(",")
        addr = addr or name
        if ":" not in addr:
            # Fill the pool port BEFORE any probing so /health hits the
            # serving port, not :80.
            addr = f"{addr}:{target_port}"
        # Options after the address: a bare token is the zone (legacy
        # position), ``role=prefill|decode`` marks disaggregation roles.
        ep_zone, ep_role = "", "collocated"
        for opt in opts:
            key, sep, val = opt.partition("=")
            if sep and key == "role":
                from llm_instance_gateway_tpu.gateway.types import POOL_ROLES

                if val not in POOL_ROLES:
                    raise ValueError(
                        f"--pod {spec!r}: unknown role {val!r} "
                        f"(expected one of {POOL_ROLES})")
                ep_role = val
            else:
                ep_zone = opt
        endpoints.append(StaticEndpoint(name=name, address=addr,
                                        zone=ep_zone, role=ep_role))

    # All membership flows through one aggregator: the reconciler is
    # full-state, so independent sources must publish a merged view, and the
    # static path must go through the reconciler too or zone filtering would
    # be silently skipped.
    endpoints_rec = EndpointsReconciler(datastore, zone=zone)
    aggregator = MembershipAggregator(endpoints_rec)
    for i, hostname in enumerate(discover_dns):
        discoverer = DNSDiscoverer(
            hostname, target_port,
            probe=probe_endpoints, interval_s=probe_interval_s,
            publish=aggregator.sink(f"dns{i or ''}"),
        )
        discoverer.start()
        watchers.append(discoverer)
    if endpoints:
        if probe_endpoints:
            prober = EndpointProber(
                endpoints, probe_interval_s=probe_interval_s,
                publish=aggregator.sink("static"),
            )
            prober.start()
            watchers.append(prober)
        else:
            aggregator.publish(
                "static",
                [Endpoint(name=ep.name, address=ep.address, ready=True,
                          zone=ep.zone, role=ep.role) for ep in endpoints],
            )
    elif probe_endpoints and not discover_dns and kcfg is None:
        logger.warning(
            "--probe-endpoints set but no --pod/--discover-dns/--kube-watch "
            "source: membership will stay empty (pool %s)", pool_name
        )

    if kcfg is not None:
        # Apiserver watches on the two CRDs + EndpointSlices — the reference
        # manager's watch set (main.go:81-129).  The YAML config still
        # bootstraps pool identity/thresholds; watch events take over from
        # there.  Membership rides the aggregator like every other source so
        # k8s + DNS/static deployments merge instead of fighting.
        from llm_instance_gateway_tpu.gateway.controllers.k8swatch import (
            KubeSource,
        )

        source = KubeSource(
            kcfg, pool_rec, model_rec, aggregator.sink("k8s"),
            service_name=kube_service, watch_slices=watch_slices,
        )
        source.start()
        watchers.append(source)

    provider = Provider(PodMetricsClient(), datastore)
    # Thresholds come from the pool document (schedulerConfig section,
    # parsed up front) — the resolution of the reference's config TODO.
    # C++ hot path when buildable, Python tree otherwise (identical
    # semantics, fuzz-verified in tests/test_native_scheduler.py) — wrapped
    # by the admission controller so the pool's admissionQueue section can
    # turn shedding into bounded queueing (hot-reloadable either way).
    from llm_instance_gateway_tpu.gateway.scheduling.admission import (
        AdmissionController,
    )

    # ONE prefix-affinity index for every scheduler instance routing this
    # pool (direct path AND the admission drain path) — split indexes
    # would learn conflicting prefix holders and flap between them.
    from llm_instance_gateway_tpu.gateway.scheduling.prefix_affinity import (
        PrefixIndex,
    )

    shared_prefix_index = PrefixIndex()
    scheduler = AdmissionController(
        make_scheduler(provider, scheduler_cfg,
                       prefix_index=shared_prefix_index),
        scheduler_cfg.admission,
        # The hysteresis drain scheduler is built lazily on first enable —
        # the default (disabled) path pays for nothing.
        drain_scheduler_factory=lambda cfg: make_scheduler(
            provider, cfg if cfg is not None else scheduler_cfg,
            prefix_index=shared_prefix_index),
    )
    scheduler.start()
    watchers.append(scheduler)  # stop() joins the drain thread
    scheduler_holder.append(scheduler)  # arm the hot-reload hook
    handler_server = Server(scheduler, datastore)
    return GatewayComponents(
        datastore=datastore, provider=provider, scheduler=scheduler,
        handler_server=handler_server, watchers=watchers,
        pool_reconciler=pool_rec, model_reconciler=model_rec,
    )


def add_common_args(parser) -> None:
    parser.add_argument("--config", required=True, help="pool/model YAML")
    parser.add_argument("--pod", action="append", default=[],
                        help="pod membership [pool/]name=host[:port]"
                             "[,zone][,role=prefill|decode] (repeatable; "
                             "pool/ prefix scopes to one pool of a "
                             "multi-pool config; role marks prefill/decode "
                             "disaggregation replicas)")
    parser.add_argument("--discover-dns", action="append", default=[],
                        metavar="[POOL/]HOSTNAME",
                        help="discover pods by resolving a headless Service "
                             "DNS name (repeatable)")
    parser.add_argument("--watch-config", action="store_true",
                        help="hot-reload pool/model config on file change")
    parser.add_argument("--probe-endpoints", action="store_true",
                        help="health-probe pods; only Ready ones are routable")
    parser.add_argument("--zone", default="",
                        help="only admit endpoints in this zone (empty = all)")
    parser.add_argument("--kube-watch", action="store_true",
                        help="watch InferencePool/InferenceModel CRDs and "
                             "EndpointSlices from the Kubernetes apiserver")
    parser.add_argument("--kube-api", default="",
                        help="apiserver base URL (default: in-cluster "
                             "service-account config)")
    parser.add_argument("--kube-namespace", default="",
                        help="namespace to watch (default: in-cluster or "
                             "'default')")
    parser.add_argument("--kube-service", default="",
                        help="kubernetes.io/service-name label for "
                             "EndpointSlice membership (comma-separated "
                             "[pool/]svc entries for multi-pool configs)")
    parser.add_argument("--kube-token-file", default="",
                        help="bearer-token file for --kube-api (in-cluster "
                             "config reads the service-account mount)")
    parser.add_argument("--kube-ca-file", default="",
                        help="CA bundle for --kube-api TLS verification "
                             "(https without it logs a loud dev-only warning)")
    parser.add_argument("--refresh-metrics-interval", type=float, default=0.05)
    parser.add_argument("--refresh-pods-interval", type=float, default=10.0)
    parser.add_argument("-v", "--verbose", action="count", default=0)


def add_resilience_args(parser) -> None:
    """Failure-policy flags for the HTTP proxy transport
    (gateway/resilience.py; defaults mirror ResilienceConfig)."""
    from llm_instance_gateway_tpu.gateway.resilience import (
        HEALTH_POLICIES,
        ResilienceConfig,
    )

    d = ResilienceConfig()
    parser.add_argument("--health-policy", choices=list(HEALTH_POLICIES),
                        default=d.health_policy,
                        help="pick-seam enforcement: log_only counts "
                             "would-avoid picks only (routing unchanged); "
                             "avoid deprioritizes degraded/unhealthy/"
                             "circuit-open replicas with a last-resort "
                             "escape hatch; strict sheds instead")
    parser.add_argument("--connect-timeout-s", type=float,
                        default=d.connect_timeout_s,
                        help="upstream TCP connect timeout (0 = unbounded)")
    parser.add_argument("--ttft-timeout-s", type=float,
                        default=d.ttft_timeout_s,
                        help="time allowed until the first upstream "
                             "response byte (SSE: first chunk; JSON: "
                             "response headers). 0 = unbounded")
    parser.add_argument("--stream-idle-timeout-s", type=float,
                        default=d.stream_idle_timeout_s,
                        help="max gap between SSE chunks / body reads "
                             "(0 = unbounded)")
    parser.add_argument("--max-retries", type=int, default=d.max_retries,
                        help="retry attempts per request for idempotent "
                             "failures (budgeted globally)")
    parser.add_argument("--retry-budget-ratio", type=float,
                        default=d.retry_budget_ratio,
                        help="retry tokens earned per primary request "
                             "(caps retry volume as a traffic fraction)")
    parser.add_argument("--hedge-ttft-s", type=float, default=d.hedge_ttft_s,
                        help="hedge non-streaming requests when no "
                             "response within this many seconds "
                             "(0 = disabled)")
    add_fairness_args(parser)
    add_placement_args(parser)
    add_capacity_args(parser)


def add_capacity_args(parser: argparse.ArgumentParser) -> None:
    """Capacity & saturation plane flags (gateway/capacity.py).
    ``add_resilience_args`` includes these."""
    from llm_instance_gateway_tpu.gateway.capacity import CapacityConfig

    c = CapacityConfig()
    parser.add_argument("--no-capacity", action="store_true",
                        help="disable the capacity plane (no saturation "
                             "indices, twin forecasts or drift alarms; "
                             "/debug/capacity serves an empty view — "
                             "routing itself is unchanged either way, the "
                             "plane is purely observational)")
    parser.add_argument("--twin-calibration", default=c.calibration_path,
                        metavar="PATH",
                        help="committed LatencyModel calibration artifact "
                             "(lig-twin-calibration/1 JSON, e.g. "
                             "TWIN_CALIBRATION.json) the twin loads; "
                             "empty = self-calibrate from live scrape "
                             "windows")
    parser.add_argument("--twin-drift-threshold", type=float,
                        default=c.drift_threshold,
                        help="predicted-vs-observed relative divergence "
                             "(EMA) above which the twin enters drift: "
                             "forecasts are marked untrusted and a "
                             "twin_drift event journals "
                             f"(default {c.drift_threshold})")


def capacity_from_args(args):
    """Build a CapacityConfig from ``add_capacity_args`` flags."""
    from llm_instance_gateway_tpu.gateway.capacity import CapacityConfig

    return CapacityConfig(
        enabled=not args.no_capacity,
        calibration_path=args.twin_calibration,
        drift_threshold=args.twin_drift_threshold,
    )


def add_placement_args(parser: argparse.ArgumentParser) -> None:
    """Adapter residency / placement-plane flags (gateway/placement.py).
    ``add_resilience_args`` includes these."""
    from llm_instance_gateway_tpu.gateway.placement import (
        PLACEMENT_MODES,
        PlacementConfig,
    )

    p = PlacementConfig()
    parser.add_argument("--placement-mode", choices=list(PLACEMENT_MODES),
                        default=p.mode,
                        help="residency-aware routing: log_only counts "
                             "picks that missed a resident replica only "
                             "(routing unchanged); prefer_resident steers "
                             "picks toward pods where the adapter is slot- "
                             "or host-RAM-resident, with a counted "
                             "last-resort escape hatch")
    parser.add_argument("--placement-prefetch-share", type=float,
                        default=p.prefetch_min_share,
                        help="pool step-seconds share at which a non-"
                             "resident adapter earns a host-RAM prefetch "
                             "(waiting adapters prefetch regardless)")
    parser.add_argument("--placement-checkpoint-root", default=p.checkpoint_root,
                        help="checkpoint path template root for prefetch "
                             "decisions ({root}/{adapter}); empty = the "
                             "sidecar resolves sources from its own config")


def placement_from_args(args):
    """Build a PlacementConfig from ``add_placement_args`` flags."""
    from llm_instance_gateway_tpu.gateway.placement import PlacementConfig

    return PlacementConfig(
        mode=args.placement_mode,
        prefetch_min_share=args.placement_prefetch_share,
        checkpoint_root=args.placement_checkpoint_root,
    )


def add_fairness_args(parser: argparse.ArgumentParser) -> None:
    """Fairness/quota flags alone — for entrypoints (gRPC ext-proc) that
    carry the handler-core admit() gate without the proxy's data-path
    resilience surface.  ``add_resilience_args`` includes these."""
    from llm_instance_gateway_tpu.gateway.fairness import (
        FAIRNESS_MODES,
        FairnessConfig,
    )

    # Defaults are None SENTINELS: flags left unset defer to the pool
    # document's schedulerConfig.fairnessPolicy section (then to
    # FairnessConfig defaults) — an explicitly-passed flag wins, per FIELD.
    f = FairnessConfig()
    parser.add_argument("--fairness-mode", choices=list(FAIRNESS_MODES),
                        default=None,
                        help="usage-seam enforcement (gateway/fairness.py): "
                             "log_only counts would-deprioritize picks only "
                             "(routing unchanged); deprioritize makes "
                             "flagged-noisy tenants lose pick ties; enforce "
                             "adds rank-weighted tenant quotas with "
                             f"one-tier criticality demotion "
                             f"(default {f.mode}; the pool document's "
                             "fairnessPolicy section overrides unset flags)")
    parser.add_argument("--fairness-over-ratio", type=float, default=None,
                        help="share / fair-share ratio beyond which a "
                             "tenant is over-quota (enforce mode; default "
                             f"{f.over_ratio})")
    parser.add_argument("--fairness-quota-rps", type=float, default=None,
                        help="full-criticality admissions per second for an "
                             "over-quota tenant; excess demotes one tier "
                             f"(default {f.quota_rps})")


def add_statebus_args(parser: argparse.ArgumentParser) -> None:
    """Replicated-state-plane flags (gateway/statebus.py): how N gateway
    replicas fronting the same pools share their tick-derived state."""
    from llm_instance_gateway_tpu.gateway.statebus import StateBusConfig

    s = StateBusConfig()
    parser.add_argument("--replica-id", default="",
                        help="this gateway's identity on the statebus "
                             "(default: hostname:port; must be unique "
                             "per replica)")
    parser.add_argument("--statebus-peer", action="append", default=[],
                        metavar="URL",
                        help="peer gateway base URL to gossip snapshots "
                             "with (repeatable, e.g. http://gw-1:8081); "
                             "none = single-replica, statebus inert")
    parser.add_argument("--statebus-staleness-s", type=float,
                        default=s.staleness_s,
                        help="peer snapshots older than this drop from "
                             "the merged view; all peers stale = "
                             "local-only enforcement fallback (journaled "
                             "statebus_stale)")
    parser.add_argument("--no-statebus-quota-partition",
                        action="store_true",
                        help="do NOT divide fairness token buckets by the "
                             "live replica count (default: partition, so "
                             "tenant quotas hold fleet-wide under "
                             "request spraying)")


def statebus_from_args(args, port: int = 0):
    """Build a StateBusConfig from ``add_statebus_args`` flags."""
    import socket

    from llm_instance_gateway_tpu.gateway.statebus import StateBusConfig

    replica_id = args.replica_id
    if not replica_id:
        replica_id = f"{socket.gethostname()}:{port or 0}"
    return StateBusConfig(
        replica_id=replica_id,
        peers=tuple(args.statebus_peer),
        staleness_s=args.statebus_staleness_s,
        partition_quota=not args.no_statebus_quota_partition,
    )


def resilience_from_args(args):
    """Build a ResilienceConfig from ``add_resilience_args`` flags."""
    from llm_instance_gateway_tpu.gateway.resilience import ResilienceConfig

    return ResilienceConfig(
        health_policy=args.health_policy,
        connect_timeout_s=args.connect_timeout_s,
        ttft_timeout_s=args.ttft_timeout_s,
        stream_idle_timeout_s=args.stream_idle_timeout_s,
        max_retries=args.max_retries,
        retry_budget_ratio=args.retry_budget_ratio,
        hedge_ttft_s=args.hedge_ttft_s,
    )


def fairness_from_args(args):
    """FairnessConfig field overrides from ``add_resilience_args`` flags.

    Returns ONLY the explicitly-passed flags as a field->value dict (None
    when every flag was left unset).  The proxy overlays these on the pool
    document's ``schedulerConfig.fairnessPolicy`` section (then defaults)
    — per FIELD, so ``--fairness-quota-rps`` alone doesn't silently reset
    a pool-doc ``mode: enforce`` back to log_only — and the overlay is
    re-applied on every hot reload, so a pool-doc update can't clobber an
    operator's explicit flags either."""
    overrides = {
        "mode": args.fairness_mode,
        "over_ratio": args.fairness_over_ratio,
        "quota_rps": args.fairness_quota_rps,
    }
    set_overrides = {k: v for k, v in overrides.items() if v is not None}
    return set_overrides or None


def components_from_args(args) -> "GatewayComponents | MultiPoolComponents":
    logging.basicConfig(
        level=logging.DEBUG if args.verbose else logging.INFO,
        format="%(asctime)s %(name)s %(levelname)s %(message)s",
    )
    comps = build_gateway(
        args.config,
        static_pods=args.pod,
        discover_dns=args.discover_dns,
        watch_config=args.watch_config,
        probe_endpoints=args.probe_endpoints,
        zone=args.zone,
        kube_watch=args.kube_watch,
        kube_api=args.kube_api,
        kube_namespace=args.kube_namespace,
        kube_service=args.kube_service,
        kube_token_file=args.kube_token_file,
        kube_ca_file=args.kube_ca_file,
    )
    comps.start_provider(
        pods_interval_s=args.refresh_pods_interval,
        metrics_interval_s=args.refresh_metrics_interval,
    )
    return comps
