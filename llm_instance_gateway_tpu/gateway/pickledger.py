"""Routing decision ledger: per-pick explainability with counterfactual
seam attribution.

Traces say *where* a request went; the advisor planes say *what they
flagged*; this module records *why a pick landed where it did*.  For a
deterministically-sampled subset of picks it keeps a bounded ring of
decision records, each capturing the stage-by-stage narrowing pipeline —
role partition -> filter tree -> health/circuit (``filter_by_policy``) ->
fairness -> placement -> prefix tie-break -> RNG draw — with surviving-
candidate counts and removed-pod attribution per stage, escape-hatch
fires, the disagg hop identity (single/prefill/decode), and the winning
pod, joined to the request's trace by ``x-lig-trace-id``.

**Counterfactual lane**: for every sampled pick the pure advisor filter
chain is re-run with each seam individually disabled (the other advisors
wrapped in a note-suppressing proxy so no counter double-fires; the
prefix index and the RNG are never touched).  A seam whose absence
changes the final survivor set *steered* this pick
(``gateway_pick_steered_total{seam}``); the changed seam with the largest
survivor-set delta is tagged *decisive* (ties break in chain order; when
no seam changed the outcome, the tag falls through to ``prefix_affinity``
if the tie-break fired, ``rng`` if the draw chose among >1 survivors,
else ``none``).

**Charging paths**: the Python ``Scheduler`` charges directly from
``_pick`` (and the disagg decode hop); the ``NativeScheduler`` must not
grow its FFI hot path, so sampled native picks are explained by a
Python-oracle *shadow replay* — the same filter tree + silent advisor
chain re-run over the same pods list, with ``shadow_match`` recording
whether the replay reproduced the native candidate set (the paths are
pinned byte-identical by the same-RNG diff tests, so a mismatch is a
drift observable, not an assert).

**Cost discipline**: sampling is a counter modulus (never an RNG draw —
the log-only invariant requires routing byte-identical with the ledger
ON), the unsampled path is one ``enabled`` check + one GIL-atomic
``itertools.count`` bump, and every record/counterfactual cost rides only
sampled picks; ``pick_ledger_ratio`` < 1.05 is gated in
``make bench-check``.

Surfaces: ``GET /debug/picks?since=`` (cursor contract of
``events.debug_events_payload``), the ``gateway_pick_*`` exposition
families, the fast-burn black-box dump (rendered by
``tools/blackbox_report.py``), ``tools/pick_report.py``, and the statebus
-> ``fleetobs.pick_steering_rollup`` fleet view on ``/debug/fleet``.
"""

from __future__ import annotations

import itertools
import time
from dataclasses import asdict, dataclass
from typing import Sequence

from llm_instance_gateway_tpu import events as events_mod
from llm_instance_gateway_tpu.lockwitness import witness_lock
from llm_instance_gateway_tpu.tracing import escape_label

# Canonical stage order of one pick (the funnel rows every record and the
# gateway_pick_narrowing family carry, in pipeline order).
STAGES = ("pool", "role_partition", "filter_tree", "health/circuit",
          "fairness", "placement", "prefix_affinity", "rng")
# The advisor seams the counterfactual lane can disable, in chain order
# (= the decisive-seam tie-break order).
SEAMS = ("health/circuit", "fairness", "placement")
# Decisive tags beyond the seams (always rendered so dashboards see a
# stable label set).
_DECISIVE_EXTRA = ("prefix_affinity", "rng", "none")
# Removed-pod attribution cap per stage row (records are ring-resident;
# a 200-pod narrowing event must not hold 200 names forever).
_REMOVED_CAP = 16

# Shared read-only counterfactual rows for the common (seam-did-nothing)
# case: (seam, changed, delta, would_add, would_remove, replayed).
# Reused across records so sampled picks on a healthy fleet allocate no
# per-seam containers at all.
_CF_NOOP = {seam: (seam, False, 0, (), (), False) for seam in SEAMS}
_NO_REMOVED: tuple = ()


@dataclass(frozen=True)
class PickLedgerConfig:
    # OFF switch: disabled() short-circuits sampled() before the counter.
    enabled: bool = True
    # Deterministic sampling: every Nth pick is recorded (counter
    # modulus, NOT an RNG draw — the scheduler RNG must see an identical
    # call sequence with the ledger on or off).  1 = every pick.
    sample_every: int = 8
    # Bounded decision-record ring (the /debug/picks cursor pages it).
    capacity: int = 512


class _SilentAdvisor:
    """Delegation proxy that suppresses an advisor's ``note_*`` hooks.

    The scheduler filter functions fire escape counters via
    ``getattr(advisor, "note_...", None)``; raising AttributeError for
    those names makes a counterfactual replay side-effect-free while
    every read (``policy``, ``avoid_set``, ``noisy``, ``resident_tiers``,
    ...) still reaches the real advisor.
    """

    def __init__(self, inner):
        self._inner = inner

    def __getattr__(self, name):
        if name.startswith("note_"):
            raise AttributeError(name)
        return getattr(self._inner, name)


def _silent(advisor):
    return None if advisor is None else _SilentAdvisor(advisor)


def _names(candidates) -> list[str]:
    return [c.pod.name for c in candidates]


def replay_filter_chain(req, candidates, health=None, usage=None,
                        placement=None):
    """Re-run the pure advisor filter chain over ``candidates`` with all
    note hooks suppressed — no escape counters, no prefix index, no RNG.
    Returns the (post-health, post-fairness, post-placement) survivor
    lists.  A strict-policy shed in the replay (possible only when the
    live pick also shed) degrades to an empty final set."""
    from llm_instance_gateway_tpu.gateway.scheduling.scheduler import (
        SchedulingError,
        filter_by_fairness,
        filter_by_placement,
        filter_by_policy,
    )

    base = list(candidates)
    try:
        s1 = filter_by_policy(_silent(health), base)
    except SchedulingError:
        return [], [], []
    s2 = filter_by_fairness(_silent(usage), req, s1)
    s3 = filter_by_placement(_silent(placement), req, s2)
    return s1, s2, s3


class PickLedger:
    """Bounded, thread-safe decision-record ring + steering aggregates.

    One instance per pool (built by ``AdvisorStack``); the scheduler
    reaches it through its ``pick_ledger`` seam attribute exactly like
    the advisor seams — ``None`` (or ``enabled=False``) means every pick
    pays one attribute read and nothing else.
    """

    def __init__(self, cfg: PickLedgerConfig | None = None,
                 journal: "events_mod.EventJournal | None" = None,
                 clock=time.time):
        self.cfg = cfg or PickLedgerConfig()
        self.journal = journal
        self._clock = clock
        self._lock = witness_lock("PickLedger._lock")
        # Pick counter for the deterministic sampling modulus.  Bumped
        # lock-free on EVERY pick (``next`` on itertools.count is
        # GIL-atomic); everything else in this class only moves on
        # sampled picks, under the lock.
        self._counter = itertools.count()
        self._picks_seen = 0            # last counter value observed
        # Decision-record ring + monotonic cursor (events.py contract).
        # Entries are flat tuples of scalars/strings/tuples, NOT live
        # dicts: a ring of 512 nested record dicts is ~13k long-lived
        # GC-tracked containers that every collection re-scans, and that
        # churn — not the charge() compute — dominated the measured pick
        # overhead.  Tuples whose leaves are atomic get untracked by the
        # collector, so the ring is invisible to it; _materialize()
        # rebuilds the documented dict shape on the (rare) read path.
        self._ring: list[tuple] = []
        self._seq = 0
        # Aggregates across sampled picks (render/rollup inputs).
        self._samples = 0
        self._stage_survivors: dict[str, int] = {}   # stage -> sum
        self._stage_removed: dict[str, int] = {}     # stage -> sum
        self._steered: dict[str, int] = {}           # seam -> picks changed
        self._decisive: dict[str, int] = {}          # tag -> picks
        self._escapes: dict[str, int] = {}           # seam -> hatch fires
        self._steered_away: dict[str, int] = {}      # pod -> removals
        self._shadow_mismatch = 0
        # Swap-published rollup cache: recomputed by tick(), read without
        # the lock by statebus/fleet/loadgen consumers (seam_rollup).
        self._rollup: dict = self._empty_rollup()
        self.last_tick = 0.0
        self.ticks = 0

    # -- sampling gate (pick hot path) ---------------------------------------
    def sampled(self) -> bool:
        """One call per pick: True when THIS pick should be recorded.
        Deterministic (pick ordinal modulus; the first pick is always
        sampled) and RNG-free, so routing stays byte-identical."""
        if not self.cfg.enabled:
            return False
        n = next(self._counter)
        self._picks_seen = n + 1
        return n % self.cfg.sample_every == 0

    # -- scheduler-facing helpers -------------------------------------------
    @staticmethod
    def escape_counters(health, usage, placement) -> tuple[int, int, int]:
        """The advisors' cumulative escape counters, read before the
        filter chain on a sampled pick; ``charge(escape_base=...)`` diffs
        them afterwards to attribute which hatch fired for THIS pick."""
        return (getattr(health, "escape_hatch_total", 0) or 0,
                getattr(usage, "escape_total", 0) or 0,
                getattr(placement, "escape_total", 0) or 0)

    def replay(self, req, candidates, advisors):
        """Shadow-replay seam for the native scheduler: the silent filter
        chain over the oracle tree's survivor set."""
        health, usage, placement = advisors
        return replay_filter_chain(req, candidates, health=health,
                                   usage=usage, placement=placement)

    # -- charge --------------------------------------------------------------
    def charge(self, req, *, winner: str, base, post_health, post_fairness,
               post_placement, hop: str = "single", path: str = "python",
               pool_n: int = 0, role_n: int = 0, tie_break: bool = False,
               advisors=(None, None, None), escapes=None, escape_base=None,
               trace_id: str = "", shadow_match=None) -> None:
        """Record one sampled pick.

        ``base``..``post_placement`` are the actual survivor lists the
        pick narrowed through (PodMetrics on both paths); ``escapes`` is
        the explicit fired-hatch list (native flag bits) or derived from
        ``escape_base`` (Python path: counter deltas).  The counterfactual
        replays run here, outside the ledger lock, advisors untouched.
        """
        health, usage, placement = advisors
        if escapes is None and escape_base is not None:
            after = self.escape_counters(health, usage, placement)
            escapes = tuple(seam for seam, b, a in
                            zip(SEAMS, escape_base, after) if a > b)
        escapes = tuple(escapes) if escapes else ()

        # Filters only ever REMOVE pods, so an unchanged survivor count
        # means an unchanged survivor set — the O(1) length checks here
        # (and the identity checks below, gating the counterfactual
        # replays) stand in for set comparisons, and unchanged stages
        # REUSE the previous name list instead of re-materializing it.
        base_names = _names(base)
        n_health = (base_names if len(post_health) == len(base_names)
                    else _names(post_health))
        n_fair = (n_health if len(post_fairness) == len(n_health)
                  else _names(post_fairness))
        n_place = (n_fair if len(post_placement) == len(n_fair)
                   else _names(post_placement))
        stage_inputs = (base_names, n_health, n_fair)
        stage_outputs = (n_health, n_fair, n_place)
        actual_final = None

        # Counterfactual lane: each seam individually disabled, the other
        # advisors silenced.  A seam whose absence changes the final set
        # steered this pick; largest delta wins the decisive tag.  A seam
        # whose live filter passed its input through unchanged is skipped
        # without a replay — disabling a no-op filter reproduces the live
        # chain exactly, so the replay cost rides only picks a seam
        # actually narrowed (this is what keeps the amortized
        # pick_ledger_ratio under its bench gate on a healthy fleet).
        cf_rows = []
        steered: list[str] = []
        decisive = ""
        best_delta = -1
        for i, seam in enumerate(SEAMS):
            alt_advisors = [health, usage, placement]
            if (alt_advisors[i] is None
                    or stage_outputs[i] is stage_inputs[i]):
                cf_rows.append(_CF_NOOP[seam])
                continue
            if actual_final is None:
                actual_final = frozenset(n_place)
            alt_advisors[i] = None
            _, _, alt_final = replay_filter_chain(
                req, base, health=alt_advisors[0], usage=alt_advisors[1],
                placement=alt_advisors[2])
            alt_set = frozenset(_names(alt_final))
            delta = alt_set ^ actual_final
            changed = bool(delta)
            if changed:
                steered.append(seam)
                if len(delta) > best_delta:
                    best_delta, decisive = len(delta), seam
            cf_rows.append((
                seam, changed, len(delta),
                tuple(sorted(alt_set - actual_final)[:_REMOVED_CAP]),
                tuple(sorted(actual_final - alt_set)[:_REMOVED_CAP]),
                True))
        if not steered:
            if tie_break:
                decisive = "prefix_affinity"
            elif len(post_placement) > 1:
                decisive = "rng"
            else:
                decisive = "none"

        # Stage funnel with removed-pod attribution (advisor stages; the
        # earlier stages carry counts only — their inputs never reach the
        # pick seam).  Everything lands in one flat tuple of scalars and
        # tuples: the ring must stay GC-UNTRACKED (see __init__), so the
        # document shape is only materialized on the read path.
        removed3 = []
        removed_total: list[str] = []
        prev = base_names
        for cur in stage_outputs:
            if cur is prev:
                removed: Sequence[str] = _NO_REMOVED
            else:
                cur_set = set(cur)
                removed = tuple(sorted(
                    name for name in prev if name not in cur_set
                )[:_REMOVED_CAP])
                removed_total.extend(removed)
            removed3.append(removed)
            prev = cur
        survivors8 = (int(pool_n), int(role_n), len(base_names),
                      len(n_health), len(n_fair), len(n_place),
                      1 if tie_break else len(n_place), 1)
        steered_t = tuple(steered)
        ts = round(self._clock(), 6)
        with self._lock:
            self._seq += 1
            self._ring.append((
                self._seq, ts, trace_id, req.model,
                req.resolved_target_model, hop, path, survivors8,
                tuple(removed3), escapes, bool(tie_break), winner,
                steered_t, decisive, tuple(cf_rows),
                None if shadow_match is None else bool(shadow_match)))
            if len(self._ring) > self.cfg.capacity:
                del self._ring[:len(self._ring) - self.cfg.capacity]
            self._samples += 1
            for stage, surv in zip(STAGES, survivors8):
                self._stage_survivors[stage] = (
                    self._stage_survivors.get(stage, 0) + surv)
            for seam, removed in zip(SEAMS, removed3):
                if removed:
                    self._stage_removed[seam] = (
                        self._stage_removed.get(seam, 0) + len(removed))
            for seam in steered:
                self._steered[seam] = self._steered.get(seam, 0) + 1
            self._decisive[decisive] = self._decisive.get(decisive, 0) + 1
            for seam in escapes:
                self._escapes[seam] = self._escapes.get(seam, 0) + 1
            for name in removed_total:
                self._steered_away[name] = (
                    self._steered_away.get(name, 0) + 1)
            if shadow_match is False:
                self._shadow_mismatch += 1
        # Journal emits AFTER the lock release (kvobs discipline).
        if self.journal is not None:
            self.journal.emit(events_mod.PICK_SAMPLE, trace_id=trace_id,
                              hop=hop, path=path, winner=winner,
                              decisive=decisive,
                              steered=",".join(steered) or "none")
            if escapes:
                self.journal.emit(events_mod.PICK_ESCAPE_EXPLAINED,
                                  trace_id=trace_id, winner=winner,
                                  seams=",".join(escapes))

    # -- rollup --------------------------------------------------------------
    def _empty_rollup(self) -> dict:
        return {"picks": 0, "samples": 0, "steered": {}, "decisive": {},
                "escapes": {}, "mean_survivors": {}, "steered_away": {},
                "shadow_mismatch": 0}

    def maybe_tick(self, min_interval_s: float = 1.0) -> None:
        if self._clock() - self.last_tick >= min_interval_s:
            self.tick()

    def tick(self, now: float | None = None) -> None:
        """Recompute and swap-publish the steering rollup (the statebus /
        fleet / loadgen read surface)."""
        now = self._clock() if now is None else now
        with self._lock:
            samples = self._samples
            rollup = {
                "picks": self._picks_seen,
                "samples": samples,
                "steered": dict(self._steered),
                "decisive": dict(self._decisive),
                "escapes": dict(self._escapes),
                "mean_survivors": {
                    stage: round(total / samples, 2)
                    for stage, total in self._stage_survivors.items()
                } if samples else {},
                "steered_away": dict(sorted(
                    self._steered_away.items(),
                    key=lambda kv: (-kv[1], kv[0]))[:8]),
                "shadow_mismatch": self._shadow_mismatch,
            }
            self.last_tick = now
            self.ticks += 1
        self._rollup = rollup  # swap-published: readers never lock

    def seam_rollup(self) -> dict:
        """The last tick's steering rollup (swap-published — safe from
        any thread without the lock)."""
        return self._rollup

    # -- export --------------------------------------------------------------
    @staticmethod
    def _materialize(entry: tuple) -> dict:
        """Rebuild the documented record dict from a flat ring entry."""
        (seq, ts, trace_id, model, adapter, hop, path, survivors8,
         removed3, escapes, tie_break, winner, steered, decisive,
         cf_rows, shadow_match) = entry
        stage_rows = []
        for i, stage in enumerate(STAGES):
            removed = removed3[i - 3] if 3 <= i < 6 else ()
            stage_rows.append({"stage": stage, "survivors": survivors8[i],
                               "removed": list(removed)})
        counterfactual = {}
        for seam, changed, delta, would_add, would_remove, replayed \
                in cf_rows:
            if replayed:
                counterfactual[seam] = {
                    "changed": changed, "delta": delta,
                    "would_add": list(would_add),
                    "would_remove": list(would_remove)}
            else:
                counterfactual[seam] = {"changed": False, "delta": 0}
        record = {
            "seq": seq,
            "ts": ts,
            "trace_id": trace_id,
            "model": model,
            "adapter": adapter,
            "hop": hop,
            "path": path,
            "stages": stage_rows,
            "escapes": list(escapes),
            "tie_break": tie_break,
            "winner": winner,
            "steered": list(steered),
            "decisive": decisive,
            "counterfactual": counterfactual,
        }
        if shadow_match is not None:
            record["shadow_match"] = shadow_match
        return record

    def records(self, since: int = 0, limit: int = 256) -> list[dict]:
        """Oldest ``limit`` records with seq > ``since`` (events.py
        cursor semantics: page with since=next_since, never skip)."""
        with self._lock:
            entries = [e for e in self._ring if e[0] > since]
        return [self._materialize(e) for e in entries[:max(0, limit)]]

    @property
    def seq(self) -> int:
        return self._seq

    def render(self) -> list[str]:
        """The ``gateway_pick_*`` families.  Canonical stage/seam labels
        always render (dashboards see a stable set); any extra keys that
        reached the aggregates render escaped."""
        with self._lock:
            samples = self._samples
            survivors = dict(self._stage_survivors)
            steered = dict(self._steered)
        lines = ["# TYPE gateway_pick_sample_total counter",
                 "gateway_pick_sample_total %d" % samples,
                 "# TYPE gateway_pick_narrowing gauge"]
        for stage in (*STAGES, *sorted(set(survivors) - set(STAGES))):
            mean = survivors.get(stage, 0) / samples if samples else 0.0
            lines.append('gateway_pick_narrowing{stage="%s"} %.2f'
                         % (escape_label(stage), mean))
        lines.append("# TYPE gateway_pick_steered_total counter")
        for seam in (*SEAMS, *sorted(set(steered) - set(SEAMS))):
            lines.append('gateway_pick_steered_total{seam="%s"} %d'
                         % (escape_label(seam), steered.get(seam, 0)))
        return lines

    def debug_payload(self) -> dict:
        """The ledger block of ``/debug/picks`` (records ride next to it
        via ``debug_picks_payload``)."""
        with self._lock:
            decisive = dict(self._decisive)
            escapes = dict(self._escapes)
            samples = self._samples
            picks = self._picks_seen
        self.maybe_tick()
        return {
            "picks": picks,
            "samples": samples,
            "decisive": decisive,
            "escapes": escapes,
            "rollup": self.seam_rollup(),
            "ticks": self.ticks,
            "last_tick": self.last_tick,
            "config": asdict(self.cfg),
        }


def debug_picks_payload(ledger: PickLedger, query) -> dict:
    """The ``/debug/picks`` response body: ``?since=<seq>`` incremental
    cursor + ``?limit=`` page size, same contract as
    ``events.debug_events_payload`` (poll with since=next_since until
    next_since == seq to drain)."""
    try:
        since = max(0, int(query.get("since", "0")))
    except ValueError:
        since = 0
    try:
        limit = max(1, min(int(query.get("limit", "256")), 2048))
    except ValueError:
        limit = 256
    rows = ledger.records(since=since, limit=limit)
    payload = ledger.debug_payload()
    payload.update({
        "seq": ledger.seq,
        "next_since": rows[-1]["seq"] if rows else ledger.seq,
        "records": rows,
    })
    return payload
