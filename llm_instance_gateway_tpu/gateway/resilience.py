"""Active robustness plane: health-enforcing routing policy, per-pod
circuit breakers, and the retry/hedge budget the proxy's data path spends.

PR 3 built the *observables* (per-replica health scores with hysteresis,
upstream error/timeout streaks, the event journal); this module makes them
load-bearing:

- ``ResilienceConfig.health_policy`` promotes the scheduler's pick seam from
  LOG-ONLY to enforcing.  ``log_only`` (the default) keeps routing
  byte-identical to PR 3 — same RNG draws, same picks — and only counts
  would-avoid decisions.  ``avoid`` deprioritizes degraded/unhealthy/
  circuit-open replicas: the pick runs over the healthy subset of the
  tree's survivors, with a last-resort escape hatch (a fully-unhealthy
  pool still serves, loudly).  ``strict`` sheds instead of using the
  escape hatch.
- ``CircuitBreaker``: per-pod closed -> open -> half_open state machine fed
  by the SAME ``record_upstream``/``record_handoff`` signals the health
  scorer consumes.  Trips on a consecutive-failure streak or a windowed
  error rate; after ``open_cooldown_s`` it admits ``half_open_probes``
  probe requests — one success closes, one failure re-opens.  Exported as
  ``gateway_circuit_state{pod}`` (0 closed / 1 open / 2 half-open), every
  transition journaled.
- ``RetryBudget``: a token bucket that caps retries to a fraction of real
  traffic (``retry_budget_ratio``) so retries cannot amplify an outage —
  the classic Envoy/Finagle retry-budget shape.  ``retry_backoff`` is
  decorrelated jitter.

``ResiliencePlane`` composes the three with the health scorer and IS the
object the proxy hands to the scheduler as ``health_advisor`` — it keeps
the scorer's ``note_pick`` counting AND answers ``should_avoid`` when the
policy enforces.
"""

from __future__ import annotations

import logging
import random
import time
from collections import deque
from dataclasses import asdict, dataclass

from llm_instance_gateway_tpu.lockwitness import witness_lock
from llm_instance_gateway_tpu import events as events_mod
from llm_instance_gateway_tpu.gateway import health as health_mod
from llm_instance_gateway_tpu.tracing import escape_label

logger = logging.getLogger(__name__)

LOG_ONLY, AVOID, STRICT = "log_only", "avoid", "strict"
HEALTH_POLICIES = (LOG_ONLY, AVOID, STRICT)

CLOSED, OPEN, HALF_OPEN = "closed", "open", "half_open"
CIRCUIT_STATE_CODE = {CLOSED: 0, OPEN: 1, HALF_OPEN: 2}


@dataclass(frozen=True)
class ResilienceConfig:
    """Knobs for the whole robustness plane (flags: ``add_resilience_args``).

    Defaults are deliberately conservative: ``log_only`` policy (routing
    unchanged), hedging off, retries bounded by a budget.  The per-phase
    timeouts replace the old single ``request_timeout_s=3600`` client
    timeout: connect / time-to-first-byte / idle-between-chunks each get
    their own bound, so a dead replica fails in seconds while a long
    healthy generation still streams for hours.
    """

    health_policy: str = LOG_ONLY
    # Circuit breaker (per pod).
    trip_consecutive: int = 5
    trip_error_rate: float = 0.5
    error_window: int = 20
    min_volume: int = 10
    open_cooldown_s: float = 10.0
    half_open_probes: int = 1
    # Retries (idempotent failures only: connect errors, 503s, TTFT
    # timeouts — nothing after the first relayed byte).
    max_retries: int = 2
    retry_budget_ratio: float = 0.2
    retry_budget_min: float = 3.0
    retry_budget_cap: float = 64.0
    backoff_base_s: float = 0.025
    backoff_cap_s: float = 1.0
    # TTFT-based hedge for non-streaming requests; 0 disables.
    hedge_ttft_s: float = 0.0
    # Per-phase timeouts (0 disables a phase's bound).
    connect_timeout_s: float = 5.0
    ttft_timeout_s: float = 300.0
    stream_idle_timeout_s: float = 120.0

    def __post_init__(self):
        if self.health_policy not in HEALTH_POLICIES:
            raise ValueError(
                f"health_policy {self.health_policy!r} not in "
                f"{HEALTH_POLICIES}")


@dataclass
class _PodCircuit:
    state: str = CLOSED
    consecutive_failures: int = 0
    opened_t: float = 0.0
    probes_inflight: int = 0
    probe_t: float = 0.0  # when the last probe pick was admitted
    opens_total: int = 0
    window: deque = None  # recent outcomes (True=ok), maxlen=error_window

    def __post_init__(self):
        if self.window is None:
            self.window = deque(maxlen=20)


class CircuitBreaker:
    """Per-pod circuit breaker over upstream outcomes; all methods
    thread-safe (request path, scheduler executor threads, and the
    observability tick all touch it)."""

    def __init__(self, cfg: ResilienceConfig | None = None,
                 journal: events_mod.EventJournal | None = None,
                 clock=time.time):
        self.cfg = cfg or ResilienceConfig()
        self.journal = journal
        self._clock = clock
        self._lock = witness_lock("CircuitBreaker._lock")
        self._pods: dict[str, _PodCircuit] = {}
        # blocked_set() cache for the pick seam: rebuilt only after a
        # state/probe change (dirty flag) or when an open pod's cooldown
        # elapses (expiry).  Unlocked reads may see a one-event-stale set
        # — harmless for routing, and the common all-closed case costs a
        # single attribute read per pick.
        self._blocked_cache: frozenset = frozenset()
        self._cache_expiry: float = float("inf")
        self._cache_dirty = False

    def _get(self, pod_name: str) -> _PodCircuit:
        pc = self._pods.get(pod_name)
        if pc is None:
            pc = self._pods[pod_name] = _PodCircuit(
                window=deque(maxlen=max(1, self.cfg.error_window)))
        return pc

    def _transition(self, pod_name: str, pc: _PodCircuit, to: str) -> None:
        frm, pc.state = pc.state, to
        self._cache_dirty = True
        if to == OPEN:
            pc.opened_t = self._clock()
            pc.opens_total += 1
        if to in (CLOSED, OPEN):
            pc.probes_inflight = 0
        if to == CLOSED:
            pc.consecutive_failures = 0
            pc.window.clear()
        log = logger.warning if to != CLOSED else logger.info
        log("circuit for pod %s: %s -> %s", pod_name, frm, to)
        if self.journal is not None:
            self.journal.emit(events_mod.CIRCUIT_TRANSITION, pod=pod_name,
                              frm=frm, to=to)

    def _maybe_half_open(self, pod_name: str, pc: _PodCircuit) -> None:
        now = self._clock()
        if (pc.state == OPEN
                and now - pc.opened_t >= self.cfg.open_cooldown_s):
            self._transition(pod_name, pc, HALF_OPEN)
        if (pc.state == HALF_OPEN and pc.probes_inflight > 0 and pc.probe_t
                and now - pc.probe_t >= self.cfg.open_cooldown_s):
            # The probe's outcome never came back (client vanished before
            # the upstream round-trip, a hop path that records elsewhere):
            # reap the stale slot, or the pod would stay probe-quota-full
            # — and therefore excluded under policy=avoid — forever.
            pc.probes_inflight = 0
            self._cache_dirty = True

    def record(self, pod_name: str, ok: bool) -> None:
        """One upstream outcome.  In half-open (including an open circuit
        whose cooldown just elapsed) this IS the probe verdict: success
        closes the circuit, failure re-opens it for a full cooldown."""
        with self._lock:
            pc = self._get(pod_name)
            self._maybe_half_open(pod_name, pc)
            if pc.state == HALF_OPEN:
                pc.probes_inflight = max(0, pc.probes_inflight - 1)
                self._transition(pod_name, pc, CLOSED if ok else OPEN)
                return
            pc.window.append(ok)
            if ok:
                pc.consecutive_failures = 0
                return
            pc.consecutive_failures += 1
            if pc.state != CLOSED:
                return
            errs = sum(1 for o in pc.window if not o)
            rate_trip = (len(pc.window) >= self.cfg.min_volume
                         and errs / len(pc.window)
                         >= self.cfg.trip_error_rate)
            if (pc.consecutive_failures >= self.cfg.trip_consecutive
                    or rate_trip):
                self._transition(pod_name, pc, OPEN)

    def state(self, pod_name: str) -> str:
        """Current state (advances open -> half_open when the cooldown has
        elapsed, so readers never see a stale open)."""
        with self._lock:
            pc = self._pods.get(pod_name)
            if pc is None:
                return CLOSED
            self._maybe_half_open(pod_name, pc)
            return pc.state

    def allow(self, pod_name: str) -> bool:
        """Pick-time consultation: closed always; open only after the
        cooldown (as a half-open probe); half-open up to
        ``half_open_probes`` concurrent probes."""
        with self._lock:
            pc = self._pods.get(pod_name)
            if pc is None:
                return True
            self._maybe_half_open(pod_name, pc)
            if pc.state == CLOSED:
                return True
            if pc.state == HALF_OPEN:
                return pc.probes_inflight < self.cfg.half_open_probes
            return False

    def note_pick(self, pod_name: str) -> None:
        """A pick landed on this pod; a half-open pod counts it as its
        in-flight probe so concurrent picks can't stampede the replica."""
        with self._lock:
            pc = self._pods.get(pod_name)
            if pc is None:
                return
            self._maybe_half_open(pod_name, pc)
            if pc.state == HALF_OPEN:
                pc.probes_inflight += 1
                pc.probe_t = self._clock()
                self._cache_dirty = True

    def blocked_set(self) -> frozenset:
        """Pods a pick must not land on right now (open inside cooldown,
        or half-open with the probe quota spent).  Served from the cache
        unless an event dirtied it or an open pod's cooldown elapsed — the
        pick-seam hot path must not pay a per-pick rebuild."""
        now = self._clock()
        if not self._cache_dirty and now < self._cache_expiry:
            return self._blocked_cache
        with self._lock:
            out = set()
            expiry = float("inf")
            for name, pc in self._pods.items():
                self._maybe_half_open(name, pc)
                if pc.state == OPEN:
                    out.add(name)
                    expiry = min(expiry,
                                 pc.opened_t + self.cfg.open_cooldown_s)
                elif (pc.state == HALF_OPEN and pc.probes_inflight
                        >= self.cfg.half_open_probes):
                    out.add(name)
                    # The stale-probe reaper frees the quota at
                    # probe_t + cooldown; the cache must revisit then.
                    expiry = min(expiry,
                                 pc.probe_t + self.cfg.open_cooldown_s)
            self._blocked_cache = frozenset(out)
            self._cache_expiry = expiry
            self._cache_dirty = False
            return self._blocked_cache

    def prune(self, live: set[str]) -> None:
        """Drop state for pods that left the pool (name reuse must not
        inherit an open circuit)."""
        with self._lock:
            for name in [n for n in self._pods if n not in live]:
                del self._pods[name]
                self._cache_dirty = True

    def render(self) -> list[str]:
        with self._lock:
            states = {}
            for name, pc in self._pods.items():
                self._maybe_half_open(name, pc)
                states[name] = pc.state
        if not states:
            return []
        lines = ["# TYPE gateway_circuit_state gauge"]
        for pod in sorted(states):
            lines.append('gateway_circuit_state{pod="%s"} %d'
                         % (escape_label(pod),
                            CIRCUIT_STATE_CODE[states[pod]]))
        return lines

    def debug_payload(self) -> dict:
        with self._lock:
            return {
                name: {"state": pc.state,
                       "consecutive_failures": pc.consecutive_failures,
                       "opens_total": pc.opens_total,
                       "probes_inflight": pc.probes_inflight}
                for name, pc in sorted(self._pods.items())
            }


class RetryBudget:
    """Token bucket bounding retries to a fraction of real traffic.

    Every primary request deposits ``ratio`` tokens (bounded by ``cap``);
    a retry withdraws one.  During an outage the deposit stream shrinks
    with successful traffic, so retry volume decays instead of doubling
    the load on whatever is left — ``min_tokens`` keeps a cold gateway
    able to retry at all.
    """

    def __init__(self, ratio: float = 0.2, min_tokens: float = 3.0,
                 cap: float = 64.0):
        self.ratio = ratio
        self.cap = max(cap, min_tokens)
        self._tokens = min_tokens
        self._lock = witness_lock("RetryBudget._lock")
        self.spent_total = 0
        self.denied_total = 0

    def note_request(self) -> None:
        with self._lock:
            self._tokens = min(self.cap, self._tokens + self.ratio)

    def try_spend(self) -> bool:
        with self._lock:
            if self._tokens >= 1.0:
                self._tokens -= 1.0
                self.spent_total += 1
                return True
            self.denied_total += 1
            return False

    @property
    def tokens(self) -> float:
        with self._lock:
            return self._tokens


def retry_backoff(rng: random.Random, prev_s: float, base_s: float,
                  cap_s: float) -> float:
    """Decorrelated-jitter backoff (AWS architecture blog shape): each
    sleep is uniform in [base, 3 * previous], capped — retries desynchronize
    across clients instead of thundering in lockstep."""
    return min(cap_s, rng.uniform(base_s, max(base_s, prev_s * 3.0)))


class ResiliencePlane:
    """One object owning the robustness state: the proxy records upstream
    outcomes through it (fanning into the health scorer AND the breaker),
    and the scheduler consults it as its ``health_advisor``
    (``note_pick``/``should_avoid``/``policy`` seam)."""

    def __init__(self, health: "health_mod.HealthScorer",
                 cfg: ResilienceConfig | None = None,
                 journal: events_mod.EventJournal | None = None,
                 clock=time.time, rng: random.Random | None = None):
        self.cfg = cfg or ResilienceConfig()
        self.health = health
        self.journal = journal
        self.breaker = CircuitBreaker(self.cfg, journal=journal, clock=clock)
        self.retry_budget = RetryBudget(
            ratio=self.cfg.retry_budget_ratio,
            min_tokens=self.cfg.retry_budget_min,
            cap=self.cfg.retry_budget_cap)
        self.rng = rng or random.Random()
        # The pick seam's note_escape_hatch runs on threaded transports;
        # an unlocked += there loses updates (concurrency lint, ISSUE 13).
        self._lock = witness_lock("ResiliencePlane._lock")
        self.escape_hatch_total = 0
        # Peer-gateway avoid overlay (statebus merged view): pods some
        # OTHER replica's health scorer or breaker currently avoids.
        # Unioned into ``avoid_set``/``should_avoid`` so a replica that
        # has not yet observed a pod failing still steers off it; local
        # detection state never includes these (each replica gossips only
        # its own observations).
        self._remote_avoid: frozenset = frozenset()

    # -- scheduler advisor seam -------------------------------------------
    @property
    def policy(self) -> str:
        return self.cfg.health_policy

    def note_pick(self, pod_name: str) -> None:
        """Must never raise or draw RNG — the log_only byte-identical
        guarantee rides on this (tests/test_health.py pins it)."""
        self.health.note_pick(pod_name)
        self.breaker.note_pick(pod_name)

    def should_avoid(self, pod_name: str) -> bool:
        """True when enforcing policy should steer picks off this pod:
        health state degraded/unhealthy, or the circuit is not admitting
        (open inside cooldown, or half-open with its probe quota full)."""
        if pod_name in self._remote_avoid:
            return True
        if self.health.state(pod_name) != health_mod.HEALTHY:
            return True
        return not self.breaker.allow(pod_name)

    def local_avoid_set(self) -> frozenset:
        """This replica's OWN avoid set (health + breaker, no peer
        overlay) — what the statebus publishes to peers."""
        bad_health = self.health.non_healthy()
        bad_circuit = self.breaker.blocked_set()
        if not bad_circuit:
            return bad_health
        if not bad_health:
            return bad_circuit
        return bad_health | bad_circuit

    def set_remote_avoid(self, pods) -> None:
        """Statebus seam: replace the peer-derived avoid overlay (empty =
        local-only fallback)."""
        self._remote_avoid = frozenset(pods)

    def avoid_set(self) -> frozenset:
        """Batch form of ``should_avoid`` — the pick seam calls this once
        per candidate set; both sides serve cached frozensets, so the
        healthy-pool common case is two attribute reads (plus one overlay
        emptiness test)."""
        local = self.local_avoid_set()
        if not self._remote_avoid:
            return local
        if not local:
            return self._remote_avoid
        return local | self._remote_avoid

    def note_escape_hatch(self) -> None:
        """Every tree survivor was avoidable; the pick proceeded over the
        full set (policy=avoid last resort).  Called from the threaded-
        transport pick seam, so the increment takes the lock."""
        with self._lock:
            self.escape_hatch_total += 1
        if self.journal is not None:
            self.journal.emit(events_mod.POLICY_ESCAPE,
                              policy=self.cfg.health_policy)

    # -- request-path feeds ------------------------------------------------
    def record_upstream(self, pod_name: str, ok: bool,
                        timeout: bool = False) -> None:
        self.health.record_upstream(pod_name, ok, timeout=timeout)
        self.breaker.record(pod_name, ok)

    def record_handoff(self, pod_name: str, ok: bool) -> None:
        self.health.record_handoff(pod_name, ok)
        self.breaker.record(pod_name, ok)

    # -- lifecycle ---------------------------------------------------------
    def tick(self) -> None:
        """Observability-loop tick: health pass first, then breaker
        bookkeeping (cooldown advance + departed-pod pruning)."""
        self.health.update()
        provider = self.health.provider
        if provider is not None:
            self.breaker.prune(
                {pm.pod.name for pm in provider.all_pod_metrics()})

    # -- export ------------------------------------------------------------
    def render(self) -> list[str]:
        return self.breaker.render()

    def debug_payload(self) -> dict:
        return {
            "policy": self.cfg.health_policy,
            "circuits": self.breaker.debug_payload(),
            "retry_budget": {
                "tokens": round(self.retry_budget.tokens, 3),
                "spent_total": self.retry_budget.spent_total,
                "denied_total": self.retry_budget.denied_total,
            },
            "escape_hatch_total": self.escape_hatch_total,
            "config": asdict(self.cfg),
        }
