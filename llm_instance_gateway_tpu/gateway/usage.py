"""Pool-wide capacity attribution: per-{model, adapter} consumption shares
and noisy-neighbor detection over the replicas' ``tpu:adapter_*_total``
families (server/usage.py).

The engine side charges every decode step, token, and KV block-second to
an {adapter}; this module answers the POOL question: *who is consuming the
fleet, and is anyone consuming far more than their admitted traffic
justifies?*  CaraServe (arxiv 2401.11240) and the heterogeneous-LoRA
serving literature (arxiv 2511.22880) both identify rank/load heterogeneity
across adapters as the dominant interference source in multi-LoRA serving;
this rollup is the attribution layer a fairness/cost-aware router needs.

Mechanics (one ``tick()`` per provider scrape/observability cadence):

- Sum each pod's cumulative per-(model, adapter) counters, difference
  against the previous tick, and EMA the resulting **consumption shares**
  per resource (``step_seconds`` | ``tokens`` | ``kv_block_seconds``).
- Derive each key's **admitted-traffic share** from the gateway's own
  ``requests_total`` deltas (a request's model name IS the adapter name
  for LoRA traffic; base-model traffic folds into the ``base`` key).
  Laplace smoothing keeps the ratio finite for keys with zero admitted
  traffic in a window (their consumption is all backlog).
- ``noisy score = step-seconds share / smoothed traffic share``: 1.0 means
  consumption proportional to admission; a long-prompt flooder scores far
  above its traffic share.  A key flags **noisy** after ``enter_ticks``
  consecutive ticks over ``noisy_ratio`` with at least ``min_share`` of
  pool step-seconds (tiny adapters never flag), and clears after
  ``exit_ticks`` below — the same dwell-style hysteresis as
  ``gateway/health.py``.  Transitions journal ``noisy_neighbor`` events
  into the flight recorder.

The rollup itself stays **observational** (``note_pick`` counts picks
serving a currently-flagged key into
``gateway_usage_would_deprioritize_total{model,adapter}`` — no RNG, no
filtering, routing byte-identical, pinned by the same-RNG diff test in
tests/test_usage.py).  Enforcement lives one layer up:
``gateway/fairness.py`` wraps this rollup and promotes the seam to
deprioritizing picks and gating admission when its mode asks for it.
"""

from __future__ import annotations

import time
from dataclasses import asdict, dataclass

from llm_instance_gateway_tpu.lockwitness import witness_lock
from llm_instance_gateway_tpu import events as events_mod
from llm_instance_gateway_tpu.tracing import escape_label, render_keyed_family

BASE = "base"
QUIET, NOISY = "quiet", "noisy"
RESOURCES = ("step_seconds", "tokens", "kv_block_seconds")


@dataclass(frozen=True)
class UsageConfig:
    # Consumption-share / traffic-share ratio at which a key is a noisy
    # candidate (2.0 = consuming double what its admission justifies).
    noisy_ratio: float = 2.0
    # Floor on the key's share of pool step-seconds: a 2x-ratio adapter
    # consuming 3% of the pool is not a neighbor problem.
    min_share: float = 0.2
    # Hysteresis (ticks are rollup update passes, like health dwell).
    enter_ticks: int = 2
    exit_ticks: int = 2
    # Weight of the newest tick's delta shares in the EMA (1.0 = no
    # smoothing; the default damps single-tick spikes without hiding a
    # sustained flood from the 2-tick detection bar).
    ema_alpha: float = 0.6


class UsageRollup:
    """Thread-safe pool rollup; ``tick()`` runs on the proxy's
    observability cadence (and lazily from ``/debug/usage``)."""

    def __init__(self, provider, metrics=None, cfg: UsageConfig | None = None,
                 journal: events_mod.EventJournal | None = None,
                 clock=time.time, request_filter=None):
        self.provider = provider
        self.metrics = metrics  # GatewayMetrics (admitted-traffic source)
        self.cfg = cfg or UsageConfig()
        self.journal = journal
        self._clock = clock
        # Multi-pool fronts share ONE GatewayMetrics across per-pool
        # rollups; the filter scopes the admitted-traffic deltas to this
        # pool's model names so pool B's requests never dilute (or
        # inflate, via the unclaimed-leftover split) pool A's traffic
        # shares.  None = claim everything (single-pool, unchanged).
        self._request_filter = request_filter
        self._lock = witness_lock("UsageRollup._lock")
        self._prev_totals: dict[str, dict] = {r: {} for r in RESOURCES}
        self._prev_requests: dict[str, float] = {}
        self._shares: dict[str, dict] = {r: {} for r in RESOURCES}
        self._traffic: dict[tuple, float] = {}
        self._scores: dict[tuple, float] = {}
        self._states: dict[tuple, str] = {}
        self._pending: dict[tuple, tuple[str, int]] = {}
        self._totals: dict[str, dict] = {r: {} for r in RESOURCES}
        self._pool_waste: dict[str, float] = {}
        # Cached flagged model/adapter names for the log-only pick seam
        # (frozenset read without the lock, like health.non_healthy()),
        # plus the name -> (model, adapter) map so the would-deprioritize
        # counter attributes throttle candidates to the actual offender.
        self._noisy_models: frozenset = frozenset()
        self._noisy_key_of: dict[str, tuple] = {}
        # Peer-gateway noisy flags (statebus merged view): name -> key
        # overlay unioned into ``_noisy_models`` so the pick seams treat a
        # tenant flagged ANYWHERE in the replica set as flagged here.
        # Local detection state (``_states``) never includes these — each
        # replica gossips only what it derived itself, so a flag can't
        # ping-pong between replicas after the origin clears it.
        self._remote_noisy: dict[str, tuple] = {}
        self.last_tick = 0.0
        self.ticks = 0
        self.would_deprioritize_total = 0
        # Keyed by (model, adapter) — the key that flagged, not just the
        # request name note_pick matched.
        self.would_deprioritize: dict[tuple, int] = {}

    # -- rollup --------------------------------------------------------------
    @staticmethod
    def _sum_pods(pods) -> tuple[dict[str, dict], dict[str, float]]:
        """(per-resource {(model, adapter): cumulative}, pool-waste sums)."""
        totals: dict[str, dict] = {r: {} for r in RESOURCES}
        waste = {"idle_slot_seconds": 0.0, "prefill_padding_tokens": 0.0}
        for pm in pods:
            m = pm.metrics
            for (model, adapter, _phase), v in getattr(
                    m, "adapter_step_seconds", {}).items():
                key = (model, adapter)
                totals["step_seconds"][key] = (
                    totals["step_seconds"].get(key, 0.0) + v)
            for (model, adapter, _phase), v in getattr(
                    m, "adapter_tokens", {}).items():
                key = (model, adapter)
                totals["tokens"][key] = totals["tokens"].get(key, 0.0) + v
            for (model, adapter), v in getattr(
                    m, "adapter_kv_block_seconds", {}).items():
                key = (model, adapter)
                totals["kv_block_seconds"][key] = (
                    totals["kv_block_seconds"].get(key, 0.0) + v)
            waste["idle_slot_seconds"] += getattr(m, "idle_slot_seconds", 0.0)
            waste["prefill_padding_tokens"] += getattr(
                m, "prefill_padding_tokens", 0)
        return totals, waste

    def maybe_tick(self, min_interval_s: float = 1.0) -> None:
        """On-demand rollup with a floor between passes — the enter/exit
        hysteresis counts UPDATE PASSES, so an unthrottled debug poller
        must not drive flag transitions at its own poll rate."""
        if self._clock() - self.last_tick >= min_interval_s:
            self.tick()

    def tick(self, now: float | None = None) -> None:
        now = self._clock() if now is None else now
        pods = self.provider.all_pod_metrics()
        totals, waste = self._sum_pods(pods)
        if self.metrics is None:
            requests = {}
        else:
            # Locked accessor when available (GatewayMetrics); plain copy
            # for bare test fakes.
            snap = getattr(self.metrics, "requests_snapshot", None)
            requests = snap() if snap is not None else dict(
                self.metrics.requests_total)
            if self._request_filter is not None:
                requests = {m: v for m, v in requests.items()
                            if self._request_filter(m)}
        cfg = self.cfg
        transitions = []
        with self._lock:
            self.last_tick = now
            self.ticks += 1
            self._totals = totals
            self._pool_waste = waste
            # Per-resource delta shares, EMA-smoothed.
            for resource in RESOURCES:
                prev = self._prev_totals[resource]
                cur = totals[resource]
                deltas = {k: max(0.0, v - prev.get(k, 0.0))
                          for k, v in cur.items()}
                self._prev_totals[resource] = dict(cur)
                total_delta = sum(deltas.values())
                if total_delta <= 0.0:
                    continue  # no movement: shares keep their EMA
                shares = self._shares[resource]
                a = cfg.ema_alpha
                for k in set(deltas) | set(shares):
                    cur_share = deltas.get(k, 0.0) / total_delta
                    shares[k] = a * cur_share + (1 - a) * shares.get(k, 0.0)
            # Keys absent from every pod's cumulative exposition are gone
            # (adapter unloaded / pod churned): drop their share EMAs so
            # the exposition doesn't grow a line per tenant ever seen.
            live = set()
            for resource in RESOURCES:
                live |= set(totals[resource])
            for resource in RESOURCES:
                shares = self._shares[resource]
                for k in [k for k in shares if k not in live]:
                    del shares[k]
            # Admitted-traffic shares over the same window.  A request's
            # model name is the adapter name for LoRA traffic; base-tenant
            # requests arrive under the SERVED model name, so each
            # (model, base) key claims its own model's traffic, and any
            # request name claimed by no key (aliases, foreign models)
            # splits evenly across the base keys — each unit of traffic is
            # counted at most once (multi-model pools must not inflate
            # every base key with the whole pool's unclaimed traffic).
            req_delta = {m: max(0.0, v - self._prev_requests.get(m, 0.0))
                         for m, v in requests.items()}
            self._prev_requests = dict(requests)
            keys = set(self._shares["step_seconds"])
            adapter_names = {adapter for (_m, adapter) in keys
                             if adapter != BASE}
            base_models = {model for (model, adapter) in keys
                           if adapter == BASE}
            leftover = sum(v for m, v in req_delta.items()
                           if m not in adapter_names
                           and m not in base_models)
            total_traffic = sum(req_delta.values())
            if keys and (total_traffic > 0 or not self._traffic):
                n = len(keys)
                # An adapter name shared by several served models splits
                # its traffic evenly — requests_total cannot attribute the
                # model, and letting each key claim the whole delta would
                # double-count (deflating every copy's noisy score).
                adapter_models: dict[str, int] = {}
                for (_m, adapter) in keys:
                    if adapter != BASE:
                        adapter_models[adapter] = (
                            adapter_models.get(adapter, 0) + 1)
                for key in keys:
                    (model, adapter) = key
                    if adapter == BASE:
                        t = (req_delta.get(model, 0.0)
                             + leftover / max(1, len(base_models)))
                    else:
                        t = (req_delta.get(adapter, 0.0)
                             / adapter_models[adapter])
                    # Laplace smoothing keeps zero-traffic keys finite.
                    smoothed = (t + 1.0) / (total_traffic + n)
                    a = cfg.ema_alpha
                    self._traffic[key] = (a * smoothed
                                          + (1 - a) * self._traffic.get(
                                              key, smoothed))
            # Scores + dwell-filtered flag state.
            for key in keys:
                share = self._shares["step_seconds"].get(key, 0.0)
                traffic = self._traffic.get(key, 1.0)
                score = share / max(traffic, 1e-9)
                self._scores[key] = round(score, 4)
                want = (NOISY if score >= cfg.noisy_ratio
                        and share >= cfg.min_share else QUIET)
                cur = self._states.get(key, QUIET)
                if want == cur:
                    self._pending.pop(key, None)
                    continue
                cand, streak = self._pending.get(key, (want, 0))
                streak = streak + 1 if cand == want else 1
                dwell = (cfg.enter_ticks if want == NOISY
                         else cfg.exit_ticks)
                if streak >= dwell:
                    self._states[key] = want
                    self._pending.pop(key, None)
                    transitions.append((key, cur, want, self._scores[key],
                                        round(share, 4)))
                else:
                    self._pending[key] = (want, streak)
            # Keys that vanished from every pod's exposition drop state —
            # journaling an exit first when one leaves while flagged, so
            # the flight recorder never shows an unmatched noisy 'enter'
            # (an operator paging on transitions would see it noisy
            # forever).
            for key in [k for k in self._states if k not in keys]:
                if self._states[key] == NOISY:
                    transitions.append((key, NOISY, QUIET,
                                        self._scores.get(key, 0.0), 0.0))
            for table in (self._scores, self._states, self._pending,
                          self._traffic):
                for key in [k for k in table if k not in keys]:
                    del table[key]
            # Flagged names for the pick seam: base-tenant requests arrive
            # under the served MODEL name, adapter traffic under the
            # adapter name — store whichever note_pick will actually see.
            self._noisy_key_of = {
                (model if adapter == BASE else adapter): (model, adapter)
                for (model, adapter), st in self._states.items()
                if st == NOISY}
            self._noisy_models = frozenset(
                self._noisy_key_of) | frozenset(self._remote_noisy)
        for key, frm, to, score, share in transitions:
            if self.journal is not None:
                self.journal.emit(events_mod.NOISY_NEIGHBOR,
                                  model=key[0], adapter=key[1], frm=frm,
                                  to=to, score=score, share=share)

    # -- log-only scheduler seam ----------------------------------------------
    def note_pick(self, pod_name: str, model: str | None) -> None:
        """Count picks serving a currently-flagged noisy model.  Must never
        influence the pick — no RNG, no exceptions, no filtering — so
        routing stays byte-identical with the seam attached (same-RNG diff
        test in tests/test_usage.py); a future fairness policy promotes
        this observable the way health_policy promoted note_pick."""
        if model is None:
            return
        key = self._noisy_key_of.get(model) or self._remote_noisy.get(model)
        if key is None:
            return
        with self._lock:
            self.would_deprioritize_total += 1
            self.would_deprioritize[key] = (
                self.would_deprioritize.get(key, 0) + 1)

    def noisy(self) -> frozenset:
        """Currently-flagged adapter/model names (cached; lock-free read)."""
        return self._noisy_models

    def seed_noisy(self, model: str, adapter: str) -> None:
        """Bench/test seam: flag one ``{model, adapter}`` key directly.
        The flag state lives in three coupled tables (``_states``,
        ``_noisy_key_of``, ``_noisy_models`` — ``tick`` rebuilds the
        latter two from the first), so external seeding must go through
        here rather than poking the fields individually."""
        name = model if adapter == BASE else adapter
        with self._lock:
            self._states[(model, adapter)] = NOISY
            # _noisy_key_of is read lock-free by note_pick: swap a rebuilt
            # dict in whole (publish-by-swap) instead of mutating the one
            # a concurrent pick may be reading.
            self._noisy_key_of = {**self._noisy_key_of,
                                  name: (model, adapter)}
            self._noisy_models = frozenset(
                self._noisy_key_of) | frozenset(self._remote_noisy)

    def set_remote_noisy(self, noisy: dict[str, tuple]) -> None:
        """Statebus seam: replace the peer-derived noisy overlay with the
        merged view's ``{request name: (model, adapter)}`` mapping (empty
        = local-only fallback).  The merged frozenset swaps identity so
        the native scheduler's noisy-mark snapshot re-marshals on the
        next pick, exactly like a local flag transition."""
        with self._lock:
            self._remote_noisy = dict(noisy)
            self._noisy_models = frozenset(
                self._noisy_key_of) | frozenset(self._remote_noisy)

    def local_noisy_keys(self) -> dict[str, tuple]:
        """LOCALLY-derived flags only (``{name: (model, adapter)}``) — the
        statebus publishes these, never the remote overlay, so a flag is
        owned by exactly one replica's detection hysteresis."""
        with self._lock:
            return dict(self._noisy_key_of)

    def shares_snapshot(self) -> dict:
        """Locked copy of the step-seconds EMA shares keyed by
        ``(model, adapter)`` — the fairness plane's quota input
        (gateway/fairness.py)."""
        with self._lock:
            return dict(self._shares["step_seconds"])

    # -- export ---------------------------------------------------------------
    def render(self) -> list[str]:
        with self._lock:
            shares = {r: dict(t) for r, t in self._shares.items()}
            scores = dict(self._scores)
            would = dict(self.would_deprioritize)
        lines = []
        share_rows = [
            (model, adapter, resource, share)
            for resource in RESOURCES
            for (model, adapter), share in sorted(shares[resource].items())
        ]
        if share_rows:
            lines.append("# TYPE gateway_usage_share gauge")
            for model, adapter, resource, share in share_rows:
                lines.append(
                    'gateway_usage_share{model="%s",adapter="%s",'
                    'resource="%s"} %.4f'
                    % (escape_label(model), escape_label(adapter),
                       escape_label(resource), share))
        if scores:
            lines.append("# TYPE gateway_noisy_neighbor_score gauge")
            for (model, adapter) in sorted(scores):
                lines.append(
                    'gateway_noisy_neighbor_score{model="%s",adapter="%s"} '
                    '%.4f' % (escape_label(model), escape_label(adapter),
                              scores[(model, adapter)]))
        lines += render_keyed_family(
            "gateway_usage_would_deprioritize_total", would,
            ("model", "adapter"))
        return lines

    def debug_payload(self) -> dict:
        """The ``/debug/usage`` JSON body (also what ``tools/lig_top.py``
        renders): adapters sorted by step-seconds share, descending."""
        with self._lock:
            keys = (set(self._shares["step_seconds"]) | set(self._scores)
                    | set(self._states))
            rows = []
            for key in keys:
                model, adapter = key
                rows.append({
                    "model": model,
                    "adapter": adapter,
                    "share": {r: round(self._shares[r].get(key, 0.0), 4)
                              for r in RESOURCES},
                    "traffic_share": round(self._traffic.get(key, 0.0), 4),
                    "score": self._scores.get(key, 0.0),
                    "state": self._states.get(key, QUIET),
                    "totals": {r: round(self._totals[r].get(key, 0.0), 4)
                               for r in RESOURCES},
                })
            rows.sort(key=lambda r: -r["share"]["step_seconds"])
            return {
                "adapters": rows,
                "pool_waste": dict(self._pool_waste),
                "noisy": sorted(self._noisy_models),
                "would_deprioritize_total": self.would_deprioritize_total,
                "ticks": self.ticks,
                "config": asdict(self.cfg),
            }
