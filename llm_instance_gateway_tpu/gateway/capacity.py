"""Capacity & saturation plane: a sim-calibrated digital twin of the pool.

The KV observatory (gateway/kvobs.py) answers "where is HBM going"; this
module answers the question the roadmap's re-roling autoscaler must ask
first: **how much load can this pool still take, and when does it run
out?**  Three pieces, one ``tick()`` on the proxy's observability cadence:

- **Saturation indices.**  Per pod and per resource, a 0..1 "how close to
  the wall" index fused from the scraped families: KV-block headroom
  (``1 - free/capacity``), decode-batch occupancy (the window mean of the
  ``tpu:decode_batch_occupancy`` histogram), queue pressure
  (waiting over waiting+running), and prefill compute (the fraction of
  wall time the replica spent prefilling, from the
  ``tpu:prefill_seconds`` accumulator delta).  The pool's index per
  resource is the max over pods — saturation is a weakest-link property.

- **The twin and its forecasts.**  The scrape deltas double as
  calibration windows (``sim/calibrate.calibrate_from_observables``):
  with no TPU access the plane fits the simulator's ``LatencyModel`` from
  live traffic (or loads the committed ``TWIN_CALIBRATION.json`` via
  ``--twin-calibration``), then drives the calibrated DES
  (``sim/run.twin_knee_rate``: bisected TTFT-p95 probes) against the
  observed arrival/mix summary to find the pool's **knee rate** — the
  offered load where TTFT p95 crosses the SLO.  Headroom-at-SLO is
  ``(knee - offered)/knee``; the **time-to-breach forecast** projects the
  offered-rate trend (least-squares slope over the recent window, the
  same horizon the SLO burn windows watch) onto the knee.  A forecast
  entering the breach horizon journals a ``capacity_forecast`` event —
  the alarm that must lead the SLO fast-burn alarm (chaos
  ``saturation_ramp`` pins the lead).

- **Drift detection.**  A twin that silently diverged would forecast
  lies, so every tick compares prediction to observation — prefill
  seconds vs ``model.prefill_s(tokens)``, decode step seconds vs
  ``model.decode_s(kv, batch)``, running occupancy vs Little's law — as
  EMA-smoothed relative divergences (``gateway_twin_drift{observable}``).
  Breaching ``--twin-drift-threshold`` for ``drift_enter_ticks``
  journals a ``twin_drift`` event and marks forecasts **untrusted**:
  surfaces keep exporting but say so (``gateway_twin_trusted 0``,
  ``"trusted": false``) instead of lying, and the breach-forecast alarm
  is suppressed until the drift clears.

Mechanics mirror ``gateway/kvobs.py``: provider read outside the lock,
delta/EMA state under it, journal emits after release, exposition via
``render()`` (the ``gateway_capacity_*``/``gateway_twin_*`` families),
JSON via ``debug_payload()`` (``GET /debug/capacity``, the fleet rollup,
fast-burn black-box dumps, ``tools/capacity_report.py``).
"""

from __future__ import annotations

import time
from dataclasses import asdict, dataclass

from llm_instance_gateway_tpu import events as events_mod
from llm_instance_gateway_tpu.lockwitness import witness_lock
from llm_instance_gateway_tpu.tracing import escape_label

# Saturation resources, in render order.
RESOURCES = ("kv", "decode_slots", "queue", "prefill_compute")

# Drift observables, in render order.
DRIFT_OBSERVABLES = ("prefill_s", "decode_step_s", "occupancy")

# Sentinel for "no breach on the current trend" — Prometheus gauges need a
# number; consumers treat negative as "none" (documented in METRICS.md).
NO_BREACH = -1.0


@dataclass(frozen=True)
class CapacityConfig:
    enabled: bool = True
    # Weight of the newest window in the mix/rate/drift EMAs (1.0 = raw).
    ema_alpha: float = 0.5
    # Committed calibration artifact (--twin-calibration); empty =
    # self-calibrate from live scrape windows.
    calibration_path: str = ""
    # Relative-divergence EMA above this for drift_enter_ticks consecutive
    # ticks = drift (--twin-drift-threshold); below for drift_clear_ticks
    # = trusted again.  0.5 = predictions 50% off — far beyond fit
    # residuals, squarely "the model no longer describes this pool".
    drift_threshold: float = 0.5
    drift_enter_ticks: int = 2
    drift_clear_ticks: int = 3
    # Observation-window floor: tick() folds a new window only once this
    # much clock has passed since the last fold (calls in between return
    # immediately, pre-scrape).  Two jobs: (a) window statistics — a 5s
    # obs tick yields too few prefill completions per window for stable
    # least-squares design matrices (rank-deficient fits); 30s windows
    # calibrate cleanly and sit between the Prometheus scrape interval
    # and the SLO engine's 1m burn windows (drift still alarms within
    # 2 windows = 60s, far inside any burn horizon); (b) tick tax — the
    # fold amortizes over min_window_s/obs_tick_s cheap early-returns,
    # which is what keeps bench.py's capacity_tick_ratio under its 1.05
    # bar.  0 = fold every call (chaos and unit tests drive virtual
    # clocks through that).
    min_window_s: float = 30.0
    # Self-calibration: refit from the newest max_fit_windows whenever at
    # least min_fit_windows accumulated, every refit_every_ticks windows
    # (32 windows at the 30s floor = a refit every ~16min — calibration
    # constants move on deploys and mix shifts, not minute scale; the
    # per-window drift EMA below is what watches the twin continuously
    # and is what forces attention long before the next refit).
    min_fit_windows: int = 6
    max_fit_windows: int = 64
    refit_every_ticks: int = 32
    # Knee search cadence (DES probes are ~ms but not free) and bounds.
    forecast_every_ticks: int = 2
    slo_ttft_s: float = 0.5
    probe_duration_s: float = 4.0
    # Assumed decode slots per replica: converts the occupancy FRACTION
    # the histogram exports into the absolute batch regressor the decode
    # fit and the DES probes share.  Wrong absolute values cancel between
    # fit and probe (both use this constant), so forecasts stay honest.
    decode_slots: int = 16
    # Offered-rate trend: least-squares slope over this many windows.
    trend_window: int = 12
    # A finite time-to-breach at or under this journals capacity_forecast.
    breach_horizon_s: float = 600.0


class CapacityPlanner:
    """Thread-safe capacity plane; ``tick()`` runs on the proxy's
    observability cadence (and lazily from ``/debug/capacity``)."""

    def __init__(self, provider, cfg: CapacityConfig | None = None,
                 journal: "events_mod.EventJournal | None" = None,
                 clock=time.time):
        self.provider = provider
        self.cfg = cfg or CapacityConfig()
        self.journal = journal
        self._clock = clock
        self._lock = witness_lock("CapacityPlanner._lock")
        # Cumulative-counter memory for per-window deltas: pod -> the
        # last scrape row (a flat float tuple, _row order).
        self._prev: dict[str, tuple] = {}
        # Self-calibration window buffer (pool-level, newest last).
        self._windows: list[dict] = []
        # The twin.
        self._model = None                        # sim.core.LatencyModel
        self._model_info: dict = {"source": "none"}
        # The PREVIOUS fold's rows, kept raw (with _prev as the newest)
        # so the per-pod saturation view is derived LAZILY at
        # render/debug time (_derive_saturation): the obs tick pays only
        # the pool-window fold, not 4 rounded dicts per pod nobody may
        # read this period.
        self._rows_old: dict[str, tuple] = {}
        self._sat_dt = 0.0
        self._sat_ticks = -1                      # derive cache key
        self._pods: dict[str, dict] = {}
        self._pool_saturation: dict[str, float] = {}
        self._mix: dict[str, float] = {}          # EMA'd arrival/mix summary
        self._forecast: dict = {"knee_rps": 0.0, "offered_rps": 0.0,
                                "headroom_ratio": 1.0,
                                "time_to_breach_s": NO_BREACH,
                                "trusted": False, "breach_alarm": False}
        self._rate_hist: list[tuple[float, float]] = []
        self._drift: dict[str, float] = {}        # observable -> EMA
        self._drift_state = "ok"
        self._drift_over = 0                      # consecutive over-threshold
        self._drift_under = 0                     # consecutive under-threshold
        self.last_tick = 0.0
        self.ticks = 0
        if self.cfg.calibration_path:
            self._load_artifact(self.cfg.calibration_path)

    def _load_artifact(self, path: str) -> None:
        from llm_instance_gateway_tpu.sim import calibrate as cal

        try:
            model, art = cal.load_calibration(path)
        except (OSError, ValueError, KeyError) as e:
            # A bad artifact degrades to self-calibration, loudly.
            self._model_info = {"source": "error", "path": path,
                                "error": str(e)}
            return
        self._model = model
        self._model_info = {"source": "artifact", "path": path,
                            "artifact_source": art.get("source", ""),
                            "residuals": art.get("residuals", {}),
                            "constants": cal.model_to_dict(model)}

    # -- rollup ---------------------------------------------------------------
    def maybe_tick(self, min_interval_s: float = 1.0) -> None:
        """On-demand rollup with a floor between passes — the window
        deltas difference cumulative counters per PASS, so an unthrottled
        debug poller must not collapse every calibration window to its
        own poll period."""
        if self._clock() - self.last_tick >= min_interval_s:
            self.tick()

    # Row layout (flat numeric tuple — the scrape/fold hot path works on
    # indices, not dicts): 0 prefill_s_sum, 1 prefill_count,
    # 2 decode_s_sum, 3 decode_count, 4 occ_sum, 5 occ_count,
    # 6 prefill_tokens, 7 decode_tokens, 8 kv_capacity, 9 kv_free,
    # 10 running, 11 waiting, 12 kv_usage_pct.
    @staticmethod
    def _row(m) -> tuple:
        """One pod's scrape row.  Direct attribute reads (the Metrics
        dataclass always carries the fields); foreign metrics objects
        fall back to the getattr path."""
        prefill_tokens = decode_tokens = 0.0
        at = getattr(m, "adapter_tokens", None)
        if at:
            for key, v in at.items():
                phase = key[2]
                if phase == "prefill":
                    prefill_tokens += v
                elif phase == "decode":
                    decode_tokens += v
        try:
            waiting = m.waiting_queue_size
            if not waiting:
                waiting = m.prefill_queue_size + m.decode_queue_size
            # No float() on the fast path: the parser already delivers
            # numbers, arithmetic downstream is type-agnostic, and `or 0`
            # covers None — 6 calls/pod/fold add up at fleet width.
            return (m.prefill_seconds_sum, m.prefill_seconds_count,
                    m.decode_step_seconds_sum, m.decode_step_seconds_count,
                    m.decode_batch_occupancy_sum,
                    m.decode_batch_occupancy_count,
                    prefill_tokens, decode_tokens,
                    m.kv_tokens_capacity or 0,
                    m.kv_tokens_free or 0,
                    m.running_queue_size or 0, waiting or 0,
                    m.kv_cache_usage_percent or 0)
        except AttributeError:
            return (float(getattr(m, "prefill_seconds_sum", 0) or 0),
                    float(getattr(m, "prefill_seconds_count", 0) or 0),
                    float(getattr(m, "decode_step_seconds_sum", 0) or 0),
                    float(getattr(m, "decode_step_seconds_count", 0) or 0),
                    float(getattr(m, "decode_batch_occupancy_sum", 0) or 0),
                    float(getattr(m, "decode_batch_occupancy_count", 0) or 0),
                    prefill_tokens, decode_tokens,
                    float(getattr(m, "kv_tokens_capacity", 0) or 0),
                    float(getattr(m, "kv_tokens_free", 0) or 0),
                    float(getattr(m, "running_queue_size", 0) or 0),
                    float(getattr(m, "total_queue_size", 0) or 0),
                    float(getattr(m, "kv_cache_usage_percent", 0) or 0))

    def tick(self, now: float | None = None) -> None:
        now = self._clock() if now is None else now
        # Window floor (cfg.min_window_s): between folds the tick is a
        # clock compare — no scrape, no lock.  Unlocked read of
        # last_tick/ticks mirrors maybe_tick (the obs tick is the only
        # writer; a stale read just delays the fold one period).
        if self.ticks and now - self.last_tick < self.cfg.min_window_s:
            return
        pod_metrics = self.provider.all_pod_metrics()
        emits: list[tuple[str, dict]] = []
        with self._lock:
            dt = now - self.last_tick if self.ticks else 0.0
            self.last_tick = now
            self.ticks += 1
            window = self._fold_windows(pod_metrics, dt)
            self._refit(window)
            self._update_drift(window, emits)
            self._update_forecast(window, now, emits)
        for kind, attrs in emits:
            if self.journal is not None:
                self.journal.emit(kind, **attrs)

    # The per-tick movements below run under self._lock (called from tick).
    def _fold_windows(self, pod_metrics, dt: float) -> dict | None:
        """One fused scrape+fold pass: per-pod accumulator deltas
        (clamped per pod, so one replica's counter reset can't push a
        pool sum negative) -> ONE pool-level observation window (the
        calibration/drift input), or None without a usable window (first
        tick, clock stall, no traffic).

        The per-pod saturation view is NOT built here: the raw rows land
        in ``_prev``/``_rows_old`` and ``_derive_saturation``
        materializes the view lazily when render()/debug_payload() ask —
        the obs tick pays only the sums (the ``capacity_tick_ratio``
        bench bound)."""
        cfg = self.cfg
        row = self._row
        old = self._prev
        new: dict[str, tuple] = {}
        t_prefill_s = t_prefills = t_decode_s = t_decode_steps = 0.0
        t_occ = t_occs = t_prefill_tokens = t_decode_tokens = 0.0
        kv_used = running = waiting = 0.0
        have_prev = dt > 0
        for pm in pod_metrics:
            name = pm.pod.name
            r = row(pm.metrics)
            new[name] = r
            if have_prev:
                p = old.get(name)
                if p is not None:
                    # No-reset fast path: the monotone counts (1, 3, 5)
                    # and token sums (6, 7 — these also shrink on
                    # adapter-table eviction) only go backwards on a
                    # replica restart, so one compare chain covers all
                    # eight deltas; the per-field clamp runs only for
                    # the pod that actually reset.
                    if (r[1] >= p[1] and r[3] >= p[3] and r[5] >= p[5]
                            and r[6] >= p[6] and r[7] >= p[7]):
                        t_prefill_s += r[0] - p[0]
                        t_prefills += r[1] - p[1]
                        t_decode_s += r[2] - p[2]
                        t_decode_steps += r[3] - p[3]
                        t_occ += r[4] - p[4]
                        t_occs += r[5] - p[5]
                        t_prefill_tokens += r[6] - p[6]
                        t_decode_tokens += r[7] - p[7]
                    else:
                        d = r[0] - p[0]
                        if d > 0.0:
                            t_prefill_s += d
                        d = r[1] - p[1]
                        if d > 0.0:
                            t_prefills += d
                        d = r[2] - p[2]
                        if d > 0.0:
                            t_decode_s += d
                        d = r[3] - p[3]
                        if d > 0.0:
                            t_decode_steps += d
                        d = r[4] - p[4]
                        if d > 0.0:
                            t_occ += d
                        d = r[5] - p[5]
                        if d > 0.0:
                            t_occs += d
                        d = r[6] - p[6]
                        if d > 0.0:
                            t_prefill_tokens += d
                        d = r[7] - p[7]
                        if d > 0.0:
                            t_decode_tokens += d
            used = r[8] - r[9]
            if used > 0.0:
                kv_used += used
            running += r[10]
            waiting += r[11]
        self._prev = new
        self._rows_old = old
        self._sat_dt = dt
        self._sat_ticks = -1  # invalidate the lazy saturation cache

        if dt <= 0 or t_prefills <= 0 or t_decode_steps <= 0:
            return None
        occ_mean = (t_occ / t_occs) if t_occs > 0 else 0.0
        window = {
            "dt_s": dt,
            "n_pods": len(new),
            "offered_rps": t_prefills / dt,
            "prefill_tokens_mean": t_prefill_tokens / t_prefills,
            "prefill_s_mean": t_prefill_s / t_prefills,
            "decode_step_s_mean": t_decode_s / t_decode_steps,
            "batch_mean": occ_mean * cfg.decode_slots,
            "kv_tokens_mean": kv_used / max(1, len(new)),
            "output_tokens_mean": t_decode_tokens / t_prefills,
            "running_mean": running,
        }
        # Arrival/mix EMA — what the DES probes are driven with.
        a = cfg.ema_alpha
        for key in ("offered_rps", "prefill_tokens_mean",
                    "output_tokens_mean"):
            self._mix[key] = (a * window[key]
                             + (1 - a) * self._mix.get(key, window[key]))
        # The window dict IS the calibration record (the fitter reads
        # its five regressor keys and ignores the rest) — append it
        # as-is rather than re-keying a copy every fold.
        self._windows.append(window)
        del self._windows[:-cfg.max_fit_windows]
        return window

    def _refit(self, window: dict | None) -> None:
        """Self-calibration: fit the twin from accumulated scrape windows
        unless a committed artifact was loaded."""
        cfg = self.cfg
        # Bootstrap fast, maintain slow: an unfitted twin retries every
        # min_fit_windows windows (forecasts stay untrusted until it
        # lands); a fitted one refits on the lazy refit_every_ticks
        # cadence — the drift EMA, not the refit, tracks the twin
        # between fits.
        cadence = (min(cfg.min_fit_windows, cfg.refit_every_ticks)
                   if self._model is None else cfg.refit_every_ticks)
        if (self._model_info.get("source") == "artifact"
                or window is None
                or len(self._windows) < cfg.min_fit_windows
                or self.ticks % max(1, cadence) != 0):
            return
        from llm_instance_gateway_tpu.sim import calibrate as cal

        try:
            model, residuals = cal.calibrate_from_observables(
                list(self._windows), min_windows=cfg.min_fit_windows)
        except ValueError as e:
            # Degenerate traffic (no spread) can't identify the constants;
            # keep the previous fit and record why.
            self._model_info.setdefault("last_fit_error", "")
            self._model_info["last_fit_error"] = str(e)
            return
        self._model = model
        self._model_info = {"source": "self", "residuals": residuals,
                            "fit_tick": self.ticks,
                            "constants": cal.model_to_dict(model)}

    def _update_drift(self, window: dict | None, emits: list) -> None:
        """Predicted-vs-observed divergence per observable, EMA'd, with
        enter/clear hysteresis driving the trusted flag."""
        cfg = self.cfg
        if self._model is None or window is None:
            return
        m = self._model
        drift = self._drift
        a = cfg.ema_alpha
        b = 1 - a
        batch_mean = window["batch_mean"]
        pre_pred = m.prefill_s(window["prefill_tokens_mean"])
        dec_pred = m.decode_s(window["kv_tokens_mean"], batch_mean)
        obs = window["prefill_s_mean"]
        div = abs(pre_pred - obs) / max(abs(obs), 1e-6)
        drift["prefill_s"] = a * div + b * drift.get("prefill_s", div)
        obs = window["decode_step_s_mean"]
        div = abs(dec_pred - obs) / max(abs(obs), 1e-6)
        drift["decode_step_s"] = a * div + b * drift.get("decode_step_s",
                                                         div)
        if batch_mean < 0.9 * cfg.decode_slots:
            # Little's law: concurrency = arrival rate x service time.
            # At saturation this open-system prediction is structurally
            # wrong (queueing absorbs the excess arrivals): comparing it
            # would fire a false drift alarm exactly when the breach
            # forecast matters most, so the observable sits out and the
            # service-time ones keep watching.
            pred = window["offered_rps"] * (
                pre_pred + window["output_tokens_mean"] * dec_pred)
            obs = window["running_mean"]
            # Denominator floors at one sequence: running_mean comes
            # from instantaneous integer queue samples, so sub-1
            # concurrency deltas are sampling noise — relative to obs
            # alone an idle pool (obs 0, pred 0.3) reads as infinite
            # divergence and false-fires drift on a perfect twin.
            div = abs(pred - obs) / max(abs(obs), pred, 1.0)
            drift["occupancy"] = a * div + b * drift.get("occupancy", div)
        worst = max(drift.values(), default=0.0)
        if worst > cfg.drift_threshold:
            self._drift_over += 1
            self._drift_under = 0
            if (self._drift_state == "ok"
                    and self._drift_over >= cfg.drift_enter_ticks):
                self._drift_state = "drift"
                emits.append((events_mod.TWIN_DRIFT, {
                    "worst": round(worst, 4),
                    "threshold": cfg.drift_threshold,
                    "drift": {k: round(v, 4)
                              for k, v in self._drift.items()},
                    "tick": self.ticks}))
        else:
            self._drift_under += 1
            self._drift_over = 0
            if (self._drift_state == "drift"
                    and self._drift_under >= cfg.drift_clear_ticks):
                self._drift_state = "ok"

    def _update_forecast(self, window: dict | None, now: float,
                         emits: list) -> None:
        """Knee search (calibrated DES probes) + offered-rate trend ->
        headroom-at-SLO and time-to-breach."""
        cfg = self.cfg
        trusted = self._model is not None and self._drift_state == "ok"
        fc = dict(self._forecast)
        fc["trusted"] = trusted
        if window is not None:
            fc["offered_rps"] = round(self._mix.get("offered_rps", 0.0), 3)
            self._rate_hist.append((now, self._mix["offered_rps"]))
            del self._rate_hist[:-cfg.trend_window]
        if (self._model is not None and window is not None
                and self.ticks % cfg.forecast_every_ticks == 0):
            from llm_instance_gateway_tpu.sim import run as sim_run

            knee = sim_run.twin_knee_rate(
                self._model,
                prompt_mean=max(8.0, self._mix["prefill_tokens_mean"]),
                output_mean=max(4.0, self._mix["output_tokens_mean"]),
                slo_ttft_s=cfg.slo_ttft_s,
                decode_slots=cfg.decode_slots,
                duration_s=cfg.probe_duration_s,
            ) * max(1, window["n_pods"])
            fc["knee_rps"] = round(knee, 3)
        knee = fc.get("knee_rps", 0.0)
        offered = fc.get("offered_rps", 0.0)
        fc["headroom_ratio"] = round(
            max(0.0, (knee - offered) / knee), 4) if knee > 0 else 0.0
        fc["time_to_breach_s"] = NO_BREACH
        if knee > 0 and len(self._rate_hist) >= 3:
            slope = _lsq_slope(self._rate_hist)
            if offered >= knee:
                fc["time_to_breach_s"] = 0.0
            elif slope > 1e-9:
                fc["time_to_breach_s"] = round((knee - offered) / slope, 1)
        breach = (trusted and fc["time_to_breach_s"] != NO_BREACH
                  and fc["time_to_breach_s"] <= cfg.breach_horizon_s)
        if breach and not self._forecast.get("breach_alarm"):
            emits.append((events_mod.CAPACITY_FORECAST, {
                "time_to_breach_s": fc["time_to_breach_s"],
                "knee_rps": knee, "offered_rps": offered,
                "headroom_ratio": fc["headroom_ratio"],
                "tick": self.ticks}))
        fc["breach_alarm"] = breach
        self._forecast = fc

    def _derive_saturation(self) -> None:
        """Materialize the per-pod saturation view from the last two
        scrape rows (idempotent per tick; runs under self._lock).  This
        is the display half of the fold, paid by render()/debug readers
        instead of the obs tick."""
        if self._sat_ticks == self.ticks:
            return
        self._sat_ticks = self.ticks
        old, dt = self._rows_old, self._sat_dt
        pods: dict[str, dict] = {}
        for name, r in self._prev.items():
            occ = pc = 0.0
            if dt > 0:
                p = old.get(name)
                if p is not None:
                    d_occs = r[5] - p[5]
                    if d_occs > 0.0:
                        occ = (r[4] - p[4]) / d_occs
                        if occ < 0.0:
                            occ = 0.0
                    pc = (r[0] - p[0]) / dt
                    pc = 1.0 if pc > 1.0 else (pc if pc > 0.0 else 0.0)
            cap = r[8]
            kv = 1.0 - r[9] / cap if cap > 0.0 else r[12]
            kv = 1.0 if kv > 1.0 else (kv if kv > 0.0 else 0.0)
            wait = r[11]
            run = r[10]
            q = wait / (wait + (run if run > 1.0 else 1.0))
            sat = {"kv": round(kv, 4), "decode_slots": round(occ, 4),
                   "queue": round(q, 4), "prefill_compute": round(pc, 4)}
            pods[name] = {"saturation": sat,
                          "saturation_index": max(sat.values())}
        self._pods = pods
        self._pool_saturation = {
            res: max((p["saturation"][res] for p in pods.values()),
                     default=0.0)
            for res in RESOURCES}

    # -- export ---------------------------------------------------------------
    def render(self) -> list[str]:
        """The ``gateway_capacity_*`` / ``gateway_twin_*`` families."""
        with self._lock:
            self._derive_saturation()
            pods = {n: dict(p["saturation"]) for n, p in self._pods.items()}
            pool = dict(self._pool_saturation)
            fc = dict(self._forecast)
            drift = dict(self._drift)
        lines = []
        if pool:
            lines.append("# TYPE gateway_capacity_saturation gauge")
            for r in RESOURCES:
                lines.append('gateway_capacity_saturation{resource="%s"} %.4f'
                             % (escape_label(r), pool.get(r, 0.0)))
        if pods:
            lines.append("# TYPE gateway_capacity_pod_saturation gauge")
            for name in sorted(pods):
                for r in RESOURCES:
                    lines.append(
                        'gateway_capacity_pod_saturation{pod="%s",'
                        'resource="%s"} %.4f'
                        % (escape_label(name), escape_label(r),
                           pods[name].get(r, 0.0)))
        lines += [
            "# TYPE gateway_capacity_offered_rps gauge",
            "gateway_capacity_offered_rps %.3f" % fc["offered_rps"],
            "# TYPE gateway_capacity_knee_rps gauge",
            "gateway_capacity_knee_rps %.3f" % fc["knee_rps"],
            "# TYPE gateway_capacity_headroom_ratio gauge",
            "gateway_capacity_headroom_ratio %.4f" % fc["headroom_ratio"],
            "# TYPE gateway_capacity_time_to_breach_seconds gauge",
            "gateway_capacity_time_to_breach_seconds %.1f"
            % fc["time_to_breach_s"],
        ]
        if drift:
            lines.append("# TYPE gateway_twin_drift gauge")
            for obs_name in DRIFT_OBSERVABLES:
                if obs_name in drift:
                    lines.append('gateway_twin_drift{observable="%s"} %.4f'
                                 % (escape_label(obs_name), drift[obs_name]))
        lines += [
            "# TYPE gateway_twin_trusted gauge",
            "gateway_twin_trusted %d" % (1 if fc["trusted"] else 0),
        ]
        return lines

    def debug_payload(self) -> dict:
        """The gateway's ``/debug/capacity`` JSON body (also what
        ``tools/capacity_report.py`` and the black-box dump embed)."""
        with self._lock:
            self._derive_saturation()
            return {
                "pods": {n: dict(p) for n, p in sorted(self._pods.items())},
                "saturation": dict(self._pool_saturation),
                "mix": {k: round(v, 3) for k, v in self._mix.items()},
                "forecast": dict(self._forecast),
                "twin": {
                    "model": dict(self._model_info),
                    "drift": {k: round(v, 4)
                              for k, v in self._drift.items()},
                    "state": self._drift_state,
                    "fit_windows": len(self._windows),
                },
                "ticks": self.ticks,
                "last_tick": self.last_tick,
                "config": asdict(self.cfg),
            }


def _lsq_slope(points: list[tuple[float, float]]) -> float:
    """Least-squares slope of (t, rate) points — the offered-load trend."""
    n = len(points)
    mt = sum(t for t, _ in points) / n
    mr = sum(r for _, r in points) / n
    denom = sum((t - mt) ** 2 for t, _ in points)
    if denom <= 0:
        return 0.0
    return sum((t - mt) * (r - mr) for t, r in points) / denom
