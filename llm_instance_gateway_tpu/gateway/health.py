"""Per-replica health scoring: fuse every per-pod signal into one 0-1 score
with hysteresis, and LOG the routing decisions the score would change.

The gateway already holds rich per-replica state — scrape freshness and
failure streaks (provider), queue/KV gauges and phase-latency means
(metrics_client), and, new in this PR, per-pod upstream error/timeout and
handoff-failure streaks recorded by the proxy's data path.  Each signal
individually is too noisy to act on; fused and hysteresis-filtered they
identify the ONE replica in a pool that is quietly degrading (CaraServe's
rank-aware serving presumes exactly this attribution).

The scorer itself stays policy-free: ``note_pick`` only counts would-be
avoidance decisions (``tpu:health_would_avoid_total``).  Enforcement lives
in ``gateway/resilience.py``: with ``health_policy=log_only`` (the default)
routing stays byte-identical to the scorer-less scheduler; ``avoid``/
``strict`` read ``state()`` through the ResiliencePlane advisor and steer
picks off non-healthy replicas.

Score composition (weighted mean of components, each clamped to [0, 1]):

====================  =====================================================
``freshness``         scrape recency/failure streak from the provider
``errors``            upstream error + handoff-failure streaks (proxy)
``queue``             total queue depth vs ``queue_sat``
``kv``                1 - KV-cache usage
``latency``           pod prefill/decode means vs the pool median
====================  =====================================================

State machine per pod: ``healthy`` -> ``degraded`` -> ``unhealthy`` with
separate enter/exit thresholds AND a dwell count (``dwell_ticks``
consecutive ticks at the candidate state) so a single bad scrape never
flips a replica's state.
"""

from __future__ import annotations

import logging
import statistics
import time
from dataclasses import asdict, dataclass

from llm_instance_gateway_tpu.lockwitness import witness_lock
from llm_instance_gateway_tpu import events as events_mod
from llm_instance_gateway_tpu.tracing import escape_label, render_counter

logger = logging.getLogger(__name__)

HEALTHY, DEGRADED, UNHEALTHY = "healthy", "degraded", "unhealthy"
STATES = (HEALTHY, DEGRADED, UNHEALTHY)


@dataclass(frozen=True)
class HealthConfig:
    # Freshness: a scrape success within this window scores 1.0; failures
    # decay the component linearly, reaching 0 at scrape_streak_floor.
    stale_after_s: float = 3.0
    scrape_streak_floor: int = 5
    # Upstream error/handoff streaks: component reaches 0 at the floor.
    error_streak_floor: int = 4
    # Queue depth considered fully saturated (component 0).
    queue_sat: int = 50
    # Pod phase-mean at this multiple of the pool median scores 0.
    latency_ratio_sat: float = 4.0
    # Hysteresis: separate enter/exit thresholds per state boundary, plus
    # a dwell (consecutive ticks at the candidate state) before committing.
    # Calibration: an idle healthy replica scores ~0.95-1.0; ONE fully-bad
    # signal (error streak at floor, or a dead scrape) lands ~0.70 —
    # degraded; two bad signals land ~0.40 — unhealthy.
    degraded_enter: float = 0.75
    degraded_exit: float = 0.85
    unhealthy_enter: float = 0.45
    unhealthy_exit: float = 0.60
    dwell_ticks: int = 2
    # Component weights (normalized at use; keep them summing to 1.0 for
    # readable scores).
    w_freshness: float = 0.30
    w_errors: float = 0.30
    w_queue: float = 0.15
    w_kv: float = 0.10
    w_latency: float = 0.15


def _clamp(v: float) -> float:
    return 0.0 if v < 0.0 else (1.0 if v > 1.0 else v)


class HealthScorer:
    """Fuses per-pod signals into scores/states; all methods thread-safe.

    ``update()`` runs on the proxy's observability tick (and lazily from
    ``/debug/health``); ``record_upstream``/``record_handoff`` are called
    from the proxy's request path; ``note_pick`` from the scheduler's pick
    seam (executor threads).
    """

    def __init__(self, provider=None, cfg: HealthConfig | None = None,
                 journal: events_mod.EventJournal | None = None,
                 clock=time.time):
        self.provider = provider
        self.cfg = cfg or HealthConfig()
        self.journal = journal
        self._clock = clock
        self._lock = witness_lock("HealthScorer._lock")
        # Proxy-fed streaks + cumulative counters (per pod name).
        self._err_streak: dict[str, int] = {}
        self._handoff_streak: dict[str, int] = {}
        self.upstream_errors: dict[str, int] = {}
        self.upstream_timeouts: dict[str, int] = {}
        self.handoff_failures: dict[str, int] = {}
        # Scoring state.
        self._scores: dict[str, float] = {}
        self._components: dict[str, dict] = {}
        self._states: dict[str, str] = {}
        self._pending: dict[str, tuple[str, int]] = {}  # candidate, streak
        # Cached non-healthy set for the pick seam (rebuilt in update()).
        self._non_healthy: frozenset = frozenset()
        self.last_update = 0.0
        # Log-only scheduler hook.
        self.would_avoid_total = 0
        self.would_avoid: dict[str, int] = {}

    # -- request-path feeds --------------------------------------------------
    def record_upstream(self, pod_name: str, ok: bool,
                        timeout: bool = False) -> None:
        """One upstream outcome for ``pod_name`` (success resets the
        streak; failures extend it and bump the cumulative counters)."""
        with self._lock:
            if ok:
                self._err_streak[pod_name] = 0
                return
            self._err_streak[pod_name] = self._err_streak.get(pod_name, 0) + 1
            self.upstream_errors[pod_name] = (
                self.upstream_errors.get(pod_name, 0) + 1)
            if timeout:
                self.upstream_timeouts[pod_name] = (
                    self.upstream_timeouts.get(pod_name, 0) + 1)

    def record_handoff(self, pod_name: str, ok: bool) -> None:
        """One disaggregation-hop outcome attributed to ``pod_name``."""
        with self._lock:
            if ok:
                self._handoff_streak[pod_name] = 0
                return
            self._handoff_streak[pod_name] = (
                self._handoff_streak.get(pod_name, 0) + 1)
            self.handoff_failures[pod_name] = (
                self.handoff_failures.get(pod_name, 0) + 1)

    # -- scoring -------------------------------------------------------------
    def _freshness(self, pod_name: str, scrape: dict, now: float) -> float:
        info = scrape.get(pod_name)
        if info is None:
            return 1.0  # providers without scrape tracking: innocent
        last_ok, streak = info
        if streak:
            return _clamp(1.0 - streak / self.cfg.scrape_streak_floor)
        if last_ok is not None and now - last_ok > self.cfg.stale_after_s:
            # No recorded failures but the scrape loop itself stalled —
            # half-credit: the data is stale but the pod may be fine.
            return 0.5
        return 1.0

    def _latency(self, m, medians: dict) -> float:
        """Pod phase means vs the pool median; no samples = no penalty."""
        worst = 1.0
        for attr, median in medians.items():
            mean = getattr(m, attr, 0.0)
            if mean <= 0.0 or median <= 0.0:
                continue
            ratio = mean / median
            comp = _clamp(1.0 - (ratio - 1.0)
                          / max(1e-9, self.cfg.latency_ratio_sat - 1.0))
            worst = min(worst, comp)
        return worst

    def maybe_update(self, min_interval_s: float = 1.0) -> None:
        """On-demand scoring with a floor between passes.  The dwell-tick
        hysteresis is defined in UPDATE PASSES, so an unthrottled debug
        poller would commit state transitions at its own poll rate instead
        of the configured cadence."""
        if self._clock() - self.last_update >= min_interval_s:
            self.update()

    def update(self, now: float | None = None) -> None:
        """Recompute every pod's score and advance the state machines."""
        now = self._clock() if now is None else now
        self.last_update = now
        provider = self.provider
        pods = provider.all_pod_metrics() if provider is not None else []
        scrape_fn = getattr(provider, "scrape_health", None)
        scrape = scrape_fn() if scrape_fn is not None else {}
        medians = {}
        for attr in ("prefill_seconds_mean", "decode_step_seconds_mean"):
            vals = [getattr(pm.metrics, attr, 0.0) for pm in pods]
            vals = [v for v in vals if v > 0.0]
            if vals:
                medians[attr] = statistics.median(vals)
        cfg = self.cfg
        w_total = (cfg.w_freshness + cfg.w_errors + cfg.w_queue + cfg.w_kv
                   + cfg.w_latency)
        transitions = []
        with self._lock:
            live = set()
            for pm in pods:
                name = pm.pod.name
                live.add(name)
                m = pm.metrics
                streak = max(self._err_streak.get(name, 0),
                             self._handoff_streak.get(name, 0))
                comp = {
                    "freshness": self._freshness(name, scrape, now),
                    "errors": _clamp(
                        1.0 - streak / cfg.error_streak_floor),
                    "queue": _clamp(
                        1.0 - m.total_queue_size / max(1, cfg.queue_sat)),
                    "kv": _clamp(1.0 - m.kv_cache_usage_percent),
                    "latency": self._latency(m, medians),
                }
                score = (cfg.w_freshness * comp["freshness"]
                         + cfg.w_errors * comp["errors"]
                         + cfg.w_queue * comp["queue"]
                         + cfg.w_kv * comp["kv"]
                         + cfg.w_latency * comp["latency"]) / w_total
                self._scores[name] = round(score, 4)
                self._components[name] = {k: round(v, 4)
                                          for k, v in comp.items()}
                t = self._advance(name, score)
                if t is not None:
                    transitions.append(t)
            # Pods that left the pool drop ALL their state — a name reused
            # by a fresh replica must not inherit an unhealthy verdict, and
            # the cumulative per-pod counters must not grow (and keep
            # emitting exposition lines) for every pod name k8s churn ever
            # produced.
            for table in (self._scores, self._components, self._states,
                          self._pending, self._err_streak,
                          self._handoff_streak, self.upstream_errors,
                          self.upstream_timeouts, self.handoff_failures,
                          self.would_avoid):
                for name in [n for n in table if n not in live]:
                    del table[name]
            self._non_healthy = frozenset(
                n for n, s in self._states.items() if s != HEALTHY)
        for name, frm, to, score in transitions:
            log = logger.warning if to != HEALTHY else logger.info
            log("pod %s health: %s -> %s (score %.3f)", name, frm, to, score)
            if self.journal is not None:
                self.journal.emit(events_mod.HEALTH_TRANSITION, pod=name,
                                  frm=frm, to=to, score=round(score, 4))

    def _target_state(self, score: float, cur: str) -> str:
        cfg = self.cfg
        if cur == HEALTHY:
            if score < cfg.unhealthy_enter:
                return UNHEALTHY
            if score < cfg.degraded_enter:
                return DEGRADED
            return HEALTHY
        if cur == DEGRADED:
            if score < cfg.unhealthy_enter:
                return UNHEALTHY
            if score > cfg.degraded_exit:
                return HEALTHY
            return DEGRADED
        # UNHEALTHY
        if score > cfg.unhealthy_exit:
            return HEALTHY if score > cfg.degraded_exit else DEGRADED
        return UNHEALTHY

    def _advance(self, name: str, score: float):
        """Dwell-filtered transition; returns (name, frm, to, score) when a
        transition commits.  Caller holds the lock."""
        cur = self._states.get(name, HEALTHY)
        want = self._target_state(score, cur)
        if want == cur:
            self._pending.pop(name, None)
            return None
        cand, streak = self._pending.get(name, (want, 0))
        streak = streak + 1 if cand == want else 1
        if streak >= self.cfg.dwell_ticks:
            self._states[name] = want
            self._pending.pop(name, None)
            return (name, cur, want, score)
        self._pending[name] = (want, streak)
        return None

    # -- read surface --------------------------------------------------------
    def score(self, pod_name: str) -> float | None:
        with self._lock:
            return self._scores.get(pod_name)

    def state(self, pod_name: str) -> str:
        with self._lock:
            return self._states.get(pod_name, HEALTHY)

    def non_healthy(self) -> frozenset:
        """Pods currently degraded/unhealthy.  Returns the cached
        frozenset maintained by ``update()`` — the enforcing pick seam
        reads this per request, and a rebuild (or even a lock) per pick
        would bust the <5% enforcement budget.  States only change inside
        ``update()``, so the cache cannot go stale between ticks."""
        return self._non_healthy

    def note_pick(self, pod_name: str) -> None:
        """Scheduler pick seam: count (and debug-log) picks landing on a
        non-healthy replica.  Must never influence the pick — no RNG, no
        exceptions, no filtering — so ``health_policy=log_only`` routing
        stays byte-identical (enforcement is ``filter_by_policy``'s job,
        upstream of the draw)."""
        with self._lock:
            st = self._states.get(pod_name, HEALTHY)
            if st == HEALTHY:
                return
            self.would_avoid_total += 1
            self.would_avoid[pod_name] = self.would_avoid.get(pod_name, 0) + 1
            n = self.would_avoid[pod_name]
        logger.debug("health: pick of %s (state=%s) counted as would-avoid "
                     "(%d so far)", pod_name, st, n)

    # -- export --------------------------------------------------------------
    def render(self) -> list[str]:
        with self._lock:
            scores = dict(self._scores)
            states = {n: self._states.get(n, HEALTHY) for n in scores}
            errors = dict(self.upstream_errors)
            timeouts = dict(self.upstream_timeouts)
            handoffs = dict(self.handoff_failures)
            avoid = dict(self.would_avoid)
        lines = []
        if scores:
            lines.append("# TYPE gateway_pod_health_score gauge")
            for pod in sorted(scores):
                lines.append(
                    'gateway_pod_health_score{pod="%s"} %.4f'
                    % (escape_label(pod), scores[pod]))
            lines.append("# TYPE gateway_pod_health_state gauge")
            for pod in sorted(states):
                lines.append(
                    'gateway_pod_health_state{pod="%s",state="%s"} 1'
                    % (escape_label(pod), escape_label(states[pod])))
        lines += render_counter("gateway_upstream_errors_total", errors,
                                "pod")
        lines += render_counter("gateway_upstream_timeouts_total", timeouts,
                                "pod")
        lines += render_counter("gateway_handoff_failures_total", handoffs,
                                "pod")
        lines += render_counter("tpu:health_would_avoid_total", avoid, "pod")
        return lines

    def debug_payload(self) -> dict:
        """The ``/debug/health`` JSON body."""
        with self._lock:
            pods = {}
            for name in sorted(self._scores):
                pods[name] = {
                    "score": self._scores[name],
                    "state": self._states.get(name, HEALTHY),
                    "components": self._components.get(name, {}),
                    "upstream_error_streak": self._err_streak.get(name, 0),
                    "handoff_failure_streak":
                        self._handoff_streak.get(name, 0),
                    "upstream_errors": self.upstream_errors.get(name, 0),
                    "upstream_timeouts": self.upstream_timeouts.get(name, 0),
                    "handoff_failures": self.handoff_failures.get(name, 0),
                    "would_avoid": self.would_avoid.get(name, 0),
                }
            return {
                "pods": pods,
                "would_avoid_total": self.would_avoid_total,
                "config": asdict(self.cfg),
            }
