"""In-memory gateway state: pool singleton, model routing table, pod membership.

Parity: reference ``pkg/ext-proc/backend/datastore.go:13-105`` —
``K8sDatastore`` with an RWMutex'd pool, a sync.Map of InferenceModels keyed by
ModelName, a sync.Map of Pods, ``RandomWeightedDraw`` for traffic splitting and
``IsCritical``.  Python port uses a single lock (the GIL makes per-field
locks unnecessary for our access pattern) and ``random.Random`` seeded per-draw
like the reference's nanosecond-seeded draw (datastore.go:81-84).
"""

from __future__ import annotations

import random
import time
from typing import Iterable

from llm_instance_gateway_tpu.lockwitness import witness_rlock
from llm_instance_gateway_tpu.api.v1alpha1 import (
    Criticality,
    InferenceModel,
    InferencePool,
)
from llm_instance_gateway_tpu.gateway.types import Pod


class Datastore:
    """Thread-safe cache of pool/models/pods consumed by scheduler + handlers."""

    def __init__(self, pods: Iterable[Pod] = ()):  # WithPods test option (:37-44)
        self._lock = witness_rlock("Datastore._lock")
        self._pool: InferencePool | None = None
        self._models: dict[str, InferenceModel] = {}
        self._pods: dict[str, Pod] = {p.name: p for p in pods}

    # -- pool (datastore.go:46-68) -----------------------------------------
    def set_pool(self, pool: InferencePool) -> None:
        with self._lock:
            self._pool = pool

    def get_pool(self) -> InferencePool:
        with self._lock:
            if self._pool is None:
                raise LookupError(
                    "InferencePool not initialized yet"
                )  # parity: getInferencePool error
            return self._pool

    def has_synced_pool(self) -> bool:
        with self._lock:
            return self._pool is not None

    # -- models (datastore.go:70-76) ---------------------------------------
    def store_model(self, model: InferenceModel) -> None:
        with self._lock:
            self._models[model.spec.model_name] = model

    def delete_model(self, model_name: str) -> None:
        with self._lock:
            self._models.pop(model_name, None)

    def fetch_model(self, model_name: str) -> InferenceModel | None:
        with self._lock:
            return self._models.get(model_name)

    def all_models(self) -> list[InferenceModel]:
        with self._lock:
            return list(self._models.values())

    # -- pods --------------------------------------------------------------
    def store_pod(self, pod: Pod) -> None:
        with self._lock:
            self._pods[pod.name] = pod

    def delete_pod(self, name: str) -> None:
        with self._lock:
            self._pods.pop(name, None)

    def get_pod(self, name: str) -> Pod | None:
        with self._lock:
            return self._pods.get(name)

    def all_pods(self) -> list[Pod]:
        with self._lock:
            return list(self._pods.values())

    def pod_names(self) -> set[str]:
        with self._lock:
            return set(self._pods)


def random_weighted_draw(
    model: InferenceModel, seed: int | None = None
) -> str:
    """Pick a target model by relative weight (datastore.go:78-98).

    Returns the chosen target model name, or the logical model name itself when
    no targets are configured (reference request.go:47-50 falls back to the
    request model when TargetModels is empty).
    """
    targets = model.spec.target_models
    if not targets:
        return model.spec.model_name
    rng = random.Random(seed if seed is not None else time.time_ns())
    total = sum(t.weight for t in targets)
    if total <= 0:
        return targets[0].name  # all-zero weights: deterministic, don't crash
    point = rng.randint(1, total)
    acc = 0
    for t in targets:
        acc += t.weight
        if point <= acc:
            return t.name
    return targets[-1].name  # unreachable; defensive


def is_critical(model: InferenceModel | None) -> bool:
    """datastore.go:100-105: nil-safe criticality check."""
    return model is not None and model.spec.criticality is Criticality.CRITICAL


def resolve_adapter_artifact(model: InferenceModel, target_name: str) -> str | None:
    """TPU addition: artifact for the drawn target, for sidecar-free hot-swap."""
    for t in model.spec.target_models:
        if t.name == target_name:
            return t.adapter_artifact
    return None
