"""Metrics provider: the gateway's live state plane.

Parity: reference ``pkg/ext-proc/backend/provider.go`` — a concurrent map of
``PodMetrics`` refreshed by two loops: pod membership from the datastore
(default every 10 s) and metrics scrapes (default every 50 ms, 5 s fetch
timeout, parallel per-pod fan-out, errors aggregated and non-fatal so stale
metrics persist; provider.go:60-179).  A debug dump loop logs all metrics at
debug verbosity every 5 s (provider.go:91-98).

The scheduler reads ``all_pod_metrics()`` — an O(pods) snapshot with no I/O on
the request path (SURVEY.md §3.2).
"""

from __future__ import annotations

import concurrent.futures as futures
import logging
import threading
import time

from llm_instance_gateway_tpu.lockwitness import witness_rlock
from llm_instance_gateway_tpu import events as events_mod
from llm_instance_gateway_tpu.gateway.datastore import Datastore
from llm_instance_gateway_tpu.gateway.metrics_client import fetch_all
from llm_instance_gateway_tpu.gateway.types import Metrics, Pod, PodMetrics

logger = logging.getLogger(__name__)

FETCH_METRICS_TIMEOUT_S = 5.0  # provider.go:14
# Scrape-failure events are throttled: first failure of a streak, then
# every Nth — a pod that is down for minutes must not fill the journal.
SCRAPE_EVENT_EVERY = 10


class Provider:
    def __init__(self, metrics_client, datastore: Datastore, max_fetch_workers: int = 32):
        self._client = metrics_client
        self._datastore = datastore
        self._metrics: dict[str, PodMetrics] = {}
        self._lock = witness_rlock("Provider._lock")
        self._stop = threading.Event()
        self._threads: list[threading.Thread] = []
        self._executor = futures.ThreadPoolExecutor(
            max_workers=max_fetch_workers, thread_name_prefix="metrics-fetch"
        )
        # Monotonic snapshot version: bumped on every state change so
        # consumers (the native scheduler's array cache) can reuse flattened
        # views between refreshes instead of re-marshalling per request.
        self.version = 0
        # Per-pod scrape freshness (health-scoring observable): last
        # successful scrape wall time + current consecutive-failure streak.
        # The proxy sets ``journal`` so failure streaks land in the flight
        # recorder (throttled).
        self.journal: events_mod.EventJournal | None = None
        self._scrape_ok_ts: dict[str, float] = {}
        self._scrape_fail_streak: dict[str, int] = {}

    # -- snapshot accessors (provider.go:34-58) ----------------------------
    def all_pod_metrics(self) -> list[PodMetrics]:
        with self._lock:
            return list(self._metrics.values())

    def snapshot(self) -> tuple[int, list[PodMetrics]]:
        """(version, pods) read atomically — consumers caching flattened
        views must take both under the same lock or a concurrent refresh can
        tag stale arrays with a newer version."""
        with self._lock:
            return self.version, list(self._metrics.values())

    def get_pod_metrics(self, pod_name: str) -> PodMetrics | None:
        with self._lock:
            return self._metrics.get(pod_name)

    def update_pod_metrics(self, pod: Pod, metrics: Metrics) -> None:
        with self._lock:
            self._metrics[pod.name] = PodMetrics(pod=pod, metrics=metrics)
            self.version += 1

    # -- lifecycle (provider.go:60-101) ------------------------------------
    def init(
        self,
        refresh_pods_interval_s: float = 10.0,
        refresh_metrics_interval_s: float = 0.05,
        debug_dump_interval_s: float = 5.0,
    ) -> None:
        """Synchronous first refresh, then background refresh loops."""
        self.refresh_pods_once()
        self.refresh_metrics_once()

        def loop(interval: float, fn) -> None:
            while not self._stop.wait(interval):
                try:
                    fn()
                except Exception:
                    logger.exception("refresh loop error")

        for interval, fn in (
            (refresh_pods_interval_s, self.refresh_pods_once),
            (refresh_metrics_interval_s, self.refresh_metrics_once),
            (debug_dump_interval_s, self._debug_dump),
        ):
            t = threading.Thread(target=loop, args=(interval, fn), daemon=True)
            t.start()
            self._threads.append(t)

    def stop(self) -> None:
        self._stop.set()
        self._executor.shutdown(wait=False, cancel_futures=True)

    # -- refresh bodies ----------------------------------------------------
    def refresh_pods_once(self) -> None:
        """Merge datastore pod membership into the metrics map (provider.go:105-132).

        New pods get zeroed metrics (scraped next tick); removed pods drop out.
        """
        want = {p.name: p for p in self._datastore.all_pods()}
        with self._lock:
            for name, pod in want.items():
                if name not in self._metrics:
                    self._metrics[name] = PodMetrics(pod=pod, metrics=Metrics())
                elif self._metrics[name].pod != pod:
                    self._metrics[name] = PodMetrics(
                        pod=pod, metrics=self._metrics[name].metrics
                    )
            for name in list(self._metrics):
                if name not in want:
                    del self._metrics[name]
            self.version += 1

    def refresh_metrics_once(self) -> list[str]:
        """Parallel scrape of every pod (provider.go:134-179); returns errors."""
        snapshot = self.all_pod_metrics()
        results, errs = fetch_all(
            self._client,
            snapshot,
            timeout_s=FETCH_METRICS_TIMEOUT_S,
            executor=self._executor,
        )
        now = time.time()
        failures: list[tuple[str, int]] = []
        with self._lock:
            for pm in snapshot:
                name = pm.pod.name
                updated = results.get(name)
                if updated is not None and name in self._metrics:
                    self._metrics[name] = PodMetrics(pod=pm.pod, metrics=updated)
                # Freshness bookkeeping: a pod missing from ``results``
                # failed or timed out this round (stale metrics persist,
                # but the health scorer must know they are stale).
                if updated is not None:
                    self._scrape_ok_ts[name] = now
                    self._scrape_fail_streak[name] = 0
                else:
                    streak = self._scrape_fail_streak.get(name, 0) + 1
                    self._scrape_fail_streak[name] = streak
                    if streak == 1 or streak % SCRAPE_EVENT_EVERY == 0:
                        failures.append((name, streak))
            for table in (self._scrape_ok_ts, self._scrape_fail_streak):
                for name in [n for n in table if n not in self._metrics]:
                    del table[name]
            self.version += 1
        journal = self.journal
        if journal is not None:
            for name, streak in failures:
                journal.emit(events_mod.SCRAPE_FAILURE, pod=name,
                             streak=streak)
        if errs:
            logger.debug("metrics refresh errors: %s", "; ".join(errs))
        return errs

    def scrape_health(self) -> dict[str, tuple[float | None, int]]:
        """pod name -> (last successful scrape wall time or None, current
        consecutive-failure streak) — the freshness component the health
        scorer fuses."""
        with self._lock:
            return {
                name: (self._scrape_ok_ts.get(name),
                       self._scrape_fail_streak.get(name, 0))
                for name in self._metrics
            }

    def _debug_dump(self) -> None:
        logger.debug("===DEBUG: current pods and metrics: %s", self.all_pod_metrics())


class StaticProvider:
    """Provider over a fixed metrics list — for tests and the simulator."""

    def __init__(self, pod_metrics: list[PodMetrics]):
        self._pm = pod_metrics

    def all_pod_metrics(self) -> list[PodMetrics]:
        return list(self._pm)
