"""Adapter residency & placement plane: tiered-LoRA orchestration at pool
scale (ROADMAP item 2 — MinT / InfiniLoRA-style disaggregated multi-LoRA
placement, arxiv 2605.13779 / 2604.07173).

At thousands-of-adapters scale only a sliver of the adapter universe fits
TPU-slot-resident; the rest must live down a residency ladder the engine
now implements (``server/lora_manager.py``: TPU slot -> host RAM -> Orbax
checkpoint, with per-tier load latency exported).  This module is the
gateway-side brain over that ladder — the ``PlacementPlanner``:

- **Inputs** (fused on the observability tick): the PR-5 usage plane's
  EMA consumption shares (``gateway/usage.py`` — who is actually hot), the
  LoRA-affinity scorer's running/waiting split (a WAITING adapter means
  parked requests are already paying its cold start), per-pod residency
  tiers scraped from ``tpu:adapter_residency_info``, and per-pod load.

- **Cost model**: a cold (disk-tier) hit costs ``disk_load_s`` of extra
  TTFT; a host-tier hit costs ``host_load_s``; a slot hit costs nothing.
  An adapter's expected cold-start tax is its traffic share times the
  load latency of its best tier — the planner spends its bounded action
  budget where that tax is largest (prefetch/migrate) and reclaims
  capacity where it rounds to zero (demote idle slots, evict idle host
  entries).

- **Decisions** are emitted as a plan, not executed here: the
  ``lora_sidecar``'s ``--planner-url`` mode polls ``/debug/placement``
  and drives its replica over the existing adapter wire
  (``/v1/load|demote|prefetch|evict_lora_adapter``).  The planner is
  therefore a pure control plane — restartable, and its decision core
  (``plan()``) is a pure function of its inputs, which is what the sim
  validates before any live rollout (``sim/run.py`` placement scenario).

- **Routing seam**: ``placement_mode=log_only`` (default) only counts
  picks that landed on a pod where the adapter was NOT RAM-resident while
  a resident replica existed (``gateway_placement_would_steer_total``) —
  routing stays byte-identical, pinned by same-RNG diff tests.
  ``prefer_resident`` promotes the seam: ``filter_by_placement``
  (scheduling/scheduler.py, mirrored natively in scheduler.cc) narrows
  survivor sets to slot/host-resident pods with the usual counted
  last-resort escape hatch.
"""

from __future__ import annotations

import time
from dataclasses import asdict, dataclass

from llm_instance_gateway_tpu.lockwitness import witness_lock
from llm_instance_gateway_tpu import events as events_mod
from llm_instance_gateway_tpu.tracing import escape_label, render_keyed_family

# Tier names mirror server/lora_manager.py's RESIDENCY_TIERS — duplicated
# (not imported) so the gateway process never pulls the server's jax stack.
TIER_SLOT, TIER_HOST, TIER_DISK = "slot", "host", "disk"

LOG_ONLY, PREFER_RESIDENT = "log_only", "prefer_resident"
PLACEMENT_MODES = (LOG_ONLY, PREFER_RESIDENT)

# Decision actions (the sidecar's executable verbs; ``migrate`` executes
# as a load on the target replica — promotion from host when prefetched,
# Orbax restore otherwise).
DEMOTE, EVICT, PREFETCH, MIGRATE = "demote", "evict", "prefetch", "migrate"


@dataclass(frozen=True)
class PlacementConfig:
    """Knobs for the placement plane (flags: ``add_placement_args``)."""

    # log_only: plan + count, routing untouched (byte-identical).
    # prefer_resident: picks narrow to pods where the adapter is slot- or
    # host-resident, with a counted escape hatch.
    mode: str = LOG_ONLY
    # An adapter whose pool step-seconds share is below this counts as
    # idle for demotion/eviction dwell purposes.
    idle_share: float = 0.005
    # Consecutive idle ticks before a slot-resident adapter demotes to
    # host RAM, and before a host-resident one evicts to disk.  Demotion
    # is cheap to undo (one device put), eviction costs a full restore —
    # hence the longer dwell.
    demote_idle_ticks: int = 3
    evict_idle_ticks: int = 6
    # Share at which an adapter earns host-RAM residency on EVERY replica
    # (head replication, the MinT shape: the Zipf head is hot enough that
    # any replica may be asked to serve it, and a host copy turns the
    # cold-start disk restore into a cheap promote wherever the pick
    # lands — the filter tree legitimately routes a hot adapter off its
    # home when the home is the busiest pod).  Below the bar, a WAITING
    # adapter still prefetches onto one replica — parked requests are
    # already paying the cold start.
    prefetch_min_share: float = 0.02
    # Share at which a hot adapter resident only on overloaded replicas
    # is replicated toward an under-utilized one.
    migrate_min_share: float = 0.25
    # A replica counts overloaded when its total queue exceeds this
    # factor x the pool median (and under-utilized below 1/factor).
    hot_queue_factor: float = 2.0
    # Decision budget per tick: a planner must never emit a load storm
    # (each prefetch is an Orbax restore on the target replica).
    max_actions_per_tick: int = 8
    # Cost-model constants: estimated extra TTFT for a cold (disk) hit
    # and a host-tier hit.  Calibrated defaults come from the engine's
    # tpu:adapter_load_seconds exposition once real loads flow.
    disk_load_s: float = 0.5
    host_load_s: float = 0.05
    # Checkpoint path template for prefetch decisions: ``{root}/{name}``.
    # Empty: decisions carry no path and the sidecar resolves the source
    # from its own config registry.
    checkpoint_root: str = ""

    def __post_init__(self):
        if self.mode not in PLACEMENT_MODES:
            raise ValueError(
                f"placement mode {self.mode!r} not in {PLACEMENT_MODES}")
        if (self.demote_idle_ticks < 1 or self.evict_idle_ticks < 1
                or self.max_actions_per_tick < 1):
            raise ValueError("placement dwell/budget knobs must be >= 1")
        if self.disk_load_s < 0 or self.host_load_s < 0:
            raise ValueError("placement load-cost constants must be >= 0")


class PlacementPlanner:
    """Gateway-side residency orchestrator + the scheduler's
    ``placement_advisor`` seam.  Thread-safe: the pick seam reads cached
    frozensets, the observability tick rebuilds them."""

    def __init__(self, provider, usage=None,
                 cfg: PlacementConfig | None = None,
                 journal: events_mod.EventJournal | None = None,
                 clock=time.time):
        self.provider = provider
        self.usage = usage          # gateway.usage.UsageRollup (may be None)
        self.cfg = cfg or PlacementConfig()
        self.journal = journal
        self._clock = clock
        self._lock = witness_lock("PlacementPlanner._lock")
        # Tick-computed state:
        self._idle: dict[tuple[str, str], int] = {}  # (pod, adapter) -> ticks
        self._decisions: list[dict] = []     # latest tick's plan
        self._residency: dict[str, dict] = {}  # pod -> {adapter: tier}
        # adapter -> frozenset(pod names) where slot- or host-resident —
        # the pick seam's mark set, swapped whole per tick so reads are
        # lock-free (same shape as usage._noisy_models).
        self._resident_pods: dict[str, frozenset] = {}
        # adapter -> (slot-tier pods, host-tier pods): the two-level mark
        # set prefer_resident steering uses — a slot pick costs nothing,
        # a host pick pays the promote, so slot-resident candidates win
        # ties over host-resident ones.  ``_tier_pods`` is the MERGED view
        # (local scrape + statebus peer overlay), swapped whole per
        # rebuild so its identity doubles as the native scheduler's
        # staleness signal; ``_local_tier_pods`` is what this replica
        # scraped itself — the statebus publishes only that.
        self._tier_pods: dict[str, tuple] = {}
        self._local_tier_pods: dict[str, tuple] = {}
        self._remote_tier_pods: dict[str, tuple] = {}
        self._have_residency = False
        self._have_local_residency = False
        self._model_of: dict[str, str] = {}  # adapter -> model (usage keys)
        # Exported counters.
        self.decisions_total: dict[tuple, int] = {}
        self.would_steer_total = 0
        self.wrong_tier_total = 0
        self.escape_total = 0
        self.ticks = 0
        self.last_tick = 0.0

    # -- config ------------------------------------------------------------
    @property
    def mode(self) -> str:
        return self.cfg.mode

    def update_config(self, cfg: PlacementConfig) -> None:
        if cfg != self.cfg:
            self.cfg = cfg

    # -- scheduler advisor seam --------------------------------------------
    def resident_pods(self, adapter: str | None) -> frozenset | None:
        """Pods where ``adapter`` is slot- or host-resident; None when the
        pool exports no residency data at all (foreign servers — the
        filter then has nothing to steer on and stays inert)."""
        if adapter is None or not self._have_residency:
            return None
        return self._resident_pods.get(adapter, frozenset())

    def resident_tiers(self, adapter: str | None) -> tuple | None:
        """(slot-tier pods, host-tier pods) for ``adapter`` — the two-
        level mark set ``filter_by_placement`` narrows on; None when no
        residency data exists."""
        if adapter is None or not self._have_residency:
            return None
        return self._tier_pods.get(adapter, (frozenset(), frozenset()))

    def resident_map(self) -> dict[str, tuple] | None:
        """The whole adapter -> (slot pods, host pods) map (swapped per
        tick, so identity doubles as a staleness signal for the native
        scheduler's snapshot marshal); None when the pool exports no
        residency."""
        if not self._have_residency:
            return None
        return self._tier_pods

    def local_resident_map(self) -> dict[str, tuple] | None:
        """This replica's OWN scraped adapter -> (slot pods, host pods)
        map, peer overlay excluded — what the statebus publishes."""
        if not self._have_local_residency:
            return None
        return self._local_tier_pods

    def set_remote_resident(self, rmap: dict[str, tuple]) -> None:
        """Statebus seam: replace the peer-derived residency overlay
        (adapter -> (slot pods, host pods); empty = local-only fallback).
        Peer gateways fronting the same pool scrape the same replicas, so
        the overlay normally agrees with the local view — its value is
        covering the window where THIS replica's scrape is stale or a pod
        is only reachable from a peer.  The merged map is swapped whole
        so the native snapshot re-marshals."""
        with self._lock:
            self._remote_tier_pods = dict(rmap)
            self._rebuild_merged_locked()

    def _rebuild_merged_locked(self) -> None:
        """Fold the local scrape and the peer overlay into the maps the
        pick seam reads (caller holds ``_lock``)."""
        if not self._remote_tier_pods:
            merged = dict(self._local_tier_pods)
        else:
            merged = {}
            for a in set(self._local_tier_pods) | set(
                    self._remote_tier_pods):
                ls, lh = self._local_tier_pods.get(
                    a, (frozenset(), frozenset()))
                rs, rh = self._remote_tier_pods.get(
                    a, (frozenset(), frozenset()))
                slot = frozenset(ls) | frozenset(rs)
                # Slot beats host: a pod in both tiers counts slot.
                host = (frozenset(lh) | frozenset(rh)) - slot
                merged[a] = (slot, host)
        self._tier_pods = merged
        self._resident_pods = {a: s | h for a, (s, h) in merged.items()}
        self._have_residency = (self._have_local_residency
                                or bool(self._remote_tier_pods))

    def note_pick(self, pod_name: str, adapter: str | None) -> None:
        """Count picks that landed OFF a resident replica while one
        existed.  Never influences the pick — no RNG, no filtering — so
        log_only keeps routing byte-identical (same-RNG diff tests).  In
        prefer_resident the count is the wrong-tier-pick observable the
        cold_start_storm chaos scenario pins at zero (escapes excepted,
        counted separately)."""
        if adapter is None or not self._have_residency:
            return
        resident = self._resident_pods.get(adapter)
        if not resident or pod_name in resident:
            return
        with self._lock:
            if self.cfg.mode == PREFER_RESIDENT:
                self.wrong_tier_total += 1
            else:
                self.would_steer_total += 1

    def note_placement_escape(self) -> None:
        """No candidate held the adapter in a RAM tier: the pick proceeded
        over the full set (the counted last-resort hatch, mirroring the
        health/fairness filters)."""
        with self._lock:
            self.escape_total += 1
        if self.journal is not None:
            self.journal.emit(events_mod.PLACEMENT_ESCAPE,
                              mode=self.cfg.mode)

    # -- decision core (pure; sim-validated) --------------------------------
    def plan(self, shares: dict[str, float], waiting: dict[str, set],
             residency: dict[str, dict], pod_load: dict[str, int],
             idle: dict[tuple[str, str], int]) -> list[dict]:
        """Compute one tick's decisions from explicit inputs.

        ``shares``: adapter -> pool step-seconds share (EMA).
        ``waiting``: adapter -> pods where requests are parked on it.
        ``residency``: pod -> {adapter: tier}.
        ``pod_load``: pod -> total queue depth.
        ``idle``: (pod, adapter) -> consecutive idle ticks (maintained by
        the caller; ``tick()`` owns the live copy, the sim its own).

        Pure function of its arguments — ``sim/run.py`` drives exactly
        this method against simulated state, so the policy that deploys
        is the policy that was validated.
        """
        cfg = self.cfg
        budget = cfg.max_actions_per_tick
        decisions: list[dict] = []

        def emit(action: str, pod: str, adapter: str, reason: str,
                 path: str = "") -> bool:
            if len(decisions) >= budget:
                return False
            decisions.append({
                "action": action, "pod": pod, "adapter": adapter,
                "path": path or (f"{cfg.checkpoint_root.rstrip('/')}/{adapter}"
                                 if cfg.checkpoint_root else ""),
                "reason": reason,
            })
            return True

        resident_anywhere: dict[str, set] = {}
        for pod, tiers in residency.items():
            for adapter in tiers:
                resident_anywhere.setdefault(adapter, set()).add(pod)
        loads = sorted(pod_load.values())
        median_load = loads[len(loads) // 2] if loads else 0

        # 1) Prefetch, two regimes:
        #    (a) head replication — adapters above prefetch_min_share stay
        #        RAM-resident on EVERY replica (hottest first), so wherever
        #        the load-aware tree lands their next request the cold
        #        start is a cheap host promote, never a disk restore;
        #    (b) waiting rescue — a colder adapter with parked requests
        #        prefetches onto the least-loaded replica (those requests
        #        are paying its cold start right now).
        for adapter in sorted(shares, key=lambda a: (-shares[a], a)):
            share = shares[adapter]
            if share < cfg.prefetch_min_share:
                break  # sorted: everything after is colder
            homes = resident_anywhere.get(adapter, ())
            for pod in sorted(pod_load, key=lambda p: (pod_load[p], p)):
                if pod in homes:
                    continue
                if not emit(PREFETCH, pod, adapter,
                            "head share %.3f >= %.3f" % (
                                share, cfg.prefetch_min_share)):
                    return decisions
        for adapter in sorted(waiting):
            if (adapter in resident_anywhere
                    or shares.get(adapter, 0.0) >= cfg.prefetch_min_share):
                continue  # head rule owns the hot ones
            target = min(pod_load, key=lambda p: (pod_load[p], p),
                         default=None)
            if target is None:
                break
            if not emit(PREFETCH, target, adapter, "waiting"):
                return decisions

        # 2) Migrate: hot adapters resident ONLY on overloaded replicas
        #    grow a copy on an under-utilized one.
        hot_bar = cfg.hot_queue_factor * max(1, median_load)
        for adapter in sorted(shares, key=lambda a: (-shares[a], a)):
            share = shares[adapter]
            if share < cfg.migrate_min_share:
                break
            homes = resident_anywhere.get(adapter)
            if not homes:
                continue  # cold: prefetch rule owns it
            if not all(pod_load.get(p, 0) > hot_bar for p in homes):
                continue  # at least one calm home: leave it be
            candidates = [p for p in pod_load
                          if p not in homes and pod_load[p] <= median_load]
            if not candidates:
                continue
            target = min(candidates, key=lambda p: (pod_load[p], p))
            if not emit(MIGRATE, target, adapter,
                        "hot (share %.3f) on overloaded replicas only"
                        % share):
                return decisions

        # 3) Demote / evict: reclaim tiers from idle adapters (dwell-
        #    filtered so one quiet tick never thrashes a working set).
        for (pod, adapter) in sorted(idle):
            ticks = idle[(pod, adapter)]
            tier = residency.get(pod, {}).get(adapter)
            if tier == TIER_SLOT and ticks >= cfg.demote_idle_ticks:
                if not emit(DEMOTE, pod, adapter,
                            "idle %d ticks in slot" % ticks):
                    return decisions
            elif tier == TIER_HOST and ticks >= cfg.evict_idle_ticks:
                if not emit(EVICT, pod, adapter,
                            "idle %d ticks in host RAM" % ticks):
                    return decisions
        return decisions

    # -- tick ---------------------------------------------------------------
    def tick(self, now: float | None = None) -> None:
        """Observability-cadence pass: fuse usage shares + residency +
        waiting split, update idle dwell, emit the tick's plan.  Runs
        AFTER ``usage.tick()`` so shares are current."""
        now = self._clock() if now is None else now
        pods = self.provider.all_pod_metrics()
        residency: dict[str, dict] = {}
        waiting: dict[str, set] = {}
        running: dict[str, set] = {}
        pod_load: dict[str, int] = {}
        have_residency = False
        for pm in pods:
            tiers = dict(pm.metrics.adapter_tiers)
            if tiers:
                have_residency = True
            residency[pm.pod.name] = tiers
            pod_load[pm.pod.name] = pm.metrics.total_queue_size
            for a in pm.metrics.waiting_adapters:
                waiting.setdefault(a, set()).add(pm.pod.name)
            for a in pm.metrics.running_adapters:
                running.setdefault(a, set()).add(pm.pod.name)
        # Adapter shares (summed over models) + adapter -> model for the
        # residency gauge's model label.
        shares: dict[str, float] = {}
        model_of: dict[str, str] = {}
        if self.usage is not None:
            for (model, adapter), share in \
                    self.usage.shares_snapshot().items():
                shares[adapter] = shares.get(adapter, 0.0) + share
                model_of.setdefault(adapter, model)
        # Idle dwell: an adapter is idle on a pod when its pool share is
        # below the bar AND nothing runs/waits on it there.
        idle: dict[tuple[str, str], int] = {}
        for pod, tiers in residency.items():
            for adapter in tiers:
                busy = (shares.get(adapter, 0.0) >= self.cfg.idle_share
                        or pod in running.get(adapter, ())
                        or pod in waiting.get(adapter, ()))
                if busy:
                    continue
                idle[(pod, adapter)] = self._idle.get((pod, adapter), 0) + 1
        decisions = self.plan(shares, waiting, residency, pod_load, idle) \
            if have_residency else []
        resident_pods: dict[str, set] = {}
        slot_pods: dict[str, set] = {}
        host_pods: dict[str, set] = {}
        for pod, tiers in residency.items():
            for adapter, tier in tiers.items():
                resident_pods.setdefault(adapter, set()).add(pod)
                (slot_pods if tier == TIER_SLOT
                 else host_pods).setdefault(adapter, set()).add(pod)
        with self._lock:
            self.ticks += 1
            self.last_tick = now
            self._idle = idle
            self._residency = residency
            self._model_of = model_of
            self._decisions = decisions
            for d in decisions:
                key = (d["action"],)
                self.decisions_total[key] = (
                    self.decisions_total.get(key, 0) + 1)
            self._local_tier_pods = {
                a: (frozenset(slot_pods.get(a, ())),
                    frozenset(host_pods.get(a, ())))
                for a in resident_pods}
            self._have_local_residency = have_residency
            self._rebuild_merged_locked()
        if self.journal is not None:
            for d in decisions:
                self.journal.emit(events_mod.PLACEMENT_DECISION,
                                  action=d["action"], pod=d["pod"],
                                  adapter=d["adapter"], reason=d["reason"])

    # -- export -------------------------------------------------------------
    def render(self) -> list[str]:
        with self._lock:
            residency = {p: dict(t) for p, t in self._residency.items()}
            model_of = dict(self._model_of)
            decisions = dict(self.decisions_total)
            would_steer = self.would_steer_total
            wrong_tier = self.wrong_tier_total
            escapes = self.escape_total
        lines = ["# TYPE gateway_adapter_residency gauge"]
        for pod in sorted(residency):
            for adapter in sorted(residency[pod]):
                lines.append(
                    'gateway_adapter_residency{model="%s",adapter="%s",'
                    'pod="%s",tier="%s"} 1'
                    % (escape_label(model_of.get(adapter, "")),
                       escape_label(adapter), escape_label(pod),
                       escape_label(residency[pod][adapter])))
        lines += render_keyed_family(
            "gateway_placement_decisions_total", decisions, ("action",))
        lines += [
            "# TYPE gateway_placement_would_steer_total counter",
            f"gateway_placement_would_steer_total {would_steer}",
            "# TYPE gateway_placement_wrong_tier_picks_total counter",
            f"gateway_placement_wrong_tier_picks_total {wrong_tier}",
            "# TYPE gateway_placement_escapes_total counter",
            f"gateway_placement_escapes_total {escapes}",
        ]
        return lines

    def debug_payload(self) -> dict:
        """The ``/debug/placement`` JSON body — the wire the lora_sidecar's
        ``--planner-url`` mode polls.  Decisions carry the target pod NAME
        and ADDRESS so a per-replica sidecar can filter to its own server
        without knowing pool topology."""
        addr_of = {pm.pod.name: pm.pod.address
                   for pm in self.provider.all_pod_metrics()}
        with self._lock:
            decisions = [dict(d, address=addr_of.get(d["pod"], ""))
                         for d in self._decisions]
            payload = {
                "mode": self.cfg.mode,
                "ticks": self.ticks,
                "decisions": decisions,
                "residency": {p: dict(t)
                              for p, t in self._residency.items()},
                "idle": {f"{pod}|{adapter}": ticks
                         for (pod, adapter), ticks in self._idle.items()},
                "counters": {
                    "decisions_total": {k[0]: v for k, v
                                        in self.decisions_total.items()},
                    "would_steer_total": self.would_steer_total,
                    "wrong_tier_picks_total": self.wrong_tier_total,
                    "escapes_total": self.escape_total,
                },
                "config": asdict(self.cfg),
            }
        return payload
