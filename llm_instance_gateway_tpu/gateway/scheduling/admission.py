"""Saturation-gated admission queueing (the reference sim's ``smart`` policy).

The plain scheduler SHEDS sheddable traffic the moment no pod passes the
thresholds (429, reference ``scheduler.go:74-90``).  The reference's best
simulated policy instead parks those requests in per-tier queues and
re-admits them as capacity frees, draining tighter tiers more often
(``simulations/.../loadbalancer.py:351-426``: saturation-gated
queueing_signal, weighted_dequeue with probability ∝ 1/target-latency).

This module carries that policy into the REAL gateway:

- ``TierQueues``: pure queueing policy (bounded per-tier FIFOs + weighted
  draw across non-empty tiers) shared verbatim by the live controller and
  the simulator, so the sim A/Bs exactly what deploys.
- ``AdmissionController``: wraps any scheduler (Python tree or the C++ hot
  path).  A shed becomes a bounded wait: the request parks, a drain thread
  retries the REAL filter tree as metrics refresh, and the transport thread
  wakes with a pod — or sheds with 429 after ``max_wait_s`` (dequeue signal
  == "the tree admits again", the gateway equivalent of the sim's
  saturation-cleared check).

Critical traffic never queues here — the tree never sheds it — so tiers
are Default/Sheddable, with Default drained ``tier_weights``-times more
often.  Opt-in per pool: ``schedulerConfig.admissionQueue`` in the
InferencePool document, hot-reloadable like the thresholds.
"""

from __future__ import annotations

import logging
import random
import threading
import time
from collections import deque
from dataclasses import dataclass, field

from llm_instance_gateway_tpu.lockwitness import witness_lock
from llm_instance_gateway_tpu.gateway.scheduling.config import AdmissionConfig
from llm_instance_gateway_tpu.gateway.scheduling.scheduler import SchedulingError

logger = logging.getLogger(__name__)


class TierQueues:
    """Bounded per-tier FIFOs with weighted draw — the dequeue policy."""

    def __init__(self, cfg: AdmissionConfig, rng: random.Random | None = None):
        self.cfg = cfg
        self._rng = rng or random.Random(0)
        self._queues: dict[str, deque] = {t: deque() for t, _ in cfg.tier_weights}
        self._weights = dict(cfg.tier_weights)

    def depth(self) -> int:
        return sum(len(q) for q in self._queues.values())

    def depths(self) -> dict[str, int]:
        return {t: len(q) for t, q in self._queues.items()}

    def push(self, tier: str, item) -> tuple[bool, object | None]:
        """``(accepted, evicted)``.

        At ``max_depth`` a higher-weight arrival no longer sheds while
        lower-weight items sit queued (the full-queue inversion): the
        NEWEST item of the lowest-weight non-empty tier below the
        arrival's weight is evicted to make room — the caller sheds the
        evicted waiter (its transport journals the ``shed``).  With no
        lower-weight occupant the arrival is refused as before
        (``(False, None)``)."""
        if self.depth() >= self.cfg.max_depth:
            evicted = self._evict_below(tier)
            if evicted is None:
                return False, None
            self._queues.setdefault(tier, deque()).append(item)
            return True, evicted
        self._queues.setdefault(tier, deque()).append(item)
        return True, None

    def _evict_below(self, tier: str):
        """Pop the newest item of the lowest-weight non-empty tier whose
        weight is strictly below ``tier``'s (unlisted tiers weigh the
        highest configured weight, matching pop_weighted)."""
        top = max(self._weights.values(), default=1.0)
        w_new = self._weights.get(tier, top)
        victim, w_victim = None, None
        for t, q in self._queues.items():
            if not q:
                continue
            w = self._weights.get(t, top)
            if w < w_new and (w_victim is None or w < w_victim):
                victim, w_victim = t, w
        if victim is None:
            return None
        return self._queues[victim].pop()

    def pop_weighted(self):
        """Draw a non-empty tier by weight; FIFO within the tier.

        Tiers without a configured weight drain at the HIGHEST configured
        weight: the only way an unlisted tier appears is Critical traffic
        parked during an empty-membership window (startup, rollout gap),
        and it must never drain behind Default."""
        candidates = [(t, q) for t, q in self._queues.items() if q]
        if not candidates:
            return None
        top = max(self._weights.values(), default=1.0)
        weights = [self._weights.get(t, top) for t, _ in candidates]
        tier, q = self._rng.choices(candidates, weights=weights, k=1)[0]
        return q.popleft()

    def push_front(self, tier: str, item) -> None:
        """Return a not-yet-admissible head to its tier (preserves FIFO)."""
        self._queues.setdefault(tier, deque()).appendleft(item)


@dataclass
class _Waiter:
    llm_req: object
    tier: str
    event: threading.Event = field(default_factory=threading.Event)
    pod: object = None
    expired: bool = False  # transport gave up; drain thread must skip it
    evicted: bool = False  # bumped by a higher-weight arrival at max_depth


class AdmissionController:
    """Scheduler wrapper: shed -> bounded queue wait -> re-schedule or 429."""

    def __init__(self, scheduler, cfg: AdmissionConfig | None = None,
                 rng: random.Random | None = None, drain_scheduler=None,
                 drain_scheduler_factory=None):
        self._scheduler = scheduler
        # Drain re-admission runs against hysteresis-scaled thresholds
        # (config.drain_scaled).  The dedicated drain scheduler is built
        # LAZILY via the factory on first enable — a disabled admission
        # queue (the default) must not pay for a second scheduler or an
        # idle drain thread.  Passing an instance pins it eagerly; with
        # neither, the drain reuses the admission scheduler (margin 1.0).
        self._drain_scheduler = drain_scheduler
        self._drain_factory = drain_scheduler_factory
        self._cfg = cfg or AdmissionConfig()
        self._rng = rng or random.Random(0)
        self._lock = witness_lock("AdmissionController._lock")
        self._queues = TierQueues(self._cfg, self._rng)
        self._work = threading.Event()
        self._running = False
        self._thread: threading.Thread | None = None
        # Transport-imposed cap on parked waiters (each occupies a handler
        # thread).  Keeps a hot-reload that enables admission from parking
        # more waiters than the already-sized worker pool can absorb.
        self._park_budget: int | None = None
        # Fairness/quota plane (gateway/fairness.py, wired by the proxy):
        # update_config pushes the pool document's fairnessPolicy section
        # into it; the admit() gate itself runs in the handler core so
        # bare-scheduler deployments get it too.
        self.fairness = None
        if self._cfg.enabled:
            self._arm()

    def set_park_budget(self, budget: int | None) -> None:
        self._park_budget = budget

    def _arm(self) -> None:
        """Build the drain scheduler (if a factory was given) and start the
        drain thread.  Idempotent."""
        from llm_instance_gateway_tpu.gateway.scheduling.config import (
            drain_scaled,
        )

        if self._drain_scheduler is None:
            if self._drain_factory is not None:
                base_cfg = getattr(self._scheduler, "cfg", None)
                self._drain_scheduler = self._drain_factory(
                    drain_scaled(base_cfg) if base_cfg is not None else None)
            else:
                self._drain_scheduler = self._scheduler
        if self._thread is None:
            self._running = True
            self._thread = threading.Thread(target=self._drain_loop,
                                            daemon=True)
            self._thread.start()

    # -- scheduler interface (drop-in for handlers/bootstrap) ---------------

    @property
    def cfg(self):
        """The wrapped scheduler's live SchedulerConfig (drop-in surface)."""
        return self._scheduler.cfg

    @property
    def prefix_index(self):
        """Wrapped scheduler's prefix-affinity index (drop-in surface: the
        request handler gates hash computation on its presence)."""
        return getattr(self._scheduler, "prefix_index", None)

    def schedule_disaggregated(self, llm_req):
        """Two-stage routing pass-through (disaggregated pools).

        A shed here degrades to the single-hop admission path: the request
        parks in the tier queues and re-admits collocated on whichever
        replica the drain tree clears first — bounded wait beats a 429 for
        disaggregated traffic exactly as for plain traffic.
        """
        inner = getattr(self._scheduler, "schedule_disaggregated", None)
        if inner is None:
            return self.schedule(llm_req), None
        try:
            return inner(llm_req)
        except SchedulingError as e:
            if not e.shed or not self._cfg.enabled:
                raise
        return self.schedule(llm_req), None

    def schedule(self, llm_req):
        try:
            return self._scheduler.schedule(llm_req)
        except SchedulingError as e:
            if not e.shed or not self._cfg.enabled:
                raise
            tier = getattr(llm_req, "criticality", "Default") or "Default"
            waiter = _Waiter(llm_req=llm_req, tier=tier)
            with self._lock:
                over_budget = (self._park_budget is not None
                               and self._queues.depth() >= self._park_budget)
                if over_budget:
                    raise SchedulingError(
                        "admission queue full; dropping request due to "
                        "limited backend resources", shed=True) from e
                accepted, evicted = self._queues.push(tier, waiter)
                if not accepted:
                    raise SchedulingError(
                        "admission queue full; dropping request due to "
                        "limited backend resources", shed=True) from e
            if evicted is not None:
                # A lower-tier waiter made room: wake its transport thread
                # with no pod so it sheds (429) now instead of timing out.
                evicted.evicted = True
                evicted.event.set()
            self._work.set()
            t_park = time.monotonic()
            if waiter.event.wait(self._cfg.max_wait_s) and waiter.pod is not None:
                # Queue-wait attribution for the tracing layer: this wait is
                # real pre-upstream latency that would otherwise be
                # indistinguishable from pick cost in the admission span.
                llm_req.admission_wait_s = time.monotonic() - t_park
                return waiter.pod
            waiter.expired = True
            if waiter.evicted:
                # Keep the shed reason truthful: this waiter did NOT
                # consume the wait window — a higher-criticality arrival
                # took its queue slot.
                raise SchedulingError(
                    "evicted from admission queue (higher-criticality "
                    "arrival or queue reshape); dropping request",
                    shed=True) from e
            raise SchedulingError(
                f"no capacity within {self._cfg.max_wait_s:.0f}s admission "
                "wait; dropping request", shed=True) from e

    def update_config(self, scheduler_cfg) -> None:
        """Hot-reload seam (pool on_update): thresholds go to the wrapped
        scheduler; the admissionQueue section re-arms this controller."""
        self._scheduler.update_config(scheduler_cfg)
        fairness_cfg = getattr(scheduler_cfg, "fairness", None)
        if fairness_cfg is not None and self.fairness is not None:
            self.fairness.update_config(fairness_cfg)
        admission = getattr(scheduler_cfg, "admission", None)
        if admission is not None and admission != self._cfg:
            with self._lock:
                self._cfg = admission
                old = self._queues
                self._queues = TierQueues(admission, self._rng)
                # Re-park waiters under the new shape; ones that no longer
                # fit (or get evicted by higher-weight re-parks) shed now.
                bumped = []
                while True:
                    w = old.pop_weighted()
                    if w is None:
                        break
                    accepted, evicted = self._queues.push(w.tier, w)
                    if not accepted:
                        bumped.append(w)
                    if evicted is not None:
                        bumped.append(evicted)
            for w in bumped:
                w.evicted = True
                w.event.set()  # pod is None: the transport sheds it
            logger.info("admission queue config updated: %s", admission)
        if self._cfg.enabled:
            self._arm()  # no-op if already armed; builds drain lazily
        if (self._drain_scheduler is not None
                and self._drain_scheduler is not self._scheduler):
            from llm_instance_gateway_tpu.gateway.scheduling.config import (
                drain_scaled,
            )

            self._drain_scheduler.update_config(drain_scaled(scheduler_cfg))

    def queue_depths(self) -> dict[str, int]:
        with self._lock:
            return self._queues.depths()

    # -- drain loop ---------------------------------------------------------

    def start(self) -> None:
        """Arm if enabled (kept for call-site symmetry; disabled admission
        costs nothing until a hot reload enables it)."""
        if self._cfg.enabled:
            self._arm()

    def stop(self) -> None:
        self._running = False
        self._work.set()
        if self._thread is not None:
            self._thread.join(timeout=5)

    def _drain_loop(self) -> None:
        while self._running:
            # Clear BEFORE inspecting the queues: a push landing after the
            # clear re-sets the event, so its wakeup can't be lost.
            self._work.clear()
            with self._lock:
                waiter = self._queues.pop_weighted()
            if waiter is None:
                self._work.wait(timeout=1.0)
                continue
            if waiter.expired:
                continue  # transport already 429'd it
            try:
                pod = self._drain_scheduler.schedule(waiter.llm_req)
            except SchedulingError:
                # Still saturated: the head returns to its tier and the loop
                # backs off one metrics refresh.
                with self._lock:
                    self._queues.push_front(waiter.tier, waiter)
                time.sleep(self._cfg.retry_interval_s)
                continue
            waiter.pod = pod
            waiter.event.set()
