"""ctypes binding for the C++ scheduler hot path (native/scheduler.cc).

``NativeScheduler`` is a drop-in for ``Scheduler`` — identical decision-tree
semantics (fuzz-verified against the Python tree), with candidate-set
computation in C++ and the final random pick kept in Python so RNG behavior
matches.  Falls back transparently when the shared library can't be built
(``available()`` is False); callers should construct via ``make_scheduler``.

Snapshot-resident fast path (the data-plane tentpole): the whole routable
world — pod metric arrays, the health/circuit avoid-set, the adapter
residency table, usage-deprioritization marks, and the threshold config —
is marshalled into a native ``State`` handle ONCE per provider snapshot
version (i.e. at scrape cadence), not per pick.  The per-pick FFI crossing
then carries only request scalars (interned adapter id, critical,
prompt_tokens) and reads the candidate set out of a persistent buffer; the
RNG draw stays in Python, so picks are byte-identical to the Python
``Scheduler`` parity oracle (same-RNG diff tests).  ``pick_many`` batches N
requests into one crossing for the bench/load rigs.

Fallback-to-Python rules: no library -> ``make_scheduler`` returns the
Python ``Scheduler``; a provider without ``snapshot()`` (or a role-filtered
subset) has no version to key the resident state on, so the state is
re-marshalled per pick — semantics identical, amortization lost.

The library auto-builds on first use via the Makefile next to the source —
the image ships g++/make, and the build is one translation unit (<1 s).
"""

from __future__ import annotations

import ctypes
import logging
import os
import random
import threading
import weakref

import numpy as np

from llm_instance_gateway_tpu.lockwitness import witness_lock
from llm_instance_gateway_tpu.gateway.scheduling.config import (
    DEFAULT_CONFIG,
    SchedulerConfig,
)
from llm_instance_gateway_tpu.gateway.scheduling.filter import FilterError
from llm_instance_gateway_tpu.gateway.scheduling.scheduler import (
    PodMetricsProvider,
    Scheduler,
    SchedulingError,
    build_decode_tree,
    build_default_tree,
    filter_by_fairness,
    filter_by_placement,
    filter_by_policy,
    split_pool_roles,
)
from llm_instance_gateway_tpu.gateway.scheduling.types import LLMRequest
from llm_instance_gateway_tpu.gateway.types import (
    ROLE_COLLOCATED,
    Pod,
    PodMetrics,
    pod_role,
)

logger = logging.getLogger(__name__)

_NATIVE_DIR = os.path.join(
    os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__)))),
    "native",
)
_LIB_PATH = os.path.join(_NATIVE_DIR, "libligsched.so")
# Must match scheduler.cc's lig_abi_version() — bumped on any exported-
# signature change so a stale prebuilt .so is refused, not miscalled.
# `make lint` (abi-drift rule) cross-checks the argtypes below against the
# C signatures and the checked-in lint/abi_baseline.json fingerprint.
_ABI_VERSION = 4
# Override the library path (e.g. the ASan/UBSan-instrumented build from
# `make native-asan`); the builder/staleness dance is skipped for overrides
# — the caller owns the file.
_LIB_ENV = "LIG_NATIVE_LIB"

LIG_SHED = -1
LIG_ERROR = -2
LIG_SHED_STRICT = -3

# filter_by_policy parity: the policy string marshals to a native mode code
# at snapshot-update time (log_only never filters natively either).
_POLICY_CODE = {"log_only": 0, "avoid": 1, "strict": 2}
# filter_by_fairness parity: deprioritize and enforce share the pick-seam
# narrowing; enforce's extra semantics (admission quotas) live entirely in
# Python (gateway/fairness.py), so the native code is binary.
_FAIRNESS_CODE = {"log_only": 0, "deprioritize": 1, "enforce": 1}
# filter_by_placement parity: log_only marshals no marks (note_pick stays
# in Python over the planner's own map — routing byte-identical).
_PLACEMENT_CODE = {"log_only": 0, "prefer_resident": 1}

_SHED_MSG = ("failed to apply filter, resulted 0 pods: dropping request due "
             "to limited backend resources")
_STRICT_MSG = ("all candidate replicas are unhealthy or circuit-open "
               "(health_policy=strict)")

_lib = None
_lib_lock = threading.Lock()

_i32p = ctypes.POINTER(ctypes.c_int32)
_i64p = ctypes.POINTER(ctypes.c_int64)
_f64p = ctypes.POINTER(ctypes.c_double)
_u8p = ctypes.POINTER(ctypes.c_uint8)


def _load_library():
    global _lib
    with _lib_lock:
        if _lib is not None:
            return _lib
        lib_path = os.environ.get(_LIB_ENV) or _LIB_PATH
        if not os.environ.get(_LIB_ENV):
            from llm_instance_gateway_tpu.utils.native_build import (
                ensure_native_lib,
            )

            if ensure_native_lib(_NATIVE_DIR, "libligsched.so",
                                 "scheduler.cc") is None:
                return None
        try:
            lib = ctypes.CDLL(lib_path)
        except OSError as e:
            logger.warning("native scheduler load failed: %s", e)
            return None
        try:
            # Version handshake BEFORE any argtype wiring: a prebuilt .so
            # from an older tree can pass the mtime staleness check, and
            # the AttributeError guard below only catches MISSING symbols
            # — an arity change on an existing one would scramble
            # arguments in the routing hot path.  Mismatch (or a pre-
            # handshake library without the symbol) falls back to Python.
            lib.lig_abi_version.restype = ctypes.c_int32
            lib.lig_abi_version.argtypes = []
            abi = lib.lig_abi_version()
            if abi != _ABI_VERSION:
                logger.warning(
                    "native scheduler ABI %d != expected %d; "
                    "falling back to Python", abi, _ABI_VERSION)
                return None
            lib.lig_state_new.restype = ctypes.c_void_p
            lib.lig_state_new.argtypes = []
            lib.lig_state_free.restype = None
            lib.lig_state_free.argtypes = [ctypes.c_void_p]
            lib.lig_state_update.restype = ctypes.c_int32
            lib.lig_state_update.argtypes = [
                ctypes.c_void_p, ctypes.c_int32,
                _i32p, _i32p, _f64p, _i64p, _i64p,  # waiting..kv_capacity
                _i32p, _i32p,                       # n_active, max_active
                _u8p,                               # avoid marks
                ctypes.c_int32, _i32p, _i32p,       # adapters CSR
                ctypes.c_int32,                     # res_ids length (v4)
                _u8p,                               # adapter noisy marks
                _i32p, _i32p,                       # placement CSR: offsets,
                ctypes.c_int32,                     # ids + length (v4),
                _u8p, _u8p,                         # tier codes, any bits
                ctypes.c_double, ctypes.c_int32, ctypes.c_int32,
                ctypes.c_double, ctypes.c_int32,
                ctypes.c_uint8, ctypes.c_uint8,     # token/prefill aware
                ctypes.c_uint8, ctypes.c_uint8,     # policy/fairness modes
                ctypes.c_uint8,                     # placement mode
            ]
            lib.lig_pick.restype = ctypes.c_int32
            lib.lig_pick.argtypes = [
                ctypes.c_void_p, ctypes.c_int32, ctypes.c_uint8,
                ctypes.c_uint8, ctypes.c_int64, _i32p, _u8p,
            ]
            lib.lig_pick_many.restype = ctypes.c_int32
            lib.lig_pick_many.argtypes = [
                ctypes.c_void_p, ctypes.c_int32,
                _i32p, _u8p, _u8p,    # adapter_ids, criticals, req_noisies
                _i64p,                # prompt_tokens
                _i32p, _i32p, _u8p,   # out_counts, out_cands, out_flags
            ]
        except AttributeError as e:
            # A stale .so predating the snapshot API: rebuildable hosts get
            # a fresh build on the next ensure; meanwhile fall back.
            logger.warning("native scheduler ABI mismatch: %s", e)
            return None
        _lib = lib
        return _lib


def available() -> bool:
    return _load_library() is not None


def _ptr(arr: np.ndarray, ctype):
    return arr.ctypes.data_as(ctypes.POINTER(ctype))


class _NativeState:
    """One native snapshot handle + the Python-side cache keys guarding it."""

    __slots__ = ("handle", "key", "avoid", "noisy", "placed", "out",
                 "intern", "_finalizer", "__weakref__")

    def __init__(self, lib):
        self.handle = lib.lig_state_new()
        if not self.handle:
            raise RuntimeError("lig_state_new failed")
        self.key = None          # (version, n_pods, policy, fairness,
        #                           placement, cfg_gen)
        self.avoid = None        # frozenset marshalled into the avoid marks
        self.noisy = frozenset()  # noisy names marshalled into the marks
        self.placed = None       # resident map marshalled into the
        #                           placement marks (identity-compared: the
        #                           planner swaps the dict whole per tick)
        self.out = np.empty(0, np.int32)  # persistent candidate buffer
        # Adapter interning for THIS state's residency CSR: name -> dense
        # id, rebuilt from scratch at every marshal so the table (and the
        # native bitmap sized from it) stays bounded by the adapters
        # actually resident in the snapshot — never by historical churn.
        # A request adapter absent from the table was not resident on any
        # pod at snapshot time (id -1: no affinity anywhere) — exactly the
        # Python tree's view of the same snapshot.
        self.intern: dict[str, int] = {}
        self._finalizer = weakref.finalize(
            self, lib.lig_state_free, self.handle)


class NativeScheduler:
    """Same interface as Scheduler.schedule; C++ candidate computation over
    a snapshot-resident native state."""

    def __init__(
        self,
        pod_metrics_provider: PodMetricsProvider,
        cfg: SchedulerConfig = DEFAULT_CONFIG,
        token_aware: bool = True,
        prefill_aware: bool = True,
        prefix_aware: bool = True,
        prefix_index=None,
        rng: random.Random | None = None,
    ):
        lib = _load_library()
        if lib is None:
            raise RuntimeError("native scheduler library unavailable")
        self._lib = lib
        self._provider = pod_metrics_provider
        self.cfg = cfg
        self.token_aware = token_aware
        self.prefill_aware = prefill_aware
        # Same post-tree prefix-affinity tie-break as the Python Scheduler
        # (scheduling/prefix_affinity.py): applied over the C++ candidate
        # set, so the fuzz-pinned candidate parity is untouched.
        # ``prefix_index`` shares one index across scheduler instances
        # routing the same pool; prefix_aware=False disables the tie-break
        # even with an injected index (see Scheduler.__init__).
        self.prefix_index = prefix_index if prefix_aware else None
        if prefix_aware and self.prefix_index is None:
            from llm_instance_gateway_tpu.gateway.scheduling.prefix_affinity import (
                PrefixIndex,
            )

            self.prefix_index = PrefixIndex()
        self._rng = rng or random.Random()
        # Decode-hop stage for disaggregated pools: the tiny Python tree
        # (2-3 filters over the decode-role subset) — not worth an FFI
        # seam, and it keeps the fuzz-pinned C++ candidate parity for the
        # main tree untouched.
        self._decode_tree = build_decode_tree(cfg, token_aware=token_aware)
        # Python-oracle tree for the pick ledger's shadow replay: sampled
        # native picks are EXPLAINED by re-running this tree + the silent
        # advisor chain in Python (gateway/pickledger.py) — the FFI hot
        # path never grows a crossing for observability.  Inert until a
        # ledger is attached.
        self._oracle_tree = build_default_tree(
            cfg, token_aware=token_aware, prefill_aware=prefill_aware)
        # Snapshot-resident native state: ``_state`` is keyed on the
        # provider's monotonic snapshot version (plus policy/config
        # generations) and re-marshalled only when one of them moves;
        # ``_scratch`` serves version-less calls (role subsets, the legacy
        # candidates() API) where there is nothing to key a cache on.
        self._state = _NativeState(lib)
        self._scratch = _NativeState(lib)
        self._cfg_gen = 0
        # (version, pods-after-role-policy, effective version) — see
        # _routable_pods.
        self._role_cache: tuple | None = None
        # The gRPC transport calls schedule() from a thread pool; the
        # native state handles and persistent buffers are shared state.
        self._call_lock = witness_lock("NativeScheduler._call_lock")
        # Health/resilience hook (gateway/resilience.py) — same seam as
        # the Python Scheduler: log_only counts would-be avoidance picks
        # and never alters the pick (candidate parity with C++ stays
        # exact); avoid/strict marshal the advisor's avoid_set into the
        # native snapshot so policy filtering costs zero extra crossings.
        self.health_advisor = None
        # Usage/fairness seam (gateway/usage.py + gateway/fairness.py) —
        # same contract as the Python Scheduler's usage_advisor.  The
        # noisy marks ride the native snapshot (per-adapter bits + per-pod
        # hog bits, refreshed whenever the advisor's noisy set moves); a
        # FairnessPolicy in deprioritize/enforce narrows candidates
        # NATIVELY (filter_by_fairness parity, fairness escape on flag
        # bit 2), while log_only keeps byte-exact parity with the Python
        # path and only counts flagged picks.
        self.usage_advisor = None
        # Placement seam (gateway/placement.py) — same contract as the
        # Python Scheduler's placement_advisor.  prefer_resident marshals
        # the planner's resident map into the snapshot (per-adapter pod
        # bits + pool-wide "resident anywhere" bits, so the escape-hatch
        # condition matches the Python filter exactly); log_only marshals
        # nothing and keeps byte-exact parity, note_pick counting in
        # Python over the planner's own map.
        self.placement_advisor = None
        # Decision-ledger seam (gateway/pickledger.py) — same contract as
        # the Python Scheduler's pick_ledger: counter-modulus sampling
        # (no RNG, no filtering, routing byte-identical), with sampled
        # picks explained via the Python-oracle shadow replay above.
        self.pick_ledger = None

    # -- marshalling --------------------------------------------------------
    def _policy_and_avoid(self) -> tuple[str, frozenset]:
        """The advisor's current policy + avoid-set (both cheap cached
        reads on the ResiliencePlane).  log_only marshals no marks."""
        advisor = self.health_advisor
        if advisor is None:
            return "log_only", frozenset()
        policy = getattr(advisor, "policy", "log_only")
        if policy == "log_only":
            return policy, frozenset()
        batch = getattr(advisor, "avoid_set", None)
        if batch is not None:
            return policy, frozenset(batch())
        return policy, None  # per-pod should_avoid: no cheap change signal

    def _fairness_and_noisy(self) -> tuple[str, frozenset]:
        """The usage advisor's fairness mode + live noisy-name set (both
        cheap cached reads on the FairnessPolicy/UsageRollup).  A bare
        rollup has no mode — log_only, marks still marshalled for the
        flag-bit observable."""
        usage = self.usage_advisor
        if usage is None:
            return "log_only", frozenset()
        mode = getattr(usage, "mode", "log_only")
        if mode not in _FAIRNESS_CODE:
            mode = "log_only"
        get_noisy = getattr(usage, "noisy", None)
        noisy = frozenset(get_noisy()) if get_noisy is not None \
            else frozenset()
        return mode, noisy

    def _placement_and_map(self) -> tuple[str, dict | None]:
        """The placement advisor's mode + resident map (adapter ->
        frozenset of pod names; swapped whole per planner tick, so object
        identity is the staleness signal).  log_only — or a pool with no
        residency data — marshals no marks."""
        advisor = self.placement_advisor
        if advisor is None:
            return "log_only", None
        mode = getattr(advisor, "mode", "log_only")
        if mode not in _PLACEMENT_CODE or _PLACEMENT_CODE[mode] == 0:
            return "log_only", None
        get_map = getattr(advisor, "resident_map", None)
        rmap = get_map() if get_map is not None else None
        if rmap is None:
            return "log_only", None
        return mode, rmap

    def _marshal(self, state: _NativeState, pods: list[PodMetrics],
                 policy: str, bad: frozenset | None, fairness: str,
                 noisy_names: frozenset, placement: str = "log_only",
                 resident_map: dict | None = None) -> None:
        """Push the full routable world into ``state`` (tick-time cost)."""
        n = len(pods)
        waiting = np.fromiter(
            (pm.metrics.total_queue_size for pm in pods), np.int32, n)
        prefill = np.fromiter(
            (pm.metrics.prefill_queue_size for pm in pods), np.int32, n)
        kv_usage = np.fromiter(
            (pm.metrics.kv_cache_usage_percent for pm in pods), np.float64, n)
        kv_free = np.fromiter(
            (pm.metrics.kv_tokens_free for pm in pods), np.int64, n)
        kv_capacity = np.fromiter(
            (pm.metrics.kv_tokens_capacity for pm in pods), np.int64, n)
        n_active = np.fromiter(
            (len(pm.metrics.active_adapters) for pm in pods), np.int32, n)
        max_active = np.fromiter(
            (pm.metrics.max_active_adapters for pm in pods), np.int32, n)
        if bad is None:
            advisor = self.health_advisor
            avoid = np.fromiter(
                (advisor.should_avoid(pm.pod.name) for pm in pods),
                np.uint8, n)
        elif bad:
            avoid = np.fromiter(
                (pm.pod.name in bad for pm in pods), np.uint8, n)
        else:
            avoid = np.zeros(n, np.uint8)
        # Adapter residency as CSR, interning names to dense ids.  The
        # table is rebuilt per marshal (see _NativeState.intern): only the
        # adapters resident in THIS snapshot get ids, so the native bitmap
        # never grows with historical adapter churn.
        table: dict[str, int] = {}
        offsets = np.empty(n + 1, np.int32)
        ids: list[int] = []
        for i, pm in enumerate(pods):
            offsets[i] = len(ids)
            for name in pm.metrics.active_adapters:
                aid = table.get(name)
                if aid is None:
                    aid = table[name] = len(table)
                ids.append(aid)
        offsets[n] = len(ids)
        res_ids = np.asarray(ids, dtype=np.int32)
        # Placement marks (prefer_resident only): the planner's resident
        # map becomes a second CSR over the SAME intern table — names
        # resident somewhere but active nowhere still intern, so a request
        # for a demotable-but-idle adapter resolves an id.  placed_any
        # carries the POOL-wide resident bit: an adapter whose only homes
        # are outside this pods list still escapes (Python filter parity).
        placed_lists: list[list[tuple[int, int]]] = [[] for _ in range(n)]
        placement_code = _PLACEMENT_CODE.get(placement, 0)
        if placement_code and resident_map:
            pod_index = {pm.pod.name: i for i, pm in enumerate(pods)}
            for adapter_name, (slot_pods, host_pods) in resident_map.items():
                aid = table.get(adapter_name)
                if aid is None:
                    aid = table[adapter_name] = len(table)
                for tier_code, pod_names in ((2, slot_pods), (1, host_pods)):
                    for pod_name in pod_names:
                        i = pod_index.get(pod_name)
                        if i is not None:
                            placed_lists[i].append((aid, tier_code))
        n_adapters = len(table)
        placed_offsets = np.empty(n + 1, np.int32)
        placed_flat: list[int] = []
        placed_tier_flat: list[int] = []
        for i in range(n):
            placed_offsets[i] = len(placed_flat)
            for aid, tier_code in placed_lists[i]:
                placed_flat.append(aid)
                placed_tier_flat.append(tier_code)
        placed_offsets[n] = len(placed_flat)
        placed_ids = np.asarray(placed_flat, dtype=np.int32)
        placed_tiers = np.asarray(placed_tier_flat, dtype=np.uint8)
        placed_any = np.zeros(max(1, n_adapters), np.uint8)
        if placement_code and resident_map:
            for adapter_name, (slot_pods, host_pods) in resident_map.items():
                if slot_pods or host_pods:
                    placed_any[table[adapter_name]] = 1
        noisy = np.zeros(max(1, n_adapters), np.uint8)
        for name in noisy_names:
            aid = table.get(name)
            if aid is not None:
                noisy[aid] = 1
        rc = self._lib.lig_state_update(
            self._void(state), n,
            _ptr(waiting, ctypes.c_int32), _ptr(prefill, ctypes.c_int32),
            _ptr(kv_usage, ctypes.c_double), _ptr(kv_free, ctypes.c_int64),
            _ptr(kv_capacity, ctypes.c_int64),
            _ptr(n_active, ctypes.c_int32), _ptr(max_active, ctypes.c_int32),
            _ptr(avoid, ctypes.c_uint8),
            n_adapters, _ptr(offsets, ctypes.c_int32),
            _ptr(res_ids, ctypes.c_int32), len(res_ids),
            _ptr(noisy, ctypes.c_uint8),
            _ptr(placed_offsets, ctypes.c_int32),
            _ptr(placed_ids, ctypes.c_int32), len(placed_ids),
            _ptr(placed_tiers, ctypes.c_uint8),
            _ptr(placed_any, ctypes.c_uint8),
            self.cfg.kv_cache_threshold,
            self.cfg.queue_threshold_critical,
            self.cfg.queueing_threshold_lora,
            self.cfg.token_headroom_factor,
            self.cfg.prefill_queue_threshold,
            1 if self.token_aware else 0,
            1 if self.prefill_aware else 0,
            _POLICY_CODE.get(policy, 0),
            _FAIRNESS_CODE.get(fairness, 0),
            placement_code,
        )
        if rc != 0:
            raise SchedulingError(f"native state update failed ({rc})")
        if state.out.shape[0] < n:
            state.out = np.empty(n, np.int32)
        state.avoid = bad
        state.noisy = noisy_names
        state.placed = resident_map if placement_code else None
        state.intern = table

    @staticmethod
    def _void(state: _NativeState):
        return ctypes.c_void_p(state.handle)

    def _ensure_state(self, version, pods: list[PodMetrics],
                      policy_mode: bool = True) -> _NativeState:
        """Return a marshalled state for ``pods``.

        With a real snapshot ``version`` the resident state is reused until
        the provider version, the scheduler config, or the advisor's
        avoid-set moves — the tick-time handshake that makes the per-pick
        call carry request scalars only.  Version-less calls (role subsets,
        ad-hoc pods lists) marshal the scratch handle every time.
        """
        if policy_mode:
            policy, bad = self._policy_and_avoid()
            fairness, noisy = self._fairness_and_noisy()
            placement, rmap = self._placement_and_map()
        else:
            policy, bad = "log_only", frozenset()
            fairness, noisy = "log_only", frozenset()
            placement, rmap = "log_only", None
        if version is None:
            self._marshal(self._scratch, pods, policy, bad, fairness, noisy,
                          placement, rmap)
            self._scratch.key = None
            return self._scratch
        state = self._state
        key = (version, len(pods), policy, fairness, placement,
               self._cfg_gen)
        # ``bad is None`` = an advisor with per-pod should_avoid only (no
        # batch set to compare): no cheap change signal, so re-marshal.
        # The noisy-name set is compared like the avoid set — a rollup
        # flag transition between provider versions must reach the
        # resident marks.  The planner's resident map is identity-compared
        # (swapped whole per tick), so a planner tick between provider
        # versions reaches the placement marks the same way.
        if (state.key != key or bad is None or state.avoid != bad
                or state.noisy != noisy or state.placed is not rmap):
            self._marshal(state, pods, policy, bad, fairness, noisy,
                          placement, rmap)
            state.key = key
        return state

    # -- candidate computation ---------------------------------------------
    def candidates(self, req: LLMRequest, pods: list[PodMetrics],
                   version: int | None = None) -> list[int]:
        """Tree survivors WITHOUT policy filtering (legacy API — the parity
        fuzz drives it; policy belongs to the pick seam)."""
        if not pods:
            # Parity: the Python tree's failure branches land in the drop
            # filter on an empty pool, i.e. shed -> 429.
            raise SchedulingError(
                "failed to apply filter, resulted 0 pods: no pods", shed=True
            )
        with self._call_lock:
            state = self._ensure_state(None, pods, policy_mode=False)
            count, _ = self._pick_candidates_locked(state, req)
            return state.out[:count].tolist()

    def _pick_candidates_locked(self, state: _NativeState,
                                req: LLMRequest) -> tuple[int, int]:
        """One FFI crossing: request scalars in, candidate count + flags
        out (candidates land in ``state.out``)."""
        adapter_id = state.intern.get(req.resolved_target_model, -1)
        flags = ctypes.c_uint8(0)
        count = self._lib.lig_pick(
            self._void(state), adapter_id,
            1 if req.critical else 0,
            # Request-noisy matched against the MARSHALLED name set (the
            # same set the per-pod hog bits were computed from), mirroring
            # note_pick's req.model matching.
            1 if req.model in state.noisy else 0,
            req.prompt_tokens,
            _ptr(state.out, ctypes.c_int32), ctypes.byref(flags))
        if count == LIG_SHED:
            raise SchedulingError(_SHED_MSG, shed=True)
        if count == LIG_SHED_STRICT:
            raise SchedulingError(_STRICT_MSG, shed=True)
        if count < 0:
            raise SchedulingError(f"native scheduler error {count}")
        return count, flags.value

    def update_config(self, cfg: SchedulerConfig) -> None:
        """Swap thresholds at runtime — re-marshalled on the next pick via
        the config generation in the snapshot cache key."""
        self.cfg = cfg
        self._cfg_gen += 1
        self._decode_tree = build_decode_tree(
            cfg, token_aware=self.token_aware)
        self._oracle_tree = build_default_tree(
            cfg, token_aware=self.token_aware,
            prefill_aware=self.prefill_aware)

    def _snapshot_pods(self):
        snapshot = getattr(self._provider, "snapshot", None)
        if snapshot is not None:
            return snapshot()  # atomic (version, pods) pair
        return None, self._provider.all_pod_metrics()

    def _routable_pods(self):
        """(pods, version, pool_total) after the single-hop role policy,
        with the O(pods) role partition cached per snapshot version — the
        per-pick path must not re-walk 200 pods to rediscover an
        unchanged split.  ``pool_total`` is the pre-partition pool size
        (the pick ledger's funnel head)."""
        version, pods = self._snapshot_pods()
        cache = self._role_cache
        if version is not None and cache is not None and cache[0] == version:
            return cache[1], cache[2], cache[3]
        total = len(pods)
        collocated = [pm for pm in pods
                      if pod_role(pm.pod) == ROLE_COLLOCATED]
        if collocated and len(collocated) != len(pods):
            use, use_version = collocated, None
        else:
            use, use_version = pods, version
        if version is not None:
            self._role_cache = (version, use, use_version, total)
        return use, use_version, total

    # -- pick ---------------------------------------------------------------
    def _finish_pick(self, req: LLMRequest, pods: list[PodMetrics],
                     cand: list[int], flags: int, hop: str = "single",
                     pool_n: int = 0) -> Pod:
        """Post-candidate seams, identical to Scheduler._pick ordering:
        escape-hatch note, prefix tie-break, RNG draw, note_pick hooks.

        Runs OUTSIDE ``_call_lock`` (``cand`` is the caller's copy of the
        candidate indices): the lazy prefix-hash resolution and the
        prefix-index bookkeeping here can cost more than the pick itself,
        and serializing them would collapse the threaded gRPC transport to
        single-thread hash speed — the Python Scheduler runs the same
        seams unlocked."""
        advisor = self.health_advisor
        if flags & 1 and advisor is not None:
            note = getattr(advisor, "note_escape_hatch", None)
            if note is not None:
                note()
        if flags & 4 and self.usage_advisor is not None:
            # Fairness escape hatch: every candidate hosted a flagged
            # adapter (scheduler.py filter_by_fairness parity).
            note = getattr(self.usage_advisor, "note_fairness_escape", None)
            if note is not None:
                note()
        if flags & 8 and self.placement_advisor is not None:
            # Placement escape hatch: the adapter is resident in the pool
            # but on no candidate (filter_by_placement parity).
            note = getattr(self.placement_advisor,
                           "note_placement_escape", None)
            if note is not None:
                note()
        pick = None
        tie_break = False
        if self.prefix_index is not None and req.prefix_hashes:
            held = self.prefix_index.prefer(req, [pods[i] for i in cand])
            if held is not None:
                pick = held.pod
                tie_break = True
        if pick is None:
            pick = pods[cand[self._rng.randrange(len(cand))]].pod
        if self.prefix_index is not None and req.prefix_hashes:
            self.prefix_index.record(req.prefix_hashes, pick.name)
        if advisor is not None:
            advisor.note_pick(pick.name)
        if self.usage_advisor is not None:
            self.usage_advisor.note_pick(pick.name, req.model)
        if self.placement_advisor is not None:
            self.placement_advisor.note_pick(
                pick.name, req.resolved_target_model)
        ledger = self.pick_ledger
        if ledger is not None and ledger.sampled():
            self._charge_shadow(ledger, req, pods, cand, flags, hop,
                                pool_n, tie_break, pick)
        return pick

    def _charge_shadow(self, ledger, req: LLMRequest,
                       pods: list[PodMetrics], cand: list[int], flags: int,
                       hop: str, pool_n: int, tie_break: bool,
                       pick: Pod) -> None:
        """Explain a sampled native pick via Python-oracle shadow replay:
        the oracle tree + silent advisor chain over the SAME pods list
        the native pick saw.  ``shadow_match`` records whether the replay
        reproduced the native candidate set — a truthfulness observable
        (the same-RNG diff tests pin the paths byte-identical), never an
        assert.  Off the FFI path entirely; sampled picks only."""
        advisors = (self.health_advisor, self.usage_advisor,
                    self.placement_advisor)
        try:
            base = self._oracle_tree.filter(req, list(pods))
        except FilterError:
            # The oracle sheds where the native path served (snapshot
            # skew): fall back to the native candidates as the funnel
            # head — still a truthful record of what survived.
            base = [pods[i] for i in cand]
        post_health, post_fairness, final = ledger.replay(
            req, base, advisors)
        actual = {pods[i].pod.name for i in cand}
        shadow_match = {pm.pod.name for pm in final} == actual
        escapes = [seam for bit, seam in
                   ((1, "health/circuit"), (4, "fairness"),
                    (8, "placement")) if flags & bit]
        ledger.charge(
            req, winner=pick.name, base=base, post_health=post_health,
            post_fairness=post_fairness, post_placement=final, hop=hop,
            path="native-shadow", pool_n=pool_n or len(pods),
            role_n=len(pods), tie_break=tie_break, advisors=advisors,
            escapes=escapes, trace_id=req.trace_id,
            shadow_match=shadow_match)

    def schedule(self, req: LLMRequest) -> Pod:
        # Same role policy as the Python Scheduler: single-hop traffic
        # prefers collocated replicas; a role-filtered SUBSET bypasses the
        # snapshot-version resident state (it keys on (version, n) and a
        # subset would poison it).
        pods, version, pool_total = self._routable_pods()
        if not pods:
            raise SchedulingError(
                "failed to apply filter, resulted 0 pods: no pods", shed=True)
        with self._call_lock:
            state = self._ensure_state(version, pods)
            count, flags = self._pick_candidates_locked(state, req)
            cand = state.out[:count].tolist()
        return self._finish_pick(req, pods, cand, flags, pool_n=pool_total)

    def pick_many(self, reqs: list[LLMRequest]) -> list[Pod]:
        """Batched scheduling: ONE FFI crossing for the whole batch (the
        bench/load-rig amortization entry).  Semantics are pick-for-pick
        identical to calling ``schedule`` in a loop — same candidate sets,
        same RNG consumption, same advisor seams — including raising the
        shed ``SchedulingError`` at the first request that sheds."""
        if not reqs:
            return []
        pods, version, pool_total = self._routable_pods()
        if not pods:
            raise SchedulingError(
                "failed to apply filter, resulted 0 pods: no pods", shed=True)
        n, n_reqs = len(pods), len(reqs)
        with self._call_lock:
            state = self._ensure_state(version, pods)
            intern = state.intern
            noisy = state.noisy
            adapter_ids = np.fromiter(
                (intern.get(r.resolved_target_model, -1) for r in reqs),
                np.int32, n_reqs)
            criticals = np.fromiter(
                (1 if r.critical else 0 for r in reqs), np.uint8, n_reqs)
            req_noisies = np.fromiter(
                (1 if r.model in noisy else 0 for r in reqs),
                np.uint8, n_reqs)
            prompt_tokens = np.fromiter(
                (r.prompt_tokens for r in reqs), np.int64, n_reqs)
            counts = np.empty(n_reqs, np.int32)
            cands = np.empty(n_reqs * n, np.int32)
            flags = np.empty(n_reqs, np.uint8)
            rc = self._lib.lig_pick_many(
                self._void(state), n_reqs,
                _ptr(adapter_ids, ctypes.c_int32),
                _ptr(criticals, ctypes.c_uint8),
                _ptr(req_noisies, ctypes.c_uint8),
                _ptr(prompt_tokens, ctypes.c_int64),
                _ptr(counts, ctypes.c_int32), _ptr(cands, ctypes.c_int32),
                _ptr(flags, ctypes.c_uint8))
            if rc != 0:
                raise SchedulingError(f"native pick_many failed ({rc})")
        # counts/cands/flags are call-local: the finish seams (prefix
        # hashing, RNG, advisors) run unlocked, same as schedule().
        picks: list[Pod] = []
        for r_idx in range(n_reqs):
            count = int(counts[r_idx])
            if count == LIG_SHED:
                raise SchedulingError(_SHED_MSG, shed=True)
            if count == LIG_SHED_STRICT:
                raise SchedulingError(_STRICT_MSG, shed=True)
            if count < 0:
                raise SchedulingError(f"native scheduler error {count}")
            cand = cands[r_idx * n:r_idx * n + count].tolist()
            picks.append(self._finish_pick(
                reqs[r_idx], pods, cand, int(flags[r_idx]),
                pool_n=pool_total))
        return picks

    def schedule_disaggregated(
        self, req: LLMRequest
    ) -> tuple[Pod, Pod | None]:
        """Two-stage routing (see ``Scheduler.schedule_disaggregated``):
        native candidates over the prefill-role subset (scratch state —
        subsets have no snapshot version), then the Python decode tree
        over the decode-role subset."""
        version, pods = self._snapshot_pods()
        prefills, decodes = split_pool_roles(pods)
        if not prefills or not decodes:
            return self.schedule(req), None
        with self._call_lock:
            state = self._ensure_state(None, prefills)
            count, flags = self._pick_candidates_locked(state, req)
            cand = state.out[:count].tolist()
        prefill_pod = self._finish_pick(req, prefills, cand, flags,
                                        hop="prefill", pool_n=len(pods))
        ledger = self.pick_ledger
        sampled = ledger is not None and ledger.sampled()
        if sampled:
            escape_base = ledger.escape_counters(
                self.health_advisor, self.usage_advisor,
                self.placement_advisor)
        try:
            decode_base = self._decode_tree.filter(req, decodes)
        except FilterError as e:
            raise SchedulingError(
                f"no decode replica for disaggregated request: {e}",
                shed=e.shed) from e
        decode_health = filter_by_policy(self.health_advisor, decode_base)
        decode_fairness = filter_by_fairness(
            self.usage_advisor, req, decode_health)
        decode_survivors = filter_by_placement(
            self.placement_advisor, req, decode_fairness)
        decode_pod = decode_survivors[
            self._rng.randrange(len(decode_survivors))].pod
        if self.health_advisor is not None:
            self.health_advisor.note_pick(decode_pod.name)
        if self.usage_advisor is not None:
            self.usage_advisor.note_pick(decode_pod.name, req.model)
        if self.placement_advisor is not None:
            self.placement_advisor.note_pick(
                decode_pod.name, req.resolved_target_model)
        if sampled:
            # The decode hop IS the Python path here (tree + filters run
            # in Python above) — charged directly, no shadow needed.
            ledger.charge(
                req, winner=decode_pod.name, base=decode_base,
                post_health=decode_health, post_fairness=decode_fairness,
                post_placement=decode_survivors, hop="decode",
                path="python", pool_n=len(pods), role_n=len(decodes),
                advisors=(self.health_advisor, self.usage_advisor,
                          self.placement_advisor),
                escape_base=escape_base, trace_id=req.trace_id)
        return prefill_pod, decode_pod


def make_scheduler(provider, cfg: SchedulerConfig = DEFAULT_CONFIG,
                   prefer_native: bool = True, **kwargs):
    """Native scheduler when buildable, Python tree otherwise."""
    if prefer_native and available():
        try:
            return NativeScheduler(provider, cfg, **kwargs)
        except RuntimeError:
            pass
    return Scheduler(provider, cfg, **kwargs)
