"""ctypes binding for the C++ scheduler hot path (native/scheduler.cc).

``NativeScheduler`` is a drop-in for ``Scheduler`` — identical decision-tree
semantics (fuzz-verified against the Python tree), with candidate-set
computation in C++ and the final random pick kept in Python so RNG behavior
matches.  Falls back transparently when the shared library can't be built
(``available()`` is False); callers should construct via ``make_scheduler``.

The library auto-builds on first use via the Makefile next to the source —
the image ships g++/make, and the build is one translation unit (<1 s).
"""

from __future__ import annotations

import ctypes
import logging
import os
import random
import threading

import numpy as np

from llm_instance_gateway_tpu.gateway.scheduling.config import (
    DEFAULT_CONFIG,
    SchedulerConfig,
)
from llm_instance_gateway_tpu.gateway.scheduling.filter import FilterError
from llm_instance_gateway_tpu.gateway.scheduling.scheduler import (
    PodMetricsProvider,
    Scheduler,
    SchedulingError,
    build_decode_tree,
    filter_by_policy,
    split_pool_roles,
)
from llm_instance_gateway_tpu.gateway.scheduling.types import LLMRequest
from llm_instance_gateway_tpu.gateway.types import (
    ROLE_COLLOCATED,
    Pod,
    PodMetrics,
    pod_role,
)

logger = logging.getLogger(__name__)

_NATIVE_DIR = os.path.join(
    os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__)))),
    "native",
)
_LIB_PATH = os.path.join(_NATIVE_DIR, "libligsched.so")

LIG_SHED = -1
LIG_ERROR = -2

_lib = None
_lib_lock = threading.Lock()


def _load_library():
    global _lib
    with _lib_lock:
        if _lib is not None:
            return _lib
        from llm_instance_gateway_tpu.utils.native_build import (
            ensure_native_lib,
        )

        if ensure_native_lib(_NATIVE_DIR, "libligsched.so",
                             "scheduler.cc") is None:
            return None
        try:
            lib = ctypes.CDLL(_LIB_PATH)
        except OSError as e:
            logger.warning("native scheduler load failed: %s", e)
            return None
        lib.lig_schedule_candidates.restype = ctypes.c_int32
        lib.lig_schedule_candidates.argtypes = [
            ctypes.c_int32,
            ctypes.POINTER(ctypes.c_int32),   # waiting
            ctypes.POINTER(ctypes.c_int32),   # prefill
            ctypes.POINTER(ctypes.c_double),  # kv_usage
            ctypes.POINTER(ctypes.c_int64),   # kv_free
            ctypes.POINTER(ctypes.c_int64),   # kv_capacity
            ctypes.POINTER(ctypes.c_uint8),   # has_affinity
            ctypes.POINTER(ctypes.c_int32),   # n_active
            ctypes.POINTER(ctypes.c_int32),   # max_active
            ctypes.c_uint8,                   # critical
            ctypes.c_int64,                   # prompt_tokens
            ctypes.c_double,                  # kv_cache_threshold
            ctypes.c_int32,                   # queue_threshold_critical
            ctypes.c_int32,                   # queueing_threshold_lora
            ctypes.c_double,                  # token_headroom_factor
            ctypes.c_int32,                   # prefill_queue_threshold
            ctypes.c_uint8,                   # token_aware
            ctypes.c_uint8,                   # prefill_aware
            ctypes.POINTER(ctypes.c_int32),   # out
        ]
        _lib = lib
        return _lib


def available() -> bool:
    return _load_library() is not None


def _ptr(arr: np.ndarray, ctype):
    return arr.ctypes.data_as(ctypes.POINTER(ctype))


class NativeScheduler:
    """Same interface as Scheduler.schedule; C++ candidate computation."""

    def __init__(
        self,
        pod_metrics_provider: PodMetricsProvider,
        cfg: SchedulerConfig = DEFAULT_CONFIG,
        token_aware: bool = True,
        prefill_aware: bool = True,
        prefix_aware: bool = True,
        prefix_index=None,
        rng: random.Random | None = None,
    ):
        lib = _load_library()
        if lib is None:
            raise RuntimeError("native scheduler library unavailable")
        self._lib = lib
        self._provider = pod_metrics_provider
        self.cfg = cfg
        self.token_aware = token_aware
        self.prefill_aware = prefill_aware
        # Same post-tree prefix-affinity tie-break as the Python Scheduler
        # (scheduling/prefix_affinity.py): applied over the C++ candidate
        # set, so the fuzz-pinned candidate parity is untouched.
        # ``prefix_index`` shares one index across scheduler instances
        # routing the same pool; prefix_aware=False disables the tie-break
        # even with an injected index (see Scheduler.__init__).
        self.prefix_index = prefix_index if prefix_aware else None
        if prefix_aware and self.prefix_index is None:
            from llm_instance_gateway_tpu.gateway.scheduling.prefix_affinity import (
                PrefixIndex,
            )

            self.prefix_index = PrefixIndex()
        self._rng = rng or random.Random()
        # Decode-hop stage for disaggregated pools: the tiny Python tree
        # (2-3 filters over the decode-role subset) — not worth an FFI
        # seam, and it keeps the fuzz-pinned C++ candidate parity for the
        # main tree untouched.
        self._decode_tree = build_decode_tree(cfg, token_aware=token_aware)
        self._snapshot: dict | None = None
        # The gRPC transport calls schedule() from a thread pool; the cached
        # arrays (including the C++ output buffer) are shared state.
        self._call_lock = threading.Lock()
        # Health/resilience hook (gateway/resilience.py) — same seam as
        # the Python Scheduler: log_only counts would-be avoidance picks
        # and never alters the pick (candidate parity with C++ stays
        # exact); avoid/strict filter via filter_by_policy in _pick.
        self.health_advisor = None
        # Usage seam (gateway/usage.py) — log-only pick counting, same
        # contract as the Python Scheduler's usage_advisor.
        self.usage_advisor = None

    def _arrays(self, req: LLMRequest, pods: list[PodMetrics],
                version: int | None):
        """Flattened metric arrays, cached per provider snapshot version.

        Marshalling Python attributes into arrays costs more than the C++
        tree itself; metrics only change at scrape cadence (50 ms), so the
        arrays are rebuilt once per snapshot and shared by every request in
        between.  Per-adapter residency vectors are cached the same way.
        ``version`` must be read atomically WITH ``pods`` (Provider.snapshot)
        or None to disable caching.
        """
        cached = self._snapshot
        if version is None or cached is None or cached["version"] != version \
                or cached["n"] != len(pods):
            n = len(pods)
            cached = {
                "version": version,
                "n": n,
                "waiting": np.fromiter(
                    (pm.metrics.total_queue_size for pm in pods), np.int32, n),
                "prefill": np.fromiter(
                    (pm.metrics.prefill_queue_size for pm in pods), np.int32, n),
                "kv_usage": np.fromiter(
                    (pm.metrics.kv_cache_usage_percent for pm in pods), np.float64, n),
                "kv_free": np.fromiter(
                    (pm.metrics.kv_tokens_free for pm in pods), np.int64, n),
                "kv_capacity": np.fromiter(
                    (pm.metrics.kv_tokens_capacity for pm in pods), np.int64, n),
                "n_active": np.fromiter(
                    (len(pm.metrics.active_adapters) for pm in pods), np.int32, n),
                "max_active": np.fromiter(
                    (pm.metrics.max_active_adapters for pm in pods), np.int32, n),
                "affinity": {},
                "out": np.empty(n, np.int32),
            }
            self._snapshot = cached
        adapter = req.resolved_target_model
        affinity = cached["affinity"].get(adapter)
        if affinity is None:
            affinity = np.fromiter(
                (adapter in pm.metrics.active_adapters for pm in pods),
                np.uint8, cached["n"],
            )
            cached["affinity"][adapter] = affinity
        return cached, affinity

    def candidates(self, req: LLMRequest, pods: list[PodMetrics],
                   version: int | None = None) -> list[int]:
        n = len(pods)
        if n == 0:
            # Parity: the Python tree's failure branches land in the drop
            # filter on an empty pool, i.e. shed -> 429.
            raise SchedulingError(
                "failed to apply filter, resulted 0 pods: no pods", shed=True
            )
        with self._call_lock:
            return self._candidates_locked(req, pods, n, version)

    def _candidates_locked(self, req, pods, n, version) -> list[int]:
        cached, affinity = self._arrays(req, pods, version)
        waiting = cached["waiting"]
        prefill = cached["prefill"]
        kv_usage = cached["kv_usage"]
        kv_free = cached["kv_free"]
        n_active = cached["n_active"]
        max_active = cached["max_active"]
        out = cached["out"]
        count = self._lib.lig_schedule_candidates(
            n,
            _ptr(waiting, ctypes.c_int32), _ptr(prefill, ctypes.c_int32),
            _ptr(kv_usage, ctypes.c_double), _ptr(kv_free, ctypes.c_int64),
            _ptr(cached["kv_capacity"], ctypes.c_int64),
            _ptr(affinity, ctypes.c_uint8), _ptr(n_active, ctypes.c_int32),
            _ptr(max_active, ctypes.c_int32),
            1 if req.critical else 0,
            req.prompt_tokens,
            self.cfg.kv_cache_threshold,
            self.cfg.queue_threshold_critical,
            self.cfg.queueing_threshold_lora,
            self.cfg.token_headroom_factor,
            self.cfg.prefill_queue_threshold,
            1 if self.token_aware else 0,
            1 if self.prefill_aware else 0,
            _ptr(out, ctypes.c_int32),
        )
        if count == LIG_SHED:
            raise SchedulingError(
                "failed to apply filter, resulted 0 pods: dropping request due "
                "to limited backend resources",
                shed=True,
            )
        if count < 0:
            raise SchedulingError(f"native scheduler error {count}")
        return out[:count].tolist()

    def update_config(self, cfg: SchedulerConfig) -> None:
        """Swap thresholds at runtime — cfg fields cross the FFI per call."""
        self.cfg = cfg
        self._decode_tree = build_decode_tree(
            cfg, token_aware=self.token_aware)

    def _snapshot_pods(self):
        snapshot = getattr(self._provider, "snapshot", None)
        if snapshot is not None:
            return snapshot()  # atomic (version, pods) pair
        return None, self._provider.all_pod_metrics()

    def _pick(self, req: LLMRequest, pods: list[PodMetrics],
              idxs: list[int]) -> Pod:
        # Same policy seam as the Python Scheduler: the C++ candidate set
        # narrows to non-avoided pods BEFORE the tie-break and the RNG
        # draw; log_only returns the indices unchanged, keeping the
        # fuzz-pinned candidate parity exact.
        idxs = filter_by_policy(self.health_advisor, idxs,
                                name_of=lambda i: pods[i].pod.name)
        pick = None
        if self.prefix_index is not None and req.prefix_hashes:
            held = self.prefix_index.prefer(req, [pods[i] for i in idxs])
            if held is not None:
                pick = held.pod
        if pick is None:
            pick = pods[idxs[self._rng.randrange(len(idxs))]].pod
        if self.prefix_index is not None and req.prefix_hashes:
            self.prefix_index.record(req.prefix_hashes, pick.name)
        if self.health_advisor is not None:
            self.health_advisor.note_pick(pick.name)
        if self.usage_advisor is not None:
            self.usage_advisor.note_pick(pick.name, req.model)
        return pick

    def schedule(self, req: LLMRequest) -> Pod:
        version, pods = self._snapshot_pods()
        # Same role policy as the Python Scheduler: single-hop traffic
        # prefers collocated replicas; a role-filtered SUBSET bypasses the
        # snapshot-version array cache (it keys on (version, n) and a
        # subset would poison it).
        collocated = [pm for pm in pods
                      if pod_role(pm.pod) == ROLE_COLLOCATED]
        if collocated and len(collocated) != len(pods):
            pods, version = collocated, None
        idxs = self.candidates(req, pods, version)
        return self._pick(req, pods, idxs)

    def schedule_disaggregated(
        self, req: LLMRequest
    ) -> tuple[Pod, Pod | None]:
        """Two-stage routing (see ``Scheduler.schedule_disaggregated``):
        C++ candidates over the prefill-role subset, then the decode tree
        over the decode-role subset."""
        version, pods = self._snapshot_pods()
        prefills, decodes = split_pool_roles(pods)
        if not prefills or not decodes:
            return self.schedule(req), None
        idxs = self.candidates(req, prefills, None)  # subset: no cache
        prefill_pod = self._pick(req, prefills, idxs)
        try:
            decode_survivors = self._decode_tree.filter(req, decodes)
        except FilterError as e:
            raise SchedulingError(
                f"no decode replica for disaggregated request: {e}",
                shed=e.shed) from e
        decode_survivors = filter_by_policy(
            self.health_advisor, decode_survivors)
        decode_pod = decode_survivors[
            self._rng.randrange(len(decode_survivors))].pod
        if self.health_advisor is not None:
            self.health_advisor.note_pick(decode_pod.name)
        if self.usage_advisor is not None:
            self.usage_advisor.note_pick(decode_pod.name, req.model)
        return prefill_pod, decode_pod


def make_scheduler(provider, cfg: SchedulerConfig = DEFAULT_CONFIG,
                   prefer_native: bool = True, **kwargs):
    """Native scheduler when buildable, Python tree otherwise."""
    if prefer_native and available():
        try:
            return NativeScheduler(provider, cfg, **kwargs)
        except RuntimeError:
            pass
    return Scheduler(provider, cfg, **kwargs)
