"""Approximate prefix-cache-aware routing (gateway side).

A replica that already holds a prompt's leading KV blocks serves it with
near-zero prefill for the shared part (the engine's content-addressed
prefix cache, ``models/paged.py``; hit volume is visible per replica as
``tpu:prefix_reused_tokens``).  The gateway cannot see replica block
tables, so it keeps the standard approximation used by prefix-aware LLM
routers: hash the prompt's leading text in fixed CHARACTER blocks
(tokenizer-free — the gateway has no tokenizer; ~4 chars/token makes a
256-char block ≈ the engine's default 64-token KV block), chain the
hashes exactly like the engine chains block content hashes, remember
which pod each chain hash was last routed to, and prefer the pod holding
the LONGEST matching chain.

Self-correcting by construction: the index is an LRU of recent routing
decisions, so a replica that restarts (losing its cache) is re-learned
within one window, and a wrong preference costs only a missed reuse.
The preference is a POST-TREE TIE-BREAK (``PrefixIndex.prefer``): both
schedulers (Python tree and C++ candidate path) run their full decision
tree first and the holder is preferred only among the tree's survivors —
it can never resurrect a replica the queue/KV/shed stages excluded, and
the fuzz-pinned Python/native candidate parity is untouched.

Interplay with relative bucketing (observed live, 2-replica rig): the
tree's queue/KV stages bucket RELATIVE to the pool minimum, so near
zero load a transient usage blip on the holder (it just served the
previous request; the 50ms scrape caught it mid-decode) can bucket it
out and the pick lands elsewhere — serialized one-at-a-time probes
therefore alternate rather than stick.  Two consequences, both fine:
hot SHARED prefixes replicate to every healthy replica within a few
requests (each then serves them as cache hits —
``gateway_pool_prefix_reused_tokens_total`` climbs pool-wide, the desirable
steady state for system prompts); and affinity binds strongest exactly
where it matters — steady concurrent load, where every replica carries
nonzero usage and small deltas stay inside the bucket, and long
session-unique prefixes (multi-turn continuations) whose holder the
tree has no reason to exclude.

Reference note: the reference tree routes on queue/LoRA/KV signals only
(``pkg/ext-proc/scheduling/scheduler.go:26-91``); prefix affinity is a
TPU-serving extension in the same spirit as the token-headroom and
prefill-queue stages, OFF under ``prefix_aware=False`` and a no-op
until a request actually repeats a prefix.
"""

from __future__ import annotations

import hashlib
from collections import OrderedDict
from typing import Sequence

from llm_instance_gateway_tpu.lockwitness import witness_lock
from llm_instance_gateway_tpu.gateway.scheduling.types import LLMRequest
from llm_instance_gateway_tpu.gateway.types import PodMetrics

# 256 chars ≈ 64 tokens — the engine's default --paged-kv-block, so one
# gateway block ≈ one replica KV block.  Whole blocks only (engine parity:
# a partial trailing block is never content-addressed).
PREFIX_BLOCK_CHARS = 256
# Hash at most this many leading blocks (~8 KB / ~2k tokens): system
# prompts and few-shot preambles — the traffic prefix caching exists for —
# fit comfortably; hashing cost stays trivially bounded per request.
MAX_BLOCKS = 32
# Load-aware cap on the prefer() tie-break: skip a holder whose waiting
# queue (absolute) or KV usage (fraction) exceeds the survivor median by
# these margins — a hot shared prefix must not pin ALL its traffic to one
# replica indefinitely (the overflow replicates the prefix, which then
# serves it as cache hits; the desirable steady state for system prompts).
HOLDER_QUEUE_SLACK = 4
HOLDER_KV_SLACK = 0.2
# Hysteresis on record(): a still-warm holder is replaced only after this
# many CONSECUTIVE picks of the same other pod for a hash — one transient
# off-holder pick (a scrape blip bucketed the holder out for 50 ms) must
# not erase affinity the holder's KV cache still backs.
DIVERGENT_PICKS_TO_STEAL = 2


def prefix_hashes(text: str, model: str = "") -> tuple[int, ...]:
    """Chained per-block hashes of the prompt's leading whole blocks.

    Chaining (each hash covers all preceding blocks) mirrors the engine's
    chain-hash keys: matching hash i implies blocks 0..i all match, so the
    longest matching hash IS the longest shared prefix.  The chain is
    SEEDED with the resolved target model: KV blocks are model-specific,
    so identical boilerplate under two models/adapters must not alias (a
    cross-model "hit" would concentrate load with zero actual reuse).
    blake2b keeps the chain stable across processes (``hash()`` is salted
    per process and the index may one day be shared between gateway
    replicas).
    """
    out: list[int] = []
    h = hashlib.blake2b(model.encode("utf-8", "surrogatepass"),
                        digest_size=8).digest() if model else b""
    limit = min(len(text) // PREFIX_BLOCK_CHARS, MAX_BLOCKS)
    for i in range(limit):
        block = text[i * PREFIX_BLOCK_CHARS:(i + 1) * PREFIX_BLOCK_CHARS]
        h = hashlib.blake2b(h + block.encode("utf-8", "surrogatepass"),
                            digest_size=8).digest()
        out.append(int.from_bytes(h, "big"))
    return tuple(out)


class PrefixIndex:
    """LRU map: chain hash -> pod name that last served that prefix."""

    def __init__(self, capacity: int = 16384):
        self.capacity = capacity
        self._map: "OrderedDict[int, str]" = OrderedDict()
        # Divergence counters: hash -> (candidate pod, consecutive picks).
        # Bounded by _map pruning (entries die with their hash).
        self._pending: dict[int, tuple[str, int]] = {}
        self._lock = witness_lock("PrefixIndex._lock")

    def record(self, hashes: Sequence[int], pod_name: str) -> None:
        """Learn ``pod_name`` as the holder of ``hashes``.

        Fresh hashes bind immediately.  A hash with a DIFFERENT current
        holder updates only after ``DIVERGENT_PICKS_TO_STEAL`` consecutive
        picks of the same new pod: a single off-holder pick (relative
        bucketing catching the holder mid-decode in one 50 ms scrape) used
        to overwrite a still-warm holder and flap affinity between
        replicas; now it takes a sustained divergence — i.e. the tree
        genuinely stopped admitting the holder — to re-learn.
        """
        if not hashes:
            return
        with self._lock:
            for h in hashes:
                cur = self._map.get(h)
                if cur is None or cur == pod_name:
                    self._map[h] = pod_name
                    self._map.move_to_end(h)
                    self._pending.pop(h, None)
                    continue
                cand, count = self._pending.get(h, (pod_name, 0))
                if cand != pod_name:
                    cand, count = pod_name, 0
                count += 1
                if count >= DIVERGENT_PICKS_TO_STEAL:
                    self._map[h] = pod_name
                    self._pending.pop(h, None)
                else:
                    self._pending[h] = (cand, count)
                self._map.move_to_end(h)  # the hash itself is hot either way
            while len(self._map) > self.capacity:
                evicted, _ = self._map.popitem(last=False)
                self._pending.pop(evicted, None)

    def lookup(self, hashes: Sequence[int]) -> tuple[str | None, int]:
        """(pod name holding the longest matching chain, depth in blocks)."""
        with self._lock:
            for depth in range(len(hashes), 0, -1):
                pod = self._map.get(hashes[depth - 1])
                if pod is not None:
                    return pod, depth
        return None, 0

    def prefer(self, req: LLMRequest,
               survivors: Sequence[PodMetrics]) -> PodMetrics | None:
        """The SURVIVOR holding the request's longest prefix, or None.

        Applied AFTER the full decision tree (Python and native schedulers
        identically): among pods the tree judged equally good, prefer the
        one whose KV cache already holds the deepest prompt prefix.
        Scans depths longest-first and skips holders the tree excluded —
        a shallower prefix on a HEALTHY replica beats a deeper one on an
        excluded replica (which is never resurrected).  A restarted
        replica's stale entries cost only missed-reuse picks until LRU
        turnover re-learns them.

        Load-aware cap: a holder whose waiting queue or KV usage exceeds
        the SURVIVOR MEDIAN by more than the slack constants is skipped
        even though the tree kept it — relative bucketing admits "the
        whole pool is busy" states where affinity would otherwise pin a
        hot shared prefix to one replica indefinitely; spilling to a
        random survivor replicates the prefix, and subsequent requests
        find it cached on both."""
        names = {pm.pod.name: pm for pm in survivors}
        hashes = req.prefix_hashes
        if not names or not hashes:
            return None
        queues = sorted(pm.metrics.total_queue_size for pm in survivors)
        kvs = sorted(pm.metrics.kv_cache_usage_percent for pm in survivors)
        # LOWER median: with an even survivor count the upper median can be
        # the holder's own load, which would make the cap unreachable on
        # the common 2-replica pool.
        mid = (len(survivors) - 1) // 2
        queue_cap = queues[mid] + HOLDER_QUEUE_SLACK
        kv_cap = kvs[mid] + HOLDER_KV_SLACK
        with self._lock:
            for depth in range(len(hashes), 0, -1):
                pod = self._map.get(hashes[depth - 1])
                if pod is None or pod not in names:
                    continue
                pm = names[pod]
                if (pm.metrics.total_queue_size > queue_cap
                        or pm.metrics.kv_cache_usage_percent > kv_cap):
                    continue  # overloaded holder: shallower/other holders
                return pm
        return None
