"""Scheduler thresholds as runtime configuration.

The reference hard-codes these with an explicit TODO to move them into the
InferencePool config (``pkg/ext-proc/scheduling/scheduler.go:16-24``):
``kvCacheThreshold=0.8``, ``queueThresholdCritical=5``,
``queueingThresholdLoRA=50``.  We resolve that TODO: thresholds live in a
dataclass, defaulted to the reference's experimentally-derived values, and can
be overridden per-pool (see ``gateway.controllers.pool``) or retuned with the
simulator (``sim/``) before burning TPU hours.
"""

from __future__ import annotations

from dataclasses import dataclass, field

from llm_instance_gateway_tpu.gateway.fairness import (
    FAIRNESS_MODES,
    FairnessConfig,
)


@dataclass(frozen=True)
class AdmissionConfig:
    """Saturation-gated admission queueing (scheduling.admission).

    The reference sim's 'smart' policy knobs: queue instead of shedding
    non-critical traffic, drain tighter tiers more often
    (``simulations/.../loadbalancer.py:351-426``)."""

    enabled: bool = False
    # A parked request sheds (429) if no capacity frees within this window.
    max_wait_s: float = 30.0
    # Total parked requests across tiers; beyond it, shed immediately.
    max_depth: int = 256
    # Drain retry cadence; metrics refresh every 50ms, so retrying much
    # faster only burns CPU on the same snapshot.
    retry_interval_s: float = 0.05
    # Relative drain frequency per tier (weighted_dequeue: tighter SLO tier
    # gets proportionally more draws).
    tier_weights: tuple[tuple[str, float], ...] = (
        ("Default", 4.0), ("Sheddable", 1.0))
    # Hysteresis: the DRAIN re-admits against thresholds scaled by this
    # factor (the reference gates dequeueing on saturation having CLEARED,
    # not merely dipped).  Parked traffic backfilling right up to the shed
    # line would eat the headroom critical bursts rely on.  0.7 measured
    # (sim A/B, 4 seeds, qps 40-90 overload on 4 replicas): Default-tier
    # SLO goodput +9pp, Sheddable +8pp, Critical within noise (-0.6pp mean).
    drain_margin: float = 0.7


@dataclass(frozen=True)
class SchedulerConfig:
    # Max KV-cache utilization for a pod to accept a sheddable request.
    kv_cache_threshold: float = 0.8
    # Max total queue depth for a pod to accept a sheddable request.
    queue_threshold_critical: int = 5
    # Queue depth above which LoRA affinity stops being worth the wait and the
    # scheduler falls through to least-queuing (scheduler.go:40-57).
    queueing_threshold_lora: int = 50
    # TPU additions -------------------------------------------------------
    # Prefer pods whose free KV tokens cover the prompt (token-aware routing
    # for long context); only applied when the request carries a token hint.
    token_headroom_factor: float = 1.0
    # Prefill queue depth above which a replica is considered prefill-saturated
    # (prefill/decode disaggregation: scheduler must not send long prompts to a
    # replica with a deep prefill backlog even if decode is idle).
    prefill_queue_threshold: int = 8
    # Saturation-gated admission queueing: opt-in queue-instead-of-shed for
    # non-critical traffic, the reference sim's 'smart' policy brought to
    # the live gateway.
    admission: AdmissionConfig = field(default_factory=AdmissionConfig)
    # Fairness & quota plane (gateway/fairness.py): usage-driven pick
    # deprioritization and rank-weighted tenant quotas, hot-reloadable
    # through the same pool document as the thresholds.
    fairness: FairnessConfig = field(default_factory=FairnessConfig)


DEFAULT_CONFIG = SchedulerConfig()

# Pool-document key (camelCase, CRD style) -> dataclass field.
_POOL_KEYS = {
    "kvCacheThreshold": "kv_cache_threshold",
    "queueThresholdCritical": "queue_threshold_critical",
    "queueingThresholdLoRA": "queueing_threshold_lora",
    "tokenHeadroomFactor": "token_headroom_factor",
    "prefillQueueThreshold": "prefill_queue_threshold",
}


_ADMISSION_KEYS = {
    "enabled": ("enabled", bool),
    "maxWaitSeconds": ("max_wait_s", float),
    "maxDepth": ("max_depth", int),
    "retryIntervalSeconds": ("retry_interval_s", float),
    "tierWeights": ("tier_weights", dict),
    "drainMargin": ("drain_margin", float),
}


def drain_scaled(cfg: SchedulerConfig) -> SchedulerConfig:
    """Thresholds the admission DRAIN schedules against: the shed thresholds
    scaled by ``drain_margin`` (hysteresis protecting critical headroom)."""
    import dataclasses

    m = cfg.admission.drain_margin
    return dataclasses.replace(
        cfg,
        kv_cache_threshold=cfg.kv_cache_threshold * m,
        queue_threshold_critical=max(1, int(cfg.queue_threshold_critical * m)),
    )


_FAIRNESS_KEYS = {
    "mode": ("mode", str),
    "overRatio": ("over_ratio", float),
    "maxShare": ("max_share", float),
    "quotaRps": ("quota_rps", float),
    "quotaBurst": ("quota_burst", float),
    "rankBase": ("rank_base", int),
    "retryAfterSeconds": ("retry_after_s", float),
}


def _parse_fairness(section) -> FairnessConfig:
    if not isinstance(section, dict):
        raise ValueError(
            f"fairnessPolicy must be a mapping, got {section!r}")
    unknown = set(section) - set(_FAIRNESS_KEYS)
    if unknown:
        raise ValueError(
            f"unknown fairnessPolicy keys {sorted(unknown)}; "
            f"valid: {sorted(_FAIRNESS_KEYS)}")
    import dataclasses

    kwargs = {}
    for doc_key, (field_name, kind) in _FAIRNESS_KEYS.items():
        if doc_key not in section:
            continue
        raw = section[doc_key]
        if kind is str:
            if raw not in FAIRNESS_MODES:
                raise ValueError(
                    f"fairnessPolicy mode must be one of "
                    f"{FAIRNESS_MODES}, got {raw!r}")
            kwargs[field_name] = raw
        else:
            try:
                value = float(raw)
            except (TypeError, ValueError) as e:
                raise ValueError(
                    f"{doc_key} must be a number, got {raw!r}") from e
            if value <= 0:
                raise ValueError(f"{doc_key} must be positive, got {raw!r}")
            kwargs[field_name] = int(value) if kind is int else value
    return dataclasses.replace(FairnessConfig(), **kwargs)


def _parse_admission(section) -> AdmissionConfig:
    if not isinstance(section, dict):
        raise ValueError(
            f"admissionQueue must be a mapping, got {section!r}")
    unknown = set(section) - set(_ADMISSION_KEYS)
    if unknown:
        raise ValueError(
            f"unknown admissionQueue keys {sorted(unknown)}; "
            f"valid: {sorted(_ADMISSION_KEYS)}")
    import dataclasses

    kwargs = {}
    for doc_key, (field_name, kind) in _ADMISSION_KEYS.items():
        if doc_key not in section:
            continue
        raw = section[doc_key]
        if kind is bool:
            if not isinstance(raw, bool):
                raise ValueError(f"{doc_key} must be true/false, got {raw!r}")
            kwargs[field_name] = raw
        elif kind is dict:
            if (not isinstance(raw, dict)
                    or not all(isinstance(v, (int, float)) and v > 0
                               for v in raw.values())):
                raise ValueError(
                    f"{doc_key} must map tier name -> positive weight, "
                    f"got {raw!r}")
            kwargs[field_name] = tuple(
                (str(t), float(w)) for t, w in sorted(raw.items()))
        else:
            try:
                value = float(raw)
            except (TypeError, ValueError) as e:
                raise ValueError(f"{doc_key} must be a number, got {raw!r}") from e
            if value <= 0:
                raise ValueError(f"{doc_key} must be positive, got {raw!r}")
            kwargs[field_name] = int(value) if kind is int else value
    return dataclasses.replace(AdmissionConfig(), **kwargs)


def from_pool_spec(overrides: dict) -> SchedulerConfig:
    """SchedulerConfig from an InferencePool's ``schedulerConfig`` section.

    The end of the reference's threshold TODO (scheduler.go:16-24): per-pool
    values arrive through the same declarative document as the pool itself.
    Unknown keys raise — silent typos in thresholds are how shedding policies
    quietly stop working.
    """
    if not overrides:
        return DEFAULT_CONFIG
    unknown = (set(overrides) - set(_POOL_KEYS)
               - {"admissionQueue", "fairnessPolicy"})
    if unknown:
        raise ValueError(
            f"unknown schedulerConfig keys {sorted(unknown)}; "
            f"valid: {sorted(_POOL_KEYS) + ['admissionQueue', 'fairnessPolicy']}"
        )
    import dataclasses

    kwargs = {}
    if "admissionQueue" in overrides:
        kwargs["admission"] = _parse_admission(overrides["admissionQueue"])
    if "fairnessPolicy" in overrides:
        kwargs["fairness"] = _parse_fairness(overrides["fairnessPolicy"])
    for doc_key, field_name in _POOL_KEYS.items():
        if doc_key in overrides:
            current = getattr(DEFAULT_CONFIG, field_name)
            raw = overrides[doc_key]
            try:
                value = float(raw)
            except (TypeError, ValueError) as e:
                # Normalize to ValueError so the hot-reload hook's
                # keep-last-good handler catches nulls/lists too.
                raise ValueError(
                    f"{doc_key} must be a number, got {raw!r}"
                ) from e
            if isinstance(current, int):
                if value != int(value):
                    raise ValueError(
                        f"{doc_key} must be an integer, got {raw!r} "
                        "(silent truncation would change the policy)"
                    )
                kwargs[field_name] = int(value)
            else:
                kwargs[field_name] = value
    return dataclasses.replace(DEFAULT_CONFIG, **kwargs)
