"""Scheduler thresholds as runtime configuration.

The reference hard-codes these with an explicit TODO to move them into the
InferencePool config (``pkg/ext-proc/scheduling/scheduler.go:16-24``):
``kvCacheThreshold=0.8``, ``queueThresholdCritical=5``,
``queueingThresholdLoRA=50``.  We resolve that TODO: thresholds live in a
dataclass, defaulted to the reference's experimentally-derived values, and can
be overridden per-pool (see ``gateway.controllers.pool``) or retuned with the
simulator (``sim/``) before burning TPU hours.
"""

from __future__ import annotations

from dataclasses import dataclass


@dataclass(frozen=True)
class SchedulerConfig:
    # Max KV-cache utilization for a pod to accept a sheddable request.
    kv_cache_threshold: float = 0.8
    # Max total queue depth for a pod to accept a sheddable request.
    queue_threshold_critical: int = 5
    # Queue depth above which LoRA affinity stops being worth the wait and the
    # scheduler falls through to least-queuing (scheduler.go:40-57).
    queueing_threshold_lora: int = 50
    # TPU additions -------------------------------------------------------
    # Prefer pods whose free KV tokens cover the prompt (token-aware routing
    # for long context); only applied when the request carries a token hint.
    token_headroom_factor: float = 1.0
    # Prefill queue depth above which a replica is considered prefill-saturated
    # (prefill/decode disaggregation: scheduler must not send long prompts to a
    # replica with a deep prefill backlog even if decode is idle).
    prefill_queue_threshold: int = 8


DEFAULT_CONFIG = SchedulerConfig()

# Pool-document key (camelCase, CRD style) -> dataclass field.
_POOL_KEYS = {
    "kvCacheThreshold": "kv_cache_threshold",
    "queueThresholdCritical": "queue_threshold_critical",
    "queueingThresholdLoRA": "queueing_threshold_lora",
    "tokenHeadroomFactor": "token_headroom_factor",
    "prefillQueueThreshold": "prefill_queue_threshold",
}


def from_pool_spec(overrides: dict) -> SchedulerConfig:
    """SchedulerConfig from an InferencePool's ``schedulerConfig`` section.

    The end of the reference's threshold TODO (scheduler.go:16-24): per-pool
    values arrive through the same declarative document as the pool itself.
    Unknown keys raise — silent typos in thresholds are how shedding policies
    quietly stop working.
    """
    if not overrides:
        return DEFAULT_CONFIG
    unknown = set(overrides) - set(_POOL_KEYS)
    if unknown:
        raise ValueError(
            f"unknown schedulerConfig keys {sorted(unknown)}; "
            f"valid: {sorted(_POOL_KEYS)}"
        )
    import dataclasses

    kwargs = {}
    for doc_key, field_name in _POOL_KEYS.items():
        if doc_key in overrides:
            current = getattr(DEFAULT_CONFIG, field_name)
            raw = overrides[doc_key]
            try:
                value = float(raw)
            except (TypeError, ValueError) as e:
                # Normalize to ValueError so the hot-reload hook's
                # keep-last-good handler catches nulls/lists too.
                raise ValueError(
                    f"{doc_key} must be a number, got {raw!r}"
                ) from e
            if isinstance(current, int):
                if value != int(value):
                    raise ValueError(
                        f"{doc_key} must be an integer, got {raw!r} "
                        "(silent truncation would change the policy)"
                    )
                kwargs[field_name] = int(value)
            else:
                kwargs[field_name] = value
    return dataclasses.replace(DEFAULT_CONFIG, **kwargs)
