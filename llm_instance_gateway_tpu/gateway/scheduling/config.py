"""Scheduler thresholds as runtime configuration.

The reference hard-codes these with an explicit TODO to move them into the
InferencePool config (``pkg/ext-proc/scheduling/scheduler.go:16-24``):
``kvCacheThreshold=0.8``, ``queueThresholdCritical=5``,
``queueingThresholdLoRA=50``.  We resolve that TODO: thresholds live in a
dataclass, defaulted to the reference's experimentally-derived values, and can
be overridden per-pool (see ``gateway.controllers.pool``) or retuned with the
simulator (``sim/``) before burning TPU hours.
"""

from __future__ import annotations

from dataclasses import dataclass


@dataclass(frozen=True)
class SchedulerConfig:
    # Max KV-cache utilization for a pod to accept a sheddable request.
    kv_cache_threshold: float = 0.8
    # Max total queue depth for a pod to accept a sheddable request.
    queue_threshold_critical: int = 5
    # Queue depth above which LoRA affinity stops being worth the wait and the
    # scheduler falls through to least-queuing (scheduler.go:40-57).
    queueing_threshold_lora: int = 50
    # TPU additions -------------------------------------------------------
    # Prefer pods whose free KV tokens cover the prompt (token-aware routing
    # for long context); only applied when the request carries a token hint.
    token_headroom_factor: float = 1.0
    # Prefill queue depth above which a replica is considered prefill-saturated
    # (prefill/decode disaggregation: scheduler must not send long prompts to a
    # replica with a deep prefill backlog even if decode is idle).
    prefill_queue_threshold: int = 8


DEFAULT_CONFIG = SchedulerConfig()
