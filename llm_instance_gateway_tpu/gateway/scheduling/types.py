"""Scheduling request type.

Parity: reference ``pkg/ext-proc/scheduling/types.go:4-11`` (``LLMRequest``)
plus a token-count hint used by TPU-side token-aware routing (long-context
requests must land on replicas with enough KV-token headroom, SURVEY.md §5
"long-context").
"""

from __future__ import annotations

from dataclasses import dataclass, field


@dataclass
class LLMRequest:
    model: str
    target_models: dict[str, int] = field(default_factory=dict)
    resolved_target_model: str = ""
    critical: bool = False
    # TPU addition: estimated prompt tokens (0 = unknown).  Enables the
    # kv-token-headroom predicate; requests without the hint fall back to the
    # reference's percent-based signal.
    prompt_tokens: int = 0
    # Full criticality tier ("Critical"/"Default"/"Sheddable"): the
    # admission queue drains tiers at different weights; ``critical`` stays
    # the filter tree's binary signal (reference types.go parity).
    criticality: str = "Default"
    # TPU addition: chained block hashes of the prompt's leading text
    # (scheduling/prefix_affinity.py) — lets the scheduler prefer the
    # replica already holding this prefix's KV blocks.  Empty = no hint.
    prefix_hashes: tuple = ()
    # Tracing attribution (filled by the scheduling layer, read by the
    # request handler): how long this request waited in the admission
    # queue before a pod admitted it, and the (prefill_hop, decode_hop)
    # pick-time split of a disaggregated two-stage pick.
    admission_wait_s: float = 0.0
    pick_hops_s: tuple | None = None
