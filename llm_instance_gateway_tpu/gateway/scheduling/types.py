"""Scheduling request type.

Parity: reference ``pkg/ext-proc/scheduling/types.go:4-11`` (``LLMRequest``)
plus a token-count hint used by TPU-side token-aware routing (long-context
requests must land on replicas with enough KV-token headroom, SURVEY.md §5
"long-context").
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Callable, Sequence


class LazyPrefixHashes(Sequence):
    """Sequence facade that defers the prefix-hash chain until a consumer
    actually touches it.

    The chain (up to 32 chained blake2b digests over 8 KB of prompt,
    prefix_affinity.py) used to run on EVERY request body in the ext-proc
    hot path; threading this thunk instead means the digests only compute
    when a prefix-aware scheduler evaluates ``req.prefix_hashes`` — for a
    prefix-unaware build (or a custom drop-in that never reads the field)
    the cost is one object allocation.  Computes once, then serves the
    cached tuple; truthiness, iteration, indexing, and equality all match
    the eager tuple the field used to hold.
    """

    __slots__ = ("_fn", "_value")

    def __init__(self, fn: Callable[[], tuple]):
        self._fn = fn
        self._value: tuple | None = None

    def _resolve(self) -> tuple:
        if self._value is None:
            self._value = tuple(self._fn())
            self._fn = None  # drop the closure (it pins the prompt text)
        return self._value

    def __bool__(self) -> bool:
        return bool(self._resolve())

    def __len__(self) -> int:
        return len(self._resolve())

    def __iter__(self):
        return iter(self._resolve())

    def __getitem__(self, i):
        return self._resolve()[i]

    def __eq__(self, other) -> bool:
        if isinstance(other, LazyPrefixHashes):
            other = other._resolve()
        return self._resolve() == tuple(other) if isinstance(
            other, (tuple, list)) else self._resolve() == other

    def __hash__(self):
        return hash(self._resolve())

    def __repr__(self) -> str:
        if self._value is None:
            return "LazyPrefixHashes(<unevaluated>)"
        return f"LazyPrefixHashes({self._value!r})"


@dataclass
class LLMRequest:
    model: str
    target_models: dict[str, int] = field(default_factory=dict)
    resolved_target_model: str = ""
    critical: bool = False
    # TPU addition: estimated prompt tokens (0 = unknown).  Enables the
    # kv-token-headroom predicate; requests without the hint fall back to the
    # reference's percent-based signal.
    prompt_tokens: int = 0
    # Full criticality tier ("Critical"/"Default"/"Sheddable"): the
    # admission queue drains tiers at different weights; ``critical`` stays
    # the filter tree's binary signal (reference types.go parity).
    criticality: str = "Default"
    # TPU addition: chained block hashes of the prompt's leading text
    # (scheduling/prefix_affinity.py) — lets the scheduler prefer the
    # replica already holding this prefix's KV blocks.  Empty = no hint.
    # May hold a ``LazyPrefixHashes`` (the request handler threads one so
    # the digest chain never runs unless a scheduler consumes it).
    prefix_hashes: "tuple | LazyPrefixHashes" = ()
    # Tracing attribution (filled by the scheduling layer, read by the
    # request handler): how long this request waited in the admission
    # queue before a pod admitted it, and the (prefill_hop, decode_hop)
    # pick-time split of a disaggregated two-stage pick.
    admission_wait_s: float = 0.0
    pick_hops_s: tuple | None = None
    # The request's x-lig-trace-id (minted by the transport before
    # scheduling): lets the pick ledger's decision records join the
    # request's trace/span timeline.  Empty for callers without tracing
    # (sim, bench) — the ledger records it verbatim.
    trace_id: str = ""
