"""Filter decision-tree framework and the filter/predicate library.

Parity: reference ``pkg/ext-proc/scheduling/filter.go``:

- ``Filter`` node with ``next_on_success`` / ``next_on_failure`` /
  ``next_on_success_or_failure`` routing (filter.go:44-73): on success the
  *filtered* set flows down; on failure the *original* set flows to the
  failure branch (so a failed refinement falls back rather than dead-ends).
- ``to_filter_func`` lifts a per-pod predicate into a set filter that fails on
  an empty result (filter.go:79-93).
- The filter functions: least-queuing with first-range bucketing
  (filter.go:102-122), least-KV-cache (:134-154), low-queueing predicate
  (:124-126), low-LoRA-cost (:163-166), LoRA-affinity (:169-172),
  can-accept-new-LoRA (:175-177), critical-request (:179-181), and the
  sheddable-admission predicate (:183-187).

TPU-native additions: prefill-queue filters for the disaggregated
prefill/decode pipeline and a KV-token-headroom predicate for long-context
token-aware routing.  All pure functions over ``PodMetrics`` snapshots — the
hot path never touches I/O (SURVEY.md §3.2).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Callable, Optional, Sequence

from llm_instance_gateway_tpu.gateway.scheduling.config import (
    DEFAULT_CONFIG,
    SchedulerConfig,
)
from llm_instance_gateway_tpu.gateway.scheduling.types import LLMRequest
from llm_instance_gateway_tpu.gateway.types import PodMetrics

FilterFunc = Callable[[LLMRequest, Sequence[PodMetrics]], list[PodMetrics]]
Predicate = Callable[[LLMRequest, PodMetrics], bool]


class FilterError(Exception):
    """Raised when a filter yields no pods and there is no failure branch.

    ``shed=True`` marks the deliberate load-shedding drop (the tree's "drop
    request" leaf) as opposed to an unexpected empty result.
    """

    def __init__(self, msg: str, shed: bool = False):
        super().__init__(msg)
        self.shed = shed


@dataclass
class Filter:
    """A node in the scheduling decision tree (filter.go:30-73)."""

    name: str
    func: FilterFunc
    next_on_success: Optional["Filter"] = None
    next_on_failure: Optional["Filter"] = None
    next_on_success_or_failure: Optional["Filter"] = None

    def filter(self, req: LLMRequest, pods: Sequence[PodMetrics]) -> list[PodMetrics]:
        try:
            filtered = self.func(req, pods)
            err = None
        except FilterError as e:
            filtered, err = [], e

        success = err is None and len(filtered) > 0
        if success:
            nxt = self.next_on_success or self.next_on_success_or_failure
            if nxt is None:
                return filtered
            return nxt.filter(req, filtered)  # pass refined set down
        nxt = self.next_on_failure or self.next_on_success_or_failure
        if nxt is None:
            if err is not None:
                raise err  # leaf failure: propagate the causing error
            raise FilterError(f"no pods available for filter {self.name}")
        return nxt.filter(req, list(pods))  # pass ORIGINAL set to fallback


def to_filter_func(predicate: Predicate, name: str = "") -> FilterFunc:
    """Lift a per-pod predicate into a set filter (filter.go:79-93)."""

    def f(req: LLMRequest, pods: Sequence[PodMetrics]) -> list[PodMetrics]:
        kept = [pm for pm in pods if predicate(req, pm)]
        if not kept:
            raise FilterError(f"no pods passed predicate {name or predicate}")
        return kept

    f.__name__ = name or getattr(predicate, "__name__", "predicate")
    return f


# ---------------------------------------------------------------------------
# Range-bucketing filters (min..min+(max-min)/divisor], reference style.
# ---------------------------------------------------------------------------


def least_queuing_filter(req: LLMRequest, pods: Sequence[PodMetrics]) -> list[PodMetrics]:
    """Keep pods in the first 1/len(pods) range of queue depth (filter.go:102-122).

    The reference deliberately buckets instead of strict-min picking: pods with
    "relatively low" queueing all survive so the next filter can discriminate,
    and the final random pick spreads load among near-ties.  Queue depths are
    ints, so the range division is integer division, exactly as in Go
    (``min+(max-min)/len(pods)``, filter.go:117).
    """
    if not pods:
        raise FilterError("no pods to filter")
    depths = [pm.metrics.total_queue_size for pm in pods]
    lo, hi = min(depths), max(depths)
    cut = lo + (hi - lo) // len(pods)
    return [pm for pm, d in zip(pods, depths) if d <= cut]


def least_kv_cache_filter(req: LLMRequest, pods: Sequence[PodMetrics]) -> list[PodMetrics]:
    """First 1/len(pods) range of KV-cache utilization (filter.go:134-154)."""
    if not pods:
        raise FilterError("no pods to filter")
    usage = [pm.metrics.kv_cache_usage_percent for pm in pods]
    lo, hi = min(usage), max(usage)
    cut = lo + (hi - lo) / len(pods)
    return [pm for pm, u in zip(pods, usage) if u <= cut]


def least_prefill_queue_filter(
    req: LLMRequest, pods: Sequence[PodMetrics]
) -> list[PodMetrics]:
    """TPU addition: first half-range of prefill queue depth.

    With prefill/decode disaggregation a new request's TTFT is gated by the
    prefill queue specifically; decode backlog matters only for TPOT.
    """
    if not pods:
        raise FilterError("no pods to filter")
    depths = [pm.metrics.prefill_queue_size for pm in pods]
    lo, hi = min(depths), max(depths)
    cut = lo + (hi - lo) // len(pods)
    return [pm for pm, d in zip(pods, depths) if d <= cut]


# ---------------------------------------------------------------------------
# Predicates (config-parameterized where the reference hard-coded).
# ---------------------------------------------------------------------------


def make_predicates(cfg: SchedulerConfig = DEFAULT_CONFIG) -> dict[str, Predicate]:
    def low_queueing(req: LLMRequest, pm: PodMetrics) -> bool:
        # filter.go:124-126 — queue below the LoRA-affinity-worthwhile bound.
        return pm.metrics.total_queue_size < cfg.queueing_threshold_lora

    def lora_affinity(req: LLMRequest, pm: PodMetrics) -> bool:
        # filter.go:169-172 — adapter already resident on the replica.
        return req.resolved_target_model in pm.metrics.active_adapters

    def can_accept_new_lora(req: LLMRequest, pm: PodMetrics) -> bool:
        # filter.go:175-177 — replica has a free adapter slot.
        return len(pm.metrics.active_adapters) < pm.metrics.max_active_adapters

    def low_lora_cost(req: LLMRequest, pm: PodMetrics) -> bool:
        # filter.go:163-166 — affinity OR free slot: loading is cheap either way.
        return (
            req.resolved_target_model in pm.metrics.active_adapters
            or len(pm.metrics.active_adapters) < pm.metrics.max_active_adapters
        )

    def critical_request(req: LLMRequest, pm: PodMetrics) -> bool:
        # filter.go:179-181 — pod-independent branch switch.
        return req.critical

    def sheddable_admission(req: LLMRequest, pm: PodMetrics) -> bool:
        # filter.go:183-187 — noQueueAndLessThanKVCacheThresholdPredicate.
        return (
            pm.metrics.total_queue_size <= cfg.queue_threshold_critical
            and pm.metrics.kv_cache_usage_percent <= cfg.kv_cache_threshold
        )

    def token_headroom(req: LLMRequest, pm: PodMetrics) -> bool:
        # TPU addition: free KV tokens cover the (hinted) prompt.  Requests
        # without a hint pass trivially so the filter is a no-op for them.
        if req.prompt_tokens <= 0 or pm.metrics.kv_tokens_capacity <= 0:
            return True
        need = int(req.prompt_tokens * cfg.token_headroom_factor)
        return pm.metrics.kv_tokens_free >= need

    def prefill_not_saturated(req: LLMRequest, pm: PodMetrics) -> bool:
        # TPU addition: avoid replicas with a deep prefill backlog.
        return pm.metrics.prefill_queue_size < cfg.prefill_queue_threshold

    return {
        "low_queueing": low_queueing,
        "lora_affinity": lora_affinity,
        "can_accept_new_lora": can_accept_new_lora,
        "low_lora_cost": low_lora_cost,
        "critical_request": critical_request,
        "sheddable_admission": sheddable_admission,
        "token_headroom": token_headroom,
        "prefill_not_saturated": prefill_not_saturated,
    }
