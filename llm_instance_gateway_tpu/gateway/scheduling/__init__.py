"""Request scheduling: filter decision tree + scheduler policies."""

from llm_instance_gateway_tpu.gateway.scheduling.scheduler import (
    Scheduler,
    SchedulingError,
)
from llm_instance_gateway_tpu.gateway.scheduling.types import LLMRequest

__all__ = ["Scheduler", "SchedulingError", "LLMRequest"]
