"""Scheduler: builds the decision trees and picks a target replica.

Parity: reference ``pkg/ext-proc/scheduling/scheduler.go:26-122``:

- ``default`` tree: critical? -> low-latency path, else sheddable path which
  drops with RESOURCE_EXHAUSTED when no replica has capacity
  (scheduler.go:74-90 -> 429 at the transport layer).
- low-latency path: queue < threshold -> LoRA affinity -> can-accept-new-LoRA,
  falling back to least-queuing -> low-LoRA-cost -> least-KV-cache
  (scheduler.go:34-72).
- Final choice: uniform random among survivors (scheduler.go:120) to spread
  near-ties.

TPU-native extensions (both ON by default — this framework routes TPU
disaggregated-continuous-batching replicas; pass ``False`` for strict
reference parity, as the parity tests do):

- ``token_aware=True`` inserts the KV-token-headroom predicate ahead of the
  queue filters so long-context requests only land where the prompt fits.
- ``prefill_aware=True`` routes on the prefill queue (TTFT-gating signal under
  prefill/decode disaggregation) before total queue depth.

"""

from __future__ import annotations

import random
import time
from typing import (
    TYPE_CHECKING,
    Any,
    Callable,
    Iterable,
    Protocol,
    Sequence,
    TypeVar,
)

from llm_instance_gateway_tpu.gateway.scheduling.config import (
    DEFAULT_CONFIG,
    SchedulerConfig,
)
from llm_instance_gateway_tpu.gateway.scheduling.filter import (
    Filter,
    FilterError,
    least_kv_cache_filter,
    least_prefill_queue_filter,
    least_queuing_filter,
    make_predicates,
    to_filter_func,
)
from llm_instance_gateway_tpu.gateway.scheduling.types import LLMRequest
from llm_instance_gateway_tpu.gateway.types import (
    ROLE_COLLOCATED,
    ROLE_DECODE,
    ROLE_PREFILL,
    Pod,
    PodMetrics,
    pod_role,
)


if TYPE_CHECKING:
    from llm_instance_gateway_tpu.gateway.scheduling.prefix_affinity import (
        PrefixIndex,
    )

# Candidate element type: the Python scheduler filters PodMetrics, the
# native scheduler filters survivor INDICES with a name_of mapper — the
# advisor filters below are generic over both.
C = TypeVar("C")


class SchedulingError(Exception):
    """Raised when no pod can serve the request.

    ``shed`` marks the load-shedding drop (reference maps it to gRPC
    RESOURCE_EXHAUSTED -> HTTP 429, server.go:95-113).
    """

    def __init__(self, msg: str, shed: bool = False):
        super().__init__(msg)
        self.shed = shed


class PodMetricsProvider(Protocol):
    """scheduler.go:108-110."""

    def all_pod_metrics(self) -> list[PodMetrics]: ...


def filter_by_policy(advisor: Any, candidates: list[C],
                     name_of: Callable[[C], str] | None = None) -> list[C]:
    """Apply the advisor's health policy over a candidate set.

    The advisor seam (``gateway/resilience.py:ResiliencePlane``) exposes
    ``policy`` + ``should_avoid``; schedulers call this AFTER the filter
    tree, BEFORE the prefix-affinity tie-break and the RNG draw.

    - ``log_only`` (or no advisor / a bare HealthScorer without a policy):
      returns ``candidates`` UNCHANGED — the byte-identical guarantee the
      same-RNG diff tests pin.
    - ``avoid``: the subset the advisor would not avoid; when EVERY
      candidate is avoidable, the full set comes back (last-resort escape
      hatch — a fully-unhealthy pool still serves) and the advisor's
      ``note_escape_hatch`` counter/journal fires.
    - ``strict``: like ``avoid`` but an all-avoidable set sheds
      (``SchedulingError(shed=True)`` -> 429) instead of escaping.

    ``name_of`` maps a candidate to its pod name (defaults to the
    ``PodMetrics`` shape; the native scheduler passes an index mapper).
    """
    if advisor is None or not candidates:
        return candidates
    policy = getattr(advisor, "policy", "log_only")
    if policy == "log_only":
        return candidates
    if name_of is None:
        name_of = lambda pm: pm.pod.name  # noqa: E731
    batch = getattr(advisor, "avoid_set", None)
    if batch is not None:
        bad = batch()  # two lock acquisitions total, not two per pod
        if not bad:
            return candidates
        preferred = [c for c in candidates if name_of(c) not in bad]
    else:
        preferred = [c for c in candidates
                     if not advisor.should_avoid(name_of(c))]
    if preferred:
        return preferred
    if policy == "strict":
        raise SchedulingError(
            "all candidate replicas are unhealthy or circuit-open "
            "(health_policy=strict)", shed=True)
    note = getattr(advisor, "note_escape_hatch", None)
    if note is not None:
        note()
    return candidates


def filter_by_fairness(
    advisor: Any, req: "LLMRequest", candidates: list[C],
    active_of: Callable[[C], Iterable[str]] | None = None,
) -> list[C]:
    """Apply the fairness advisor's pick deprioritization over a candidate
    set (``gateway/fairness.py:FairnessPolicy``); schedulers call this
    AFTER ``filter_by_policy``, BEFORE the prefix tie-break and RNG draw.

    - ``log_only`` (or no advisor / a bare UsageRollup without a mode):
      returns ``candidates`` UNCHANGED — the byte-identical guarantee the
      same-RNG diff tests pin.
    - ``deprioritize`` / ``enforce``: pods hosting a currently-flagged
      noisy adapter are *marked*.  A quiet request narrows to unmarked
      survivors (isolation: the flood can't degrade cotenants on its
      replicas); when EVERY candidate is marked the full set comes back
      and ``note_fairness_escape`` fires — the same counted last-resort
      shape as ``filter_by_policy``.  A request whose OWN key is flagged
      narrows to the marked pods instead (containment: the flood keeps
      its existing replicas but can't claim fresh ones); no marked
      candidate is not an escape — there is nothing to avoid.

    ``active_of`` maps a candidate to its resident-adapter names (defaults
    to the ``PodMetrics`` shape; the native scheduler's candidate indices
    are resolved before this runs, so both paths share this function).
    An advisor exposing ``noisy_pods`` (FairnessPolicy) serves the mark
    set from a per-tick cache instead — one frozenset membership test per
    candidate on the hot path (the <5% ``pick_fairness_ratio`` bound).
    """
    if advisor is None or not candidates:
        return candidates
    if getattr(advisor, "mode", "log_only") == "log_only":
        return candidates
    flagged = advisor.noisy()
    if not flagged:
        return candidates
    get_marked = getattr(advisor, "noisy_pods", None)
    marked = get_marked() if get_marked is not None else None
    if marked is not None:
        hosts = [c.pod.name in marked for c in candidates]
    else:
        if active_of is None:
            active_of = lambda pm: pm.metrics.active_adapters  # noqa: E731
        hosts = [any(a in flagged for a in active_of(c))
                 for c in candidates]
    if req.model in flagged:
        preferred = [c for c, h in zip(candidates, hosts) if h]
        return preferred or candidates
    preferred = [c for c, h in zip(candidates, hosts) if not h]
    if preferred:
        return preferred
    note = getattr(advisor, "note_fairness_escape", None)
    if note is not None:
        note()
    return candidates


def filter_by_placement(
    advisor: Any, req: "LLMRequest", candidates: list[C],
    name_of: Callable[[C], str] | None = None,
) -> list[C]:
    """Apply the placement plane's residency steering over a candidate
    set (``gateway/placement.py:PlacementPlanner``); schedulers call this
    AFTER ``filter_by_fairness``, BEFORE the prefix tie-break and RNG
    draw.

    - ``log_only`` (or no advisor): returns ``candidates`` UNCHANGED —
      the byte-identical guarantee the same-RNG diff tests pin (the
      advisor's ``note_pick`` still counts would-steer picks).
    - ``prefer_resident``: narrows to pods where the request's adapter is
      RAM-resident, slot tier winning ties over host tier (a slot pick
      decodes immediately, a host pick pays the promote's device put, a
      cold pick pays the full Orbax restore); when the adapter IS
      resident somewhere but on NO candidate, the full set comes back and
      ``note_placement_escape`` fires — the same counted last-resort
      shape as the health/fairness filters.  An adapter resident NOWHERE
      (cold tail, base-model traffic) is not an escape: there is nothing
      to steer toward, and the planner's prefetch rule — not the pick
      seam — owns it.  A pool exporting no residency data at all
      (``resident_pods`` returns None) likewise leaves the set untouched.
    """
    if advisor is None or not candidates:
        return candidates
    if getattr(advisor, "mode", "log_only") != "prefer_resident":
        return candidates
    get_tiers = getattr(advisor, "resident_tiers", None)
    if get_tiers is not None:
        tiers = get_tiers(req.resolved_target_model)
        slot_set, host_set = tiers if tiers is not None \
            else (frozenset(), frozenset())
    else:  # flat advisor (tests/fakes): one tier, no slot preference
        slot_set = advisor.resident_pods(req.resolved_target_model) \
            or frozenset()
        host_set = frozenset()
    if not slot_set and not host_set:
        return candidates
    # One pass, both tiers (this filter rides the pick hot path — the
    # <5% pick_placement_ratio bound in BASELINE_BENCH.json).
    slot_pref: list = []
    host_pref: list = []
    if name_of is None:
        for c in candidates:
            name = c.pod.name
            if name in slot_set:
                slot_pref.append(c)
            elif name in host_set:
                host_pref.append(c)
    else:
        for c in candidates:
            name = name_of(c)
            if name in slot_set:
                slot_pref.append(c)
            elif name in host_set:
                host_pref.append(c)
    preferred = slot_pref or host_pref
    if preferred:
        return preferred
    note = getattr(advisor, "note_placement_escape", None)
    if note is not None:
        note()
    return candidates


def _drop_filter() -> Filter:
    def drop(req: LLMRequest, pods: Sequence[PodMetrics]) -> list[PodMetrics]:
        raise FilterError(
            "dropping request due to limited backend resources", shed=True
        )

    return Filter(name="drop request", func=drop)


def build_default_tree(
    cfg: SchedulerConfig = DEFAULT_CONFIG,
    token_aware: bool = False,
    prefill_aware: bool = False,
) -> Filter:
    """Construct the reference decision tree (scheduler.go:26-91)."""
    preds = make_predicates(cfg)

    def queue_filter(tail: Filter | None) -> Filter:
        """Queue-depth stage ending in ``tail``.

        With ``prefill_aware`` the stage is prefill-queue bucketing followed by
        total-queue bucketing; the tail is attached to the *last* node so later
        wiring can't clobber the inner chain.
        """
        least_queue = Filter(
            name="least queuing",
            func=least_queuing_filter,
            next_on_success_or_failure=tail,
        )
        if prefill_aware:
            return Filter(
                name="least prefill queuing",
                func=least_prefill_queue_filter,
                next_on_success_or_failure=least_queue,
            )
        return least_queue

    def with_token_headroom(inner: Filter) -> Filter:
        if not token_aware:
            return inner
        return Filter(
            name="token headroom",
            func=to_filter_func(preds["token_headroom"], "token_headroom"),
            next_on_success=inner,
            next_on_failure=inner,  # headroom is advisory: fall back, don't fail
        )

    # queueLoRAAndKVCacheFilter (scheduler.go:35-46)
    queue_lora_kv = queue_filter(
        Filter(
            name="low cost LoRA",
            func=to_filter_func(preds["low_lora_cost"], "low_lora_cost"),
            next_on_success_or_failure=Filter(
                name="least KV cache percent", func=least_kv_cache_filter
            ),
        )
    )

    # queueAndKVCacheFilter (scheduler.go:49-56)
    queue_kv = queue_filter(
        Filter(name="least KV cache percent", func=least_kv_cache_filter)
    )

    # lowLatencyFilter (scheduler.go:58-72)
    low_latency = Filter(
        name="low queueing filter",
        func=to_filter_func(preds["low_queueing"], "low_queueing"),
        next_on_success=Filter(
            name="affinity LoRA",
            func=to_filter_func(preds["lora_affinity"], "lora_affinity"),
            next_on_success=queue_kv,
            next_on_failure=Filter(
                name="can accept LoRA Adapter",
                func=to_filter_func(preds["can_accept_new_lora"], "can_accept_new_lora"),
                next_on_success_or_failure=queue_kv,
            ),
        ),
        next_on_failure=queue_lora_kv,
    )

    # sheddableRequestFilter (scheduler.go:74-90)
    sheddable = Filter(
        name="has capacity for sheddable requests",
        func=to_filter_func(preds["sheddable_admission"], "sheddable_admission"),
        next_on_success=queue_lora_kv,
        next_on_failure=_drop_filter(),
    )

    # defaultFilter (scheduler.go:27-32)
    return Filter(
        name="critical request",
        func=to_filter_func(preds["critical_request"], "critical_request"),
        next_on_success=with_token_headroom(low_latency),
        next_on_failure=with_token_headroom(sheddable),
    )


def build_decode_tree(
    cfg: SchedulerConfig = DEFAULT_CONFIG,
    token_aware: bool = True,
) -> Filter:
    """Decode-hop stage for disaggregated pools: KV headroom first (the
    decode replica holds this request's KV for its WHOLE lifetime — the
    signal that gates TPOT stability), then total queue depth.  Prefill
    signals are irrelevant here: a decode-role replica admits handoffs
    straight into decode slots and its prefill queue stays empty."""
    preds = make_predicates(cfg)
    kv_then_queue = Filter(
        name="least KV cache percent",
        func=least_kv_cache_filter,
        next_on_success_or_failure=Filter(
            name="least queuing", func=least_queuing_filter),
    )
    if not token_aware:
        return kv_then_queue
    return Filter(
        name="token headroom",
        func=to_filter_func(preds["token_headroom"], "token_headroom"),
        next_on_success=kv_then_queue,
        next_on_failure=kv_then_queue,  # advisory: fall back, don't fail
    )


def split_pool_roles(
    pods: Sequence[PodMetrics],
) -> tuple[list[PodMetrics], list[PodMetrics]]:
    """(prefill-role, decode-role) partitions; collocated pods are in
    neither (they serve single-hop traffic)."""
    prefills = [pm for pm in pods if pod_role(pm.pod) == ROLE_PREFILL]
    decodes = [pm for pm in pods if pod_role(pm.pod) == ROLE_DECODE]
    return prefills, decodes


class Scheduler:
    """scheduler.go:93-122, with configurable thresholds and TPU options."""

    def __init__(
        self,
        pod_metrics_provider: PodMetricsProvider,
        cfg: SchedulerConfig = DEFAULT_CONFIG,
        token_aware: bool = True,
        prefill_aware: bool = True,
        prefix_aware: bool = True,
        prefix_index: "PrefixIndex | None" = None,
        rng: random.Random | None = None,
        tree: Filter | None = None,
    ) -> None:
        self._provider = pod_metrics_provider
        self.cfg = cfg
        self._token_aware = token_aware
        self._prefill_aware = prefill_aware
        # Prefix-cache-aware tie-break (scheduling/prefix_affinity.py),
        # applied AFTER the tree over its survivor set — identical seam in
        # the native scheduler, so the two implementations stay
        # parity-comparable.  Inert until requests carry prefix_hashes AND
        # a prefix repeats.  ``prefix_index`` injects a SHARED index when
        # several scheduler instances route one pool (e.g. the admission
        # controller's drain scheduler) — split indexes would learn
        # conflicting holders and flap.  prefix_aware=False disables the
        # tie-break even with an injected index (the flag is the OFF
        # switch; the index argument only chooses whose state to share).
        self.prefix_index = prefix_index if prefix_aware else None
        if prefix_aware and self.prefix_index is None:
            from llm_instance_gateway_tpu.gateway.scheduling.prefix_affinity import (
                PrefixIndex,
            )

            self.prefix_index = PrefixIndex()
        self._custom_tree = tree is not None
        self._tree = tree or build_default_tree(
            cfg, token_aware=token_aware, prefill_aware=prefill_aware
        )
        # Decode-hop stage for disaggregated pools (role-split replicas);
        # inert while every pod is collocated.
        self._decode_tree = build_decode_tree(cfg, token_aware=token_aware)
        self._rng = rng or random.Random()
        # Health/resilience hook (set by the proxy).  With the default
        # ``log_only`` policy ``note_pick`` only counts would-be avoidance
        # decisions into tpu:health_would_avoid_total — no RNG draws, no
        # filtering, routing byte-identical to a scheduler without the
        # hook (pinned by the same-RNG diff tests).  With ``avoid`` /
        # ``strict`` (gateway/resilience.py) the survivor set additionally
        # passes through ``filter_by_policy`` before the tie-break/draw.
        self.health_advisor: Any = None
        # Usage/fairness seam (gateway/usage.py + gateway/fairness.py, set
        # by the proxy).  A bare UsageRollup (or a FairnessPolicy in
        # ``log_only``) only counts flagged picks into
        # gateway_usage_would_deprioritize_total — no RNG, no filtering,
        # routing byte-identical (pinned by same-RNG diff tests).  A
        # FairnessPolicy in ``deprioritize``/``enforce`` additionally runs
        # the survivor set through ``filter_by_fairness`` after the health
        # policy filter and before the tie-break/draw.
        self.usage_advisor: Any = None
        # Placement seam (gateway/placement.py, set by the proxy).  A
        # PlacementPlanner in ``log_only`` only counts picks that missed
        # a resident replica (gateway_placement_would_steer_total) —
        # routing byte-identical, pinned by same-RNG diff tests.  In
        # ``prefer_resident`` the survivor set additionally passes through
        # ``filter_by_placement`` after the fairness filter.
        self.placement_advisor: Any = None
        # Decision-ledger seam (gateway/pickledger.py, set by the proxy).
        # Sampling is a counter modulus — no RNG draws, no filtering —
        # so routing stays byte-identical with the ledger attached
        # (pinned by same-RNG diff tests); all record/counterfactual
        # work rides sampled picks only.
        self.pick_ledger: Any = None

    def update_config(self, cfg: SchedulerConfig) -> None:
        """Swap thresholds at runtime (pool hot-reload); rebuilds the tree.

        A caller-injected custom tree is left untouched — thresholds belong
        to the default tree; silently replacing a custom policy on reload
        would be a worse surprise than ignoring the new numbers.
        """
        self.cfg = cfg
        if self._custom_tree:
            import logging

            logging.getLogger(__name__).warning(
                "scheduler has a custom filter tree; ignoring threshold reload"
            )
            return
        self._tree = build_default_tree(
            cfg, token_aware=self._token_aware,
            prefill_aware=self._prefill_aware,
        )
        self._decode_tree = build_decode_tree(
            cfg, token_aware=self._token_aware)

    def _survivors(self, req: LLMRequest,
                   pods: Sequence[PodMetrics]) -> list[PodMetrics]:
        try:
            survivors = self._tree.filter(req, pods)
        except FilterError as e:
            raise SchedulingError(
                f"failed to apply filter, resulted 0 pods: {e}", shed=e.shed
            ) from e
        if not survivors:
            raise SchedulingError("failed to apply filter, resulted 0 pods")
        return survivors

    def _pick(self, req: LLMRequest, survivors: Sequence[PodMetrics],
              hop: str = "single", pool_n: int = 0, role_n: int = 0) -> Pod:
        # Enforcing health policy narrows the candidate set FIRST, so the
        # prefix-affinity tie-break can't pin a request to an avoided
        # holder (log_only returns the set unchanged); fairness
        # deprioritization runs over whatever survives it.
        ledger = self.pick_ledger
        sampled = ledger is not None and ledger.sampled()
        base = survivors
        if sampled:
            escape_base = ledger.escape_counters(
                self.health_advisor, self.usage_advisor,
                self.placement_advisor)
            base = list(survivors)  # pin the funnel head for the record
        post_health = filter_by_policy(self.health_advisor, base)
        post_fairness = filter_by_fairness(self.usage_advisor, req,
                                           post_health)
        final = filter_by_placement(self.placement_advisor, req,
                                    post_fairness)
        pick = None
        tie_break = False
        if self.prefix_index is not None and req.prefix_hashes:
            held = self.prefix_index.prefer(req, final)
            if held is not None:
                pick = held.pod
                tie_break = True
        if pick is None:
            pick = final[self._rng.randrange(len(final))].pod
        if self.prefix_index is not None and req.prefix_hashes:
            # The pick is about to prefill (and, with the engine's prefix
            # cache on, retain) this prefix: future lookups route here.
            self.prefix_index.record(req.prefix_hashes, pick.name)
        if self.health_advisor is not None:
            self.health_advisor.note_pick(pick.name)
        if self.usage_advisor is not None:
            self.usage_advisor.note_pick(pick.name, req.model)
        if self.placement_advisor is not None:
            self.placement_advisor.note_pick(
                pick.name, req.resolved_target_model)
        if sampled:
            ledger.charge(
                req, winner=pick.name, base=base, post_health=post_health,
                post_fairness=post_fairness, post_placement=final,
                hop=hop, path="python", pool_n=pool_n, role_n=role_n,
                tie_break=tie_break,
                advisors=(self.health_advisor, self.usage_advisor,
                          self.placement_advisor),
                escape_base=escape_base, trace_id=req.trace_id)
        return pick

    def schedule(self, req: LLMRequest) -> Pod:
        pods = self._provider.all_pod_metrics()
        # Role-split pools: single-hop traffic stays off the specialized
        # replicas when collocated ones exist (a decode replica serving a
        # full request would prefill on its decode-critical MXU); in a
        # FULLY split pool single-hop is the degraded fallback and any
        # replica can take it (roles are advisory, engines are complete).
        collocated = [pm for pm in pods
                      if pod_role(pm.pod) == ROLE_COLLOCATED]
        role_set = collocated or list(pods)
        return self._pick(req, self._survivors(req, role_set),
                          pool_n=len(pods), role_n=len(role_set))

    def schedule_disaggregated(
        self, req: LLMRequest
    ) -> tuple[Pod, Pod | None]:
        """Two-stage routing for disaggregated pools.

        Returns ``(prefill_pod, decode_pod)``: the prefill replica is
        picked by the FULL decision tree over the prefill-role set (its
        prefill-queue/TTFT stages are exactly the signals that matter for
        hop 1, and prefix affinity applies here — that is where prefill
        reuse lives), then the decode replica by KV-headroom/queue signals
        over the decode-role set (``build_decode_tree``).  Pools without
        both roles fall back to single-hop: ``(schedule(req), None)``.
        """
        pods = self._provider.all_pod_metrics()
        prefills, decodes = split_pool_roles(pods)
        if not prefills or not decodes:
            return self.schedule(req), None
        t0 = time.perf_counter()
        prefill_pod = self._pick(req, self._survivors(req, prefills),
                                 hop="prefill", pool_n=len(pods),
                                 role_n=len(prefills))
        t1 = time.perf_counter()
        ledger = self.pick_ledger
        sampled = ledger is not None and ledger.sampled()
        if sampled:
            escape_base = ledger.escape_counters(
                self.health_advisor, self.usage_advisor,
                self.placement_advisor)
        try:
            decode_base = self._decode_tree.filter(req, decodes)
        except FilterError as e:
            raise SchedulingError(
                f"no decode replica for disaggregated request: {e}",
                shed=e.shed) from e
        decode_health = filter_by_policy(self.health_advisor, decode_base)
        decode_fairness = filter_by_fairness(
            self.usage_advisor, req, decode_health)
        decode_survivors = filter_by_placement(
            self.placement_advisor, req, decode_fairness)
        decode_pod = decode_survivors[
            self._rng.randrange(len(decode_survivors))].pod
        if self.health_advisor is not None:
            self.health_advisor.note_pick(decode_pod.name)
        if self.usage_advisor is not None:
            self.usage_advisor.note_pick(decode_pod.name, req.model)
        if self.placement_advisor is not None:
            self.placement_advisor.note_pick(
                decode_pod.name, req.resolved_target_model)
        if sampled:
            ledger.charge(
                req, winner=decode_pod.name, base=decode_base,
                post_health=decode_health, post_fairness=decode_fairness,
                post_placement=decode_survivors, hop="decode",
                path="python", pool_n=len(pods), role_n=len(decodes),
                advisors=(self.health_advisor, self.usage_advisor,
                          self.placement_advisor),
                escape_base=escape_base, trace_id=req.trace_id)
        # Per-hop pick split for the tracing layer (the admission span's
        # attribution of "pick" into prefill-hop vs decode-hop cost).
        req.pick_hops_s = (t1 - t0, time.perf_counter() - t1)
        return prefill_pod, decode_pod
