"""Replicated control-plane state bus: N gateways, one brain.

Everything the observability tick derives — usage shares and noisy
flags, health/circuit avoid sets, placement resident maps, fairness
bucket levels — historically lived in ONE proxy process.  At the
million-user scale the ROADMAP targets, a single gateway replica is both
the throughput bottleneck and a SPOF; the reference solves the analogous
problem with a reconciler/datastore layer every picker reads (PAPER.md
backend layer), and MinT (arxiv 2605.13779) is the managed-control-plane
scale target.  This module is that layer for the standalone gateway:

- **Snapshots**: each observability tick, every pool's advisor stack
  (``gateway/advisors.py``) contributes its LOCALLY-derived state to a
  versioned per-replica document — ``(replica_id, tick_seq)`` monotonic
  versions, one doc per replica, per-pool key families inside
  (``noisy`` / ``avoid`` / ``resident`` / ``buckets`` / ``shares``).
- **Gossip**: replicas exchange docs over a small HTTP push-pull
  (``POST /statebus/exchange``: send every doc you know, receive every
  doc the peer knows) — one round trip equalizes both sides, and
  transitively-learned docs mean a line topology still converges.
  Merge is last-writer-wins per replica (highest ``seq``), so a key
  family is owned by exactly one replica's detection logic and can
  never ping-pong.
- **Merged view**: the freshest doc per peer (staleness-bounded) folds
  into per-pool overlays the advisors already know how to wear —
  ``usage.set_remote_noisy`` / ``resilience.set_remote_avoid`` /
  ``placement.set_remote_resident`` — so BOTH scheduler paths (the
  Python filter chain and the native snapshot marshals) see peer state
  through the exact seams the PR-9 lint already guards, with zero
  scheduler changes.
- **Global fairness**: with N live replicas spraying one tenant's
  traffic, each replica's token buckets refill at ``quota_rps / N``
  (``fairness.set_quota_scale``) — the fleet-wide admission rate for a
  throttled tenant stays what the operator configured.
- **Staleness fallback**: when every peer goes quiet past
  ``staleness_s``, the overlays empty and enforcement degrades to
  local-only — journaled as ``statebus_stale``, with ``statebus_rejoin``
  when fresh peer state returns.  A partitioned replica keeps serving
  (the ``replica_partition`` chaos scenario pins zero 5xx through the
  partition and rejoin within 2 ticks).

``tools/statebus_report.py`` renders the merged-vs-local divergence per
replica from ``/debug/statebus``.
"""

from __future__ import annotations

import asyncio
import time
import uuid
from dataclasses import asdict, dataclass

import aiohttp

from llm_instance_gateway_tpu.lockwitness import witness_lock
from llm_instance_gateway_tpu import events as events_mod
from llm_instance_gateway_tpu.tracing import (
    Histogram,
    escape_label,
    render_counter,
    render_histogram,
)

# Merge cost is µs-scale dict folding; the pick-latency buckets fit.
MERGE_BUCKETS = (1e-5, 2.5e-5, 5e-5, 1e-4, 2.5e-4, 5e-4, 1e-3, 2.5e-3,
                 5e-3, 1e-2, 5e-2)


@dataclass(frozen=True)
class StateBusConfig:
    """Knobs for the replicated state plane (flags:
    ``bootstrap.add_statebus_args``)."""

    # This gateway's identity on the bus.  Empty = a random stable id is
    # minted at construction (bootstrap defaults to host:port).
    replica_id: str = ""
    # Peer gateway base URLs (e.g. ``http://gw-1:8081``); empty = the
    # bus is inert beyond local snapshots and /debug/statebus.
    peers: tuple = ()
    # A replica's doc older than this (by local receive time) drops out
    # of the merged view; when EVERY peer is stale the bus falls back to
    # local-only enforcement (journaled).
    staleness_s: float = 15.0
    # Per-peer exchange round-trip bound.
    exchange_timeout_s: float = 2.0
    # Divide fairness token buckets by the live replica count so tenant
    # quotas hold fleet-wide (False: every replica enforces the full
    # quota locally — N x over-admission under spraying).
    partition_quota: bool = True
    # A replica whose snapshot ages past ``evict_factor x staleness_s``
    # is FORGOTTEN entirely (doc dropped, stops being regossiped, its
    # snapshot-age series ends).  Replica identities default to
    # host:port — pod churn mints new ones, and without eviction the
    # doc set, the exchange payload, and the metric cardinality grow
    # monotonically fleet-wide.  Well past the staleness bound so a
    # partitioned replica's doc survives long enough to version-compare
    # on rejoin.
    evict_factor: float = 10.0

    def __post_init__(self):
        if self.staleness_s <= 0 or self.exchange_timeout_s <= 0:
            raise ValueError("statebus staleness/timeout must be > 0")
        if self.evict_factor < 2.0:
            raise ValueError("statebus evict_factor must be >= 2 "
                             "(eviction inside the staleness window "
                             "would flap stale/rejoin)")


class StateBus:
    """The replicated state plane over one gateway's per-pool advisor
    stacks.  Thread-safe: the observability tick, the exchange endpoint
    (event loop), and /debug readers all touch it."""

    def __init__(self, stacks: dict, cfg: StateBusConfig | None = None,
                 journal: "events_mod.EventJournal | None" = None,
                 clock=time.time):
        self.stacks = stacks
        self.cfg = cfg or StateBusConfig()
        self.replica_id = self.cfg.replica_id or f"gw-{uuid.uuid4().hex[:8]}"
        self.journal = journal
        self._clock = clock
        self._lock = witness_lock("StateBus._lock")
        self._seq = 0
        # Boot epoch: a restarted replica reuses its id but restarts its
        # seq counter at 1 — without an epoch, peers holding its OLD doc
        # reject the fresh ones until seq catches up (one tick per unit,
        # i.e. a rejoin stall exactly as long as the previous uptime in
        # ticks).  Versions compare as (boot, seq): a newer boot always
        # wins for the same replica id.  Found in the live two-proxy
        # restart drill, not the in-process tests — same-process rigs
        # never re-mint a bus.
        self._boot = round(self._clock(), 6)
        # replica -> {"doc": versioned snapshot, "recv_ts": local clock at
        # acceptance}.  Staleness is judged by LOCAL receive time, never
        # the doc's own ``ts`` — peer clocks may skew, and a replica that
        # stopped talking is stale regardless of what its clock claimed.
        self._docs: dict[str, dict] = {}
        self._ever_saw_peer = False
        self._stale = False
        # Exported state.
        self.merge_hist = Histogram(MERGE_BUCKETS)
        self.stale_fallbacks_total = 0
        self.exchanges: dict[str, int] = {}
        self.last_apply_scale = 1.0

    # -- snapshot (publish side) -------------------------------------------
    def snapshot(self) -> dict:
        """Build + store this replica's versioned doc from every stack's
        LOCAL state (remote overlays are never re-published — each key
        family has exactly one owning replica)."""
        pools: dict[str, dict] = {}
        for name, stack in self.stacks.items():
            resident = stack.placement.local_resident_map() or {}
            pools[name] = {
                "noisy": {n: list(k)
                          for n, k in stack.usage.local_noisy_keys().items()},
                "avoid": sorted(stack.resilience.local_avoid_set()),
                "resident": {a: [sorted(s), sorted(h)]
                             for a, (s, h) in resident.items()},
                "buckets": stack.fairness.bucket_levels(),
                "shares": [[m, a, round(v, 4)] for (m, a), v in
                           sorted(stack.usage.shares_snapshot().items())],
                # Pick-ledger steering rollup (gateway/pickledger.py):
                # swap-published read, never blocks a pick.  Peers fold
                # these into the /debug/fleet steering view
                # (fleetobs.pick_steering_rollup); merged_overlays
                # ignores unknown keys, so pre-ledger peers interop.
                "picks": (stack.pickledger.seam_rollup()
                          if getattr(stack, "pickledger", None)
                          is not None else {}),
            }
        now = self._clock()
        with self._lock:
            self._seq += 1
            doc = {"replica": self.replica_id, "boot": self._boot,
                   "seq": self._seq, "ts": round(now, 6), "pools": pools}
            self._docs[self.replica_id] = {"doc": doc, "recv_ts": now}
        return doc

    # -- merge (gossip receive side) ---------------------------------------
    def merge(self, docs: list[dict]) -> int:
        """Fold peer docs in: last-writer-wins per replica by
        ``(boot, seq)`` — seq orders one process lifetime, the boot
        epoch orders RESTARTS of the same replica id (a restarted
        replica's seq resets to 1; without the epoch its fresh docs
        would lose to its own pre-restart ghost).  Malformed entries are
        skipped (a hostile/buggy peer must not poison the bus).
        Returns how many docs were accepted."""
        t0 = time.perf_counter()
        now = self._clock()
        accepted = 0
        with self._lock:
            for doc in docs or ():
                if not isinstance(doc, dict):
                    continue
                replica = doc.get("replica")
                seq = doc.get("seq")
                boot = doc.get("boot", 0.0)
                pools = doc.get("pools")
                if (not isinstance(replica, str) or not replica
                        or not isinstance(seq, int)
                        or not isinstance(boot, (int, float))
                        or not isinstance(pools, dict)
                        or any(not isinstance(p, dict)
                               for p in pools.values())):
                    continue
                if replica == self.replica_id:
                    continue  # our own state gossiped back
                cur = self._docs.get(replica)
                if cur is not None and (
                        cur["doc"].get("boot", 0.0),
                        cur["doc"]["seq"]) >= (boot, seq):
                    continue
                self._docs[replica] = {"doc": doc, "recv_ts": now}
                self._ever_saw_peer = True
                accepted += 1
        self.merge_hist.observe(time.perf_counter() - t0)
        return accepted

    def all_docs(self) -> list[dict]:
        """Every doc this replica knows (its own + learned) — the
        push-pull payload; transitive gossip rides on this."""
        with self._lock:
            return [e["doc"] for e in self._docs.values()]

    # -- merged view (apply side) ------------------------------------------
    def _fresh_remote(self, now: float) -> dict[str, dict]:
        """replica -> doc for peers within the staleness bound (caller
        need not hold the lock; the dict is a copy)."""
        bound = self.cfg.staleness_s
        with self._lock:
            return {r: e["doc"] for r, e in self._docs.items()
                    if r != self.replica_id and now - e["recv_ts"] <= bound}

    @staticmethod
    def merged_overlays(pool: str, docs: dict[str, dict]) -> dict:
        """Fold the fresh peer docs into one pool's overlay: noisy-name
        union, avoid-set union, resident-map per-tier union.

        Every inner family is type-checked before use: ``merge`` vets
        doc shape down to the pool dicts only, and an overlay raise here
        would freeze apply()/tick() fleet-wide on every pass until the
        poisoned doc evicts — a hostile/buggy peer degrades to being
        ignored, never to breaking the bus."""
        noisy: dict[str, tuple] = {}
        avoid: set[str] = set()
        resident: dict[str, tuple] = {}
        for doc in docs.values():
            p = doc.get("pools", {}).get(pool)
            if not isinstance(p, dict):
                continue
            fam = p.get("noisy")
            if isinstance(fam, dict):
                for name, key in fam.items():
                    if (isinstance(name, str)
                            and isinstance(key, (list, tuple))
                            and len(key) == 2):
                        noisy[name] = tuple(key)
            fam = p.get("avoid")
            if isinstance(fam, (list, tuple)):
                avoid.update(x for x in fam if isinstance(x, str))
            fam = p.get("resident")
            if isinstance(fam, dict):
                for a, tiers in fam.items():
                    if not (isinstance(a, str)
                            and isinstance(tiers, (list, tuple))
                            and len(tiers) == 2
                            and all(isinstance(t, (list, tuple))
                                    for t in tiers)):
                        continue
                    cs, ch = resident.get(a, (frozenset(), frozenset()))
                    slot = cs | frozenset(
                        x for x in tiers[0] if isinstance(x, str))
                    host = (ch | frozenset(
                        x for x in tiers[1] if isinstance(x, str))) - slot
                    resident[a] = (slot, host)
        return {"noisy": noisy, "avoid": frozenset(avoid),
                "resident": resident}

    def apply(self, now: float | None = None) -> None:
        """Overlay the merged peer view onto every stack's advisors and
        partition the fairness quota by the live replica count.  When all
        peers are stale the overlays empty — local-only enforcement —
        with the ``statebus_stale`` / ``statebus_rejoin`` transitions
        journaled exactly once each."""
        now = self._clock() if now is None else now
        # Forget long-dead replica identities (pod churn mints new
        # host:port ids): their docs stop being regossiped and their
        # snapshot-age series end.  ``_ever_saw_peer`` stays true — a
        # fleet member whose peers ALL died is still degraded, not a
        # born-single replica.
        bound = self.cfg.evict_factor * self.cfg.staleness_s
        with self._lock:
            for rid in [r for r, e in self._docs.items()
                        if r != self.replica_id
                        and now - e["recv_ts"] > bound]:
                del self._docs[rid]
        fresh = self._fresh_remote(now)
        if self._ever_saw_peer:
            if not fresh and not self._stale:
                self._stale = True
                self.stale_fallbacks_total += 1
                if self.journal is not None:
                    self.journal.emit(events_mod.STATEBUS_STALE,
                                      replica=self.replica_id,
                                      known_peers=len(self._docs) - 1)
            elif fresh and self._stale:
                self._stale = False
                if self.journal is not None:
                    self.journal.emit(events_mod.STATEBUS_REJOIN,
                                      replica=self.replica_id,
                                      peers=len(fresh))
        live = len(fresh) + 1
        scale = (1.0 / live) if self.cfg.partition_quota else 1.0
        self.last_apply_scale = scale
        for pool, stack in self.stacks.items():
            overlay = self.merged_overlays(pool, fresh)
            stack.usage.set_remote_noisy(overlay["noisy"])
            stack.resilience.set_remote_avoid(overlay["avoid"])
            stack.placement.set_remote_resident(overlay["resident"])
            stack.fairness.set_quota_scale(scale)

    def tick(self) -> None:
        """The synchronous half of the bus, run from the observability
        tick: publish this replica's snapshot, then apply the freshest
        merged view.  Peer exchange (the async half) happens separately
        — in-process rigs drive ``exchange_with`` instead."""
        self.snapshot()
        self.apply()

    @property
    def stale(self) -> bool:
        return self._stale

    def live_replicas(self, now: float | None = None) -> int:
        now = self._clock() if now is None else now
        return len(self._fresh_remote(now)) + 1

    # -- transports ---------------------------------------------------------
    async def exchange(self, session: aiohttp.ClientSession) -> None:
        """One push-pull round with every configured peer, CONCURRENTLY:
        POST our full doc set, merge whatever each peer answers.  Peer
        rounds are independent, so the wall cost of a partition is ONE
        exchange timeout, not one per dead peer — a serial walk would
        stall the observability loop ~2 s x peers exactly when fast-burn
        detection matters most.  Failures count, never raise: a dead
        peer degrades to staleness, not an exception."""
        docs = self.all_docs()
        timeout = aiohttp.ClientTimeout(total=self.cfg.exchange_timeout_s)

        async def one(peer: str) -> None:
            url = peer.rstrip("/") + "/statebus/exchange"
            try:
                async with session.post(url, json=docs,
                                        timeout=timeout) as resp:
                    if resp.status == 200:
                        self.merge(await resp.json())
                        self.exchanges["ok"] = self.exchanges.get(
                            "ok", 0) + 1
                    else:
                        self.exchanges["error"] = self.exchanges.get(
                            "error", 0) + 1
            except (aiohttp.ClientError, OSError, ValueError,
                    TimeoutError, asyncio.TimeoutError):
                self.exchanges["error"] = self.exchanges.get(
                    "error", 0) + 1

        await asyncio.gather(*(one(p) for p in self.cfg.peers))

    def exchange_with(self, other: "StateBus") -> None:
        """In-process push-pull (tests, chaos, loadgen replicas in one
        process): both sides end up knowing the union of both doc sets —
        the same post-condition one HTTP round trip produces."""
        other.merge(self.all_docs())
        self.merge(other.all_docs())

    # -- export -------------------------------------------------------------
    def render(self) -> list[str]:
        """The ``gateway_statebus_*`` families."""
        now = self._clock()
        with self._lock:
            ages = {r: max(0.0, now - e["recv_ts"])
                    for r, e in self._docs.items()}
            stale_total = self.stale_fallbacks_total
            exchanges = dict(self.exchanges)
        fresh_peers = sum(1 for r, age in ages.items()
                          if r != self.replica_id
                          and age <= self.cfg.staleness_s)
        lines = ["# TYPE gateway_statebus_peers gauge",
                 f"gateway_statebus_peers {fresh_peers}"]
        lines.append("# TYPE gateway_statebus_snapshot_age_seconds gauge")
        for replica in sorted(ages):
            lines.append(
                'gateway_statebus_snapshot_age_seconds{replica="%s"} %.3f'
                % (escape_label(replica), ages[replica]))
        lines += render_histogram("gateway_statebus_merge_seconds",
                                  self.merge_hist)
        lines += ["# TYPE gateway_statebus_stale_fallbacks_total counter",
                  f"gateway_statebus_stale_fallbacks_total {stale_total}"]
        lines += render_counter("gateway_statebus_exchanges_total",
                                exchanges, "outcome")
        return lines

    def debug_payload(self) -> dict:
        """The ``/debug/statebus`` body: per-replica versions/ages, this
        replica's local snapshot, and the merged overlay currently worn
        by the advisors — ``tools/statebus_report.py``'s input."""
        now = self._clock()
        fresh = self._fresh_remote(now)
        with self._lock:
            replicas = {
                r: {"seq": e["doc"]["seq"],
                    "age_s": round(max(0.0, now - e["recv_ts"]), 3),
                    "fresh": r == self.replica_id or r in fresh,
                    "pools": sorted(e["doc"].get("pools", {}))}
                for r, e in sorted(self._docs.items())}
            local = self._docs.get(self.replica_id)
            local_pools = dict(local["doc"]["pools"]) if local else {}
        merged = {}
        for pool in self.stacks:
            overlay = self.merged_overlays(pool, fresh)
            merged[pool] = {
                "noisy": {n: list(k) for n, k in overlay["noisy"].items()},
                "avoid": sorted(overlay["avoid"]),
                "resident": {a: [sorted(s), sorted(h)]
                             for a, (s, h) in overlay["resident"].items()},
            }
        # Fleet quota view: every replica's bucket levels per pool (own
        # + fresh peers) — statebus_report renders the per-tenant fleet
        # spend next to each replica's partition.
        fleet: dict[str, dict] = {}
        all_fresh = dict(fresh)
        if local is not None:
            all_fresh[self.replica_id] = local["doc"]
        for rid, doc in all_fresh.items():
            for pool, fams in doc.get("pools", {}).items():
                buckets = fams.get("buckets")
                if isinstance(buckets, list) and buckets:
                    fleet.setdefault(pool, {})[rid] = buckets
        return {
            "replica": self.replica_id,
            "seq": self._seq,
            "stale": self._stale,
            "quota_scale": self.last_apply_scale,
            "live_replicas": len(fresh) + 1,
            "peers": list(self.cfg.peers),
            "replicas": replicas,
            "local": local_pools,
            "merged": merged,
            "fleet_buckets": fleet,
            "counters": {
                "stale_fallbacks_total": self.stale_fallbacks_total,
                "exchanges": dict(self.exchanges),
            },
            "config": asdict(self.cfg),
        }
