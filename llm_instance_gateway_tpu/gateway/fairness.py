"""Fairness & quota plane: the enforcement layer over the PR-5 usage plane.

``gateway/usage.py`` answers *who is consuming the pool*; this module makes
the gateway **act** on that attribution — the promotion of the log-only
``usage_advisor`` seam the same way ``gateway/resilience.py`` promoted the
health seam.  CaraServe (arxiv 2401.11240) and the heterogeneous-LoRA
serving line (arxiv 2511.22880) both show rank/load heterogeneity across
adapters is the dominant interference source in multi-LoRA serving; the
two levers here are exactly the ones they argue for:

- **Pick deprioritization** (``mode=deprioritize`` or ``enforce``): pods
  currently hosting a flagged-noisy adapter are *marked*; a quiet tenant's
  pick narrows to unmarked survivors (isolation — the flood can't degrade
  cotenants on its replicas), while the flagged tenant's own picks narrow
  to the marked pods (containment — the flood can't claim fresh replicas
  while flagged).  Both narrowings run AFTER the health/circuit policy
  filter and BEFORE the prefix tie-break / RNG draw, with the same
  counted last-resort escape hatch shape as ``filter_by_policy`` (a pool
  where every survivor hosts the hog still serves, loudly).  ``log_only``
  keeps routing byte-identical — pinned by same-RNG diff tests across the
  health x circuit x usage x fairness planes in tests/test_fairness.py.

- **Weighted-fair admission quotas** (``mode=enforce``): each
  ``{model, adapter}`` key gets a rank-weighted fair share of the pool
  (``weight = rank_base / rank``, so a rank-64 flood earns a SMALLER share
  than rank-8 tenants — its steps cost proportionally more TPU).  A key
  whose EMA step-seconds share (PR-5 ``gateway_usage_share``) exceeds
  ``over_ratio x fair_share`` is **throttled**: its requests spend a
  per-key token bucket (refill ``quota_rps``, cost scaled by rank) and an
  empty bucket demotes the request ONE criticality tier instead of
  hard-shedding (Critical -> Default -> Sheddable).  Under pool saturation
  degradation therefore proceeds strictly lowest-criticality-first: the
  filter tree sheds Sheddable first, demoted Default next, and the 429
  carries ``Retry-After``.  Decisions journal ``quota_throttle`` /
  ``fairness_demote`` events and export
  ``gateway_quota_throttles_total{model,adapter}``,
  ``gateway_fairness_demotions_total{model,adapter}``, and the
  ``gateway_tenant_quota_remaining{model,adapter}`` gauge.

Config: ``add_resilience_args``-style bootstrap flags
(``--fairness-mode`` etc., gateway/bootstrap.py) plus hot-reloadable
``schedulerConfig.fairnessPolicy`` keys in the InferencePool document
(scheduling/config.py) — the same dual path the admission queue uses.
"""

from __future__ import annotations

import time
from dataclasses import asdict, dataclass, replace

from llm_instance_gateway_tpu.lockwitness import witness_lock
from llm_instance_gateway_tpu import events as events_mod
from llm_instance_gateway_tpu.tracing import render_keyed_family

BASE = "base"
LOG_ONLY, DEPRIORITIZE, ENFORCE = "log_only", "deprioritize", "enforce"
FAIRNESS_MODES = (LOG_ONLY, DEPRIORITIZE, ENFORCE)

# Criticality ladder for one-tier demotion (graceful degradation order:
# sheddable traffic dies first, critical last).
_DEMOTE = {"Critical": "Default", "Default": "Sheddable"}


@dataclass(frozen=True)
class FairnessConfig:
    """Knobs for the fairness/quota plane (flags: ``add_resilience_args``;
    pool document: ``schedulerConfig.fairnessPolicy``)."""

    # log_only: observe only (routing byte-identical to the PR-5 seam).
    # deprioritize: flagged keys lose pick ties (isolation + containment).
    # enforce: deprioritize + rank-weighted admission quotas with one-tier
    # demotion.
    mode: str = LOG_ONLY
    # A key is over-quota when its EMA step-seconds share exceeds
    # over_ratio x its rank-weighted fair share.  The default (3x) only
    # throttles flagrant over-consumption: a busy-but-proportional tenant
    # legitimately exceeds an equal split, and enforcement that bites at
    # 1.5x would punish ordinary traffic skew (the adapter_flood chaos
    # scenario pins a flooding hog throttling while a 60%-of-traffic
    # quiet tenant does not).
    over_ratio: float = 3.0
    # Absolute ceiling on any key's share before the quota bites
    # regardless of over_ratio: with few tenants ``over_ratio x fair``
    # can exceed 1.0 and the quota could never bind — a 2-tenant pool's
    # 90%-share hog must still throttle.  Keys whose FAIR share already
    # exceeds this cap (near-single-tenant pools) are exempt: the pool is
    # legitimately theirs.
    max_share: float = 0.85
    # Token bucket for throttled keys: full-criticality admissions per
    # second while over quota; excess demotes one tier.  The burst cap
    # bounds how fast a key exits a quiet period.
    quota_rps: float = 4.0
    quota_burst: float = 8.0
    # Rank scaling: fair-share weight = rank_base / rank (base tenants and
    # unknown ranks weigh 1.0); bucket cost = rank / rank_base, so a
    # rank-64 request spends 8x a rank-8 one against the same bucket.
    rank_base: int = 8
    # Retry-After hint (seconds) the proxy stamps on 429 shed responses.
    retry_after_s: float = 1.0

    def __post_init__(self):
        if self.mode not in FAIRNESS_MODES:
            raise ValueError(
                f"fairness mode {self.mode!r} not in {FAIRNESS_MODES}")
        if self.over_ratio <= 0 or self.quota_rps <= 0 \
                or self.quota_burst <= 0 or self.rank_base <= 0 \
                or not 0 < self.max_share <= 1:
            raise ValueError("fairness ratios/rates must be positive "
                             "(max_share in (0, 1])")


class FairnessPolicy:
    """The object the proxy hands to the scheduler as ``usage_advisor``
    (superset of the UsageRollup seam: ``noisy``/``note_pick`` delegate to
    the rollup, so ``log_only`` stays byte-identical) and to the handler
    core as the admission gate (``admit``).  Thread-safe: the pick seam,
    the transport threads, and the observability tick all touch it."""

    def __init__(self, usage, cfg: FairnessConfig | None = None,
                 journal: events_mod.EventJournal | None = None,
                 provider=None, clock=time.time,
                 cli_overrides: dict | None = None):
        self.usage = usage          # gateway.usage.UsageRollup
        # Explicitly-passed CLI flags (field -> value) pin those FIELDS:
        # overlaid on the initial config here and re-applied on every
        # ``update_config``, so a pool-doc hot reload (with or without a
        # fairnessPolicy section) can never clobber an operator's flags,
        # while unpinned fields still track the pool document.
        self._cli_overrides = dict(cli_overrides or {})
        self.cfg = replace(cfg or FairnessConfig(), **self._cli_overrides)
        self.journal = journal
        self.provider = provider    # adapter-rank source (may be None)
        self._clock = clock
        self._lock = witness_lock("FairnessPolicy._lock")
        # Tick-computed state (all keyed by (model, adapter)):
        self._fair_shares: dict[tuple, float] = {}
        self._shares: dict[tuple, float] = {}
        self._costs: dict[tuple, float] = {}      # bucket cost per request
        self._throttled: dict[str, tuple] = {}    # request name -> key
        self._buckets: dict[tuple, list] = {}     # key -> [tokens, last_t]
        # Exported counters.
        self.quota_throttles: dict[tuple, int] = {}
        self.fairness_demotions: dict[tuple, int] = {}
        self.escape_total = 0
        self.ticks = 0
        # Global-fairness partition (statebus): with N live gateway
        # replicas spraying one tenant's traffic, each replica serves
        # ~1/N of it, so each local token bucket refills (and bursts) at
        # 1/N of the configured rate — the FLEET-wide admission rate for
        # a throttled tenant stays quota_rps regardless of replica count.
        # 1.0 (single gateway / statebus absent) reproduces the exact
        # pre-statebus behavior.
        self.quota_scale = 1.0
        # (noisy-set identity, pods hosting a flagged adapter): the pick
        # seam's cached mark set — the rollup rebuilds its noisy frozenset
        # every tick, so object identity is the cheap staleness signal
        # (same shape as health.non_healthy() / breaker.blocked_set()).
        self._noisy_pods_cache: tuple = (None, frozenset())

    # -- config ------------------------------------------------------------
    @property
    def mode(self) -> str:
        return self.cfg.mode

    def update_config(self, cfg: FairnessConfig) -> None:
        """Hot-reload seam (pool ``schedulerConfig.fairnessPolicy`` via
        AdmissionController.update_config).  CLI-pinned fields are
        re-overlaid so a reload can't clobber them.  Buckets keep their
        levels — a reload must not hand every throttled tenant a fresh
        burst."""
        cfg = replace(cfg, **self._cli_overrides)
        if cfg != self.cfg:
            self.cfg = cfg

    # -- scheduler advisor seam (superset of UsageRollup's) ----------------
    def noisy(self) -> frozenset:
        return self.usage.noisy()

    def note_pick(self, pod_name: str, model: str | None) -> None:
        """Log-only counting rides the rollup unchanged — no RNG, no
        exceptions — so attaching this policy in ``log_only`` keeps picks
        byte-identical (tests/test_fairness.py pins it)."""
        self.usage.note_pick(pod_name, model)

    def noisy_pods(self) -> frozenset | None:
        """Pods currently hosting a flagged-noisy adapter — the pick
        seam's mark set (``filter_by_fairness``), cached per noisy-set
        generation so the per-pick cost is one frozenset membership test
        per candidate.  None when no provider is attached (the filter
        falls back to scanning candidate residency directly)."""
        if self.provider is None:
            return None
        flagged = self.usage.noisy()
        if not flagged:
            return frozenset()
        cached_id, cached = self._noisy_pods_cache
        if cached_id is flagged:
            return cached
        pods = frozenset(
            pm.pod.name for pm in self.provider.all_pod_metrics()
            if any(a in flagged for a in pm.metrics.active_adapters))
        self._noisy_pods_cache = (flagged, pods)
        return pods

    def note_fairness_escape(self) -> None:
        """Every survivor hosted a flagged adapter; the pick proceeded
        over the full set (deprioritize last resort).  Called from the
        threaded-transport pick seam, so the increment takes the lock."""
        with self._lock:
            self.escape_total += 1
        if self.journal is not None:
            self.journal.emit(events_mod.FAIRNESS_ESCAPE,
                              mode=self.cfg.mode)

    # -- tick (fair shares + throttle set) ---------------------------------
    def _pool_ranks(self) -> dict[str, int]:
        """Adapter name -> rank, merged over the pool's replicas (max wins:
        the costliest resident copy is the one the quota must price)."""
        ranks: dict[str, int] = {}
        if self.provider is None:
            return ranks
        for pm in self.provider.all_pod_metrics():
            for name, rank in getattr(pm.metrics, "adapter_ranks",
                                      {}).items():
                if rank and rank > ranks.get(name, 0):
                    ranks[name] = rank
        return ranks

    def tick(self, now: float | None = None) -> None:
        """Observability-cadence pass: rank-weighted fair shares from the
        rollup's EMA step-seconds shares, then the throttled set.  Runs
        AFTER ``usage.tick()`` so shares are current."""
        now = self._clock() if now is None else now
        shares = self.usage.shares_snapshot()
        ranks = self._pool_ranks()
        cfg = self.cfg
        weights: dict[tuple, float] = {}
        costs: dict[tuple, float] = {}
        for (model, adapter) in shares:
            rank = (ranks.get(adapter, cfg.rank_base)
                    if adapter != BASE else cfg.rank_base)
            weights[(model, adapter)] = cfg.rank_base / max(1, rank)
            costs[(model, adapter)] = max(1.0, rank / cfg.rank_base)
        total_w = sum(weights.values())
        fair = ({k: w / total_w for k, w in weights.items()}
                if total_w > 0 else {})
        throttled: dict[str, tuple] = {}
        for key, share in shares.items():
            if not fair or fair[key] >= cfg.max_share:
                continue  # near-single-tenant: the pool is theirs
            bar = min(cfg.over_ratio * fair[key], cfg.max_share)
            if share > bar:
                model, adapter = key
                # Match what the admission/pick seams actually see: base
                # tenants arrive under the served MODEL name, adapter
                # traffic under the adapter name (usage.py semantics).
                # The same adapter name served under TWO models collides
                # on that name; a request can't be attributed to one key
                # at admission time, so charge the dominant offender
                # (highest pool share) rather than iteration-order's last.
                name = model if adapter == BASE else adapter
                prev = throttled.get(name)
                if prev is None or shares.get(prev, 0.0) < share:
                    throttled[name] = key
        with self._lock:
            self.ticks += 1
            self._shares = shares
            self._fair_shares = fair
            self._costs = costs
            self._throttled = throttled
            # GC buckets for keys that left the attribution plane, so the
            # gauge exposition stays bounded by live tenants.
            for key in [k for k in self._buckets if k not in shares]:
                del self._buckets[key]

    def throttled(self) -> frozenset:
        """Currently over-quota request names (lock-free-ish read for
        tests/chaos assertions)."""
        return frozenset(self._throttled)

    def set_quota_scale(self, scale: float) -> None:
        """Statebus seam: partition the tenant quota across the live
        gateway replica set (``scale = 1 / live_replicas``).  Existing
        bucket levels above the new burst cap clamp on their next refill
        (``min(burst, ...)`` in ``admit``), so a shrink takes effect
        within one admission, not one idle period.

        The even split assumes the load balancer sprays a tenant's
        traffic roughly uniformly (many sessions hashed across
        replicas).  A tenant pinned WHOLE to one replica by affinity
        sees quota_rps/N there, i.e. over-throttling by N — if that is
        your topology, run ``--no-statebus-quota-partition`` (full local
        quotas; fleet-wide rate then bounded by N x quota_rps)."""
        self.quota_scale = max(1e-6, min(1.0, scale))

    def bucket_levels(self) -> list[list]:
        """Token-bucket levels per throttled key as
        ``[[model, adapter, tokens], ...]`` — published on the statebus
        so ``tools/statebus_report.py`` can show the fleet-wide quota
        spend next to each replica's partition."""
        with self._lock:
            return [[k[0], k[1], round(b[0], 4)]
                    for k, b in sorted(self._buckets.items())]

    # -- admission gate ----------------------------------------------------
    def admit(self, llm_req) -> str | None:
        """Quota gate, called by the handler core BEFORE scheduling.

        Returns the tier the request was demoted to (None = untouched).
        Never raises and never hard-sheds: an over-quota request is worth
        one tier less, and the filter tree / admission queue then applies
        the normal lowest-criticality-first degradation under saturation.
        """
        if self.cfg.mode != ENFORCE:
            return None
        key = self._throttled.get(llm_req.model)
        if key is None:
            return None
        cfg = self.cfg
        now = self._clock()
        scale = self.quota_scale
        cost = self._costs.get(key, 1.0)
        # The burst ceiling scales with the partition but NEVER below one
        # request's cost: min(burst, ...) clamps every refill, so a
        # ceiling under the cost would starve the tenant at full priority
        # forever on every replica (the partition is meant to scale the
        # RATE, not zero out admission).
        burst = max(cfg.quota_burst * scale, cost)
        with self._lock:
            bucket = self._buckets.get(key)
            if bucket is None:
                bucket = self._buckets[key] = [burst, now]
            tokens, last = bucket
            tokens = min(burst,
                         tokens + max(0.0, now - last)
                         * cfg.quota_rps * scale)
            if tokens >= cost:
                bucket[0], bucket[1] = tokens - cost, now
                return None
            bucket[0], bucket[1] = tokens, now
            self.quota_throttles[key] = self.quota_throttles.get(key, 0) + 1
        if self.journal is not None:
            self.journal.emit(events_mod.QUOTA_THROTTLE, model=key[0],
                              adapter=key[1],
                              criticality=llm_req.criticality)
        frm = llm_req.criticality or "Default"
        to = _DEMOTE.get(frm)
        if to is None:
            return None  # already Sheddable: the tree sheds it first
        llm_req.criticality = to
        llm_req.critical = False
        with self._lock:
            self.fairness_demotions[key] = (
                self.fairness_demotions.get(key, 0) + 1)
        if self.journal is not None:
            self.journal.emit(events_mod.FAIRNESS_DEMOTE, model=key[0],
                              adapter=key[1], frm=frm, to=to)
        return to

    # -- export ------------------------------------------------------------
    def render(self) -> list[str]:
        with self._lock:
            throttles = dict(self.quota_throttles)
            demotions = dict(self.fairness_demotions)
            # Only CURRENTLY-throttled tenants: a key back under quota
            # would otherwise export its last (frozen) bucket level
            # forever — refill is lazy in admit(), so the gauge never
            # visibly recovers.  Bucket levels are kept (not GC'd) so a
            # re-throttled oscillator doesn't restart with a full burst.
            live = set(self._throttled.values())
            remaining = {key: bucket[0]
                         for key, bucket in self._buckets.items()
                         if key in live}
        lines = render_keyed_family(
            "gateway_quota_throttles_total", throttles,
            ("model", "adapter"))
        lines += render_keyed_family(
            "gateway_fairness_demotions_total", demotions,
            ("model", "adapter"))
        lines += render_keyed_family(
            "gateway_tenant_quota_remaining", remaining,
            ("model", "adapter"), kind="gauge", fmt="%.3f")
        return lines

    def debug_payload(self) -> dict:
        with self._lock:
            throttled = dict(self._throttled)
            rows = []
            for name, key in sorted(throttled.items()):
                rows.append({
                    "name": name, "model": key[0], "adapter": key[1],
                    "share": round(self._shares.get(key, 0.0), 4),
                    "fair_share": round(self._fair_shares.get(key, 0.0), 4),
                    "cost": self._costs.get(key, 1.0),
                    "quota_remaining": round(
                        self._buckets.get(key, [self.cfg.quota_burst])[0],
                        3),
                    "throttles": self.quota_throttles.get(key, 0),
                    "demotions": self.fairness_demotions.get(key, 0),
                })
            return {
                "mode": self.cfg.mode,
                "quota_scale": self.quota_scale,
                "throttled": rows,
                "quota_throttles_total": sum(self.quota_throttles.values()),
                "fairness_demotions_total": sum(
                    self.fairness_demotions.values()),
                "escape_total": self.escape_total,
                "ticks": self.ticks,
                "config": asdict(self.cfg),
            }
