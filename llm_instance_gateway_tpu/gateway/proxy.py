"""Standalone gateway: an L7 reverse proxy embedding the ext-proc handler core.

The reference runs as an Envoy ext-proc sidecar: Envoy terminates HTTP, calls
the EPP over gRPC, then routes to the ORIGINAL_DST cluster using the
``target-pod`` header (``pkg/manifests/patch_policy.yaml:14-42``).  On GKE
that wiring is reproduced by the manifests under ``deploy/``; for
environments without Envoy (and for the TPU pools' leaner data path) this
module IS the proxy: it terminates OpenAI-style HTTP, runs the identical
four-phase handler core inline (request headers -> body -> schedule ->
forward -> response phases), and streams the model server's reply back.

Endpoints:
- ``POST /v1/completions`` and ``/v1/chat/completions`` — routed inference.
- ``GET  /metrics``  — gateway self-telemetry (scheduler decisions, shed rate,
  pick latency, TTFT/TPOT/e2e histograms; resolves reference TODO
  provider.go:140).
- ``GET  /debug/traces`` — recent request traces (``?trace_id=`` filters,
  ``?since=<seq>`` serves incremental deltas — the same cursor contract
  as ``/debug/events``, what the fleet collector polls); each trace
  merges the proxy's own spans with the model servers' spans returned in
  their ``x-lig-spans`` response headers, so one JSON document answers
  "where did this request spend its time?" across up to three processes.
- ``GET  /debug/slo`` — per-model SLO compliance + multi-window burn rates
  + burn state (gateway/slo.py), evaluated on demand.
- ``GET  /debug/health`` — per-replica 0-1 health scores with components
  and hysteresis states (gateway/health.py), plus the resilience plane:
  health policy, per-pod circuit-breaker states, retry-budget level
  (gateway/resilience.py).
- ``GET  /debug/usage`` — pool-wide capacity attribution: per-{model,
  adapter} consumption shares, noisy-neighbor scores/flags, pool-waste
  aggregates (gateway/usage.py; live console: ``tools/lig_top.py``).
- ``GET  /debug/kv`` — the fleet KV economy view (gateway/kvobs.py):
  per-pod reuse efficiency / parked-KV share over the replicas'
  ``tpu:kv_*`` ledger families and the cross-replica prefix duplication
  index ("prefix P resident on k replicas, N blocks duplicated");
  rendered by ``tools/kv_report.py``.
- ``GET  /debug/capacity`` — the capacity & saturation plane
  (gateway/capacity.py): per-pod per-resource saturation indices, the
  sim-calibrated twin's headroom-at-SLO and time-to-breach forecasts, and
  the twin-drift trust state; rendered by ``tools/capacity_report.py``.
- ``GET  /debug/events`` — the flight recorder (events.py): admission
  rejections, pick outcomes, disagg fallbacks, scrape failures, SLO/health
  transitions, noisy-neighbor flags; ``?since=<seq>`` for incremental
  polling.
- ``GET  /debug/fleet`` — the fleet observability view (gateway/fleetobs.py):
  every peer gateway's and pool pod's traces/events/slo/health pulled
  through the incremental cursors, cross-replica traces stitched into
  causally-ordered timelines with clock-skew normalization, event journals
  merged by (replica, seq), fleet-wide SLO rollup; rendered by
  ``tools/fleet_report.py``.
- ``GET  /healthz``  — 200 once the InferencePool is synced (main.go:43-52).
- ``GET  /v1/models`` — logical models from the datastore.

On an SLO fast burn the proxy snapshots events + traces + metrics + SLO and
health payloads into a black-box dump file (``LIG_BLACKBOX_DIR``, cooldown
``LIG_BLACKBOX_COOLDOWN_S``); ``tools/blackbox_report.py`` renders the
post-mortem timeline.

Every response — success or error — carries the request's ``x-lig-trace-id``
(error bodies embed it too) so clients and the loadgen can correlate.

Failure policy (gateway/resilience.py): idempotent upstream failures
(connect errors, 503s, TTFT timeouts — anything before the first relayed
byte) retry with decorrelated-jitter backoff under a global retry budget,
re-running admission + pick each attempt so ``health_policy=avoid`` steers
the re-pick off the failed replica; non-streaming requests can hedge on a
slow TTFT; per-phase timeouts (connect / TTFT / stream-idle) replace the
old single 3600 s client timeout.
"""

from __future__ import annotations

import argparse
import asyncio
import json
import logging
import os
import tempfile
import time
import uuid

import aiohttp
from aiohttp import web

from llm_instance_gateway_tpu import events as events_mod
from llm_instance_gateway_tpu.gateway import capacity as capacity_mod
from llm_instance_gateway_tpu.gateway import fleetobs
from llm_instance_gateway_tpu.gateway import pickledger as pickledger_mod
from llm_instance_gateway_tpu.gateway import slo as slo_mod
from llm_instance_gateway_tpu.gateway import statebus as statebus_mod
from llm_instance_gateway_tpu.gateway.advisors import (
    AdvisorStack,
    merge_exposition_blocks,
)
from llm_instance_gateway_tpu.gateway.datastore import Datastore
from llm_instance_gateway_tpu.gateway.handlers.messages import (
    RequestBody,
    RequestHeaders,
    ResponseBody,
    ResponseHeaders,
)
from llm_instance_gateway_tpu.gateway.handlers.server import (
    ProcessingError,
    RequestContext,
    Server,
)
from llm_instance_gateway_tpu.gateway.resilience import retry_backoff
from llm_instance_gateway_tpu.gateway.telemetry import GatewayMetrics, Timer
from llm_instance_gateway_tpu import tracing

logger = logging.getLogger(__name__)

# Fast-relay final-usage window: the zero-copy path keeps only the trailing
# bytes of the stream (as whole chunk references, never per-chunk copies) to
# parse the final usage chunk from; SSE usage envelopes are a few hundred
# bytes, so 16 KB of tail is orders of magnitude of margin.
RELAY_TAIL_BYTES = 16384
# Upstream keepalive pool: how long an idle per-pod connection survives and
# how many concurrent connections one pod may hold.  Reuse is the point —
# a fresh TCP handshake per request is pure data-plane tax.
UPSTREAM_KEEPALIVE_S = float(os.environ.get("LIG_UPSTREAM_KEEPALIVE_S", "30"))
UPSTREAM_CONNS_PER_POD = int(os.environ.get("LIG_UPSTREAM_CONNS_PER_POD",
                                            "32"))


def final_data_line(tail: bytes) -> bytes:
    """Last complete ``data: `` line of an SSE stream that is not the
    ``[DONE]`` terminator, from the stream's trailing bytes — the fast
    relay's end-of-stream usage parse (raw bytes; the per-chunk loop never
    re-frames lines).  Matches the slow path's incremental scan: only
    ``\\n``-terminated lines count."""
    lines = tail.split(b"\n")
    for line in reversed(lines[:-1]):
        if line.startswith(b"data: ") and line != b"data: [DONE]":
            return line
    return b""


class GatewayProxy:
    def __init__(
        self,
        handler_server: Server,
        provider,
        datastore: Datastore,
        resilience_cfg=None,
        slo_cfg: "slo_mod.SLOConfig | None" = None,
        health_cfg=None,
        usage_cfg=None,
        fairness_cfg=None,
        placement_cfg=None,
        blackbox_dir: str | None = None,
        fast_relay: bool = True,
        pools: dict | None = None,
        statebus_cfg: "statebus_mod.StateBusConfig | None" = None,
        pickledger_cfg: "pickledger_mod.PickLedgerConfig | None" = None,
        capacity_cfg: "capacity_mod.CapacityConfig | None" = None,
    ):
        self.server = handler_server
        self.provider = provider
        self.datastore = datastore
        self.metrics = GatewayMetrics()
        # Re-export per-replica prefix-cache reuse at the gateway /metrics
        # (the KV-affinity observable; see GatewayMetrics.pool_signals_fn).
        self.metrics.pool_signals_fn = provider.all_pod_metrics
        # Request tracing (tracing.py): bounded span ring served by
        # /debug/traces; sampling/capacity via LIG_TRACE_* env.
        self.tracer = tracing.Tracer()
        # ONE flight recorder per gateway process; every pool's advisor
        # stack journals into it (events carry pod/model attributes).
        self.journal = events_mod.EventJournal()
        # Per-pool advisor stacks (gateway/advisors.py).  A single-pool
        # gateway gets exactly one stack over its own provider/scheduler
        # — identical wiring to the historical inline construction.  A
        # multi-pool front (``pools`` = MultiPoolComponents.pools) gets a
        # FULL stack per pool: each pool's scheduler carries its own
        # advisor seams (Python AND native paths) and each pool's handler
        # core its own fairness admit() gate — the PR-7 "enforcement
        # INACTIVE" carve-out is gone.
        self.stacks: dict[str, AdvisorStack] = {}
        if pools:
            for name, comps in pools.items():
                ds = comps.datastore
                self.stacks[name] = AdvisorStack(
                    name, comps.provider,
                    scheduler=comps.scheduler,
                    server=comps.handler_server,
                    metrics=self.metrics, journal=self.journal,
                    resilience_cfg=resilience_cfg, health_cfg=health_cfg,
                    usage_cfg=usage_cfg, fairness_cfg=fairness_cfg,
                    placement_cfg=placement_cfg,
                    pickledger_cfg=pickledger_cfg,
                    capacity_cfg=capacity_cfg,
                    # Scope this pool's admitted-traffic shares to its own
                    # models (the shared GatewayMetrics counts everything).
                    request_filter=(
                        lambda m, _ds=ds: _ds.fetch_model(m) is not None))
                if hasattr(comps.provider, "journal"):
                    comps.provider.journal = self.journal
            self._default_pool = next(iter(pools))
            default = getattr(handler_server, "_default", None)
            if default in self.stacks:
                self._default_pool = default
        else:
            pool_name = "default"
            get_pool = getattr(datastore, "get_pool", None)
            if get_pool is not None:
                try:
                    pool_name = get_pool().name or pool_name
                except Exception:
                    pass
            self.stacks[pool_name] = AdvisorStack(
                pool_name, provider,
                scheduler=getattr(handler_server, "scheduler", None),
                server=handler_server,
                metrics=self.metrics, journal=self.journal,
                resilience_cfg=resilience_cfg, health_cfg=health_cfg,
                usage_cfg=usage_cfg, fairness_cfg=fairness_cfg,
                placement_cfg=placement_cfg,
                pickledger_cfg=pickledger_cfg,
                capacity_cfg=capacity_cfg)
            self._default_pool = pool_name
            # Scrape failures land in the flight recorder (Provider
            # emits, throttled); StaticProvider lacks the attribute.
            if hasattr(provider, "journal"):
                provider.journal = self.journal
        # Back-compat aliases: the default pool's planes under the
        # historical names.  Single-pool deployments (and every existing
        # caller/test) see exactly the old object graph; the data path
        # routes per-pod through ``_stack_for_pod`` so multi-pool fronts
        # feed the RIGHT pool's health scorer and breaker.
        stack = self.stacks[self._default_pool]
        self.health = stack.health
        self.resilience = stack.resilience
        self.usage = stack.usage
        self.kvobs = stack.kvobs
        self.capacity = stack.capacity
        self.fairness = stack.fairness
        self.placement = stack.placement
        self.pickledger = stack.pickledger
        self._pod_stack_cache: dict[str, AdvisorStack] = {}
        # SLO engine stays gateway-wide: it reads the shared
        # GatewayMetrics histograms, which span every pool this process
        # fronts.
        self.slo = slo_mod.SLOEngine(
            self.metrics, cfg=slo_cfg, journal=self.journal,
            on_fast_burn=self._on_fast_burn)
        # Replicated control-plane state bus (gateway/statebus.py): the
        # tick's derived state becomes versioned per-pool snapshots
        # gossiped between gateway replicas; the merged view overlays the
        # stacks' advisors so N gateways share one brain.  Peer-less
        # (the default) it is inert beyond serving /debug/statebus.
        self.statebus = statebus_mod.StateBus(
            self.stacks, cfg=statebus_cfg, journal=self.journal)
        # Fleet observability collector (gateway/fleetobs.py): pulls the
        # peer gateways' (the statebus peer list — the fleet topology is
        # already wired) and every pool pod's debug surfaces through the
        # incremental cursors, stitches cross-replica traces, and serves
        # /debug/fleet.  Peer-less single-pool gateways still get the
        # local+pods view (streaming decode spans live only on pods).
        self.fleet = fleetobs.FleetCollector(
            self.statebus.replica_id,
            peer_urls=self.statebus.cfg.peers,
            pods_fn=self._fleet_pods,
            local_fn=self._fleet_local_payloads,
            journal=self.journal)
        # Black-box dump directory + dump-storm cooldown; both env-tunable.
        self.blackbox_dir = (
            blackbox_dir or os.environ.get("LIG_BLACKBOX_DIR")
            or os.path.join(tempfile.gettempdir(), "lig-blackbox"))
        self._blackbox_cooldown_s = float(
            os.environ.get("LIG_BLACKBOX_COOLDOWN_S", "60"))
        self._last_dump_t = 0.0  # of the last SUCCESSFUL dump
        self._dump_inflight = False
        # Evaluation cadence for the background tick (0 disables the task;
        # /debug/slo and /debug/health still evaluate on demand).
        self.obs_tick_s = float(os.environ.get("LIG_SLO_TICK_S", "5"))
        self._obs_task: asyncio.Task | None = None
        # Strong refs to in-flight KV-release tasks (the event loop only
        # keeps weak ones; see _spawn_release).
        self._release_tasks: set = set()
        self._session: aiohttp.ClientSession | None = None
        # Data-plane fast path (this PR's tentpole): the zero-copy SSE
        # relay.  ``fast_relay=False`` keeps the pre-existing line-scanning
        # relay — the byte-parity oracle the A/B tests compare against.
        self.fast_relay = fast_relay
        # Preallocated header templates: the per-request mutation copies a
        # template and stamps the request-scoped values instead of
        # rebuilding the static keys on every hop.
        self._sse_headers_tpl = {
            "Content-Type": "text/event-stream",
            "Cache-Control": "no-cache",
        }
        self._upstream_headers_tpl = {"Content-Type": "application/json"}

    # -- app wiring --------------------------------------------------------
    def build_app(self) -> web.Application:
        app = web.Application()
        app.router.add_post("/v1/completions", self.handle_completion)
        app.router.add_post("/v1/chat/completions", self.handle_completion)
        app.router.add_get("/metrics", self.handle_metrics)
        app.router.add_get("/debug/traces", self.handle_debug_traces)
        app.router.add_get("/debug/slo", self.handle_debug_slo)
        app.router.add_get("/debug/health", self.handle_debug_health)
        app.router.add_get("/debug/usage", self.handle_debug_usage)
        app.router.add_get("/debug/kv", self.handle_debug_kv)
        app.router.add_get("/debug/capacity", self.handle_debug_capacity)
        app.router.add_get("/debug/picks", self.handle_debug_picks)
        app.router.add_get("/debug/placement", self.handle_debug_placement)
        app.router.add_get("/debug/statebus", self.handle_debug_statebus)
        app.router.add_get("/debug/fleet", self.handle_debug_fleet)
        app.router.add_post("/statebus/exchange",
                            self.handle_statebus_exchange)
        app.router.add_get("/debug/events", self.handle_debug_events)
        app.router.add_get("/healthz", self.handle_health)
        app.router.add_get("/v1/models", self.handle_models)
        app.on_startup.append(self._on_startup)
        app.on_cleanup.append(self._on_cleanup)
        return app

    async def _on_startup(self, app) -> None:
        # Per-phase timeouts (gateway/resilience.py) replace the old single
        # total timeout: connect is bounded here; TTFT and idle-between-
        # chunks are enforced per request on the data path, so a dead
        # replica fails in seconds while a long healthy stream runs
        # indefinitely.
        rcfg = self.resilience.cfg
        # Per-pod keepalive connection pool: upstream connections are
        # reused across requests (a handshake per request is data-plane
        # tax), with creation/reuse counted per pod through aiohttp's
        # trace hooks — the ``gateway_upstream_connections_total`` family
        # and the reuse-ratio gauge come straight from these two events.
        connector = aiohttp.TCPConnector(
            limit=0, limit_per_host=UPSTREAM_CONNS_PER_POD,
            keepalive_timeout=UPSTREAM_KEEPALIVE_S)
        trace_cfg = aiohttp.TraceConfig()

        async def _conn_created(session, ctx, params) -> None:
            pod = (getattr(ctx, "trace_request_ctx", None) or {}).get("pod")
            self.metrics.record_upstream_conn(pod or "?", reused=False)

        async def _conn_reused(session, ctx, params) -> None:
            pod = (getattr(ctx, "trace_request_ctx", None) or {}).get("pod")
            self.metrics.record_upstream_conn(pod or "?", reused=True)

        trace_cfg.on_connection_create_end.append(_conn_created)
        trace_cfg.on_connection_reuseconn.append(_conn_reused)
        self._session = aiohttp.ClientSession(
            connector=connector,
            timeout=aiohttp.ClientTimeout(
                total=None, connect=rcfg.connect_timeout_s or None),
            trace_configs=[trace_cfg],
        )
        if self.obs_tick_s > 0:
            self._obs_task = asyncio.get_running_loop().create_task(
                self._observability_loop())

    async def _on_cleanup(self, app) -> None:
        if self._obs_task is not None:
            self._obs_task.cancel()
            self._obs_task = None
        if self._session is not None:
            await self._session.close()

    def control_tick(self) -> None:
        """One full control-plane pass: every pool's advisor stack
        (health/breaker, usage shares, fairness quotas, placement), the
        gateway-wide SLO engine, then the statebus snapshot+apply — the
        tick-derived state becomes this replica's published snapshot and
        the freshest peer state overlays the advisors.  Synchronous (no
        I/O): chaos and tests drive it explicitly; peer exchange is the
        async half in ``_observability_loop``."""
        for stack in self.stacks.values():
            stack.tick()
        self.slo.tick()
        self.statebus.tick()
        # Prune the pod->stack route cache against live membership (the
        # breaker.prune pattern): pod names are never reused, so without
        # this the cache grows monotonically under membership churn.
        if len(self.stacks) > 1 and self._pod_stack_cache:
            live = set()
            for stack in self.stacks.values():
                live |= stack.pod_names()
            for name in [n for n in self._pod_stack_cache
                         if n not in live]:
                del self._pod_stack_cache[name]

    async def _observability_loop(self) -> None:
        """Background evaluation tick: per-pool advisor stacks first
        (cheap, feed the journal), the SLO engine (may fire the black-box
        dump), the statebus snapshot/merge, then the peer push-pull
        exchange."""
        while True:
            await asyncio.sleep(self.obs_tick_s)
            try:
                self.control_tick()
            except Exception:
                logger.exception("observability tick failed")
            try:
                if self.statebus.cfg.peers and self._session is not None:
                    await self.statebus.exchange(self._session)
                    self.statebus.apply()  # fold what the exchange brought
            except Exception:
                logger.exception("statebus exchange failed")

    def _on_fast_burn(self, model: str, objective: str, burns: dict) -> None:
        """SLO fast-burn hook: snapshot everything into a black-box dump
        (rate-limited — a breach across N models must not write N dumps a
        second) and journal where it went.

        The file write runs OFF the event loop when one is running: a
        fast burn is exactly when the gateway is already degraded, and a
        multi-MB synchronous dump to slow disk would stall every in-flight
        request.  The cooldown stamps only on SUCCESS — a failed write
        (disk full, unwritable dir) retries on the next breach tick before
        the pre-incident journal rotates out."""
        now = time.time()
        if (self._dump_inflight
                or now - self._last_dump_t < self._blackbox_cooldown_s):
            return
        self._dump_inflight = True
        reason = {"trigger": "fast_burn", "model": model,
                  "objective": objective,
                  "burns": {k: (round(v, 3) if v is not None else None)
                            for k, v in burns.items()}}

        def write() -> None:
            try:
                # Pod profiler snapshots: best-effort bounded fetches off
                # the event loop (this runs in the executor) — a wedged
                # pod costs one timeout, never the dump.
                pods = self._fleet_pods()
                profiles = fleetobs.collect_pod_payloads(
                    pods, "/debug/profile", thread_name="blackbox-profile")
                # KV economy at dump time: the gateway rollup (refreshed —
                # the breach may predate the last observability tick) plus
                # each pod's raw ledger snapshot; unreachable pods degrade
                # to error markers, never a lost dump.
                self.kvobs.maybe_tick(max(1.0, self.obs_tick_s))
                kv_payload = {
                    "gateway": self.kvobs.debug_payload(),
                    "pods": fleetobs.collect_pod_payloads(
                        pods, "/debug/kv", thread_name="blackbox-kv"),
                }
                # Twin state at dump time: saturation, forecasts and the
                # drift trust flag — was capacity exhaustion forecast, and
                # was the forecast trusted, when the burn hit?
                capacity_payload = None
                if self.capacity.cfg.enabled:
                    self.capacity.maybe_tick(max(1.0, self.obs_tick_s))
                    capacity_payload = {
                        name: stack.capacity.debug_payload()
                        for name, stack in self.stacks.items()}
                # Decision records at dump time: the last sampled picks
                # per pool — "why were requests landing where they were in
                # the 30s before the breach" (tools/blackbox_report.py
                # renders the funnel + decisive seams).
                picks_payload = {
                    name: pickledger_mod.debug_picks_payload(
                        stack.pickledger, {"limit": "64"})
                    for name, stack in self.stacks.items()}
                path = slo_mod.write_blackbox(
                    self.blackbox_dir, reason, journal=self.journal,
                    tracer=self.tracer, metrics_text=self._render_metrics(),
                    slo_payload=self.slo.debug_payload(),
                    health_payload=self.health.debug_payload(),
                    usage_payload=self.usage.debug_payload(),
                    statebus_payload=self.statebus.debug_payload(),
                    profile_payload=profiles,
                    kv_payload=kv_payload,
                    picks_payload=picks_payload,
                    capacity_payload=capacity_payload)
                self._last_dump_t = time.time()
                self.journal.emit(events_mod.BREACH_DUMP, model=model,
                                  objective=objective, path=path)
                logger.warning(
                    "SLO fast burn (%s/%s): black-box dump written to %s",
                    model, objective, path)
            except OSError:
                logger.exception("black-box dump failed")
            finally:
                self._dump_inflight = False

        try:
            asyncio.get_running_loop().run_in_executor(None, write)
        except RuntimeError:
            write()  # synchronous contexts (tests, CLI tools)

    # -- fleet observability seams -----------------------------------------
    def _fleet_pods(self) -> list:
        """Live ``(pod_name, address)`` membership across every pool this
        gateway fronts — the fleet collector's pod source list."""
        out = []
        for stack in self.stacks.values():
            for pm in stack.provider.all_pod_metrics():
                out.append((pm.pod.name, pm.pod.address))
        return out

    def _fleet_local_payloads(self) -> dict:
        """This replica's own debug payloads, handed to the fleet
        collector without an HTTP round trip to ourselves."""
        # The journal pages OLDEST-first from a cursor: anchor the cursor
        # 512 rows behind the head so the fleet view carries the NEWEST
        # local events (the pre-breach window), not the ring's stale tail.
        events_since = max(0, self.journal.seq - 512)
        return {
            "traces": tracing.debug_traces_payload(
                self.tracer, {"limit": "256"}),
            "events": events_mod.debug_events_payload(
                self.journal, {"since": str(events_since), "limit": "512"}),
            "slo": self.slo.debug_payload(),
            "health": self.health.debug_payload(),
        }

    # -- per-pool routing of data-path signals -----------------------------
    def _stack_for_pod(self, pod_name: str) -> AdvisorStack:
        """The advisor stack owning ``pod_name``.  Single-pool fronts
        short-circuit to the only stack; multi-pool lookups are cached
        (pods never migrate between pools — membership churn only adds
        names)."""
        if len(self.stacks) == 1:
            return self.stacks[self._default_pool]
        stack = self._pod_stack_cache.get(pod_name)
        if stack is not None:
            return stack
        for stack in self.stacks.values():
            if pod_name in stack.pod_names():
                self._pod_stack_cache[pod_name] = stack
                return stack
        return self.stacks[self._default_pool]

    def _record_upstream(self, pod_name: str, ok: bool,
                         timeout: bool = False) -> None:
        """Route an upstream outcome to the owning pool's resilience plane
        (health scorer + circuit breaker)."""
        self._stack_for_pod(pod_name).resilience.record_upstream(
            pod_name, ok, timeout=timeout)

    def _record_handoff(self, pod_name: str, ok: bool) -> None:
        self._stack_for_pod(pod_name).resilience.record_handoff(
            pod_name, ok)

    # -- request path ------------------------------------------------------
    def _error_response(self, status: int, message: str, kind: str,
                        trace_id: str,
                        headers: dict | None = None) -> web.Response:
        """Error envelope with the trace id in BOTH the body and the header
        — failed requests are the ones most worth correlating.  429s get a
        ``Retry-After`` hint (graceful-degradation contract: shed clients
        back off instead of hammering a saturated pool)."""
        all_headers = {tracing.TRACE_HEADER: trace_id, **(headers or {})}
        if status == 429 and "Retry-After" not in all_headers:
            all_headers["Retry-After"] = str(
                max(1, int(self.fairness.cfg.retry_after_s)))
        return web.json_response(
            {"error": {"message": message, "type": kind,
                       "trace_id": trace_id}},
            status=status,
            headers=all_headers,
        )

    @staticmethod
    def _body_ttft_s(resp_body: bytes) -> float | None:
        """Server-reported first-token latency from a completions envelope
        (``ttft_ms``), as seconds — None when the envelope doesn't carry it
        (chat)."""
        try:
            v = json.loads(resp_body).get("ttft_ms")
            return float(v) / 1e3 if v is not None else None
        except (json.JSONDecodeError, ValueError, AttributeError, TypeError):
            return None

    def _finish_phase(self, req_ctx, trace_id: str, path: str, t_req: float,
                      t_first: float | None, t_last: float,
                      status: str = "ok") -> None:
        """Observe a finished request into the gateway TTFT/TPOT/e2e
        histograms and stamp the trace's summary fields.

        ``t_first`` is the wall clock at which the FIRST generated token
        existed (stream: first data chunk; JSON: server-reported ttft or
        prefill-hop completion); TPOT spreads the remaining wall over the
        remaining tokens.  ``status`` rides the trace summary (e.g.
        ``client_disconnect`` for a partially-delivered stream — the
        observation still lands in the histograms, so e2e percentiles see
        the aborted request).
        """
        model = req_ctx.model or "?"
        completion = req_ctx.usage.completion_tokens
        ttft_s = (t_first - t_req) if t_first else None
        tpot_s = None
        if t_first and completion > 1:
            tpot_s = max(0.0, t_last - t_first) / (completion - 1)
        self.metrics.record_phase(model, path, ttft_s, tpot_s,
                                  e2e_s=t_last - t_req)
        self.tracer.annotate(trace_id, model=model, path=path, status=status)

    async def handle_completion(self, request: web.Request) -> web.Response:
        body = await request.read()
        req_ctx = RequestContext()
        # Request-scoped tracing: honor an inbound id or mint one; it rides
        # to the replica and back so one id follows the request across the
        # gateway, the scheduler decision, and the model server (SURVEY.md
        # §5: the reference's only decision-path observability was verbose
        # logs; this is the structured equivalent).
        request_id = request.headers.get("x-request-id") or uuid.uuid4().hex[:16]
        trace_id = (request.headers.get(tracing.TRACE_HEADER)
                    or tracing.new_trace_id())
        req_ctx.trace_id = trace_id
        t_req = time.time()
        loop = asyncio.get_running_loop()
        rcfg = self.resilience.cfg
        # Hedging is for non-streaming requests only (two live SSE relays
        # for one client are unmergeable); the flag lives in the body, so
        # parse it only when hedging is enabled at all.
        hedge_ok = False
        if rcfg.hedge_ttft_s > 0:
            try:
                hedge_ok = not json.loads(body).get("stream", False)
            except (json.JSONDecodeError, AttributeError, UnicodeDecodeError):
                hedge_ok = False

        # Phase 1: headers through the same core the gRPC transport uses.
        self.server.process(req_ctx, RequestHeaders(headers=dict(request.headers)))

        # Phase 2 + forward, as a bounded retry loop: each attempt re-runs
        # admission + pick (so a failure recorded on the previous attempt
        # steers the re-pick under health_policy=avoid) and one upstream
        # forward.  Only failures where NO byte has reached the client are
        # retried, every retry spends the global retry budget, and backoff
        # is decorrelated jitter — retries cannot amplify an outage.
        attempt = 0
        backoff_s = 0.0
        while True:
            # Scheduling is CPU-only (no I/O) but can walk a large pool;
            # run in executor to keep the event loop responsive.
            try:
                with Timer() as t:
                    result = await loop.run_in_executor(
                        None, self.server.process, req_ctx,
                        RequestBody(body=body)
                    )
            except ProcessingError as e:
                self.metrics.record_error(req_ctx.model or None,
                                          pre_admission=True)
                self.journal.emit(events_mod.ADMISSION_REJECT, trace_id,
                                  model=req_ctx.model or "", status=e.status,
                                  error=str(e)[:200])
                self.tracer.record(trace_id, "gateway.admission", t_req,
                                   time.time(), error=str(e))
                self.tracer.annotate(trace_id, model=req_ctx.model or "",
                                     status="error")
                kind = ("invalid_request_error" if e.status == 400
                        else "api_error")
                return self._error_response(e.status, str(e), kind, trace_id)
            if attempt == 0:
                self.metrics.record_request(req_ctx.model or "?")
                self.resilience.retry_budget.note_request()
            if result.immediate_status is not None:
                self.metrics.record_shed(req_ctx.model or None)
                self.journal.emit(events_mod.SHED, trace_id,
                                  model=req_ctx.model or "",
                                  status=result.immediate_status)
                self.tracer.record(trace_id, "gateway.admission", t_req,
                                   time.time(), shed=True)
                self.tracer.annotate(trace_id, model=req_ctx.model or "",
                                     status="shed")
                return self._error_response(
                    result.immediate_status,
                    "dropping request due to limited backend resources",
                    "rate_limit_exceeded", trace_id)

            pod = req_ctx.target_pod
            affinity_hit = False
            pm = (self.provider.get_pod_metrics(pod.name)
                  if hasattr(self.provider, "get_pod_metrics") else None)
            if pm is not None:
                affinity_hit = (req_ctx.resolved_target_model
                                in pm.metrics.active_adapters)
            self.metrics.record_pick(pod.name, t.seconds, affinity_hit)
            # One span covers admission + scheduler pick (the pick's own
            # cost rides as an attribute — it is also a full histogram
            # family).  Queue-wait and per-hop pick splits attribute a slow
            # admission to admission-queue parking vs prefill-hop vs
            # decode-hop pick cost.
            attribution = {}
            if req_ctx.admission_wait_s:
                attribution["queue_wait_s"] = round(req_ctx.admission_wait_s, 6)
            if req_ctx.pick_hops_s is not None:
                attribution["pick_prefill_s"] = round(req_ctx.pick_hops_s[0], 6)
                attribution["pick_decode_s"] = round(req_ctx.pick_hops_s[1], 6)
            if attempt:
                attribution["attempt"] = attempt
            self.tracer.record(trace_id, "gateway.admission", t_req,
                               time.time(), pod=pod.name,
                               pick_s=round(t.seconds, 6), **attribution)

            # Forward to the picked replica (Envoy's ORIGINAL_DST role).
            out_body = result.body if result.body is not None else body
            decode_pod = getattr(req_ctx, "decode_pod", None)
            self.journal.emit(
                events_mod.PICK, trace_id, model=req_ctx.model or "",
                pod=pod.name,
                **({"decode_pod": decode_pod.name} if decode_pod else {}),
                **({"attempt": attempt} if attempt else {}))
            if decode_pod is not None:
                # Disaggregated pick: relay prefill -> handoff -> decode.
                resp = await self._disagg_forward(
                    request, pod, decode_pod, out_body, request_id, req_ctx,
                    trace_id, t_req)
                if resp is not None:
                    return resp
                # Either hop refused (draining, long prompt, unsupported
                # params): serve single-hop on the prefill replica — every
                # engine is complete regardless of role.
                self.journal.emit(events_mod.DISAGG_FALLBACK, trace_id,
                                  model=req_ctx.model or "",
                                  prefill_pod=pod.name,
                                  decode_pod=decode_pod.name)
                logger.info("request=%s disaggregated path unavailable; "
                            "single-hop on %s", request_id, pod.name)

            resp, failure = await self._forward_collocated(
                request, pod, body, out_body, request_id, req_ctx, trace_id,
                t_req, hedge_ok=hedge_ok and decode_pod is None)
            if resp is not None:
                return resp

            # Retry-eligible failure: nothing reached the client yet.
            if (attempt >= rcfg.max_retries
                    or not self.resilience.retry_budget.try_spend()):
                self.metrics.record_error(req_ctx.model or None)
                self.tracer.annotate(trace_id, status="upstream_error")
                status = 504 if "timeout" in failure else 502
                return self._error_response(
                    status,
                    f"upstream {failure} after {attempt + 1} attempt(s)",
                    "api_error", trace_id)
            attempt += 1
            self.metrics.record_retry(failure)
            self.journal.emit(events_mod.RETRY, trace_id, pod=pod.name,
                              reason=failure, attempt=attempt)
            backoff_s = retry_backoff(
                self.resilience.rng, backoff_s or rcfg.backoff_base_s,
                rcfg.backoff_base_s, rcfg.backoff_cap_s)
            await asyncio.sleep(backoff_s)

    @staticmethod
    async def _bounded(awaitable, timeout_s: float):
        """Await with an optional bound (0 disables) — every upstream
        await on the data path goes through a per-phase limit; an
        unbounded hop would resurrect the hung-request failure mode the
        per-phase timeouts exist to kill."""
        if timeout_s and timeout_s > 0:
            return await asyncio.wait_for(awaitable, timeout_s)
        return await awaitable

    async def _post_upstream(self, path: str, pod, out_body: bytes,
                             request_id: str, trace_id: str):
        """POST to one replica, bounded by the TTFT timeout: the await
        resolves when response HEADERS are up (SSE: immediately; JSON: when
        generation finished server-side).  Raises asyncio.TimeoutError /
        aiohttp.ClientError for the caller to classify."""
        ttft = self.resilience.cfg.ttft_timeout_s
        headers = dict(self._upstream_headers_tpl)
        headers["x-request-id"] = request_id
        headers[tracing.TRACE_HEADER] = trace_id
        headers[self.server.target_pod_header] = pod.address
        coro = self._session.post(
            f"http://{pod.address}{path}",
            data=out_body,
            headers=headers,
            trace_request_ctx={"pod": pod.name},
        )
        return await (asyncio.wait_for(coro, ttft) if ttft > 0 else coro)

    def _repick_pod(self, body: bytes, exclude: str,
                    demoted_to: str | None = None):
        """Scheduler re-pick for a hedge, on a throwaway context (runs in
        the executor).  None when admission fails or the pick lands on the
        pod already being hedged against."""
        ctx = RequestContext()
        # A hedge probe must not spend the tenant's quota bucket again —
        # the primary attempt already charged this client request — and
        # must keep the primary's demotion: hedges fire under exactly the
        # saturation quotas target, so an undemoted probe would restore
        # the priority the quota removed.
        ctx.fairness_charged = True
        ctx.fairness_demoted_to = demoted_to
        try:
            result = self.server.process(ctx, RequestBody(body=body))
        except ProcessingError:
            return None
        if result.immediate_status is not None or ctx.target_pod is None:
            return None
        return None if ctx.target_pod.name == exclude else ctx.target_pod

    async def _post_with_hedge(self, request, pod, raw_body: bytes,
                               out_body: bytes, request_id: str,
                               trace_id: str,
                               demoted_to: str | None = None):
        """TTFT-based hedge: when the primary hasn't produced response
        headers within ``hedge_ttft_s``, re-pick a different replica and
        race a second identical request; first success wins, the loser is
        cancelled.  Returns (upstream, winning_pod, outcome)."""
        primary = asyncio.ensure_future(
            self._post_upstream(request.path, pod, out_body, request_id,
                                trace_id))
        done, _ = await asyncio.wait(
            {primary}, timeout=self.resilience.cfg.hedge_ttft_s)
        if done:
            return primary.result(), pod, None  # may raise; caller classifies
        loop = asyncio.get_running_loop()
        hedge_pod = await loop.run_in_executor(
            None, self._repick_pod, raw_body, pod.name, demoted_to)
        if hedge_pod is None:
            self.metrics.record_hedge("no_candidate")
            return (await primary), pod, None
        self.metrics.record_hedge("fired")
        self.journal.emit(events_mod.HEDGE, trace_id, pod=pod.name,
                          hedge_pod=hedge_pod.name)
        hedge = asyncio.ensure_future(
            self._post_upstream(request.path, hedge_pod, out_body,
                                request_id, trace_id))
        owner = {primary: pod, hedge: hedge_pod}
        pending = set(owner)
        while pending:
            done, pending = await asyncio.wait(
                pending, return_when=asyncio.FIRST_COMPLETED)
            winners = [tk for tk in done
                       if not tk.cancelled() and tk.exception() is None]
            if not winners:
                continue  # this round only produced failures; wait the rest
            winner = primary if primary in winners else winners[0]
            for tk in set(owner) - {winner}:
                if tk.done() and not tk.cancelled():
                    if tk.exception() is None:
                        # The loser also answered: its success still counts
                        # (clears streaks / half-open probe accounting).
                        self._record_upstream(owner[tk].name,
                                                        ok=True)
                        tk.result().close()
                    else:
                        # The loser's failure still reaches the breaker.
                        self._record_upstream(
                            owner[tk].name, ok=False,
                            timeout=isinstance(tk.exception(),
                                               asyncio.TimeoutError))
                else:
                    tk.cancel()
            outcome = "won" if winner is hedge else "lost"
            self.metrics.record_hedge(outcome)
            return winner.result(), owner[winner], outcome
        # Both attempts failed: surface the primary's error (the caller's
        # pod attribution matches), after recording the hedge-side failure.
        self.metrics.record_hedge("failed")
        self._record_upstream(
            hedge_pod.name, ok=False,
            timeout=isinstance(hedge.exception(), asyncio.TimeoutError))
        raise primary.exception()

    async def _forward_collocated(self, request, pod, raw_body: bytes,
                                  out_body: bytes, request_id: str, req_ctx,
                                  trace_id: str, t_req: float,
                                  hedge_ok: bool = False):
        """One single-hop forward attempt.

        Returns ``(response, None)`` when a client-ready response exists
        (success, streamed, or a passthrough non-503 upstream status), or
        ``(None, reason)`` for a retry-eligible failure — exactly the set
        where no byte has reached the client: connect errors, TTFT
        timeouts, 503s, and failed non-streaming body reads.
        """
        rcfg = self.resilience.cfg
        t_up0 = time.time()
        hedge_outcome = None

        def _failed(reason: str, err, timeout: bool = False):
            self._record_upstream(pod.name, ok=False,
                                            timeout=timeout)
            self.journal.emit(events_mod.UPSTREAM_ERROR, trace_id,
                              pod=pod.name, reason=reason,
                              error=str(err)[:200])
            self.tracer.record(trace_id, "gateway.upstream", t_up0,
                               time.time(), pod=pod.name, error=str(err))
            logger.warning("upstream %s failed (%s): %s",
                           pod.address, reason, err)
            return None, reason

        try:
            if hedge_ok:
                upstream, pod, hedge_outcome = await self._post_with_hedge(
                    request, pod, raw_body, out_body, request_id, trace_id,
                    demoted_to=req_ctx.fairness_demoted_to)
            else:
                upstream = await self._post_upstream(
                    request.path, pod, out_body, request_id, trace_id)
        except asyncio.TimeoutError as e:
            return _failed("ttft_timeout", str(e) or "ttft timeout",
                           timeout=True)
        except (aiohttp.ClientError, ConnectionResetError, OSError) as e:
            return _failed("connect", e)
        status = upstream.status
        try:
            if status == 503:
                # Draining / queue-full replica: the canonical idempotent
                # retry case (no generation happened).
                upstream.release()
                return _failed("upstream_503", "upstream answered 503")
            if "text/event-stream" in upstream.headers.get("Content-Type", ""):
                # Streamed generation: relay SSE chunks as they arrive —
                # buffering would defeat streaming, and usage accounting
                # happens from the stream's final chunk if present.  A
                # stream that dies BEFORE its first chunk comes back as a
                # retry-eligible failure (already recorded by the relay).
                return await self._relay_stream(
                    request, upstream, pod, req_ctx,
                    trace=(trace_id, t_req, "collocated", t_up0))
            idle = rcfg.stream_idle_timeout_s
            resp_body = await (asyncio.wait_for(upstream.read(), idle)
                               if idle > 0 else upstream.read())
            self.tracer.record_wire(
                trace_id, upstream.headers.get(tracing.SPANS_HEADER))
        except asyncio.TimeoutError as e:
            upstream.close()
            return _failed("read_timeout", str(e) or "body read timeout",
                           timeout=True)
        except (aiohttp.ClientError, ConnectionResetError, OSError) as e:
            upstream.close()
            return _failed("read", e)
        t_up1 = time.time()
        # 5xx from the replica counts against its health (the server
        # answered, but wrongly); 2xx-4xx reset the error streak.
        self._record_upstream(pod.name, ok=status < 500)
        self.tracer.record(trace_id, "gateway.upstream", t_up0, t_up1,
                           pod=pod.name, status=status,
                           **({"hedge": hedge_outcome} if hedge_outcome
                              else {}))

        # Phases 3+4: response headers + usage accounting.
        hdr_result = self.server.process(req_ctx, ResponseHeaders())
        try:
            self.server.process(req_ctx, ResponseBody(body=resp_body))
            self.metrics.record_usage(
                req_ctx.model,
                req_ctx.usage.prompt_tokens,
                req_ctx.usage.completion_tokens,
            )
        except ProcessingError:
            pass  # non-JSON upstream bodies skip accounting

        server_ttft = self._body_ttft_s(resp_body)
        self._finish_phase(
            req_ctx, trace_id, "collocated", t_req,
            t_first=(t_up0 + server_ttft) if server_ttft is not None else None,
            t_last=t_up1)
        logger.info(
            "request=%s trace=%s model=%s target=%s pod=%s status=%d "
            "prompt_tokens=%d completion_tokens=%d total_ms=%.1f",
            request_id, trace_id, req_ctx.model, req_ctx.resolved_target_model,
            pod.name, status, req_ctx.usage.prompt_tokens,
            req_ctx.usage.completion_tokens, (time.time() - t_req) * 1e3,
        )
        headers = {"x-served-by": pod.name, "x-request-id": request_id,
                   tracing.TRACE_HEADER: trace_id, **hdr_result.set_headers}
        return web.Response(body=resp_body, status=status, headers=headers,
                            content_type="application/json"), None

    async def _disagg_forward(self, request: web.Request, prefill_pod,
                              decode_pod, out_body: bytes, request_id: str,
                              req_ctx, trace_id: str,
                              t_req: float) -> web.StreamResponse | None:
        """Two-hop data path for a disaggregated pick.

        Hop 1 posts the (possibly rewritten) body to the prefill replica's
        ``/v1/prefill`` and receives the serialized ``PrefillHandoff``;
        hop 2 posts it to the decode replica's ``/v1/attach``, which decodes
        to completion and answers in the normal OpenAI envelope (SSE
        included).  Returns None to signal single-hop fallback — any 4xx/5xx
        from either hop (draining replica, prompt beyond the prefill bucket,
        params the handoff path doesn't carry) degrades gracefully rather
        than failing the request.

        Tracing: both hops get their own gateway-side spans, and each hop's
        ``x-lig-spans`` response header (engine queue/prefill, handoff
        serialize/deserialize/attach, decode) merges into the SAME trace —
        the proxy's /debug/traces shows the full three-process timeline.
        """
        t_pre0 = time.time()
        hop_pod = prefill_pod  # which hop an exception below attributes to
        engine_req_id = None  # the prefill engine's id, for abandon-release
        rcfg = self.resilience.cfg
        resp_obj = None  # in-flight hop response, closed on failure
        try:
            # Both hops ride the per-phase bounds: response headers within
            # the TTFT budget, body within the idle budget — a blackholed
            # replica must degrade this request to single-hop in bounded
            # time, not hang it (the single total timeout is gone).
            pre = resp_obj = await self._bounded(
                self._session.post(
                    f"http://{prefill_pod.address}/v1/prefill",
                    data=out_body,
                    headers={"Content-Type": "application/json",
                             "x-request-id": request_id,
                             tracing.TRACE_HEADER: trace_id},
                    trace_request_ctx={"pod": prefill_pod.name},
                ), rcfg.ttft_timeout_s)
            if pre.status != 200:
                logger.warning(
                    "prefill hop %s returned %d; falling back",
                    prefill_pod.address, pre.status)
                pre.release()
                self._record_handoff(prefill_pod.name, ok=False)
                self.tracer.record(
                    trace_id, "gateway.prefill_hop", t_pre0, time.time(),
                    pod=prefill_pod.name, status=pre.status,
                    fallback=True)
                return None
            handoff = await self._bounded(pre.read(),
                                          rcfg.stream_idle_timeout_s)
            engine_req_id = pre.headers.get("x-request-id")
            self.tracer.record_wire(
                trace_id, pre.headers.get(tracing.SPANS_HEADER))
            t_pre1 = time.time()
            self.tracer.record(trace_id, "gateway.prefill_hop", t_pre0,
                               t_pre1, pod=prefill_pod.name,
                               wire_bytes=len(handoff))
            t_att0 = time.time()
            hop_pod = decode_pod
            upstream = resp_obj = await self._bounded(
                self._session.post(
                    f"http://{decode_pod.address}/v1/attach",
                    data=handoff,
                    headers={"Content-Type": "application/octet-stream",
                             "x-request-id": request_id,
                             tracing.TRACE_HEADER: trace_id},
                    trace_request_ctx={"pod": decode_pod.name},
                ), rcfg.ttft_timeout_s)
            status = upstream.status
            if status != 200:
                logger.warning(
                    "attach hop %s returned %d; falling back",
                    decode_pod.address, status)
                upstream.release()
                self._record_handoff(decode_pod.name, ok=False)
                self.tracer.record(
                    trace_id, "gateway.attach_hop", t_att0, time.time(),
                    pod=decode_pod.name, status=status, fallback=True)
                return None
            if "text/event-stream" in upstream.headers.get(
                    "Content-Type", ""):
                resp, fail = await self._relay_stream(
                    request, upstream, decode_pod, req_ctx,
                    trace=(trace_id, t_req, "disaggregated", t_att0),
                    served_by=f"{prefill_pod.name}+{decode_pod.name}")
                if resp is not None:
                    return resp
                # The attach stream died before its first chunk: the
                # decode engine holds abandoned work — release it and
                # fall back single-hop (nothing reached the client).
                self._record_handoff(decode_pod.name, ok=False)
                if engine_req_id:
                    self._spawn_release(decode_pod, engine_req_id, trace_id)
                self.tracer.record(
                    trace_id, "gateway.attach_hop", t_att0, time.time(),
                    pod=decode_pod.name, fallback=True, error=fail)
                return None
            resp_body = await self._bounded(upstream.read(),
                                            rcfg.stream_idle_timeout_s)
            self.tracer.record_wire(
                trace_id, upstream.headers.get(tracing.SPANS_HEADER))
        except (aiohttp.ClientError, asyncio.TimeoutError) as e:
            if resp_obj is not None:
                resp_obj.close()
            # No record_error here: the caller serves the request single-hop
            # next, and THAT path records the request's actual outcome — a
            # recovered hop must not inflate the error rate (non-200 hop
            # statuses above are treated identically).  The health scorer
            # and breaker DO see it: hop failures are a per-replica
            # degradation signal regardless of the request's final outcome.
            self._record_handoff(hop_pod.name, ok=False)
            if hop_pod is decode_pod and engine_req_id:
                # The decode hop died AFTER the handoff bytes were posted:
                # the decode engine may have parked (or be decoding) KV
                # nobody will ever read — the caller reroutes single-hop
                # next.  Best-effort release of the abandoned work; the
                # engine-side TTL sweep (--handoff-ttl-s) is the backstop
                # when this message is lost too.
                self._spawn_release(decode_pod, engine_req_id, trace_id)
            logger.warning("disaggregated path %s->%s failed: %s",
                           prefill_pod.address, decode_pod.address, e)
            return None
        t_att1 = time.time()
        self._record_handoff(prefill_pod.name, ok=True)
        self._record_handoff(decode_pod.name, ok=True)
        self.tracer.record(trace_id, "gateway.attach_hop", t_att0, t_att1,
                           pod=decode_pod.name, status=status)
        hdr_result = self.server.process(req_ctx, ResponseHeaders())
        try:
            self.server.process(req_ctx, ResponseBody(body=resp_body))
            self.metrics.record_usage(
                req_ctx.model,
                req_ctx.usage.prompt_tokens,
                req_ctx.usage.completion_tokens,
            )
        except ProcessingError:
            pass
        # TTFT on the two-hop path: the first token exists the moment the
        # prefill hop returns (it rides the handoff's sampling carry).
        self._finish_phase(req_ctx, trace_id, "disaggregated", t_req,
                           t_first=t_pre1, t_last=t_att1)
        logger.info(
            "request=%s trace=%s model=%s disaggregated prefill=%s decode=%s "
            "status=%d prompt_tokens=%d completion_tokens=%d",
            request_id, trace_id, req_ctx.model, prefill_pod.name,
            decode_pod.name, status, req_ctx.usage.prompt_tokens,
            req_ctx.usage.completion_tokens,
        )
        headers = {
            "x-served-by": f"{prefill_pod.name}+{decode_pod.name}",
            "x-request-id": request_id,
            tracing.TRACE_HEADER: trace_id,
            **hdr_result.set_headers,
        }
        return web.Response(body=resp_body, status=status, headers=headers,
                            content_type="application/json")

    def _spawn_release(self, pod, engine_req_id: str,
                       trace_id: str) -> None:
        """Fire-and-forget ``POST /v1/prefill/release`` at ``pod``: cancel
        work abandoned by a failed hop (queued / parked / decoding KV whose
        response path is gone).  Journaled either way — the release is
        best-effort, the flight recorder is the audit trail."""

        async def release() -> None:
            ok = False
            try:
                # Bounded: the pod being released is the one that just
                # failed — an unbounded POST at it would pin this task for
                # the life of the process.
                async with await asyncio.wait_for(
                    self._session.post(
                        f"http://{pod.address}/v1/prefill/release",
                        json={"request_id": engine_req_id},
                        headers={tracing.TRACE_HEADER: trace_id},
                        trace_request_ctx={"pod": pod.name},
                    ), timeout=5.0,
                ) as r:
                    ok = (r.status == 200
                          and bool((await r.json()).get("released")))
            except Exception:  # best-effort: a failed release must never
                pass           # surface as an unhandled task exception
            self.journal.emit(events_mod.KV_RELEASE, trace_id, pod=pod.name,
                              request_id=engine_req_id, released=ok)

        # The loop holds only a weak ref to tasks: keep a strong one until
        # completion or the release can be garbage-collected mid-flight.
        task = asyncio.get_running_loop().create_task(release())
        self._release_tasks.add(task)
        task.add_done_callback(self._release_tasks.discard)

    def _client_disconnected(self, req_ctx, pod, trace_id, t_req, path,
                             t_up0, t_first) -> None:
        """Mid-stream client disconnect accounting: journal the event,
        count it, and observe the PARTIAL request into the e2e histograms
        with the trace summary stamped ``client_disconnect`` — previously
        these requests vanished from every aggregate."""
        now = time.time()
        self.metrics.record_client_disconnect(req_ctx.model or None)
        self.journal.emit(events_mod.CLIENT_DISCONNECT, trace_id or "",
                          pod=pod.name, model=req_ctx.model or "")
        logger.info("client disconnected mid-stream (pod=%s)", pod.name)
        if trace_id:
            self.tracer.record(trace_id, "gateway.stream", t_up0, now,
                               pod=pod.name, client_disconnect=True)
            self._finish_phase(req_ctx, trace_id, path, t_req,
                               t_first=t_first, t_last=now,
                               status="client_disconnect")

    async def _relay_stream(self, request: web.Request, upstream, pod,
                            req_ctx, trace=None,
                            served_by: str | None = None):
        """Relay an SSE stream.  Returns ``(response, None)`` once any byte
        has been committed to the client, or ``(None, reason)`` when the
        stream died BEFORE its first chunk — that failure is still
        retry-eligible, so the 200 headers must not be sent yet (a
        committed stream that later breaks is terminated with the error
        event + [DONE] instead; bubbling up would make the handler try to
        send a second response).

        Two relay modes, byte-parity pinned by tests/test_fast_relay.py:

        - **fast** (default, ``self.fast_relay``): zero-copy — every
          upstream chunk is written to the client verbatim with NO
          per-chunk decode/split/re-encode; the only per-chunk work is
          appending a chunk *reference* to a bounded tail deque.  The
          final usage chunk and ``[DONE]`` exclusion are parsed ONCE at
          stream end from the raw tail bytes (``final_data_line``).
        - **slow** (the pre-existing path, kept as the parity oracle):
          SSE lines are re-framed through a byte buffer per chunk so a
          data line split across transport chunks still parses.

        Per-phase timeouts: the FIRST chunk is bounded by ``ttft_timeout_s``
        and every later inter-chunk gap by ``stream_idle_timeout_s`` — a
        braking replica fails or terminates in bounded time instead of
        hanging the client for the old 3600 s total.

        A ``ConnectionResetError`` (or handler-task cancellation) from the
        client side is journaled as ``client_disconnect``, counted, and
        the partial request still lands in the e2e histograms.

        ``trace`` = (trace_id, t_req, path, t_up0): streaming is where real
        client-observed TTFT/TPOT live — the first relayed data chunk stamps
        TTFT, the final chunk closes the stream span and TPOT spreads over
        the final usage count.
        """
        trace_id, t_req, path, t_up0 = trace or (None, 0.0, "collocated", 0.0)
        rcfg = self.resilience.cfg
        chunks = upstream.content.iter_any()
        # First chunk BEFORE prepare(): until a byte is relayed, a dead
        # stream is an idempotent failure the caller may retry/reroute —
        # committing 200 headers here would forfeit that.
        pending = None
        try:
            pending = await self._bounded(chunks.__anext__(),
                                          rcfg.ttft_timeout_s)
        except StopAsyncIteration:
            pending = None  # legitimate empty stream: relay it as-is
        except asyncio.TimeoutError:
            upstream.close()
            self._record_upstream(pod.name, ok=False, timeout=True)
            self.journal.emit(events_mod.UPSTREAM_ERROR, trace_id or "",
                              pod=pod.name, stream=True,
                              error="no first chunk within TTFT budget")
            if trace_id:
                self.tracer.record(trace_id, "gateway.stream", t_up0,
                                   time.time(), pod=pod.name,
                                   error="ttft timeout")
            logger.warning("stream from %s produced no first chunk in time",
                           pod.address)
            return None, "ttft_timeout"
        except (aiohttp.ClientError, ConnectionResetError, OSError) as e:
            upstream.close()
            self._record_upstream(pod.name, ok=False)
            self.journal.emit(events_mod.UPSTREAM_ERROR, trace_id or "",
                              pod=pod.name, stream=True,
                              error=str(e)[:200] or "stream broke pre-first-"
                                                    "chunk")
            if trace_id:
                self.tracer.record(trace_id, "gateway.stream", t_up0,
                                   time.time(), pod=pod.name, error=str(e))
            logger.warning("stream from %s broke before first chunk: %s",
                           pod.address, e)
            return None, "read"
        headers = dict(self._sse_headers_tpl)
        headers["x-served-by"] = served_by or pod.name
        if trace_id:
            headers[tracing.TRACE_HEADER] = trace_id
        resp = web.StreamResponse(status=upstream.status, headers=headers)
        await resp.prepare(request)
        fast = self.fast_relay
        last_data_line = b""
        buf = b""
        # Fast relay: chunk REFERENCES only — the deque keeps enough tail
        # bytes for the end-of-stream usage parse, trimmed by whole chunks.
        tail: list[bytes] = []
        tail_len = 0
        t_first = None
        try:
            while pending is not None:
                chunk = pending
                if t_first is None:
                    t_first = time.time()
                if fast:
                    tail.append(chunk)
                    tail_len += len(chunk)
                    while (len(tail) > 1
                           and tail_len - len(tail[0]) >= RELAY_TAIL_BYTES):
                        tail_len -= len(tail.pop(0))
                else:
                    buf += chunk
                    *lines, buf = buf.split(b"\n")
                    for line in lines:
                        if (line.startswith(b"data: ")
                                and line != b"data: [DONE]"):
                            last_data_line = line
                try:
                    await resp.write(chunk)
                except (ConnectionResetError, ConnectionError):
                    # The UPSTREAM was serving fine — its streaks/probe
                    # accounting must not dangle on the client's exit.
                    self._record_upstream(pod.name, ok=True)
                    upstream.close()
                    self._client_disconnected(req_ctx, pod, trace_id, t_req,
                                              path, t_up0, t_first)
                    return resp, None
                try:
                    pending = await self._bounded(
                        chunks.__anext__(), rcfg.stream_idle_timeout_s)
                except StopAsyncIteration:
                    pending = None
        except asyncio.CancelledError:
            # aiohttp cancels the handler task when the CLIENT's connection
            # drops mid-stream — account for the partial request, then let
            # the cancellation propagate (swallowing it would break the
            # server's teardown contract).
            self._record_upstream(pod.name, ok=True)
            upstream.close()
            self._client_disconnected(req_ctx, pod, trace_id, t_req,
                                      path, t_up0, t_first)
            raise
        except (aiohttp.ClientError, asyncio.TimeoutError) as e:
            timed_out = isinstance(e, asyncio.TimeoutError)
            if timed_out:
                upstream.close()  # the hung read owns the connection
            self.metrics.record_error(req_ctx.model or None)
            self._record_upstream(pod.name, ok=False,
                                            timeout=timed_out)
            self.journal.emit(events_mod.UPSTREAM_ERROR, trace_id or "",
                              pod=pod.name, stream=True,
                              error=str(e)[:200] or "stream idle timeout")
            if trace_id:
                self.tracer.record(trace_id, "gateway.stream", t_up0,
                                   time.time(), pod=pod.name, error=str(e))
                self.tracer.annotate(trace_id, status="stream_error")
            logger.warning("upstream stream from %s broke: %s", pod.address, e)
            try:
                await resp.write(
                    b'data: {"error": {"message": "upstream stream interrupted"}}\n\n'
                    b"data: [DONE]\n\n"
                )
            except (ConnectionResetError, ConnectionError):
                # The client is ALSO gone: account for it instead of
                # silently dropping the request from every aggregate.
                self._client_disconnected(req_ctx, pod, trace_id, t_req,
                                          path, t_up0, t_first)
            except asyncio.CancelledError:
                self._client_disconnected(req_ctx, pod, trace_id, t_req,
                                          path, t_up0, t_first)
                raise
            return resp, None
        t_end = time.time()
        self._record_upstream(pod.name, ok=True)
        if fast:
            last_data_line = final_data_line(b"".join(tail))
        try:
            final = json.loads(last_data_line[len(b"data: "):])
            usage = final.get("usage") or {}
            self.metrics.record_usage(
                req_ctx.model,
                int(usage.get("prompt_tokens", 0) or 0),
                int(usage.get("completion_tokens", 0) or 0),
            )
            req_ctx.usage.prompt_tokens = int(usage.get("prompt_tokens", 0) or 0)
            req_ctx.usage.completion_tokens = int(
                usage.get("completion_tokens", 0) or 0)
        except (json.JSONDecodeError, ValueError):
            pass
        if trace_id:
            self.tracer.record(trace_id, "gateway.stream", t_up0, t_end,
                               pod=pod.name)
            self._finish_phase(req_ctx, trace_id, path, t_req,
                               t_first=t_first, t_last=t_end)
        return resp, None

    # -- ops endpoints -----------------------------------------------------
    def _render_metrics(self) -> str:
        """The full gateway exposition page: request-path counters and
        histograms (GatewayMetrics) plus the observability control plane's
        families — SLO gauges, per-pool advisor stacks (health, circuits,
        usage, fairness, placement — merged so shared families keep one
        ``# TYPE`` line and per-stack scalar counters sum), the statebus,
        and the event counters."""
        text = self.metrics.render()
        if len(self.stacks) == 1:
            stack_lines = self.stacks[self._default_pool].render()
        else:
            stack_lines = merge_exposition_blocks(
                [stack.render() for stack in self.stacks.values()])
        extra = (self.slo.render() + stack_lines
                 + self.statebus.render()
                 + self.fleet.render()
                 + self.journal.render_prom("gateway_events_total"))
        if extra:
            text += "\n".join(extra) + "\n"
        return text

    async def handle_metrics(self, request: web.Request) -> web.Response:
        return web.Response(text=self._render_metrics(),
                            content_type="text/plain")

    async def handle_debug_traces(self, request: web.Request) -> web.Response:
        """Recent request traces as JSON (``?trace_id=`` exact filter,
        ``?limit=`` count cap) — the merged cross-process timeline."""
        return web.json_response(
            tracing.debug_traces_payload(self.tracer, request.query))

    async def handle_debug_slo(self, request: web.Request) -> web.Response:
        """Per-model SLO compliance, windowed burn rates, and burn state.
        Evaluates on demand (floored at the configured cadence — ring
        growth AND the tick-denominated hysteresis must track
        LIG_SLO_TICK_S, not an aggressive poller) so a curl sees the
        current state even when the background task is disabled."""
        self.slo.maybe_tick(max(1.0, self.obs_tick_s))
        return web.json_response(self.slo.debug_payload())

    async def handle_debug_health(self, request: web.Request) -> web.Response:
        """Per-replica health scores, components, states, would-avoid
        counters, plus the resilience plane (policy, per-pod circuit
        states, retry budget).  Floored at the configured cadence: the
        dwell-tick hysteresis counts update PASSES, so a fast poller must
        not drive transitions.  Multi-pool fronts add a ``pools`` section
        (one health+resilience payload per pool) next to the default
        pool's top-level fields."""
        for stack in self.stacks.values():
            stack.health.maybe_update(max(1.0, self.obs_tick_s))
        payload = self.health.debug_payload()
        payload["resilience"] = self.resilience.debug_payload()
        if len(self.stacks) > 1:
            payload["pools"] = {
                name: dict(stack.health.debug_payload(),
                           resilience=stack.resilience.debug_payload())
                for name, stack in self.stacks.items()}
        return web.json_response(payload)

    async def handle_debug_usage(self, request: web.Request) -> web.Response:
        """Pool-wide capacity attribution: per-{model, adapter} consumption
        shares, admitted-traffic shares, noisy-neighbor scores/flags, and
        pool-waste aggregates (gateway/usage.py; rendered live by
        ``tools/lig_top.py``) — plus the fairness plane's throttle and
        demotion state (gateway/fairness.py).  Floored at the configured
        cadence — the enter/exit hysteresis counts rollup passes.
        Multi-pool fronts add a ``pools`` section (one usage+fairness+
        residency payload per pool) next to the default pool's top-level
        fields."""
        for stack in self.stacks.values():
            stack.usage.maybe_tick(max(1.0, self.obs_tick_s))
        payload = self.usage.debug_payload()
        payload["fairness"] = self.fairness.debug_payload()
        # Residency alongside the usage shares (pod -> adapter -> tier):
        # lig-top renders WHERE each tenant's weights live next to what
        # they consume.
        payload["residency"] = self.placement.debug_payload()["residency"]
        if len(self.stacks) > 1:
            payload["pools"] = {
                name: dict(
                    stack.usage.debug_payload(),
                    fairness=stack.fairness.debug_payload(),
                    residency=stack.placement.debug_payload()["residency"])
                for name, stack in self.stacks.items()}
        return web.json_response(payload)

    async def handle_debug_kv(self, request: web.Request) -> web.Response:
        """The fleet KV economy view (gateway/kvobs.py): per-pod reuse
        efficiency, parked-KV share, and the cross-replica prefix
        duplication index joined over the pods' ``tpu:kv_prefix_*``
        tables.  Floored at the configured cadence — the savings-rate
        EMAs difference cumulative counters per rollup pass.  Multi-pool
        fronts add a ``pools`` section next to the default pool's
        top-level fields.  Rendered by ``tools/kv_report.py``; the
        fast-burn black-box dump embeds the same payload."""
        for stack in self.stacks.values():
            stack.kvobs.maybe_tick(max(1.0, self.obs_tick_s))
        payload = self.kvobs.debug_payload()
        if len(self.stacks) > 1:
            payload["pools"] = {
                name: stack.kvobs.debug_payload()
                for name, stack in self.stacks.items()}
        return web.json_response(payload)

    async def handle_debug_capacity(self,
                                    request: web.Request) -> web.Response:
        """The capacity & saturation plane (gateway/capacity.py):
        per-pod per-resource saturation indices, the calibrated twin's
        knee/headroom/time-to-breach forecasts, drift divergences and the
        trust state.  Floored at the configured cadence — the calibration
        windows difference cumulative counters per rollup pass.
        Multi-pool fronts add a ``pools`` section.  Rendered by
        ``tools/capacity_report.py``; the fast-burn black-box dump embeds
        the same payload."""
        for stack in self.stacks.values():
            if stack.capacity.cfg.enabled:
                stack.capacity.maybe_tick(max(1.0, self.obs_tick_s))
        payload = self.capacity.debug_payload()
        if len(self.stacks) > 1:
            payload["pools"] = {
                name: stack.capacity.debug_payload()
                for name, stack in self.stacks.items()}
        return web.json_response(payload)

    async def handle_debug_picks(self, request: web.Request) -> web.Response:
        """The routing decision ledger (gateway/pickledger.py): sampled
        per-pick explanation records — stage-by-stage candidate
        narrowing, removed-pod attribution, escape-hatch fires, and the
        counterfactual "decisive seam" tag.  ``?since=<seq>`` incremental
        cursor + ``?limit=`` cap, mirroring /debug/events; records join
        traces via their ``trace_id`` (the ``x-lig-trace-id`` the proxy
        mints).  Multi-pool fronts add a ``pools`` section.  Rendered by
        ``tools/pick_report.py``; the fast-burn black-box dump embeds the
        same payload."""
        payload = pickledger_mod.debug_picks_payload(
            self.pickledger, request.query)
        if len(self.stacks) > 1:
            payload["pools"] = {
                name: pickledger_mod.debug_picks_payload(
                    stack.pickledger, request.query)
                for name, stack in self.stacks.items()}
        return web.json_response(payload)

    async def handle_debug_placement(self, request: web.Request) -> web.Response:
        """The placement plane's state + this tick's decisions — the wire
        ``tools/lora_sidecar.py --planner-url`` polls.  Floored at the
        configured cadence like the other debug surfaces (idle dwell
        counts planner passes).  Multi-pool fronts add a ``pools``
        section (one planner payload per pool) — a sidecar polls with
        ``?pool=<name>`` to read exactly its pool's slice."""
        for stack in self.stacks.values():
            stack.usage.maybe_tick(max(1.0, self.obs_tick_s))
            if (stack.placement.ticks == 0
                    or time.time() - stack.placement.last_tick
                    >= max(1.0, self.obs_tick_s)):
                stack.placement.tick()
        pool = request.query.get("pool")
        if pool:
            stack = self.stacks.get(pool)
            if stack is None:
                return web.json_response(
                    {"error": f"unknown pool {pool!r}",
                     "pools": sorted(self.stacks)}, status=404)
            return web.json_response(stack.placement.debug_payload())
        payload = self.placement.debug_payload()
        if len(self.stacks) > 1:
            payload["pools"] = {
                name: stack.placement.debug_payload()
                for name, stack in self.stacks.items()}
        return web.json_response(payload)

    async def handle_debug_statebus(self,
                                    request: web.Request) -> web.Response:
        """The replicated state plane's view: this replica's local
        snapshot, every known replica's versions/ages, and the merged
        per-pool overlay the advisors currently apply —
        ``tools/statebus_report.py`` renders the divergence table."""
        return web.json_response(self.statebus.debug_payload())

    async def handle_debug_fleet(self, request: web.Request) -> web.Response:
        """The fleet observability view (gateway/fleetobs.py): one pull of
        every peer gateway's and pool pod's debug surfaces (incremental
        cursors — deltas only), stitched cross-replica traces, the merged
        fleet journal, fleet-wide SLO rollup, and per-gateway health.
        ``?limit=`` caps stitched traces (1..256, default 64).  Rendered
        by ``tools/fleet_report.py``; dead sources degrade to their
        cached view with an error marker, never a failed page."""
        try:
            limit = max(1, min(int(request.query.get("limit", "64")), 256))
        except ValueError:
            limit = 64
        session = self._session
        if session is None:
            # Called before startup (tests, one-shot tools): a throwaway
            # session is fine at debug-endpoint cadence.
            async with aiohttp.ClientSession() as tmp:
                payload = await self.fleet.collect(tmp, limit=limit)
        else:
            payload = await self.fleet.collect(session, limit=limit)
        # The fleet KV economy rollup rides along so a peer (or
        # tools/fleet_report.py) reads duplication context without a
        # second pull; per-pod joins live at /debug/kv.
        self.kvobs.maybe_tick(max(1.0, self.obs_tick_s))
        payload["kv"] = self.kvobs.debug_payload()
        # Capacity rollup rides along too: headroom/forecast/trust per
        # pool, so a fleet console answers "which pool runs out first"
        # without a second pull; full detail lives at /debug/capacity.
        if self.capacity.cfg.enabled:
            self.capacity.maybe_tick(max(1.0, self.obs_tick_s))
            payload["capacity"] = {
                name: {"saturation": cap["saturation"],
                       "forecast": cap["forecast"]}
                for name, stack in self.stacks.items()
                for cap in [stack.capacity.debug_payload()]}
        # Fleet pick-steering rollup: which replicas/pools are steering
        # picks and why, joined from the statebus docs already gossiped
        # (no extra pull) — per-pick joins live at /debug/picks.
        payload["picks"] = fleetobs.pick_steering_rollup(
            self.statebus.all_docs())
        return web.json_response(payload)

    async def handle_statebus_exchange(
            self, request: web.Request) -> web.Response:
        """Push-pull gossip endpoint: a peer POSTs the snapshot docs it
        knows (its own + transitively learned ones); we merge them and
        answer with OUR full doc set, so one round trip equalizes both
        sides even across replicas that never talk directly.

        A gateway with NO peers configured refuses the exchange: the
        statebus's peer-less contract is "inert beyond /debug/statebus",
        and merged docs steer enforcement — an open endpoint would let
        any client that can reach the port flag tenants noisy or mark
        every pod avoided.  (With peers configured, restrict reachability
        of this port to the gateway fleet — the gossip wire carries no
        authentication, like the rest of the gateway's surfaces.)"""
        if not self.statebus.cfg.peers:
            return web.json_response(
                {"error": "statebus has no peers configured "
                          "(--statebus-peer); exchange refused"},
                status=403)
        try:
            docs = await request.json()
        except (json.JSONDecodeError, UnicodeDecodeError):
            return web.json_response({"error": "malformed docs"},
                                     status=400)
        if not isinstance(docs, list):
            return web.json_response({"error": "expected a doc list"},
                                     status=400)
        self.statebus.merge(docs)
        self.statebus.apply()
        return web.json_response(self.statebus.all_docs())

    async def handle_debug_events(self, request: web.Request) -> web.Response:
        """The flight recorder: ``?since=<seq>`` incremental cursor,
        ``?kind=`` filter, ``?limit=`` cap."""
        return web.json_response(
            events_mod.debug_events_payload(self.journal, request.query))

    async def handle_health(self, request: web.Request) -> web.Response:
        if self.datastore.has_synced_pool():
            return web.Response(text="ok")
        return web.Response(status=503, text="InferencePool not synced")

    async def handle_models(self, request: web.Request) -> web.Response:
        models = [
            {"id": m.spec.model_name, "object": "model",
             "criticality": m.spec.criticality.value}
            for m in self.datastore.all_models()
        ]
        return web.json_response({"object": "list", "data": models})


def main(argv: list[str] | None = None) -> None:
    from llm_instance_gateway_tpu.gateway import bootstrap

    parser = argparse.ArgumentParser(description="TPU-native inference gateway")
    parser.add_argument("--port", type=int, default=8081)
    parser.add_argument("--no-fast-relay", action="store_true",
                        help="disable the zero-copy SSE relay fast path "
                             "(falls back to the line-scanning relay; the "
                             "A/B axis for byte-parity and perf checks)")
    parser.add_argument("--no-pick-ledger", action="store_true",
                        help="disable the routing decision ledger "
                             "(/debug/picks goes empty; routing itself is "
                             "unchanged either way — the ledger is log-only)")
    parser.add_argument("--pick-sample-every", type=int, default=8,
                        help="sample every Nth pick into the decision "
                             "ledger (1 = every pick; default 8)")
    bootstrap.add_common_args(parser)
    bootstrap.add_resilience_args(parser)
    bootstrap.add_statebus_args(parser)
    args = parser.parse_args(argv)

    comps = bootstrap.components_from_args(args)
    proxy = GatewayProxy(comps.handler_server, comps.provider, comps.datastore,
                         resilience_cfg=bootstrap.resilience_from_args(args),
                         fairness_cfg=bootstrap.fairness_from_args(args),
                         placement_cfg=bootstrap.placement_from_args(args),
                         capacity_cfg=bootstrap.capacity_from_args(args),
                         fast_relay=not args.no_fast_relay,
                         pickledger_cfg=pickledger_mod.PickLedgerConfig(
                             enabled=not args.no_pick_ledger,
                             sample_every=max(1, args.pick_sample_every)),
                         pools=getattr(comps, "pools", None),
                         statebus_cfg=bootstrap.statebus_from_args(
                             args, port=args.port))
    try:
        web.run_app(proxy.build_app(), port=args.port)
    finally:
        comps.stop()


if __name__ == "__main__":
    main()
