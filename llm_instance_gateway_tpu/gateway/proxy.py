"""Standalone gateway: an L7 reverse proxy embedding the ext-proc handler core.

The reference runs as an Envoy ext-proc sidecar: Envoy terminates HTTP, calls
the EPP over gRPC, then routes to the ORIGINAL_DST cluster using the
``target-pod`` header (``pkg/manifests/patch_policy.yaml:14-42``).  On GKE
that wiring is reproduced by the manifests under ``deploy/``; for
environments without Envoy (and for the TPU pools' leaner data path) this
module IS the proxy: it terminates OpenAI-style HTTP, runs the identical
four-phase handler core inline (request headers -> body -> schedule ->
forward -> response phases), and streams the model server's reply back.

Endpoints:
- ``POST /v1/completions`` and ``/v1/chat/completions`` — routed inference.
- ``GET  /metrics``  — gateway self-telemetry (scheduler decisions, shed rate,
  pick latency; resolves reference TODO provider.go:140).
- ``GET  /healthz``  — 200 once the InferencePool is synced (main.go:43-52).
- ``GET  /v1/models`` — logical models from the datastore.
"""

from __future__ import annotations

import argparse
import asyncio
import json
import logging
import time
import uuid

import aiohttp
from aiohttp import web

from llm_instance_gateway_tpu.gateway.datastore import Datastore
from llm_instance_gateway_tpu.gateway.handlers.messages import (
    RequestBody,
    RequestHeaders,
    ResponseBody,
    ResponseHeaders,
)
from llm_instance_gateway_tpu.gateway.handlers.server import (
    ProcessingError,
    RequestContext,
    Server,
)
from llm_instance_gateway_tpu.gateway.telemetry import GatewayMetrics, Timer

logger = logging.getLogger(__name__)


class GatewayProxy:
    def __init__(
        self,
        handler_server: Server,
        provider,
        datastore: Datastore,
        request_timeout_s: float = 3600.0,
    ):
        self.server = handler_server
        self.provider = provider
        self.datastore = datastore
        self.metrics = GatewayMetrics()
        # Re-export per-replica prefix-cache reuse at the gateway /metrics
        # (the KV-affinity observable; see GatewayMetrics.pool_signals_fn).
        self.metrics.pool_signals_fn = provider.all_pod_metrics
        self.request_timeout_s = request_timeout_s
        self._session: aiohttp.ClientSession | None = None

    # -- app wiring --------------------------------------------------------
    def build_app(self) -> web.Application:
        app = web.Application()
        app.router.add_post("/v1/completions", self.handle_completion)
        app.router.add_post("/v1/chat/completions", self.handle_completion)
        app.router.add_get("/metrics", self.handle_metrics)
        app.router.add_get("/healthz", self.handle_health)
        app.router.add_get("/v1/models", self.handle_models)
        app.on_startup.append(self._on_startup)
        app.on_cleanup.append(self._on_cleanup)
        return app

    async def _on_startup(self, app) -> None:
        self._session = aiohttp.ClientSession(
            timeout=aiohttp.ClientTimeout(total=self.request_timeout_s)
        )

    async def _on_cleanup(self, app) -> None:
        if self._session is not None:
            await self._session.close()

    # -- request path ------------------------------------------------------
    async def handle_completion(self, request: web.Request) -> web.Response:
        body = await request.read()
        req_ctx = RequestContext()
        # Request-scoped tracing: honor an inbound id or mint one; it rides
        # to the replica and back so one id follows the request across the
        # gateway, the scheduler decision, and the model server (SURVEY.md
        # §5: the reference's only decision-path observability was verbose
        # logs; this is the structured equivalent).
        request_id = request.headers.get("x-request-id") or uuid.uuid4().hex[:16]
        t_start = time.perf_counter()
        loop = asyncio.get_running_loop()

        # Phase 1+2: headers then body, through the same core the gRPC
        # transport uses.  Scheduling is CPU-only (no I/O) but can walk a
        # large pool; run in executor to keep the event loop responsive.
        self.server.process(req_ctx, RequestHeaders(headers=dict(request.headers)))
        try:
            with Timer() as t:
                result = await loop.run_in_executor(
                    None, self.server.process, req_ctx, RequestBody(body=body)
                )
        except ProcessingError as e:
            self.metrics.record_error()
            kind = "invalid_request_error" if e.status == 400 else "api_error"
            return web.json_response(
                {"error": {"message": str(e), "type": kind}}, status=e.status
            )
        self.metrics.record_request(req_ctx.model or "?")
        if result.immediate_status is not None:
            self.metrics.record_shed()
            return web.json_response(
                {"error": {"message": "dropping request due to limited backend resources",
                            "type": "rate_limit_exceeded"}},
                status=result.immediate_status,
            )

        pod = req_ctx.target_pod
        affinity_hit = False
        pm = self.provider.get_pod_metrics(pod.name) if hasattr(self.provider, "get_pod_metrics") else None
        if pm is not None:
            affinity_hit = req_ctx.resolved_target_model in pm.metrics.active_adapters
        self.metrics.record_pick(pod.name, t.seconds, affinity_hit)

        # Forward to the picked replica (Envoy's ORIGINAL_DST role).
        out_body = result.body if result.body is not None else body
        decode_pod = getattr(req_ctx, "decode_pod", None)
        if decode_pod is not None:
            # Disaggregated pick: relay prefill-hop -> handoff -> decode-hop.
            resp = await self._disagg_forward(
                request, pod, decode_pod, out_body, request_id, req_ctx)
            if resp is not None:
                return resp
            # Either hop refused (draining, long prompt, unsupported
            # params): serve single-hop on the prefill replica — every
            # engine is complete regardless of role.
            logger.info("request=%s disaggregated path unavailable; "
                        "single-hop on %s", request_id, pod.name)
        url = f"http://{pod.address}{request.path}"
        try:
            async with self._session.post(
                url,
                data=out_body,
                headers={
                    "Content-Type": "application/json",
                    "x-request-id": request_id,
                    self.server.target_pod_header: pod.address,
                },
            ) as upstream:
                status = upstream.status
                if "text/event-stream" in upstream.headers.get("Content-Type", ""):
                    # Streamed generation: relay SSE chunks as they arrive —
                    # buffering would defeat streaming, and usage accounting
                    # happens from the stream's final chunk if present.
                    return await self._relay_stream(request, upstream, pod, req_ctx)
                resp_body = await upstream.read()
        except (aiohttp.ClientError, asyncio.TimeoutError) as e:
            self.metrics.record_error()
            logger.warning("upstream %s failed: %s", pod.address, e)
            return web.json_response(
                {"error": {"message": f"upstream error: {e}", "type": "api_error"}},
                status=502,
            )

        # Phases 3+4: response headers + usage accounting.
        hdr_result = self.server.process(req_ctx, ResponseHeaders())
        try:
            self.server.process(req_ctx, ResponseBody(body=resp_body))
            self.metrics.record_usage(
                req_ctx.model,
                req_ctx.usage.prompt_tokens,
                req_ctx.usage.completion_tokens,
            )
        except ProcessingError:
            pass  # non-JSON upstream bodies (e.g. SSE streams) skip accounting

        logger.info(
            "request=%s model=%s target=%s pod=%s status=%d prompt_tokens=%d "
            "completion_tokens=%d pick_us=%.0f total_ms=%.1f",
            request_id, req_ctx.model, req_ctx.resolved_target_model, pod.name,
            status, req_ctx.usage.prompt_tokens, req_ctx.usage.completion_tokens,
            t.seconds * 1e6, (time.perf_counter() - t_start) * 1e3,
        )
        headers = {"x-served-by": pod.name, "x-request-id": request_id,
                   **hdr_result.set_headers}
        return web.Response(body=resp_body, status=status, headers=headers,
                            content_type="application/json")

    async def _disagg_forward(self, request: web.Request, prefill_pod,
                              decode_pod, out_body: bytes, request_id: str,
                              req_ctx) -> web.StreamResponse | None:
        """Two-hop data path for a disaggregated pick.

        Hop 1 posts the (possibly rewritten) body to the prefill replica's
        ``/v1/prefill`` and receives the serialized ``PrefillHandoff``;
        hop 2 posts it to the decode replica's ``/v1/attach``, which decodes
        to completion and answers in the normal OpenAI envelope (SSE
        included).  Returns None to signal single-hop fallback — any 4xx/5xx
        from either hop (draining replica, prompt beyond the prefill bucket,
        params the handoff path doesn't carry) degrades gracefully rather
        than failing the request.
        """
        try:
            async with self._session.post(
                f"http://{prefill_pod.address}/v1/prefill",
                data=out_body,
                headers={"Content-Type": "application/json",
                         "x-request-id": request_id},
            ) as pre:
                if pre.status != 200:
                    logger.warning(
                        "prefill hop %s returned %d; falling back",
                        prefill_pod.address, pre.status)
                    return None
                handoff = await pre.read()
            async with self._session.post(
                f"http://{decode_pod.address}/v1/attach",
                data=handoff,
                headers={"Content-Type": "application/octet-stream",
                         "x-request-id": request_id},
            ) as upstream:
                status = upstream.status
                if status != 200:
                    logger.warning(
                        "attach hop %s returned %d; falling back",
                        decode_pod.address, status)
                    return None
                if "text/event-stream" in upstream.headers.get(
                        "Content-Type", ""):
                    return await self._relay_stream(
                        request, upstream, decode_pod, req_ctx)
                resp_body = await upstream.read()
        except (aiohttp.ClientError, asyncio.TimeoutError) as e:
            # No record_error here: the caller serves the request single-hop
            # next, and THAT path records the request's actual outcome — a
            # recovered hop must not inflate the error rate (non-200 hop
            # statuses above are treated identically).
            logger.warning("disaggregated path %s->%s failed: %s",
                           prefill_pod.address, decode_pod.address, e)
            return None
        hdr_result = self.server.process(req_ctx, ResponseHeaders())
        try:
            self.server.process(req_ctx, ResponseBody(body=resp_body))
            self.metrics.record_usage(
                req_ctx.model,
                req_ctx.usage.prompt_tokens,
                req_ctx.usage.completion_tokens,
            )
        except ProcessingError:
            pass
        logger.info(
            "request=%s model=%s disaggregated prefill=%s decode=%s "
            "status=%d prompt_tokens=%d completion_tokens=%d",
            request_id, req_ctx.model, prefill_pod.name, decode_pod.name,
            status, req_ctx.usage.prompt_tokens,
            req_ctx.usage.completion_tokens,
        )
        headers = {
            "x-served-by": f"{prefill_pod.name}+{decode_pod.name}",
            "x-request-id": request_id,
            **hdr_result.set_headers,
        }
        return web.Response(body=resp_body, status=status, headers=headers,
                            content_type="application/json")

    async def _relay_stream(self, request: web.Request, upstream, pod,
                            req_ctx) -> web.StreamResponse:
        """Relay an SSE stream; never raises once headers are sent.

        A mid-stream upstream failure must terminate THIS prepared response
        (error event + [DONE]) — bubbling up would make the handler try to
        send a second response on the same request.  SSE lines are re-framed
        through a byte buffer so a data line split across transport chunks
        still parses (usage rides the final chunk).
        """
        resp = web.StreamResponse(
            status=upstream.status,
            headers={
                "Content-Type": "text/event-stream",
                "Cache-Control": "no-cache",
                "x-served-by": pod.name,
            },
        )
        await resp.prepare(request)
        last_data_line = b""
        buf = b""
        try:
            async for chunk in upstream.content.iter_any():
                buf += chunk
                *lines, buf = buf.split(b"\n")
                for line in lines:
                    if line.startswith(b"data: ") and line != b"data: [DONE]":
                        last_data_line = line
                await resp.write(chunk)
        except (aiohttp.ClientError, asyncio.TimeoutError) as e:
            self.metrics.record_error()
            logger.warning("upstream stream from %s broke: %s", pod.address, e)
            try:
                await resp.write(
                    b'data: {"error": {"message": "upstream stream interrupted"}}\n\n'
                    b"data: [DONE]\n\n"
                )
            except ConnectionResetError:
                pass
            return resp
        try:
            final = json.loads(last_data_line[len(b"data: "):])
            usage = final.get("usage") or {}
            self.metrics.record_usage(
                req_ctx.model,
                int(usage.get("prompt_tokens", 0) or 0),
                int(usage.get("completion_tokens", 0) or 0),
            )
        except (json.JSONDecodeError, ValueError):
            pass
        return resp

    # -- ops endpoints -----------------------------------------------------
    async def handle_metrics(self, request: web.Request) -> web.Response:
        return web.Response(text=self.metrics.render(), content_type="text/plain")

    async def handle_health(self, request: web.Request) -> web.Response:
        if self.datastore.has_synced_pool():
            return web.Response(text="ok")
        return web.Response(status=503, text="InferencePool not synced")

    async def handle_models(self, request: web.Request) -> web.Response:
        models = [
            {"id": m.spec.model_name, "object": "model",
             "criticality": m.spec.criticality.value}
            for m in self.datastore.all_models()
        ]
        return web.json_response({"object": "list", "data": models})


def main(argv: list[str] | None = None) -> None:
    from llm_instance_gateway_tpu.gateway import bootstrap

    parser = argparse.ArgumentParser(description="TPU-native inference gateway")
    parser.add_argument("--port", type=int, default=8081)
    bootstrap.add_common_args(parser)
    args = parser.parse_args(argv)

    comps = bootstrap.components_from_args(args)
    proxy = GatewayProxy(comps.handler_server, comps.provider, comps.datastore)
    try:
        web.run_app(proxy.build_app(), port=args.port)
    finally:
        comps.stop()


if __name__ == "__main__":
    main()
