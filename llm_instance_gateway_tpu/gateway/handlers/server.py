"""Handler server: per-request dispatch loop over processing messages.

Parity: reference ``pkg/ext-proc/handlers/server.go:17-128`` — ``NewServer``
wiring, the per-stream ``RequestContext``, the phase dispatch, and the
RESOURCE_EXHAUSTED -> 429 immediate-response mapping (:95-113).  Transports
(gRPC stream, HTTP proxy) feed messages through ``Server.process``.
"""

from __future__ import annotations

import logging
from dataclasses import dataclass, field

from llm_instance_gateway_tpu.gateway.datastore import Datastore
from llm_instance_gateway_tpu.gateway.handlers import request as request_handlers
from llm_instance_gateway_tpu.gateway.handlers import response as response_handlers
from llm_instance_gateway_tpu.gateway.handlers.messages import (
    ProcessingMessage,
    ProcessingResult,
    RequestBody,
    RequestHeaders,
    RequestTrailers,
    ResponseBody,
    ResponseHeaders,
    ResponseTrailers,
)
from llm_instance_gateway_tpu.gateway.handlers.response import Usage
from llm_instance_gateway_tpu.gateway.scheduling.scheduler import SchedulingError
from llm_instance_gateway_tpu.gateway.types import Pod

logger = logging.getLogger(__name__)

DEFAULT_TARGET_POD_HEADER = "target-pod"  # main.go:34 flag default
# Second hop of a disaggregated pick: the decode replica's address.  The
# standalone proxy relays the handoff between the two hops itself; the
# ext-proc transport surfaces the header for an Envoy-side implementation.
DEFAULT_DECODE_POD_HEADER = "x-decode-pod"


@dataclass
class RequestContext:
    """Per-HTTP-request state shared across phases (server.go:124-128)."""

    target_pod: Pod | None = None
    # Disaggregated pools: the decode-role replica of a two-stage pick
    # (None = single-hop).  target_pod is then the prefill hop.
    decode_pod: Pod | None = None
    model: str = ""
    resolved_target_model: str = ""
    # End-to-end tracing (tracing.py): honored from the inbound
    # x-lig-trace-id header or minted in the headers/body phase, injected
    # into the upstream header set, and echoed in every response.
    trace_id: str = ""
    # Scheduling attribution for the admission span: time parked in the
    # admission queue, and the (prefill, decode) pick split of a two-stage
    # disaggregated pick (None = single-hop).
    admission_wait_s: float = 0.0
    pick_hops_s: tuple | None = None
    usage: Usage = field(default_factory=Usage)
    # Fairness quota memo (handlers/request.py): the tenant bucket is
    # charged ONCE per client request; proxy retry attempts and hedge
    # re-picks reuse/flag the context and replay the decision instead of
    # spending another token per internal attempt.
    fairness_charged: bool = False
    fairness_demoted_to: str | None = None


class ProcessingError(Exception):
    """Fatal processing error.

    ``status`` is the HTTP status the standalone proxy returns (the gRPC
    transport maps any ProcessingError to stream abort, like the reference's
    non-ResourceExhausted branch at server.go:110-112).  Malformed/unroutable
    client input is 400; internal failures 500.
    """

    def __init__(self, msg: str, status: int = 500):
        super().__init__(msg)
        self.status = status


class Server:
    def __init__(
        self,
        scheduler,
        datastore: Datastore,
        target_pod_header: str = DEFAULT_TARGET_POD_HEADER,
        decode_pod_header: str = DEFAULT_DECODE_POD_HEADER,
    ):
        self.scheduler = scheduler
        self.datastore = datastore
        self.target_pod_header = target_pod_header
        self.decode_pod_header = decode_pod_header
        # Fairness/quota admission gate (gateway/fairness.py, wired by the
        # proxy): consulted in the body phase BEFORE scheduling, so an
        # over-quota tenant's request is demoted one criticality tier on
        # every transport (HTTP proxy AND gRPC ext-proc).  None = off.
        self.fairness = None

    def process(
        self, req_ctx: RequestContext, msg: ProcessingMessage
    ) -> ProcessingResult:
        """Dispatch one phase message (server.go:58-120).

        Sheddable-drop becomes ``immediate_status=429``; malformed input and
        internal errors raise ``ProcessingError`` for the transport to map.
        """
        try:
            if isinstance(msg, RequestHeaders):
                return request_handlers.handle_request_headers(req_ctx, msg)
            if isinstance(msg, RequestBody):
                return request_handlers.handle_request_body(self, req_ctx, msg)
            if isinstance(msg, ResponseHeaders):
                return response_handlers.handle_response_headers(req_ctx, msg)
            if isinstance(msg, ResponseBody):
                return response_handlers.handle_response_body(req_ctx, msg)
            if isinstance(msg, RequestTrailers):
                return ProcessingResult(phase="request_trailers")
            if isinstance(msg, ResponseTrailers):
                return ProcessingResult(phase="response_trailers")
        except SchedulingError as e:
            if e.shed:
                # server.go:100-109: ResourceExhausted -> 429 TooManyRequests.
                logger.info("shedding request: %s", e)
                return ProcessingResult(phase="immediate", immediate_status=429)
            raise ProcessingError(f"failed to find target pod: {e}") from e
        except request_handlers.RequestError as e:
            raise ProcessingError(str(e), status=400) from e
        except response_handlers.ResponseError as e:
            raise ProcessingError(str(e), status=500) from e
        raise ProcessingError(f"unknown request type {type(msg).__name__}")
