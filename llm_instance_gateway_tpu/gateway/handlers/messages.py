"""Transport-agnostic processing messages.

The reference's handler layer consumes Envoy ``ProcessingRequest`` protos and
emits ``ProcessingResponse`` protos (``pkg/ext-proc/handlers/server.go:51-121``).
We keep the same four-phase shape (request headers/body, response
headers/body) but as plain dataclasses, so the same handler core backs:

- the gRPC ext-proc transport (``gateway/extproc``), which (de)serializes
  these to the wire proto, and
- the standalone reverse-proxy transport (``gateway/proxy``), which maps HTTP
  requests directly.
"""

from __future__ import annotations

from dataclasses import dataclass, field


@dataclass
class RequestHeaders:
    headers: dict[str, str] = field(default_factory=dict)


@dataclass
class RequestBody:
    body: bytes = b""


@dataclass
class ResponseHeaders:
    headers: dict[str, str] = field(default_factory=dict)


@dataclass
class ResponseBody:
    body: bytes = b""
    end_of_stream: bool = True


@dataclass
class RequestTrailers:
    """Trailer phases: Envoy sends these when the processing mode asks for
    them (or when usage rides in trailers of a streamed response).  The EPP
    passes trailers through unmodified — the reference has no trailer
    handling at all and would abort the stream; answering with an empty
    TrailersResponse is the compatible upgrade."""

    headers: dict[str, str] = field(default_factory=dict)


@dataclass
class ResponseTrailers:
    headers: dict[str, str] = field(default_factory=dict)


ProcessingMessage = (
    RequestHeaders | RequestBody | ResponseHeaders | ResponseBody
    | RequestTrailers | ResponseTrailers
)


@dataclass
class ProcessingResult:
    """What the transport must do with the in-flight HTTP message.

    Mirrors the subset of Envoy's CommonResponse/ImmediateResponse the
    reference uses: header mutations (request.go:82-97), body mutation
    (request.go:110-114), ClearRouteCache (request.go:128-139), and an
    immediate status for shedding (server.go:100-109 -> 429).
    """

    phase: str = ""
    set_headers: dict[str, str] = field(default_factory=dict)
    body: bytes | None = None  # None = leave body unmodified
    clear_route_cache: bool = False
    immediate_status: int | None = None  # e.g. 429; short-circuits the request
