"""Request-phase handlers: model resolution, traffic split, scheduling, mutation.

Parity: reference ``pkg/ext-proc/handlers/request.go``:

- ``HandleRequestHeaders`` (:122-142): respond with ClearRouteCache=true so
  the proxy recomputes the target cluster from the injected header.
- ``HandleRequestBody`` (:19-120): JSON body must carry ``model``; the model
  must be registered as an InferenceModel (no passthrough, :42-45); weighted
  draw over TargetModels resolves the served model (:46-51); the body's
  ``model`` field is rewritten only when resolution changed it (:62-70);
  the scheduler picks a pod and the transport gets the target-pod header +
  Content-Length (:82-97).

TPU addition: a prompt-token estimate is attached to the LLMRequest so the
token-headroom filter can do long-context-aware placement.
"""

from __future__ import annotations

import json

from llm_instance_gateway_tpu.gateway.datastore import (
    is_critical,
    random_weighted_draw,
)
from llm_instance_gateway_tpu.gateway.handlers.messages import (
    ProcessingResult,
    RequestBody,
    RequestHeaders,
)
from llm_instance_gateway_tpu.gateway.scheduling.prefix_affinity import (
    prefix_hashes,
)
from llm_instance_gateway_tpu.gateway.scheduling.types import (
    LazyPrefixHashes,
    LLMRequest,
)
from llm_instance_gateway_tpu.tracing import (
    TRACE_HEADER,
    header_trace_id,
    new_trace_id,
)


class RequestError(Exception):
    """Malformed or unroutable request (transport maps to 4xx/5xx)."""


def prompt_text(body: dict) -> str:
    """The request's prompt as one string (completions or chat shapes)."""
    prompt = body.get("prompt")
    if isinstance(prompt, str):
        return prompt
    if isinstance(prompt, list):
        return " ".join(p for p in prompt if isinstance(p, str))
    if isinstance(body.get("messages"), list):
        return " ".join(
            str(m.get("content", "")) for m in body["messages"] if isinstance(m, dict)
        )
    return ""




def handle_request_headers(req_ctx, msg: RequestHeaders) -> ProcessingResult:
    """request.go:122-142.  Also adopts (or mints) the request's trace id
    from the x-lig-trace-id header so one id follows the request across the
    gateway decision path and both model-server hops."""
    if not req_ctx.trace_id:
        req_ctx.trace_id = header_trace_id(msg.headers) or new_trace_id()
    return ProcessingResult(phase="request_headers", clear_route_cache=True)


def handle_request_body(server, req_ctx, msg: RequestBody) -> ProcessingResult:
    """request.go:19-120.  ``server`` provides datastore/scheduler/header name."""
    # A multi-pool front (multipool.MultiPoolServer) already parsed the body
    # to pick the pool; reuse its parse instead of decoding large prompts twice.
    body = getattr(req_ctx, "_parsed_body", None)
    if not isinstance(body, dict):
        try:
            body = json.loads(msg.body)
        except (json.JSONDecodeError, UnicodeDecodeError) as e:
            raise RequestError(f"error unmarshaling request body: {e}") from e
    model = body.get("model")
    if not isinstance(model, str):
        raise RequestError("model not found in request")

    model_obj = server.datastore.fetch_model(model)
    if model_obj is None:
        # No passthrough of unregistered models (request.go:39-45).
        raise RequestError(
            f"error finding a model object in InferenceModel for input {model}"
        )
    model_name = model
    if model_obj.spec.target_models:
        model_name = random_weighted_draw(model_obj)
        if not model_name:
            raise RequestError(
                f"error getting target model name for model {model_obj.name}"
            )

    # Adopt/mint the trace id HERE too: gRPC clients (and the load rig) may
    # open the stream at the body phase without a headers message.
    if not req_ctx.trace_id:
        req_ctx.trace_id = new_trace_id()
    # Model identity is known from here on — record it BEFORE scheduling so
    # a shed (SchedulingError below) still carries the model dimension into
    # gateway_shed_total and the trace.
    req_ctx.model = model
    req_ctx.resolved_target_model = model_name

    text = prompt_text(body)
    llm_req = LLMRequest(
        model=model,
        resolved_target_model=model_name,
        critical=is_critical(model_obj),
        prompt_tokens=len(text) // 4,
        criticality=(model_obj.spec.criticality.value
                     if model_obj.spec.criticality else "Default"),
        # The hash chain (up to 32 chained blake2b calls over 8 KB of
        # prompt) used to run on EVERY request body in the ext-proc hot
        # path; the lazy thunk defers it until a scheduler actually
        # evaluates req.prefix_hashes — a prefix-unaware build (or a
        # custom drop-in that never reads the field) never pays it, and a
        # consumer that does read it gets the identical tuple.
        # Model-seeded: identical boilerplate under different models must
        # not alias (their KV blocks can't be shared).
        prefix_hashes=LazyPrefixHashes(
            lambda: prefix_hashes(text, model=model_name)),
        # Joins the pick ledger's decision record to this request's trace.
        trace_id=req_ctx.trace_id,
    )

    request_body = msg.body
    if llm_req.model != llm_req.resolved_target_model:
        body["model"] = llm_req.resolved_target_model
        request_body = json.dumps(body).encode()

    # Fairness/quota gate (gateway/fairness.py): an over-quota tenant's
    # request is demoted ONE criticality tier before scheduling — the
    # filter tree and admission queue then apply the normal
    # lowest-criticality-first degradation under saturation.  Never sheds
    # here; never touches the request when the policy is off/log_only.
    # Charged ONCE per client request: the proxy's retry loop re-enters
    # this phase with the same req_ctx per attempt, and re-spending the
    # bucket there would halve the effective quota exactly during the
    # saturation windows quotas exist for — replay the memoized decision
    # instead.
    fairness = getattr(server, "fairness", None)
    if fairness is not None:
        if req_ctx.fairness_charged:
            if req_ctx.fairness_demoted_to is not None:
                llm_req.criticality = req_ctx.fairness_demoted_to
                llm_req.critical = False
        else:
            req_ctx.fairness_charged = True
            req_ctx.fairness_demoted_to = fairness.admit(llm_req)

    # Disaggregated pools get a two-stage pick (prefill replica + decode
    # replica); schedulers without the seam (custom drop-ins) stay
    # single-hop.  Both raise SchedulingError.
    disagg = getattr(server.scheduler, "schedule_disaggregated", None)
    if disagg is not None:
        target_pod, decode_pod = disagg(llm_req)
    else:
        target_pod, decode_pod = server.scheduler.schedule(llm_req), None

    req_ctx.target_pod = target_pod
    req_ctx.decode_pod = decode_pod
    # Scheduling-layer attribution (admission-queue wait, per-hop pick
    # split) rides to the transport for the admission span's attrs.
    req_ctx.admission_wait_s = getattr(llm_req, "admission_wait_s", 0.0)
    req_ctx.pick_hops_s = getattr(llm_req, "pick_hops_s", None)

    set_headers = {
        server.target_pod_header: target_pod.address,
        # Trace propagation: the upstream replica (and any Envoy-side
        # implementation of the two-hop relay) sees the same trace id.
        TRACE_HEADER: req_ctx.trace_id,
        # Body was (possibly) mutated: Content-Length must follow
        # (request.go:89-96).
        "Content-Length": str(len(request_body)),
    }
    if decode_pod is not None:
        from llm_instance_gateway_tpu.gateway.handlers.server import (
            DEFAULT_DECODE_POD_HEADER,
        )

        set_headers[getattr(server, "decode_pod_header",
                            DEFAULT_DECODE_POD_HEADER)] = decode_pod.address

    return ProcessingResult(
        phase="request_body",
        set_headers=set_headers,
        body=request_body,
    )
