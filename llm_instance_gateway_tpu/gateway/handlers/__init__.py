"""Request/response handlers: the ext-proc processing core, transport-agnostic."""

from llm_instance_gateway_tpu.gateway.handlers.server import (
    RequestContext,
    Server,
)

__all__ = ["Server", "RequestContext"]
