"""Response-phase handlers: debug header + OpenAI usage accounting.

Parity: reference ``pkg/ext-proc/handlers/response.go:13-94``.
"""

from __future__ import annotations

import json
from dataclasses import dataclass

from llm_instance_gateway_tpu.gateway.handlers.messages import (
    ProcessingResult,
    ResponseBody,
    ResponseHeaders,
)


class ResponseError(Exception):
    pass


@dataclass
class Usage:
    prompt_tokens: int = 0
    completion_tokens: int = 0
    total_tokens: int = 0


def handle_response_headers(req_ctx, msg: ResponseHeaders) -> ProcessingResult:
    """response.go:13-38: debug marker header only."""
    return ProcessingResult(
        phase="response_headers",
        set_headers={"x-went-into-resp-headers": "true"},
    )


def handle_response_body(req_ctx, msg: ResponseBody) -> ProcessingResult:
    """response.go:64-83: parse OpenAI ``usage`` into the request context.

    Groundwork for per-model token accounting (SURVEY.md §5 observability).
    """
    try:
        body = json.loads(msg.body)
    except (json.JSONDecodeError, UnicodeDecodeError) as e:
        raise ResponseError(f"unmarshaling response body: {e}") from e
    usage = body.get("usage") or {}
    req_ctx.usage = Usage(
        prompt_tokens=int(usage.get("prompt_tokens", 0) or 0),
        completion_tokens=int(usage.get("completion_tokens", 0) or 0),
        total_tokens=int(usage.get("total_tokens", 0) or 0),
    )
    return ProcessingResult(phase="response_body")
