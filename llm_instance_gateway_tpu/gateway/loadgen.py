"""Gateway load benchmark: the ghz-style ext-proc stress rig.

Parity: reference ``pkg/ext-proc/test/benchmark/benchmark.go:20-110`` — spin a
local ext-proc server with ``numFakePods`` fake pods × ``numModelsPerPod``
adapters (default 200×5 = 1000 models), fire N Process requests
round-robining model names, and report throughput + latency summary.

Two transports (the data-plane fast-path A/B; every emission carries which
one ran as ``relay_mode``):

- **fast** (default): drives the handler ``Server.process`` in-process —
  no gRPC stream, no proto (de)serialization — i.e. the pick →
  header-mutate hot path alone, the loop the ≥10k routed picks/s/core
  target is about.
- **slow** (``--no-fast-path``): the pre-existing gRPC ext-proc stream,
  paying the full proto marshalling tax per request — the baseline the
  fast/slow ratio in every artifact compares against.

Run:  python -m llm_instance_gateway_tpu.gateway.loadgen --requests 10000
Also imported by bench.py for the scheduler-throughput component.
"""

from __future__ import annotations

import argparse
import bisect
import hashlib
import json
import math
import random
import time

from llm_instance_gateway_tpu.api.v1alpha1 import Criticality
from llm_instance_gateway_tpu.gateway.handlers.messages import RequestBody
from llm_instance_gateway_tpu.gateway.handlers.server import (
    DEFAULT_DECODE_POD_HEADER,
    DEFAULT_TARGET_POD_HEADER,
    RequestContext,
)
from llm_instance_gateway_tpu.gateway.scheduling.prefix_affinity import (
    PREFIX_BLOCK_CHARS,
)
from llm_instance_gateway_tpu.gateway.testing import (
    build_handler_server,
    fake_metrics,
    fake_pod,
    generate_request,
    make_model,
    start_ext_proc,
)
from llm_instance_gateway_tpu.gateway.types import Pod
from llm_instance_gateway_tpu.tracing import TRACE_HEADER


def model_name(i: int) -> str:  # benchmark.go:71-73
    return f"adapter-{i}"


def attach_pick_ledger(outer_scheduler, sample_every: int = 8):
    """Wire a standalone decision ledger into a rig scheduler's
    ``pick_ledger`` seam (the AdvisorStack does this in production; bare
    loadgen rigs have no stack).  Returns the ledger, or None when the
    scheduler predates the seam."""
    from llm_instance_gateway_tpu.gateway import pickledger

    sched = getattr(outer_scheduler, "_scheduler", outer_scheduler)
    if not hasattr(sched, "pick_ledger"):
        return None
    ledger = pickledger.PickLedger(
        cfg=pickledger.PickLedgerConfig(sample_every=sample_every))
    sched.pick_ledger = ledger
    return ledger


def pick_funnel_block(ledger) -> dict | None:
    """The artifact's ``pick_funnel`` section: per-stage mean narrowing
    + per-seam steering counts from one ledger's rollup."""
    if ledger is None:
        return None
    ledger.tick()
    roll = ledger.seam_rollup()
    return {
        "samples": roll["samples"],
        "mean_survivors": roll["mean_survivors"],
        "steered": roll["steered"],
        "decisive": roll["decisive"],
    }


CRITICALITY_TIERS = {"critical": Criticality.CRITICAL,
                     "default": Criticality.DEFAULT,
                     "sheddable": Criticality.SHEDDABLE}


def parse_criticality_mix(spec: str) -> dict[str, float]:
    """``"critical=0.1,default=0.6,sheddable=0.3"`` -> normalized weight
    dict keyed by tier name (``Critical``/``Default``/``Sheddable``).
    Weights normalize; unknown tiers raise — a typo'd tier would silently
    skew the traffic shape the chaos scenario and sim calibration share."""
    mix: dict[str, float] = {}
    for part in spec.split(","):
        part = part.strip()
        if not part:
            continue
        name, _, raw = part.partition("=")
        tier = CRITICALITY_TIERS.get(name.strip().lower())
        if tier is None:
            raise ValueError(
                f"criticality-mix entry {part!r}: tier must be one of "
                f"{sorted(CRITICALITY_TIERS)}")
        try:
            w = float(raw)
        except ValueError:
            raise ValueError(f"criticality-mix entry {part!r}: weight must "
                             "be a number") from None
        if w <= 0:
            raise ValueError(f"criticality-mix entry {part!r}: weight must "
                             "be > 0")
        mix[tier.value] = mix.get(tier.value, 0.0) + w
    if not mix:
        raise ValueError("empty criticality mix")
    total = sum(mix.values())
    return {k: v / total for k, v in mix.items()}


def assign_tiers(model_names: list[str], mix: dict[str, float],
                 seed: int = 0) -> dict[str, str]:
    """Seeded weighted tier assignment per model name: uniform round-robin
    traffic over the models then matches the mix in expectation, and the
    same seed reproduces the same shape run over run."""
    rng = random.Random(seed)
    tiers = sorted(mix)
    weights = [mix[t] for t in tiers]
    return {name: rng.choices(tiers, weights=weights)[0]
            for name in model_names}


def parse_adapter_mix(spec: str, normalize: bool = True) -> dict[str, float]:
    """``"a=0.7,b=0.2,base=0.1"`` -> normalized weight dict.  ``base``
    routes to the shared base model (no adapter); weights need not sum to
    1 (they normalize), but must be positive.  ``normalize=False`` keeps
    the raw weights — the --adapter-universe overlay path, where
    ``base=0.1`` must mean a 0.1 ABSOLUTE share carved out of the Zipf
    mass, not "100% of a one-entry mix"."""
    mix: dict[str, float] = {}
    for part in spec.split(","):
        part = part.strip()
        if not part:
            continue
        name, _, raw = part.partition("=")
        try:
            w = float(raw)
        except ValueError:
            raise ValueError(f"adapter-mix entry {part!r}: weight must be "
                             "a number") from None
        if not name or w <= 0:
            raise ValueError(f"adapter-mix entry {part!r}: need name=weight "
                             "with weight > 0")
        mix[name.strip()] = mix.get(name.strip(), 0.0) + w
    if not mix:
        raise ValueError("empty adapter mix")
    if not normalize:
        return mix
    total = sum(mix.values())
    return {k: v / total for k, v in mix.items()}


def build_universe_mix(universe: int, zipf_s: float,
                       extra_mix: dict[str, float] | None = None
                       ) -> dict[str, float]:
    """Zipf adapter mix over a synthetic universe — THE SAME weights and
    ``zipf-0000..`` naming as ``sim/run.py`` (one shared helper, so a
    loadgen per-residency-tier report and a sim ``ttft_by_adapter``
    report cross-correlate by adapter name and can never silently
    diverge).  ``extra_mix`` entries (an explicit ``--adapter-mix``,
    e.g. ``base=0.1``) merge on top and the whole thing renormalizes, so
    the universe composes with the existing mix machinery instead of
    replacing it."""
    from llm_instance_gateway_tpu.sim.run import universe_name, zipf_weights

    if universe <= 0:
        raise ValueError("adapter universe must be > 0")
    mix = {universe_name(k): w
           for k, w in enumerate(zipf_weights(universe, zipf_s))}
    if extra_mix:
        extra_total = sum(extra_mix.values())
        scale = max(0.0, 1.0 - extra_total)
        mix = {name: w * scale for name, w in mix.items()}
        mix.update(extra_mix)
        total = sum(mix.values())
        mix = {name: w / total for name, w in mix.items()}
    return mix


def assign_residency_tiers(mix: dict[str, float], slot_per_pod: int = 16,
                           host_per_pod: int = 128) -> dict[str, str]:
    """Adapter -> residency tier for the universe fixture: the hottest
    ``slot_per_pod`` adapters are slot-resident, the next
    ``host_per_pod`` host-RAM-resident, the long tail disk-only — the
    <10%-resident shape of the tentpole's target scenario."""
    ranked = sorted((n for n in mix if n != "base"),
                    key=lambda n: (-mix[n], n))
    tiers: dict[str, str] = {}
    for i, name in enumerate(ranked):
        if i < slot_per_pod:
            tiers[name] = "slot"
        elif i < slot_per_pod + host_per_pod:
            tiers[name] = "host"
    return tiers


def build_mix_fixture(num_fake_pods: int, mix: dict[str, float],
                      tiers: dict[str, str] | None = None):
    """Weighted-adapter rig: every pod serves ALL mix adapters (affinity
    is trivially satisfiable — the variable under test is the traffic
    skew, the reproducible noisy-neighbor input), plus the shared base
    model for the ``base`` key."""
    adapters = sorted(n for n in mix if n != "base")
    pods = {}
    for i in range(num_fake_pods):
        if tiers is None:
            active = {name: 0 for name in adapters}
            max_adapters = len(adapters) + 1
        else:
            # Universe rig: only slot-resident adapters are ACTIVE (the
            # engine's lora_requests_info semantics); the host tier rides
            # adapter_tiers, the long tail is absent (disk).
            active = {n for n, t in tiers.items() if t == "slot"}
            active = {name: 0 for name in active}
            max_adapters = max(1, len(active))
        pods[fake_pod(i)] = fake_metrics(
            queue=i % 5, kv=(i % 10) / 10.0,
            adapters=active,
            max_adapters=max_adapters,
            adapter_tiers=tiers or {},
        )
    models = [make_model(name, Criticality.CRITICAL) for name in adapters]
    models.append(make_model("shared-base", Criticality.CRITICAL))
    return pods, models


def build_fixture(num_fake_pods: int, num_models_per_pod: int,
                  with_base_model: bool = False, role_split: bool = False):
    """benchmark.go:75-106: pod i serves adapters i*M..i*M+M-1.

    ``role_split`` alternates prefill/decode roles across the fleet
    (disaggregated-pool rig): the scheduler then runs TWO-stage picks and
    every response must carry both target headers."""
    pods = {}
    models = []
    total = num_fake_pods * num_models_per_pod
    for i in range(num_fake_pods):
        adapters = {
            model_name(i * num_models_per_pod + j): 0
            for j in range(num_models_per_pod)
        }
        role = ("prefill" if i % 2 == 0 else "decode") if role_split \
            else "collocated"
        pods[fake_pod(i, role=role)] = fake_metrics(
            queue=i % 5, kv=(i % 10) / 10.0, adapters=adapters,
            max_adapters=num_models_per_pod + 1,
        )
    for i in range(total):
        models.append(make_model(model_name(i), Criticality.CRITICAL))
    if with_base_model:
        # A shared base model with NO adapter: session-prefix traffic
        # routes through it so the prefix tie-break is the only stickiness
        # source (adapter traffic is already pod-pinned by LoRA affinity).
        # Session mode only — the recorded baseline fixture stays 1000.
        models.append(make_model("shared-base", Criticality.CRITICAL))
    return pods, models


class ConsistentRing:
    """Consistent-hash ring spraying request keys across N gateway
    replicas (``--gateways``).  Virtual nodes smooth the load split;
    blake2b keeps the mapping stable across processes and runs, so the
    SAME key (model name, session id) always lands on the SAME replica —
    the property that keeps prefix/session affinity coherent when a
    fleet of gateways fronts one pool (each replica's prefix index only
    ever learns the keys hashed to it)."""

    def __init__(self, n_replicas: int, vnodes: int = 64):
        if n_replicas < 1:
            raise ValueError("need at least one replica")
        points: list[tuple[int, int]] = []
        for r in range(n_replicas):
            for v in range(vnodes):
                h = int.from_bytes(
                    hashlib.blake2b(f"{r}:{v}".encode(),
                                    digest_size=8).digest(), "big")
                points.append((h, r))
        points.sort()
        self._points = points
        self.vnodes = vnodes

    def replica_of(self, key: str) -> int:
        h = int.from_bytes(
            hashlib.blake2b(key.encode(), digest_size=8).digest(), "big")
        i = bisect.bisect_left(self._points, (h, -1))
        if i == len(self._points):
            i = 0
        return self._points[i][1]


def build_pool_fixture(tag: str, pool_index: int, num_fake_pods: int,
                       num_models_per_pod: int):
    """One pool's pods+models with a ``tag`` namespace (multi-pool rig):
    pod ``{tag}-pod-i`` serves adapters ``i*M..i*M+M-1`` — the same
    shape as ``build_fixture``, disjoint across pools."""
    pods = {}
    total = num_fake_pods * num_models_per_pod
    for i in range(num_fake_pods):
        adapters = {f"{tag}-adapter-{i * num_models_per_pod + j}": 0
                    for j in range(num_models_per_pod)}
        pods[Pod(name=f"{tag}-pod-{i}",
                 address=f"10.{pool_index}.{i // 250}.{i % 250}:8000")] = \
            fake_metrics(queue=i % 5, kv=(i % 10) / 10.0,
                         adapters=adapters,
                         max_adapters=num_models_per_pod + 1)
    models = [make_model(f"{tag}-adapter-{k}", Criticality.CRITICAL)
              for k in range(total)]
    return pods, models


def _build_gateway_replica(pool_fixtures: list, seed: int, replica: int,
                           fairness_cfg=None):
    """One in-process gateway replica fronting every pool: a real handler
    ``Server`` + seeded ``Scheduler`` per pool, a real ``AdvisorStack``
    wired into each pool's seams, a ``MultiPoolServer`` front (when >1
    pool), and a ``StateBus`` over the stacks — the full control-plane
    shape the proxy runs, minus HTTP."""
    from llm_instance_gateway_tpu import events as events_mod
    from llm_instance_gateway_tpu.gateway.advisors import AdvisorStack
    from llm_instance_gateway_tpu.gateway.multipool import MultiPoolServer
    from llm_instance_gateway_tpu.gateway.scheduling.scheduler import (
        Scheduler,
    )
    from llm_instance_gateway_tpu.gateway.statebus import (
        StateBus,
        StateBusConfig,
    )

    journal = events_mod.EventJournal(capacity=64)
    servers: dict[str, object] = {}
    datastores: dict[str, object] = {}
    stacks: dict[str, object] = {}
    for k, (pool, pods, models) in enumerate(pool_fixtures):
        # Deterministic per-(replica, pool) RNG: the parity harness
        # rebuilds an identical replica (same seeds, same request
        # stream) with the hog flagged LOCALLY and diffs picks 1:1.
        server = build_handler_server(
            pods, models,
            scheduler_factory=lambda provider, _k=k: Scheduler(
                provider, rng=random.Random(seed * 7919 + replica * 97 + _k)))
        provider = server.scheduler._provider
        stacks[pool] = AdvisorStack(pool, provider,
                                    scheduler=server.scheduler,
                                    server=server, journal=journal,
                                    fairness_cfg=fairness_cfg)
        servers[pool] = server
        datastores[pool] = server.datastore
    if len(pool_fixtures) > 1:
        front = MultiPoolServer(servers, datastores,
                                default=pool_fixtures[0][0])
    else:
        front = servers[pool_fixtures[0][0]]
    bus = StateBus(stacks, cfg=StateBusConfig(replica_id=f"gw-{replica}"))
    return front, stacks, bus


def run_multi_gateway(requests: int = 20000, gateways: int = 4,
                      pools: int = 2, num_fake_pods: int = 50,
                      num_models_per_pod: int = 5, seed: int = 0,
                      parity_requests: int = 400) -> dict:
    """The N-gateway × M-pool rig behind ``--gateways``.

    Two phases:

    - **Throughput**: ``requests`` bodies spray across ``gateways``
      in-process replicas by consistent hash of the model name; each
      replica's batch runs in its own timed loop (replicas are separate
      processes in production — the GIL forbids honest in-process
      parallel timing), interleaved with a single-replica baseline over
      the same fixture, three passes each, best wall kept.
      ``aggregate_rps`` is the MAKESPAN view: total requests over the
      slowest replica's wall — what an N-process fleet would serve,
      conservative under consistent-hash load imbalance.

    - **Enforcement parity** (the pick-for-pick diff harness): fresh
      replicas with ``fairness_mode=deprioritize``; a hog adapter is
      noisy-flagged on replica 0 ONLY, one statebus gossip round runs
      (= one observability tick), then every replica processes its
      stream and an identically-seeded ORACLE twin — the hog flagged
      locally, i.e. the single-gateway brain — processes the same
      stream.  Picks must match 1:1: enforcement decisions reach every
      replica within one tick of single-gateway parity.
    """
    from llm_instance_gateway_tpu.gateway.fairness import FairnessConfig

    fixtures = [(f"p{p}",) + build_pool_fixture(f"p{p}", p, num_fake_pods,
                                                num_models_per_pod)
                for p in range(pools)]
    all_models = [m.spec.model_name
                  for _, _, models in fixtures for m in models]
    ring = ConsistentRing(gateways)
    # Assign the request stream up front: round-robin over every pool's
    # models, replica by consistent hash of the model (the affinity key).
    streams: list[list[bytes]] = [[] for _ in range(gateways)]
    for i in range(requests):
        target = all_models[i % len(all_models)]
        streams[ring.replica_of(target)].append(generate_request(target))

    def timed_run(front, bodies: list[bytes]) -> tuple[float, list[float]]:
        lats = []
        t0 = time.perf_counter()
        for body in bodies:
            t1 = time.perf_counter()
            res = front.process(RequestContext(), RequestBody(body=body))
            lats.append(time.perf_counter() - t1)
            assert res.immediate_status is None, res.immediate_status
        return time.perf_counter() - t0, lats

    def pct(lats: list[float], p: float) -> float:
        if not lats:
            return 0.0
        lats = sorted(lats)
        return lats[min(len(lats) - 1, int(p * len(lats)))]

    # Phase 1: throughput.  Baseline and replicas run INTERLEAVED, three
    # passes each, best wall kept — the same min-over-interleaved
    # posture as tools/bench_check.py: CPU-noise drift across the run
    # must not masquerade as (or hide) a scaling regression on either
    # side of the ratio.
    base_front, _, _ = _build_gateway_replica(fixtures, seed, replica=999)
    replicas = [_build_gateway_replica(fixtures, seed, replica=r)
                for r in range(gateways)]
    fronts = [front for front, _, _ in replicas]
    base_wall = float("inf")
    best: dict[int, tuple[float, list[float]]] = {}
    for _ in range(3):
        wall, _ = timed_run(base_front, [b for s in streams for b in s])
        base_wall = min(base_wall, wall)
        for r in range(gateways):
            wall, lats = timed_run(fronts[r], streams[r])
            if r not in best or wall < best[r][0]:
                best[r] = (wall, lats)
    single_rps = requests / base_wall
    per_replica: dict[str, dict] = {}
    for r in range(gateways):
        wall, lats = best[r]
        per_replica[f"gw-{r}"] = {
            "requests": len(streams[r]),
            "rps": round(len(streams[r]) / wall, 1) if wall > 0 else 0.0,
            "p50_us": round(pct(lats, 0.5) * 1e6, 1),
            "p99_us": round(pct(lats, 0.99) * 1e6, 1),
        }
    # Makespan aggregate: N replicas run in parallel in production, so
    # the fleet serves the whole stream in the SLOWEST replica's wall —
    # conservative (sum-of-rates overshoots N x when min-over-runs gets
    # lucky on the smaller per-replica batches) and naturally capped at
    # ~N x modulo the consistent-hash load imbalance.
    aggregate_rps = requests / max(w for w, _ in best.values())

    # Phase 2: enforcement parity within one statebus tick.
    hog = "p0-adapter-0"  # active on p0-pod-0 only (fixture shape)
    fcfg = FairnessConfig(mode="deprioritize")
    merged = [_build_gateway_replica(fixtures, seed + 1, r,
                                     fairness_cfg=fcfg)
              for r in range(gateways)]
    oracle = [_build_gateway_replica(fixtures, seed + 1, r,
                                     fairness_cfg=fcfg)
              for r in range(gateways)]
    # The flood is detected on replica 0 ONLY; oracles (the one-brain
    # reference) all know it locally.
    merged[0][1]["p0"].usage.seed_noisy(hog, hog)
    for _, stacks, _ in oracle:
        stacks["p0"].usage.seed_noisy(hog, hog)
        for stack in stacks.values():
            stack.fairness.set_quota_scale(1.0 / gateways)
    pre_visible = all(hog in stacks["p0"].fairness.noisy()
                      for _, stacks, _ in merged[1:])
    # One gossip round = one tick: full-mesh push-pull + apply.
    for _, _, bus in merged:
        bus.snapshot()
    for a in range(gateways):
        for b in range(a + 1, gateways):
            merged[a][2].exchange_with(merged[b][2])
    for _, _, bus in merged:
        bus.apply()
    post_visible = all(hog in stacks["p0"].fairness.noisy()
                       for _, stacks, _ in merged)
    # Identical per-replica parity streams: quiet + hog traffic mixed
    # (seeded), spread over both pools.
    prng = random.Random(seed + 2)
    parity_targets = [
        hog if prng.random() < 0.2
        else all_models[prng.randrange(len(all_models))]
        for _ in range(parity_requests)]
    checked = mismatches = 0
    for r in range(gateways):
        bodies = [generate_request(t) for t in parity_targets
                  if ring.replica_of(t) == r]
        for body in bodies:
            ctx_m, ctx_o = RequestContext(), RequestContext()
            res_m = merged[r][0].process(ctx_m, RequestBody(body=body))
            res_o = oracle[r][0].process(ctx_o, RequestBody(body=body))
            checked += 1
            if (res_m.set_headers.get(DEFAULT_TARGET_POD_HEADER)
                    != res_o.set_headers.get(DEFAULT_TARGET_POD_HEADER)):
                mismatches += 1
    bus0 = merged[0][2]
    # Fleet pick funnel: weighted per-stage mean narrowing + per-seam
    # steering summed across every throughput replica's per-pool ledger
    # (the AdvisorStack wires one into each scheduler).
    funnel_samples = 0
    funnel_means: dict[str, float] = {}
    funnel_steered: dict[str, int] = {}
    for _, stacks, _ in replicas:
        for stack in stacks.values():
            block = pick_funnel_block(stack.pickledger)
            if not block or not block["samples"]:
                continue
            n = block["samples"]
            funnel_samples += n
            for stage, mean in block["mean_survivors"].items():
                funnel_means[stage] = funnel_means.get(stage, 0.0) + mean * n
            for seam, count in block["steered"].items():
                funnel_steered[seam] = funnel_steered.get(seam, 0) + count
    pick_funnel = {
        "samples": funnel_samples,
        "mean_survivors": {
            stage: round(total / funnel_samples, 2)
            for stage, total in funnel_means.items()
        } if funnel_samples else {},
        "steered": funnel_steered,
    }
    return {
        "mode": "multi_gateway",
        "gateways": gateways,
        "pools": pools,
        "requests": requests,
        "num_fake_pods_per_pool": num_fake_pods,
        "num_models": len(all_models),
        "spray": {"mode": "consistent_hash", "vnodes": ring.vnodes},
        "per_replica": per_replica,
        "single_replica_rps": round(single_rps, 1),
        "aggregate_rps": round(aggregate_rps, 1),
        "scaling_x": round(aggregate_rps / single_rps, 2),
        "scaling_note": ("aggregate = requests / slowest replica wall "
                         "(makespan; replicas are separate processes in "
                         "production), best-of-3 interleaved passes; "
                         "mild superlinearity is real cache locality — "
                         "each replica touches only its consistent-hash "
                         "bucket's model subset"),
        "parity": {
            "hog": hog,
            "fairness_mode": fcfg.mode,
            "noisy_visible_on_peers_pre_exchange": pre_visible,
            "noisy_visible_on_peers_post_exchange": post_visible,
            "converged_after_exchanges": 1,
            "checked_picks": checked,
            "pick_mismatches_vs_single_brain": mismatches,
        },
        "statebus": {
            "live_replicas": bus0.live_replicas(),
            "quota_scale": bus0.last_apply_scale,
        },
        "pick_funnel": pick_funnel,
        "relay_mode": "fast",
        "scheduler": "python",
    }


def session_prompt(sid: int, k: int, prefix_chars: int) -> str:
    """A prompt whose leading ``prefix_chars`` are identical for every
    request of session ``sid`` (multi-turn / per-tenant template traffic),
    followed by a per-request suffix."""
    return (f"{sid:04d}" * (prefix_chars // 4 + 1))[:prefix_chars] + f" q{k}"


ARRIVAL_SHAPES = ("poisson", "burst", "diurnal")


def build_arrival_timeline(shape: str, n: int, rate_rps: float = 100.0,
                           seed: int = 0, burst_factor: float = 8.0,
                           duty: float = 0.2,
                           period_s: float = 10.0) -> list[float]:
    """Seeded VIRTUAL arrival timestamps for ``n`` requests.

    The rig's dispatch loop is a synchronous tight loop (it measures
    gateway processing cost, not wall-clock pacing), so arrival shapes
    are virtual: a seeded timeline stamped onto the run and recorded in
    the emission (``arrival_summary``) — the reproducible offered-load
    shape the sim's calibration scenarios and the capacity plane's
    forecast tests consume.

    - ``poisson``: memoryless exponential inter-arrivals at ``rate_rps``.
    - ``burst``: on/off square wave — ``duty`` of each ``period_s`` runs
      at ``burst_factor`` x the off rate, normalized so the MEAN rate
      stays ``rate_rps``.
    - ``diurnal``: sinusoidal modulation with period ``period_s`` (a
      compressed day): the instantaneous rate swings 0.25x..1.75x the
      mean.
    """
    if shape not in ARRIVAL_SHAPES:
        raise ValueError(f"unknown arrival shape {shape!r} "
                         f"(choices: {ARRIVAL_SHAPES})")
    rng = random.Random(seed)
    out: list[float] = []
    t = 0.0
    for _ in range(n):
        if shape == "poisson":
            rate = rate_rps
        elif shape == "burst":
            base = rate_rps / (duty * burst_factor + (1.0 - duty))
            in_burst = (t % period_s) < duty * period_s
            rate = base * (burst_factor if in_burst else 1.0)
        else:  # diurnal
            rate = rate_rps * (1.0
                               + 0.75 * math.sin(2.0 * math.pi * t / period_s))
        t += rng.expovariate(max(rate, 1e-6))
        out.append(t)
    return out


def arrival_summary(shape: str, timeline: list[float], rate_rps: float,
                    seed: int) -> dict:
    """The emission block describing a virtual arrival timeline: the
    shape + seed (enough to regenerate it exactly), the offered-rate
    series in 1s windows (capped), and the burstiness observables a
    reader compares across shapes (peak-to-mean, inter-arrival CV —
    ~1 for poisson, >1 for bursty)."""
    n = len(timeline)
    duration = timeline[-1] if timeline else 0.0
    counts: dict[int, int] = {}
    for ts in timeline:
        counts[int(ts)] = counts.get(int(ts), 0) + 1
    series = [counts.get(s, 0) for s in range(int(duration) + 1)]
    mean = n / max(duration, 1e-9)
    inter = [b - a for a, b in zip(timeline, timeline[1:])]
    cv = 0.0
    if inter:
        mi = sum(inter) / len(inter)
        var = sum((x - mi) ** 2 for x in inter) / len(inter)
        cv = (var ** 0.5) / max(mi, 1e-12)
    return {
        "shape": shape, "seed": seed, "rate_rps": rate_rps,
        "requests": n,
        "virtual_duration_s": round(duration, 1),
        "mean_rps": round(mean, 1),
        "peak_1s_rps": max(series) if series else 0,
        "peak_to_mean": round((max(series) if series else 0)
                              / max(mean, 1e-9), 2),
        "interarrival_cv": round(cv, 3),
        # The head of the 1s offered-rate series (bounded: a long run's
        # full series belongs in --trace-out territory, not the summary).
        "offered_rps_windows": series[:64],
    }


def run_load(
    requests: int = 10000,
    num_fake_pods: int = 200,
    num_models_per_pod: int = 5,
    port: int = 19102,
    streams: int = 8,
    use_native: bool = False,
    session_prefix_chars: int = 0,
    session_count: int = 64,
    role_split: bool = False,
    trace_out: str | None = None,
    adapter_mix: dict[str, float] | None = None,
    mix_seed: int = 0,
    criticality_mix: dict[str, float] | None = None,
    adapter_universe: int = 0,
    adapter_zipf: float = 1.1,
    fast_path: bool = True,
    arrival: str | None = None,
    arrival_rate_rps: float = 100.0,
    arrival_seed: int = 0,
) -> dict:
    """Fire ``requests`` Process calls; return a ghz-style summary dict.

    ``use_native`` swaps the Python filter tree for the C++ scheduler hot
    path (``scheduling/native.py``) — the A/B the recorded results compare.
    ``fast_path`` picks the transport: in-process ``Server.process``
    dispatch (fast; no gRPC stream, no proto marshalling) vs the
    pre-existing gRPC ext-proc stream (slow) — the summary's
    ``relay_mode`` field records which one ran.
    ``session_prefix_chars`` > 0 switches to session traffic: every request
    carries one of ``session_count`` shared prompt prefixes, measuring the
    prefix-affinity path's hot-loop cost (hashing rides the pick) and its
    stickiness (distinct pods per session; 1.0 = every repeat landed on
    the session's replica).  ``role_split`` makes the fleet half
    prefill-role / half decode-role: every pick becomes TWO-stage
    (prefill replica by the full tree, decode replica by KV headroom) and
    the summary reports the two-stage rate + per-hop header coverage.
    ``adapter_mix`` (``parse_adapter_mix`` output) switches to WEIGHTED
    adapter traffic drawn from a seeded RNG — the reproducible
    noisy-neighbor input — and the summary gains a per-adapter latency
    breakdown."""
    if session_prefix_chars and session_prefix_chars < PREFIX_BLOCK_CHARS:
        raise ValueError(
            f"session_prefix_chars must be >= {PREFIX_BLOCK_CHARS} (the "
            "affinity hash covers whole blocks only; a shorter prefix "
            "would measure a no-op)")
    if (adapter_mix or adapter_universe) and session_prefix_chars:
        raise ValueError("adapter-mix and session modes are exclusive "
                         "(each defines its own traffic shape)")
    if (adapter_mix or adapter_universe) and role_split:
        raise ValueError("adapter-mix builds an all-collocated fleet; "
                         "combining it with --role-split would report a "
                         "meaningless two_stage_rate")
    residency_tiers: dict[str, str] | None = None
    if adapter_universe:
        # Seeded Zipf draw over a synthetic universe, composing with an
        # explicit --adapter-mix (its entries overlay, e.g. base=0.1) and
        # with --criticality-mix (tier assignment over the same models).
        adapter_mix = build_universe_mix(adapter_universe, adapter_zipf,
                                         extra_mix=adapter_mix)
        residency_tiers = assign_residency_tiers(adapter_mix)
        pods, models = build_mix_fixture(num_fake_pods, adapter_mix,
                                         tiers=residency_tiers)
    elif adapter_mix:
        pods, models = build_mix_fixture(num_fake_pods, adapter_mix)
    else:
        pods, models = build_fixture(
            num_fake_pods, num_models_per_pod,
            with_base_model=bool(session_prefix_chars),
            role_split=role_split)
    tier_of: dict[str, str] = {}
    if criticality_mix:
        # Re-register the fixture's models with seeded weighted tiers so
        # uniform traffic over them reproduces the requested criticality
        # shape — the traffic mold the adapter_flood chaos scenario and
        # future sim calibration share.
        tier_of = assign_tiers(
            sorted(m.spec.model_name for m in models), criticality_mix,
            seed=mix_seed)
        models = [make_model(m.spec.model_name,
                             Criticality(tier_of[m.spec.model_name]))
                  for m in models]
    factory = None
    if use_native:
        from llm_instance_gateway_tpu.gateway.scheduling.native import (
            available, make_scheduler)

        if not available():
            raise RuntimeError("native scheduler library unavailable")
        factory = make_scheduler
    total_models = num_fake_pods * num_models_per_pod
    latencies: list[float] = []
    session_pods: dict[int, set[str]] = {}
    session_requests: dict[int, int] = {}
    two_stage_hits = 0
    trace_hits = 0  # responses carrying the echoed x-lig-trace-id
    # Weighted adapter draw: seeded, so a mix scenario replays exactly.
    mix_rng = random.Random(mix_seed)
    mix_names = sorted(adapter_mix) if adapter_mix else []
    mix_weights = [adapter_mix[n] for n in mix_names] if adapter_mix \
        else []
    per_adapter_lat: dict[str, list[float]] = {}
    per_tier_lat: dict[str, list[float]] = {}
    per_tier_shed: dict[str, int] = {}
    # Residency-tier breakdown (universe mode): latency of requests whose
    # adapter is slot- / host- / disk-tier in the fixture — the TTFT-
    # by-tier shape the placement scenario's acceptance bar reads.
    per_res_tier_lat: dict[str, list[float]] = {}

    def res_tier_account(adapter: str | None, latency_s: float) -> None:
        if residency_tiers is None or adapter is None:
            return
        tier = ("base" if adapter == "base"
                else residency_tiers.get(adapter, "disk"))
        per_res_tier_lat.setdefault(tier, []).append(latency_s)
    sheds = 0  # only nonzero under --criticality-mix (asserted otherwise)

    def body_for(i: int) -> tuple[bytes, int | None, str | None, str]:
        if adapter_mix:
            name = mix_rng.choices(mix_names, weights=mix_weights)[0]
            target = "shared-base" if name == "base" else name
            return generate_request(target), None, name, target
        if session_prefix_chars:
            sid = i % session_count
            return generate_request(
                "shared-base",
                prompt=session_prompt(sid, i, session_prefix_chars)), \
                sid, None, "shared-base"
        target = model_name(i % total_models)
        return generate_request(target), None, None, target

    def tier_account(target: str, latency_s: float, shed: bool) -> None:
        """Per-criticality-tier latency/shed tally (criticality-mix mode)."""
        tier = tier_of.get(target)
        if tier is None:
            return
        if shed:
            per_tier_shed[tier] = per_tier_shed.get(tier, 0) + 1
        else:
            per_tier_lat.setdefault(tier, []).append(latency_s)

    def account(keys: dict, sid: int | None) -> None:
        """Per-response bookkeeping shared by both transports; ``keys``
        maps set-header name -> value."""
        nonlocal trace_hits, two_stage_hits
        if TRACE_HEADER in keys:
            trace_hits += 1
        if role_split and (DEFAULT_TARGET_POD_HEADER in keys
                           and DEFAULT_DECODE_POD_HEADER in keys):
            two_stage_hits += 1
        if sid is not None:
            session_requests[sid] = session_requests.get(sid, 0) + 1
            target = keys.get(DEFAULT_TARGET_POD_HEADER)
            if target:
                session_pods.setdefault(sid, set()).add(target)

    if fast_path:
        # In-process dispatch: the handler core alone — request parse,
        # admission, pick, header mutation — with ZERO transport framing.
        server = build_handler_server(pods, models, scheduler_factory=factory)
        ledger = attach_pick_ledger(server.scheduler)
        t_start = time.perf_counter()
        for i in range(requests):
            body, sid, adapter, target = body_for(i)
            msg = RequestBody(body=body)
            # Body construction stays OUTSIDE the sample, matching the
            # slow path (which builds every body before its timer): the
            # latency A/B measures the gateway's processing, not the rig's
            # request generator.
            t0 = time.perf_counter()
            res = server.process(RequestContext(), msg)
            t1 = time.perf_counter()
            shed = res.immediate_status is not None
            if criticality_mix:
                # Sheddable-tier traffic MAY shed under a saturated
                # fixture — that is the per-tier breakdown's whole point.
                tier_account(target, t1 - t0, shed)
            else:
                assert not shed, f"request {i} shed ({res.immediate_status})"
            if shed:
                # Sheds stay OUT of the headline latency/trace tallies
                # (a near-instant 429 would deflate p50/p99 and make
                # mix artifacts incomparable to non-mix ones); the
                # per-tier rows above carry them.
                sheds += 1
                continue
            latencies.append(t1 - t0)
            if adapter is not None:
                per_adapter_lat.setdefault(adapter, []).append(t1 - t0)
            res_tier_account(adapter, t1 - t0)
            account(res.set_headers, sid)
        wall = time.perf_counter() - t_start
    else:
        import grpc

        from llm_instance_gateway_tpu.gateway.extproc import (
            ext_proc_v3_pb2 as pb,
        )
        from llm_instance_gateway_tpu.gateway.extproc.service import (
            make_process_stub,
        )

        server = start_ext_proc(pods, models, port=port,
                                scheduler_factory=factory)
        ledger = attach_pick_ledger(server.handler_server.scheduler)
        try:
            channel = grpc.insecure_channel(f"localhost:{port}")
            stub = make_process_stub(channel)
            t_start = time.perf_counter()
            # Round-robin model names (benchmark.go:64-69), batched into
            # streams.
            sent = 0
            while sent < requests:
                batch = min(requests - sent, max(1, requests // streams))
                bodies = [body_for(sent + k) for k in range(batch)]
                msgs = [
                    pb.ProcessingRequest(request_body=pb.HttpBody(body=body))
                    for body, _, _, _ in bodies
                ]
                t0 = time.perf_counter()
                # One stream per batch: measures per-message processing
                # inline.
                for k, resp in enumerate(stub(iter(msgs))):
                    t1 = time.perf_counter()
                    lat = t1 - t0
                    t0 = t1
                    shed = resp.WhichOneof("response") != "request_body"
                    if criticality_mix:
                        tier_account(bodies[k][3], lat, shed)
                    else:
                        assert not shed
                    if shed:
                        sheds += 1  # headline tallies exclude sheds
                        continue
                    latencies.append(lat)
                    adapter = bodies[k][2]
                    if adapter is not None:
                        per_adapter_lat.setdefault(adapter, []).append(lat)
                    res_tier_account(adapter, lat)
                    keys = {
                        h.header.key: (h.header.raw_value.decode("utf-8",
                                                                 "replace")
                                       if h.header.raw_value
                                       else h.header.value)
                        for h in (resp.request_body.response
                                  .header_mutation.set_headers)
                    }
                    account(keys, bodies[k][1])
                sent += batch
            wall = time.perf_counter() - t_start
            channel.close()
        finally:
            server.stop(None)

    latencies.sort()

    def pct(p: float) -> float:
        if not latencies:
            return 0.0  # every request shed (saturated mix fixture)
        return latencies[min(len(latencies) - 1, int(p * len(latencies)))]

    out = {
        "requests": requests,
        "num_fake_pods": num_fake_pods,
        "num_models": len(models),
        "wall_s": round(wall, 3),
        "rps": round(requests / wall, 1),
        "p50_us": round(pct(0.5) * 1e6, 1),
        "p99_us": round(pct(0.99) * 1e6, 1),
        # 1.0 = every SERVED response echoed a trace id in its header
        # mutation (the client-side correlation contract; sheds never
        # reach the trace-echo path and are excluded).
        "trace_id_rate": round(trace_hits / max(1, requests - sheds), 4),
        # Which data-plane transport ran: "fast" = in-process dispatch,
        # "slow" = gRPC ext-proc stream — so every future artifact carries
        # the fast/slow axis alongside the scheduler one.
        "relay_mode": "fast" if fast_path else "slow",
    }
    funnel = pick_funnel_block(ledger)
    if funnel is not None:
        # Per-stage mean narrowing + per-seam steering over the sampled
        # picks of THIS run (gateway/pickledger.py; no advisors attached
        # on the bare rig, so steering is the filter tree's alone).
        out["pick_funnel"] = funnel
    if trace_out:
        # Raw per-request samples in the shape tools/trace_report.py reads
        # ({"phases": {name: [seconds...]}}): the ext-proc Process round
        # trip IS the gateway decision phase under this rig.
        with open(trace_out, "w") as f:
            json.dump({"phases": {"extproc.process": latencies}}, f)
    if adapter_universe:
        # Universe mode: the flat per-adapter dump would be 1000+ rows —
        # the per-RESIDENCY-tier breakdown is the shape that matters (the
        # slot/host/disk latency split the placement plane acts on).
        out["adapter_universe"] = adapter_universe
        out["adapter_zipf"] = adapter_zipf
        tiers_summary = {}
        for tier in sorted(per_res_tier_lat):
            vals = sorted(per_res_tier_lat[tier])
            tiers_summary[tier] = {
                "requests": len(vals),
                "p50_us": round(vals[len(vals) // 2] * 1e6, 1),
                "p99_us": round(
                    vals[min(len(vals) - 1, int(0.99 * len(vals)))] * 1e6, 1),
            }
        out["per_residency_tier"] = tiers_summary
    elif adapter_mix:
        # Per-adapter latency breakdown: the observable a noisy-neighbor
        # scenario compares against the gateway's usage attribution.
        out["adapter_mix"] = {k: round(v, 4)
                              for k, v in sorted(adapter_mix.items())}
        breakdown = {}
        for name in sorted(per_adapter_lat):
            vals = sorted(per_adapter_lat[name])
            breakdown[name] = {
                "requests": len(vals),
                "p50_us": round(vals[len(vals) // 2] * 1e6, 1),
                "p99_us": round(
                    vals[min(len(vals) - 1, int(0.99 * len(vals)))] * 1e6, 1),
            }
        out["per_adapter"] = breakdown
    if criticality_mix:
        # Per-tier latency/shed breakdown: the traffic shape + observable
        # the adapter_flood chaos scenario and sim calibration share
        # (zero critical sheds is an acceptance invariant there).
        out["criticality_mix"] = {k: round(v, 4)
                                  for k, v in sorted(criticality_mix.items())}
        # Headline latencies cover served traffic only; the shed count
        # keeps rps (= requests/wall) interpretable next to them.
        out["sheds"] = sheds
        tiers = {}
        for tier in sorted(set(per_tier_lat) | set(per_tier_shed)):
            vals = sorted(per_tier_lat.get(tier, []))
            row = {"requests": len(vals) + per_tier_shed.get(tier, 0),
                   "shed": per_tier_shed.get(tier, 0)}
            if vals:
                row["p50_us"] = round(vals[len(vals) // 2] * 1e6, 1)
                row["p99_us"] = round(
                    vals[min(len(vals) - 1, int(0.99 * len(vals)))] * 1e6, 1)
            tiers[tier] = row
        out["per_tier"] = tiers
    if role_split:
        # 1.0 = every response carried BOTH hop headers (prefill target +
        # x-decode-pod) — the two-stage pick ran on every request.
        out["two_stage_rate"] = round(two_stage_hits / requests, 4)
    if session_prefix_chars:
        if not session_pods:
            raise RuntimeError(
                "session mode matched no target-pod headers — the "
                "measurement is broken, not perfectly sticky")
        per = [len(p) for p in session_pods.values()]
        out["sessions"] = len(per)
        out["session_prefix_chars"] = session_prefix_chars
        # 1.0 = perfect stickiness; N = the session sprayed over N pods.
        out["distinct_pods_per_session_avg"] = round(sum(per) / len(per), 2)
        # Estimated prefix-cache reuse from stickiness alone: a request can
        # hit a pod-local prefix cache iff its pod already served this
        # session once, so each distinct pod a session touched charges one
        # compulsory miss.  This is the upper bound the routing achieves —
        # the ledger's measured reuse_efficiency (/debug/kv) reads at or
        # below it when engines evict.
        total = sum(session_requests.values())
        hits = sum(max(0, session_requests[sid] - len(pods))
                   for sid, pods in session_pods.items())
        out["est_prefix_reuse_rate"] = round(hits / max(1, total), 4)
        # Token-weighted: only the shared prefix chars of each hitting
        # prompt are actually reusable.
        prompt_chars = session_prefix_chars + len(" q0")
        out["est_reuse_efficiency"] = round(
            (hits / max(1, total))
            * (session_prefix_chars / prompt_chars), 4)
    if arrival:
        # Virtual offered-load shape (--arrival): seeded, reproducible,
        # recorded so the artifact carries the load SHAPE alongside the
        # latency numbers — the input the capacity twin's trend
        # forecasts and sim calibration replay.
        timeline = build_arrival_timeline(arrival, requests,
                                          rate_rps=arrival_rate_rps,
                                          seed=arrival_seed)
        out["arrival"] = arrival_summary(arrival, timeline,
                                         arrival_rate_rps, arrival_seed)
    return out


def main(argv=None):
    parser = argparse.ArgumentParser()
    parser.add_argument("--requests", type=int, default=10000)
    parser.add_argument("--fake-pods", type=int, default=200)
    parser.add_argument("--models-per-pod", type=int, default=5)
    parser.add_argument("--native", action="store_true",
                        help="C++ scheduler hot path instead of the Python "
                             "filter tree")
    parser.add_argument("--session-prefix-chars", type=int, default=0,
                        help="session traffic: shared prompt prefixes of "
                             "this many chars (measures prefix-affinity "
                             "cost + stickiness)")
    parser.add_argument("--sessions", type=int, default=64)
    parser.add_argument("--role-split", action="store_true",
                        help="disaggregated-pool rig: half the fake fleet "
                             "prefill-role, half decode-role; measures the "
                             "two-stage pick rate and cost")
    parser.add_argument("--trace-out", default=None, metavar="PATH",
                        help="write per-request phase samples as JSON for "
                             "tools/trace_report.py")
    parser.add_argument("--adapter-mix", default=None, metavar="SPEC",
                        help='weighted adapter traffic, e.g. '
                             '"a=0.7,b=0.2,base=0.1" ("base" = the shared '
                             'base model); seeded draw for reproducible '
                             'noisy-neighbor scenarios, per-adapter '
                             'latency breakdown in the report')
    parser.add_argument("--mix-seed", type=int, default=0,
                        help="seed for the weighted adapter draw")
    parser.add_argument("--adapter-universe", type=int, default=0,
                        metavar="N",
                        help="long-tail traffic: N synthetic adapters with "
                             "seeded Zipf-weighted traffic (composes with "
                             "--adapter-mix overlays and --criticality-mix); "
                             "the fixture tiers the hottest adapters "
                             "slot/host-resident and the report gains a "
                             "per-residency-tier latency breakdown")
    parser.add_argument("--adapter-zipf", type=float, default=1.1,
                        metavar="S",
                        help="Zipf exponent for --adapter-universe traffic")
    parser.add_argument("--criticality-mix", default=None, metavar="SPEC",
                        help='weighted criticality tiers, e.g. '
                             '"critical=0.1,default=0.6,sheddable=0.3": '
                             "the fixture's models get seeded tier "
                             "assignments and the report gains a per-tier "
                             "latency/shed breakdown")
    parser.add_argument("--arrival", default=None, choices=ARRIVAL_SHAPES,
                        help="stamp a seeded VIRTUAL arrival timeline on "
                             "the run (poisson | burst | diurnal) and "
                             "record its offered-rate shape in the "
                             "emission — the reproducible load shape sim "
                             "calibration and capacity-forecast tests "
                             "replay; the dispatch loop itself stays a "
                             "tight loop")
    parser.add_argument("--arrival-rate", type=float, default=100.0,
                        metavar="RPS",
                        help="mean rate of the virtual arrival timeline")
    parser.add_argument("--arrival-seed", type=int, default=0,
                        help="seed for the virtual arrival timeline")
    parser.add_argument("--no-fast-path", action="store_true",
                        help="drive the gRPC ext-proc stream (proto "
                             "marshalling per request) instead of the "
                             "in-process fast path — the slow side of the "
                             "relay_mode A/B")
    parser.add_argument("--gateways", type=int, default=1, metavar="N",
                        help="spray requests across N in-process gateway "
                             "replicas by consistent hash (per-replica "
                             "rps/p99 breakdown + single-replica scaling "
                             "ratio + pick-for-pick statebus enforcement "
                             "parity in the report)")
    parser.add_argument("--pools", type=int, default=1, metavar="M",
                        help="with --gateways: each replica fronts M "
                             "independent pools (MultiPoolServer routing; "
                             "disjoint pod/model namespaces per pool)")
    parser.add_argument("--seed", type=int, default=0,
                        help="seed for the multi-gateway rig's scheduler "
                             "RNGs and parity traffic draw")
    args = parser.parse_args(argv)
    if args.gateways > 1:
        if (args.adapter_mix or args.adapter_universe
                or args.session_prefix_chars or args.role_split
                or args.criticality_mix or args.no_fast_path
                or args.native):
            parser.error("--gateways composes with --fake-pods/"
                         "--models-per-pod/--pools only (each replica "
                         "runs the plain fast-path PYTHON-scheduler "
                         "fixture; --native has no multi-gateway path "
                         "yet and would silently measure the wrong "
                         "scheduler)")
        print(json.dumps(run_multi_gateway(
            requests=args.requests, gateways=args.gateways,
            pools=max(1, args.pools), num_fake_pods=args.fake_pods,
            num_models_per_pod=args.models_per_pod, seed=args.seed)))
        return
    summary = run_load(args.requests, args.fake_pods, args.models_per_pod,
                       use_native=args.native,
                       session_prefix_chars=args.session_prefix_chars,
                       session_count=args.sessions,
                       role_split=args.role_split,
                       trace_out=args.trace_out,
                       adapter_mix=(parse_adapter_mix(
                                        args.adapter_mix,
                                        normalize=not args.adapter_universe)
                                    if args.adapter_mix else None),
                       mix_seed=args.mix_seed,
                       criticality_mix=(
                           parse_criticality_mix(args.criticality_mix)
                           if args.criticality_mix else None),
                       adapter_universe=args.adapter_universe,
                       adapter_zipf=args.adapter_zipf,
                       fast_path=not args.no_fast_path,
                       arrival=args.arrival,
                       arrival_rate_rps=args.arrival_rate,
                       arrival_seed=args.arrival_seed)
    summary["scheduler"] = "native" if args.native else "python"
    print(json.dumps(summary))


if __name__ == "__main__":
    main()
