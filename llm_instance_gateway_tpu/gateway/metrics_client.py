"""Model-server metrics adapter: scrape + map TPU serving metrics.

Parity: reference ``pkg/ext-proc/backend/vllm/metrics.go`` — scrape
``http://<pod>/metrics``, parse Prometheus text, map the server's counters
into ``gateway.types.Metrics``, and derive the active-LoRA set from a labeled
info gauge, selecting the *latest* series when multiple are exposed
(metrics.go:135-150).

Where vLLM exports CUDA-side counters (``vllm:gpu_cache_usage_perc``,
``vllm:num_requests_waiting``), our TPU server (``server/metrics.py``) exports
the contract below.  The names are the seam between the gateway and any
TPU model server (JetStream-style) that wants to join a pool:

=====================================  =======================================
``tpu:prefill_queue_size``             requests awaiting prefill (gauge)
``tpu:decode_queue_size``              requests awaiting a decode slot (gauge)
``tpu:num_requests_running``           in-flight requests (gauge)
``tpu:num_requests_waiting``           total queued (prefill+decode) (gauge)
``tpu:kv_cache_usage_perc``            paged-KV utilization 0..1 (gauge)
``tpu:kv_tokens_capacity``             total KV token capacity (gauge)
``tpu:kv_tokens_free``                 free KV token headroom (gauge)
``tpu:decode_tokens_per_sec``          recent decode throughput (gauge)
``tpu:prefix_reused_tokens``           cumulative prompt tokens served from
                                       the prefix cache (counter, optional)
``tpu:prefill_seconds``                prefill compute latency (histogram,
                                       optional; mean = _sum/_count feeds
                                       Metrics.prefill_seconds_mean)
``tpu:handoff_seconds``                handoff serialize / deserialize+attach
                                       latency (histogram, optional)
``tpu:decode_step_seconds``            per-step decode cadence (histogram,
                                       optional; mean feeds
                                       Metrics.decode_step_seconds_mean)
``tpu:dispatch_wall_seconds``          step-profiler dispatch wall (histogram,
                                       optional; phase-summed mean feeds
                                       Metrics.dispatch_wall_seconds_mean)
``tpu:dispatch_gap_seconds``           inter-dispatch gaps by kind (histogram,
                                       optional; kind="host" mean feeds
                                       Metrics.dispatch_host_gap_seconds_mean)
``tpu:lora_requests_info``             labels ``running_lora_adapters`` (CSV),
                                       ``max_lora``; gauge value = unix ts of
                                       the snapshot (latest series wins)
=====================================  =======================================
"""

from __future__ import annotations

import concurrent.futures as futures
import threading
import urllib.error
import urllib.request

from llm_instance_gateway_tpu.gateway.types import Metrics, Pod, PodMetrics
from llm_instance_gateway_tpu.utils import prom_parse

# Metric-name contract (metrics.go:19-32 equivalent).
LORA_INFO_METRIC = "tpu:lora_requests_info"
LORA_ADAPTERS_LABEL = "running_lora_adapters"
LORA_WAITING_LABEL = "waiting_lora_adapters"
LORA_MAX_LABEL = "max_lora"
LORA_RANKS_LABEL = "adapter_ranks"  # optional name:rank CSV (rank-aware fairness)
LORA_TIERS_LABEL = "resident_tiers"  # optional name:tier CSV (residency summary)
# Residency ladder (server/lora_manager.py): one info line per tier with an
# ``adapters`` CSV; value is a unix timestamp (latest series wins per tier).
RESIDENCY_INFO_METRIC = "tpu:adapter_residency_info"
RESIDENCY_TIER_LABEL = "tier"
RESIDENCY_ADAPTERS_LABEL = "adapters"
PREFILL_QUEUE_METRIC = "tpu:prefill_queue_size"
DECODE_QUEUE_METRIC = "tpu:decode_queue_size"
RUNNING_METRIC = "tpu:num_requests_running"
WAITING_METRIC = "tpu:num_requests_waiting"
KV_USAGE_METRIC = "tpu:kv_cache_usage_perc"
KV_CAPACITY_METRIC = "tpu:kv_tokens_capacity"
KV_FREE_METRIC = "tpu:kv_tokens_free"
KV_PARKED_METRIC = "tpu:kv_parked_tokens"
DECODE_TPS_METRIC = "tpu:decode_tokens_per_sec"
PREFIX_REUSED_METRIC = "tpu:prefix_reused_tokens"
PREFILL_SECONDS_METRIC = "tpu:prefill_seconds"
DECODE_STEP_SECONDS_METRIC = "tpu:decode_step_seconds"
DECODE_BATCH_OCCUPANCY_METRIC = "tpu:decode_batch_occupancy"
# Step-timeline profiler families (server/profiler.py; optional).
DISPATCH_WALL_SECONDS_METRIC = "tpu:dispatch_wall_seconds"
DISPATCH_GAP_SECONDS_METRIC = "tpu:dispatch_gap_seconds"
# Capacity-attribution families (server/usage.py; all optional).
ADAPTER_STEP_SECONDS_METRIC = "tpu:adapter_step_seconds_total"
ADAPTER_TOKENS_METRIC = "tpu:adapter_tokens_total"
ADAPTER_KV_SECONDS_METRIC = "tpu:adapter_kv_block_seconds_total"
IDLE_SLOT_SECONDS_METRIC = "tpu:idle_slot_seconds_total"
PREFILL_PADDING_METRIC = "tpu:prefill_padding_tokens_total"
# KV economy ledger families (server/kv_ledger.py; all optional).
KV_BLOCKS_METRIC = "tpu:kv_blocks"
KV_BLOCKS_TOTAL_METRIC = "tpu:kv_blocks_total"
KV_BLOCK_TOKENS_METRIC = "tpu:kv_block_tokens"
KV_BLOCK_EVENTS_METRIC = "tpu:kv_block_events_total"
KV_PREFIX_HITS_METRIC = "tpu:kv_prefix_hits_total"
KV_PREFIX_TOKENS_SAVED_METRIC = "tpu:kv_prefix_tokens_saved_total"
KV_PREFIX_RESIDENT_METRIC = "tpu:kv_prefix_resident_blocks"


class FetchError(Exception):
    pass


def families_to_metrics(
    families: dict[str, list[prom_parse.Sample]], existing: Metrics
) -> tuple[Metrics, list[str]]:
    """Map parsed families onto a cloned Metrics (promToPodMetrics, :73-129).

    Missing families leave the existing (stale) values in place and are
    reported in the returned error list — the reference aggregates per-metric
    errors with multierr and keeps going (metrics.go:78-128).
    """
    updated = existing.clone()
    errs: list[str] = []

    def latest_value(name: str) -> float | None:
        s = prom_parse.latest_sample(families.get(name, []))
        if s is None:
            errs.append(f"metric family {name!r} not found")
            return None
        return s.value

    v = latest_value(RUNNING_METRIC)
    if v is not None:
        updated.running_queue_size = int(v)
    v = latest_value(WAITING_METRIC)
    if v is not None:
        updated.waiting_queue_size = int(v)
    v = latest_value(KV_USAGE_METRIC)
    if v is not None:
        updated.kv_cache_usage_percent = float(v)

    # TPU-specific signals are optional for foreign servers: absence is not an
    # error if the total-queue contract is satisfied.
    for name, setter in (
        (PREFILL_QUEUE_METRIC, lambda m, x: setattr(m, "prefill_queue_size", int(x))),
        (DECODE_QUEUE_METRIC, lambda m, x: setattr(m, "decode_queue_size", int(x))),
        (KV_CAPACITY_METRIC, lambda m, x: setattr(m, "kv_tokens_capacity", int(x))),
        (KV_FREE_METRIC, lambda m, x: setattr(m, "kv_tokens_free", int(x))),
        (KV_PARKED_METRIC, lambda m, x: setattr(m, "kv_parked_tokens", int(x))),
        (DECODE_TPS_METRIC, lambda m, x: setattr(m, "decode_tokens_per_sec", float(x))),
        (PREFIX_REUSED_METRIC, lambda m, x: setattr(m, "prefix_reused_tokens", int(x))),
    ):
        s = prom_parse.latest_sample(families.get(name, []))
        if s is not None:
            setter(updated, s.value)

    # Phase-latency histograms (optional): the parser sees a histogram as
    # its component families, so mean = <fam>_sum / <fam>_count.  The labels
    # (model/role) are single-valued per replica — latest sample suffices.
    for fam, attr in (
        (PREFILL_SECONDS_METRIC, "prefill_seconds_mean"),
        (DECODE_STEP_SECONDS_METRIC, "decode_step_seconds_mean"),
    ):
        s_sum = prom_parse.latest_sample(families.get(fam + "_sum", []))
        s_count = prom_parse.latest_sample(families.get(fam + "_count", []))
        if s_sum is not None and s_count is not None and s_count.value > 0:
            setattr(updated, attr, s_sum.value / s_count.value)

    # CUMULATIVE histogram sums/counts (optional), summed ACROSS label
    # series: the capacity plane (gateway/capacity.py) differences these
    # between scrape ticks into per-window means — the observation windows
    # the twin's self-calibration fits.  Means alone can't give windows
    # (they average over all time); the raw accumulators can.
    for fam, sum_attr, count_attr in (
        (PREFILL_SECONDS_METRIC,
         "prefill_seconds_sum", "prefill_seconds_count"),
        (DECODE_STEP_SECONDS_METRIC,
         "decode_step_seconds_sum", "decode_step_seconds_count"),
        (DECODE_BATCH_OCCUPANCY_METRIC,
         "decode_batch_occupancy_sum", "decode_batch_occupancy_count"),
    ):
        sums = families.get(fam + "_sum", [])
        counts = families.get(fam + "_count", [])
        if sums and counts:
            setattr(updated, sum_attr, sum(s.value for s in sums))
            setattr(updated, count_attr, sum(s.value for s in counts))

    # Step-timeline profiler means (optional): the wall family sums
    # ACROSS its phase series (one engine, several phases); the gap mean
    # reads only kind="host" — idle gaps are queue emptiness, not the
    # host-sync tax the dispatch-bound levers target.
    def _multi_series_mean(fam: str, label: str | None = None,
                           value: str | None = None) -> float | None:
        total = count = 0.0
        for s in families.get(fam + "_sum", []):
            if label is None or s.labels.get(label) == value:
                total += s.value
        for s in families.get(fam + "_count", []):
            if label is None or s.labels.get(label) == value:
                count += s.value
        return total / count if count > 0 else None

    v = _multi_series_mean(DISPATCH_WALL_SECONDS_METRIC)
    if v is not None:
        updated.dispatch_wall_seconds_mean = v
    v = _multi_series_mean(DISPATCH_GAP_SECONDS_METRIC, "kind", "host")
    if v is not None:
        updated.dispatch_host_gap_seconds_mean = v

    # Capacity attribution (optional): every labeled sample folds in, keyed
    # by its (model, adapter[, phase]) labels — replicas expose one model,
    # so "latest sample" selection does not apply; rebuild the dicts whole
    # each scrape (cumulative counters, never merged with stale keys).
    for fam, attr, with_phase in (
        (ADAPTER_STEP_SECONDS_METRIC, "adapter_step_seconds", True),
        (ADAPTER_TOKENS_METRIC, "adapter_tokens", True),
        (ADAPTER_KV_SECONDS_METRIC, "adapter_kv_block_seconds", False),
    ):
        samples = families.get(fam, [])
        if samples:
            table = {}
            for s in samples:
                adapter = s.labels.get("adapter", "")
                if not adapter:
                    continue
                model = s.labels.get("model", "")
                key = ((model, adapter, s.labels.get("phase", ""))
                       if with_phase else (model, adapter))
                table[key] = s.value
            setattr(updated, attr, table)
    for fam, setter in (
        (IDLE_SLOT_SECONDS_METRIC,
         lambda m, x: setattr(m, "idle_slot_seconds", float(x))),
        (PREFILL_PADDING_METRIC,
         lambda m, x: setattr(m, "prefill_padding_tokens", int(x))),
    ):
        s = prom_parse.latest_sample(families.get(fam, []))
        if s is not None:
            setter(updated, s.value)

    # KV economy ledger (optional): state-labeled block gauges and the
    # prefix-keyed reuse tables, rebuilt whole each scrape (a prefix
    # evicted from the replica's bounded table must drop here too — the
    # duplication index would otherwise count ghosts).
    kv_blocks = {}
    for s in families.get(KV_BLOCKS_METRIC, []):
        state = s.labels.get("state", "")
        if state:
            kv_blocks[state] = int(s.value)
    if kv_blocks:
        updated.kv_blocks = kv_blocks
    for name, setter in (
        (KV_BLOCKS_TOTAL_METRIC,
         lambda m, x: setattr(m, "kv_blocks_total", int(x))),
        (KV_BLOCK_TOKENS_METRIC,
         lambda m, x: setattr(m, "kv_block_tokens", int(x))),
    ):
        s = prom_parse.latest_sample(families.get(name, []))
        if s is not None:
            setter(updated, s.value)
    events = {}
    for s in families.get(KV_BLOCK_EVENTS_METRIC, []):
        kind = s.labels.get("kind", "")
        if kind:
            events[kind] = s.value
    if events:
        updated.kv_block_events = events
    for fam, attr in (
        (KV_PREFIX_HITS_METRIC, "kv_prefix_hits"),
        (KV_PREFIX_TOKENS_SAVED_METRIC, "kv_prefix_tokens_saved"),
        (KV_PREFIX_RESIDENT_METRIC, "kv_prefix_resident_blocks"),
    ):
        samples = families.get(fam, [])
        if samples:
            table = {}
            for s in samples:
                prefix = s.labels.get("prefix", "")
                if prefix:
                    table[prefix] = s.value
            setattr(updated, attr, table)

    # LoRA info: latest series by gauge-value timestamp (metrics.go:135-150 —
    # the reference compares the *gauge value*, which vLLM sets to a unix ts).
    # Running AND waiting adapters union into the affinity set (the
    # reference unions both CSVs into ActiveModels).
    lora_samples = families.get(LORA_INFO_METRIC, [])
    if lora_samples:
        best = max(lora_samples, key=lambda s: s.value)
        adapters: dict[str, int] = {}
        csv = best.labels.get(LORA_ADAPTERS_LABEL, "")
        waiting_csv = best.labels.get(LORA_WAITING_LABEL, "")
        for name in (csv + "," + waiting_csv).split(","):
            name = name.strip()
            if name:
                adapters[name] = 0
        updated.active_adapters = adapters
        # Running/waiting split kept ALONGSIDE the union: the placement
        # planner reads waiting as its prefetch-urgency signal.
        updated.running_adapters = frozenset(
            n.strip() for n in csv.split(",") if n.strip())
        updated.waiting_adapters = frozenset(
            n.strip() for n in waiting_csv.split(",") if n.strip())
        # Optional name:rank CSV (our server exports it; foreign vLLM-style
        # servers simply lack the label and ranks stay unknown).
        ranks: dict[str, int] = {}
        for entry in best.labels.get(LORA_RANKS_LABEL, "").split(","):
            name, sep, raw_rank = entry.strip().rpartition(":")
            if not sep or not name:
                continue
            try:
                ranks[name] = int(float(raw_rank))
            except (ValueError, OverflowError):  # "inf" overflows int()
                errs.append(
                    f"invalid {LORA_RANKS_LABEL} entry: {entry!r}")
        updated.adapter_ranks = ranks
        # Optional name:tier residency summary CSV — the fallback source
        # for adapter_tiers when the dedicated residency family is absent
        # (the family below overrides when present).
        tiers: dict[str, str] = {}
        for entry in best.labels.get(LORA_TIERS_LABEL, "").split(","):
            name, sep, tier = entry.strip().rpartition(":")
            if sep and name and tier:
                tiers[name] = tier
        updated.adapter_tiers = tiers
        raw_max = best.labels.get(LORA_MAX_LABEL)
        if raw_max is None:
            # Without max_lora the slot-room predicates are permanently false
            # for this pod — surface the misconfiguration instead of silently
            # degrading LoRA placement.
            errs.append(f"{LORA_INFO_METRIC} missing {LORA_MAX_LABEL} label")
        else:
            try:
                updated.max_active_adapters = int(float(raw_max))
            except ValueError:
                errs.append(f"invalid {LORA_MAX_LABEL} label: {best.labels}")

    # Residency ladder (optional): per-tier info lines; latest sample per
    # tier wins (value = unix ts, like the LoRA info gauge).  Rebuilt whole
    # each scrape so demoted/evicted adapters drop their tier immediately.
    res_samples = families.get(RESIDENCY_INFO_METRIC, [])
    if res_samples:
        by_tier: dict[str, prom_parse.Sample] = {}
        for s in res_samples:
            tier = s.labels.get(RESIDENCY_TIER_LABEL, "")
            if tier and (tier not in by_tier or s.value > by_tier[tier].value):
                by_tier[tier] = s
        tiers = {}
        for tier, s in by_tier.items():
            for name in s.labels.get(RESIDENCY_ADAPTERS_LABEL, "").split(","):
                name = name.strip()
                if name:
                    tiers[name] = tier
        updated.adapter_tiers = tiers
    return updated, errs


class PodMetricsClient:
    """HTTP scraper (FetchMetrics, metrics.go:38-68)."""

    def __init__(self, timeout_s: float = 5.0,
                 scheme: str = "http") -> None:
        self.timeout_s = timeout_s
        self.scheme = scheme
        # Build/load the native scanner NOW (seconds of g++ on first build):
        # lazily it would fire on the first production-sized scrape and
        # stall the 50ms loop with the loader lock held, going stale on
        # every pod exactly at startup.
        prom_parse._load_native()

    def fetch_metrics(self, pod: Pod, existing: Metrics) -> Metrics:
        url = f"{self.scheme}://{pod.address}/metrics"
        try:
            with urllib.request.urlopen(url, timeout=self.timeout_s) as resp:
                if resp.status != 200:
                    raise FetchError(
                        f"unexpected status code from {pod}: {resp.status}"
                    )
                body = resp.read().decode("utf-8", errors="replace")
        except (urllib.error.URLError, OSError, TimeoutError) as e:
            raise FetchError(f"failed to fetch metrics from {pod}: {e}") from e
        # C scanner on the 50ms hot loop (pure-Python fallback inside).
        families = prom_parse.parse_text_fast(body)
        updated, _errs = families_to_metrics(families, existing)
        return updated


class FakePodMetricsClient:
    """Test fake (backend/fake.go:10-21): per-pod canned results or errors."""

    def __init__(
        self,
        res: dict[str, Metrics] | None = None,
        err: dict[str, Exception] | None = None,
    ) -> None:
        self.res = res or {}
        self.err = err or {}

    def fetch_metrics(self, pod: Pod, existing: Metrics) -> Metrics:
        if pod.name in self.err:
            raise self.err[pod.name]
        if pod.name in self.res:
            return self.res[pod.name].clone()
        return existing.clone()


def fetch_all(
    client,
    pods: list[PodMetrics],
    timeout_s: float = 5.0,
    executor: futures.ThreadPoolExecutor | None = None,
) -> tuple[dict[str, Metrics], list[str]]:
    """Parallel per-pod fetch fan-out (provider.go:145-162).

    Pass a persistent ``executor`` (Provider owns and passes its own) —
    creating and context-managing a pool per call would both churn threads at
    the 50 ms refresh cadence and, worse, block past ``timeout_s`` in
    ``shutdown(wait=True)`` while a slow endpoint drips bytes.  With a shared
    pool, stragglers keep a worker busy past the deadline but never block the
    refresh loop; the bounded pool size caps the damage from a wedged pod.
    The module-level fallback pool exists only for executor-less callers
    (tests, one-shot scripts).
    """
    results: dict[str, Metrics] = {}
    errs: list[str] = []
    if not pods:
        return results, errs
    ex = executor or _default_executor()
    futs = {ex.submit(client.fetch_metrics, pm.pod, pm.metrics): pm.pod for pm in pods}
    done, not_done = futures.wait(futs, timeout=timeout_s)
    for fut in done:
        pod = futs[fut]
        try:
            results[pod.name] = fut.result()
        except Exception as e:  # non-fatal: stale metrics persist
            errs.append(str(e))
    for fut in not_done:
        fut.cancel()  # cancels queued fetches; running ones finish in background
        errs.append(f"timeout fetching metrics from {futs[fut]}")
    return results, errs


_SHARED_EXECUTOR: futures.ThreadPoolExecutor | None = None
_SHARED_EXECUTOR_LOCK = threading.Lock()


def _default_executor() -> futures.ThreadPoolExecutor:
    global _SHARED_EXECUTOR
    with _SHARED_EXECUTOR_LOCK:
        if _SHARED_EXECUTOR is None:
            _SHARED_EXECUTOR = futures.ThreadPoolExecutor(
                max_workers=32, thread_name_prefix="metrics-fetch"
            )
        return _SHARED_EXECUTOR
