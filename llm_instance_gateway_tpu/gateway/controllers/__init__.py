"""Control plane: reconcilers keeping the datastore in sync with intent.

Reference parity: the three controller-runtime reconcilers
(``pkg/ext-proc/backend/{inferencepool,inferencemodel,endpointslice}_reconciler.go``)
re-expressed as transport-independent ``update_datastore`` cores plus
pluggable watch sources (file polling here; a k8s informer adapter slots into
the same seam on GKE).  The reference's own tests call ``updateDatastore``
directly (SURVEY.md §4) — ours do too.
"""

from llm_instance_gateway_tpu.gateway.controllers.reconcilers import (
    Endpoint,
    EndpointsReconciler,
    InferenceModelReconciler,
    InferencePoolReconciler,
)

__all__ = [
    "Endpoint",
    "EndpointsReconciler",
    "InferenceModelReconciler",
    "InferencePoolReconciler",
]
