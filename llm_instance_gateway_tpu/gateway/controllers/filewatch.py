"""Watch sources: file-polling config watcher and health-probing membership.

The k8s deployment uses informers; everywhere else these two sources drive
the same reconcilers:

- ``ConfigWatcher`` polls a multi-doc YAML of InferencePool/InferenceModel
  documents (mtime-gated, like the sidecar's PollingObserver — the watchdog
  package the reference uses isn't in this image, ``sidecar.py:247-252``).
- ``EndpointProber`` turns a static endpoint list into *liveness-driven*
  membership by probing each replica's ``/health``: the local equivalent of
  EndpointSlice Ready conditions (``endpointslice_reconciler.go:107-111``),
  so a dead replica leaves the scheduler pool within one probe interval
  instead of serving stale metrics forever.
"""

from __future__ import annotations

import concurrent.futures as futures
import logging
import os
import threading
import urllib.request
from dataclasses import dataclass
from typing import Callable

import yaml

from llm_instance_gateway_tpu.lockwitness import witness_lock
from llm_instance_gateway_tpu.api import v1alpha1
from llm_instance_gateway_tpu.gateway.controllers.reconcilers import (
    Endpoint,
    EndpointsReconciler,
    InferenceModelReconciler,
    InferencePoolReconciler,
)

logger = logging.getLogger(__name__)


class ConfigWatcher:
    def __init__(
        self,
        path: str,
        pool_reconciler: InferencePoolReconciler,
        model_reconciler: InferenceModelReconciler,
        poll_interval_s: float = 2.0,
    ):
        self.path = path
        self.pool_reconciler = pool_reconciler
        self.model_reconciler = model_reconciler
        self.poll_interval_s = poll_interval_s
        self._mtime = 0.0
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None

    def sync_once(self) -> bool:
        """Parse + reconcile if the file changed; returns whether it did."""
        try:
            mtime = os.stat(self.path).st_mtime
        except OSError:
            return False
        if mtime == self._mtime:
            return False
        self._mtime = mtime
        try:
            with open(self.path) as f:
                docs = list(yaml.safe_load_all(f))
            pools, models = v1alpha1.from_documents(docs)
        except (OSError, yaml.YAMLError, ValueError) as e:
            logger.error("config reload failed (keeping last good state): %s", e)
            return False
        for pool in pools:
            self.pool_reconciler.reconcile(pool)
        self.model_reconciler.resync(models)
        logger.info("config synced: %d pools, %d models", len(pools), len(models))
        return True

    def start(self) -> None:
        self.sync_once()

        def loop():
            while not self._stop.wait(self.poll_interval_s):
                try:
                    self.sync_once()
                except Exception:
                    logger.exception("config watch error")

        self._thread = threading.Thread(target=loop, daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()


@dataclass
class StaticEndpoint:
    name: str
    address: str  # host:port of the serving endpoint
    zone: str = ""
    role: str = "collocated"  # disaggregation role (gateway/types.py)


def probe_health(address: str, timeout_s: float = 2.0,
                 health_path: str = "/health") -> bool:
    """Shared readiness probe (status-only; bodies are free-form)."""
    try:
        with urllib.request.urlopen(
            f"http://{address}{health_path}", timeout=timeout_s
        ) as resp:
            return resp.status == 200
    except (OSError, urllib.error.URLError):
        return False


def probe_health_many(addresses: list[str], timeout_s: float = 2.0,
                      health_path: str = "/health") -> dict[str, bool]:
    """Concurrent probes: a pool of dead replicas costs one timeout, not N."""
    if not addresses:
        return {}
    with futures.ThreadPoolExecutor(max_workers=min(16, len(addresses))) as ex:
        results = ex.map(
            lambda a: (a, probe_health(a, timeout_s, health_path)), addresses
        )
        return dict(results)


class MembershipAggregator:
    """Merges endpoint lists from multiple sources into one reconcile.

    ``EndpointsReconciler.reconcile`` is full-state (it deletes pods absent
    from its input, reference endpointslice semantics), so independent
    sources (static --pod list, DNS discovery) must publish through one
    aggregator or they'd continuously delete each other's pods.  Endpoints
    are keyed by name; the last source to publish a name wins.
    """

    def __init__(self, reconciler: EndpointsReconciler):
        self._reconciler = reconciler
        self._lock = witness_lock("MembershipAggregator._lock")
        self._sources: dict[str, list[Endpoint]] = {}

    def publish(self, source: str, endpoints: list[Endpoint]) -> None:
        with self._lock:
            self._sources[source] = list(endpoints)
            merged: dict[str, Endpoint] = {}
            for eps in self._sources.values():
                for ep in eps:
                    merged[ep.name] = ep
            union = list(merged.values())
        self._reconciler.reconcile(union)

    def sink(self, source: str) -> Callable[[list[Endpoint]], None]:
        return lambda endpoints: self.publish(source, endpoints)


class DNSDiscoverer:
    """Headless-Service pod discovery: resolve A records, optionally probe.

    On GKE a headless Service (``clusterIP: None``) publishes one A record
    per Ready pod — kube-dns already applies readiness, so probing is
    belt-and-braces (and catches pods that pass k8s readiness but wedge at
    the app layer).  This is the RBAC-free alternative to the reference's
    EndpointSlice informer.
    """

    def __init__(
        self,
        hostname: str,
        port: int,
        reconciler: "EndpointsReconciler | None" = None,
        probe: bool = True,
        interval_s: float = 5.0,
        probe_timeout_s: float = 2.0,
        publish: Callable[[list[Endpoint]], None] | None = None,
    ):
        self.hostname = hostname
        self.port = port
        if publish is None:
            if reconciler is None:
                raise ValueError("need a reconciler or a publish sink")
            publish = reconciler.reconcile
        self._publish = publish
        self.probe = probe
        self.interval_s = interval_s
        self.probe_timeout_s = probe_timeout_s
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None

    def _resolve(self) -> list[str]:
        import socket

        try:
            infos = socket.getaddrinfo(
                self.hostname, self.port, proto=socket.IPPROTO_TCP
            )
        except socket.gaierror as e:
            logger.warning("DNS discovery for %s failed: %s", self.hostname, e)
            return []
        return sorted({info[4][0] for info in infos})

    def discover_once(self) -> list[Endpoint]:
        addresses = {}
        for ip in self._resolve():
            host = f"[{ip}]" if ":" in ip else ip  # bracket IPv6 literals
            addresses[ip] = f"{host}:{self.port}"
        if self.probe:
            health = probe_health_many(
                list(addresses.values()), self.probe_timeout_s
            )
        else:
            health = {a: True for a in addresses.values()}
        endpoints = [
            Endpoint(name=ip, address=addr, ready=health.get(addr, False))
            for ip, addr in addresses.items()
        ]
        self._publish(endpoints)
        return endpoints

    def start(self) -> None:
        self.discover_once()

        def loop():
            while not self._stop.wait(self.interval_s):
                try:
                    self.discover_once()
                except Exception:
                    logger.exception("DNS discovery error")

        self._thread = threading.Thread(target=loop, daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()


class EndpointProber:
    def __init__(
        self,
        endpoints: list[StaticEndpoint],
        reconciler: EndpointsReconciler | None = None,
        probe_interval_s: float = 5.0,
        probe_timeout_s: float = 2.0,
        health_path: str = "/health",
        publish: Callable[[list[Endpoint]], None] | None = None,
    ):
        self.endpoints = list(endpoints)
        if publish is None:
            if reconciler is None:
                raise ValueError("need a reconciler or a publish sink")
            publish = reconciler.reconcile
        self._publish = publish
        self.probe_interval_s = probe_interval_s
        self.probe_timeout_s = probe_timeout_s
        self.health_path = health_path
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None

    def probe_once(self) -> list[Endpoint]:
        health = probe_health_many(
            [ep.address for ep in self.endpoints],
            self.probe_timeout_s, self.health_path,
        )
        results = [
            Endpoint(name=ep.name, address=ep.address,
                     ready=health.get(ep.address, False), zone=ep.zone,
                     role=getattr(ep, "role", "collocated"))
            for ep in self.endpoints
        ]
        self._publish(results)
        return results

    def start(self) -> None:
        self.probe_once()

        def loop():
            while not self._stop.wait(self.probe_interval_s):
                try:
                    self.probe_once()
                except Exception:
                    logger.exception("endpoint probe error")

        self._thread = threading.Thread(target=loop, daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
