"""Kubernetes watch source: informer-style list+watch over raw HTTPS.

The reference's control plane is three controller-runtime reconcilers fed by
apiserver watches (``main.go:81-129``,
``backend/{inferencepool,inferencemodel,endpointslice}_reconciler.go``).
This module supplies the same event source for our reconciler cores without
any kubernetes client dependency (none ships in this image): a minimal REST
client speaking the list+watch protocol directly —

- LIST to seed state and learn the collection ``resourceVersion``;
- WATCH (``?watch=1&resourceVersion=N&allowWatchBookmarks=true``) as a
  newline-delimited JSON stream of ADDED/MODIFIED/DELETED/BOOKMARK events;
- 410 Gone (the server compacted our resourceVersion) → relist;
- disconnect → reconnect with capped exponential backoff.

In-cluster credentials come from the standard service-account mount
(``/var/run/secrets/kubernetes.io/serviceaccount``); tests and dev rigs
inject a base URL + token directly (``KubeConfig``) against a fake
apiserver, mirroring the reference's fake-watch reconciler tests
(``inferencemodel_reconciler_test.go:41-147``).
"""

from __future__ import annotations

import json
import logging
import ssl
import threading
import urllib.error
import urllib.parse
import urllib.request
from collections.abc import Callable, Mapping
from dataclasses import dataclass

from llm_instance_gateway_tpu.lockwitness import witness_lock
from llm_instance_gateway_tpu.api.v1alpha1 import (
    GROUP,
    inference_model_from_doc,
    inference_pool_from_doc,
)
from llm_instance_gateway_tpu.gateway.controllers.reconcilers import Endpoint

logger = logging.getLogger(__name__)

SA_DIR = "/var/run/secrets/kubernetes.io/serviceaccount"
GROUP_PATH = f"/apis/{GROUP}/v1alpha1"  # the CRDs in deploy/crds/


@dataclass
class KubeConfig:
    base_url: str               # e.g. https://10.0.0.1:443
    token: str = ""
    ca_file: str | None = None  # None = no TLS verification (tests/http)
    namespace: str = "default"

    @staticmethod
    def in_cluster() -> "KubeConfig":
        """Standard pod environment (raises if not running in a cluster)."""
        import os

        host = os.environ["KUBERNETES_SERVICE_HOST"]
        port = os.environ.get("KUBERNETES_SERVICE_PORT", "443")
        with open(f"{SA_DIR}/token") as f:
            token = f.read().strip()
        try:
            with open(f"{SA_DIR}/namespace") as f:
                namespace = f.read().strip()
        except OSError:
            namespace = "default"
        return KubeConfig(
            base_url=f"https://{host}:{port}",
            token=token,
            ca_file=f"{SA_DIR}/ca.crt",
            namespace=namespace,
        )


class KubeClient:
    """Minimal apiserver REST: JSON GET + streaming watch."""

    def __init__(self, config: KubeConfig, timeout_s: float = 30.0):
        self.config = config
        self.timeout_s = timeout_s
        if config.ca_file:
            self._ssl = ssl.create_default_context(cafile=config.ca_file)
        elif config.base_url.startswith("https"):
            logger.warning(
                "kube apiserver %s: https WITHOUT a CA file — TLS "
                "verification is DISABLED (dev only; pass a ca_file / "
                "--kube-ca-file in production)", config.base_url)
            self._ssl = ssl.create_default_context()
            self._ssl.check_hostname = False
            self._ssl.verify_mode = ssl.CERT_NONE
        else:
            self._ssl = None

    def _open(self, path: str, query: Mapping[str, str] | None = None,
              timeout_s: float | None = None):
        url = self.config.base_url + path
        if query:
            url += "?" + urllib.parse.urlencode(query)
        req = urllib.request.Request(url)
        if self.config.token:
            req.add_header("Authorization", f"Bearer {self.config.token}")
        return urllib.request.urlopen(
            req, timeout=timeout_s or self.timeout_s, context=self._ssl
        )

    def list(self, path: str, query: Mapping[str, str] | None = None) -> dict:
        with self._open(path, query) as resp:
            return json.loads(resp.read())

    def watch(self, path: str, resource_version: str,
              query: Mapping[str, str] | None = None,
              timeout_s: float = 300.0):
        """Yield watch event dicts until the server closes the stream.

        The server-side timeout (``timeoutSeconds``) bounds each session, so
        a silent connection death can't stall the informer forever.
        """
        q = dict(query or {})
        q.update({
            "watch": "1",
            "resourceVersion": resource_version,
            "allowWatchBookmarks": "true",
            "timeoutSeconds": str(int(timeout_s)),
        })
        with self._open(path, q, timeout_s=timeout_s + 10) as resp:
            for line in resp:
                line = line.strip()
                if not line:
                    continue
                yield json.loads(line)


class GoneError(Exception):
    """resourceVersion too old (HTTP 410 or ERROR event status 410)."""


class Informer:
    """List+watch loop for one collection, running on its own thread.

    ``on_sync(items)`` receives every LIST result (initial and after a 410
    relist) — full desired state, the reconciler ``resync`` seam.
    ``on_event(type, object)`` receives individual watch events.
    """

    def __init__(
        self,
        client: KubeClient,
        path: str,
        on_sync: Callable[[list[dict]], None],
        on_event: Callable[[str, dict], None],
        query: Mapping[str, str] | None = None,
        backoff_s: float = 1.0,
        max_backoff_s: float = 30.0,
        watch_timeout_s: float = 300.0,
    ):
        self.client = client
        self.path = path
        self.query = dict(query or {})
        self.on_sync = on_sync
        self.on_event = on_event
        self.backoff_s = backoff_s
        self.max_backoff_s = max_backoff_s
        self.watch_timeout_s = watch_timeout_s
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        self.synced = threading.Event()  # first successful LIST happened

    # -- one protocol cycle -------------------------------------------------

    def _list_once(self) -> str:
        doc = self.client.list(self.path, self.query)
        items = doc.get("items") or []
        self.on_sync(items)
        self.synced.set()
        return (doc.get("metadata") or {}).get("resourceVersion", "0")

    def _watch_once(self, rv: str) -> str:
        for event in self.client.watch(
            self.path, rv, self.query, timeout_s=self.watch_timeout_s
        ):
            etype = event.get("type", "")
            obj = event.get("object") or {}
            if etype == "BOOKMARK":
                rv = (obj.get("metadata") or {}).get("resourceVersion", rv)
                continue
            if etype == "ERROR":
                if (obj.get("code") == 410
                        or "too old" in str(obj.get("message", ""))):
                    raise GoneError(obj.get("message", "410 Gone"))
                raise RuntimeError(f"watch error event: {obj}")
            rv = (obj.get("metadata") or {}).get("resourceVersion", rv)
            try:
                self.on_event(etype, obj)
            except Exception:
                # One malformed object must not kill the stream (rv already
                # advanced; retrying the same event would loop forever).
                logger.exception("%s: dropping bad %s event", self.path, etype)
            if self._stop.is_set():
                break
        return rv

    def run_forever(self) -> None:
        backoff = self.backoff_s
        rv: str | None = None
        while not self._stop.is_set():
            try:
                if rv is None:
                    rv = self._list_once()
                rv = self._watch_once(rv)
                backoff = self.backoff_s  # a clean session resets backoff
            except GoneError:
                logger.info("%s: resourceVersion compacted; relisting", self.path)
                rv = None
            except urllib.error.HTTPError as e:
                if e.code == 410:
                    rv = None
                    continue
                logger.warning("%s: watch HTTP %s; retrying", self.path, e.code)
                self._stop.wait(backoff)
                backoff = min(backoff * 2, self.max_backoff_s)
            except Exception as e:
                if self._stop.is_set():
                    break
                logger.warning("%s: watch failed (%s); retrying", self.path, e)
                self._stop.wait(backoff)
                backoff = min(backoff * 2, self.max_backoff_s)

    def start(self) -> None:
        self._thread = threading.Thread(target=self.run_forever, daemon=True)
        self._thread.start()

    def signal_stop(self) -> None:
        """Flag the loop to exit without waiting (threads block in socket
        reads up to the watch session timeout; signal all, then join)."""
        self._stop.set()

    def stop(self) -> None:
        self.signal_stop()
        if self._thread is not None:
            self._thread.join(timeout=5)


def endpoints_from_slice(doc: Mapping) -> list[Endpoint]:
    """discovery.k8s.io/v1 EndpointSlice -> Endpoint list
    (endpointslice_reconciler.go:50-79: Ready condition + zone)."""
    out: list[Endpoint] = []
    slice_name = (doc.get("metadata") or {}).get("name", "")
    for i, ep in enumerate(doc.get("endpoints") or []):
        addresses = ep.get("addresses") or []
        if not addresses:
            continue
        conditions = ep.get("conditions") or {}
        ready = conditions.get("ready")
        target = ep.get("targetRef") or {}
        name = target.get("name") or f"{slice_name}-{i}"
        out.append(Endpoint(
            name=name,
            address=addresses[0],
            ready=bool(True if ready is None else ready),  # nil = ready
            zone=ep.get("zone") or "",
        ))
    return out


class KubeSource:
    """Wire the three informers to the reconciler cores.

    The GKE-mode equivalent of ``filewatch.ConfigWatcher`` + ``DNSDiscoverer``:
    InferencePool and InferenceModel CRDs plus EndpointSlices labeled
    ``kubernetes.io/service-name=<service>`` drive the datastore, exactly the
    reference manager's watch set (``main.go:89-121``).
    """

    def __init__(
        self,
        config: KubeConfig,
        pool_reconciler,
        model_reconciler,
        endpoints_sink,
        service_name: str = "",
        client: KubeClient | None = None,
        watch_slices: bool = True,
    ):
        self.client = client or KubeClient(config)
        ns = config.namespace
        self._slices: dict[str, list[Endpoint]] = {}
        self._slices_lock = witness_lock("KubeSource._slices_lock")
        # Accepts an EndpointsReconciler-shaped object OR a bare publish
        # callable (e.g. a MembershipAggregator sink).
        self._publish_endpoints = (
            endpoints_sink.reconcile
            if hasattr(endpoints_sink, "reconcile") else endpoints_sink)

        def parse_each(items, parse):
            out = []
            for doc in items:
                try:
                    out.append(parse(doc))
                except Exception:
                    # One malformed object must not wedge the relist loop.
                    name = (doc.get("metadata") or {}).get("name", "?")
                    logger.exception("skipping malformed object %r", name)
            return out

        def pool_sync(items: list[dict]) -> None:
            for pool in parse_each(items, inference_pool_from_doc):
                pool_reconciler.reconcile(pool)
            # The endpoints reconciler gates on pool availability; slices
            # listed before the pool arrived were dropped — replay them now
            # (controller-runtime requeues on the poolAvailable predicate,
            # endpointslice_reconciler.go:81-105; this is our equivalent).
            self._publish()

        def pool_event(etype: str, doc: dict) -> None:
            if etype in ("ADDED", "MODIFIED"):
                pool_reconciler.reconcile(inference_pool_from_doc(doc))
                self._publish()
            # DELETED pool: keep last-known pool (matches the reference,
            # which never clears the datastore pool on delete).

        def model_sync(items: list[dict]) -> None:
            model_reconciler.resync(parse_each(items, inference_model_from_doc))

        def model_event(etype: str, doc: dict) -> None:
            model_reconciler.reconcile(
                inference_model_from_doc(doc), deleted=(etype == "DELETED"))

        def slices_sync(items: list[dict]) -> None:
            with self._slices_lock:
                self._slices = {
                    (d.get("metadata") or {}).get("name", str(i)):
                        endpoints_from_slice(d)
                    for i, d in enumerate(items)
                }
            self._publish()

        def slice_event(etype: str, doc: dict) -> None:
            name = (doc.get("metadata") or {}).get("name", "")
            with self._slices_lock:
                if etype == "DELETED":
                    self._slices.pop(name, None)
                else:
                    self._slices[name] = endpoints_from_slice(doc)
            self._publish()

        self.pool_informer = Informer(
            self.client, f"{GROUP_PATH}/namespaces/{ns}/inferencepools",
            pool_sync, pool_event,
        )
        self.model_informer = Informer(
            self.client, f"{GROUP_PATH}/namespaces/{ns}/inferencemodels",
            model_sync, model_event,
        )
        self.slice_informer = None
        if watch_slices:
            slice_query = {}
            if service_name:
                slice_query["labelSelector"] = (
                    f"kubernetes.io/service-name={service_name}")
            self.slice_informer = Informer(
                self.client,
                f"/apis/discovery.k8s.io/v1/namespaces/{ns}/endpointslices",
                slices_sync, slice_event, query=slice_query,
            )
        self._informers = tuple(
            inf for inf in (self.pool_informer, self.model_informer,
                            self.slice_informer) if inf is not None)

    def _publish(self) -> None:
        with self._slices_lock:
            merged = [ep for eps in self._slices.values() for ep in eps]
        self._publish_endpoints(merged)

    def start(self) -> None:
        for inf in self._informers:
            inf.start()

    def stop(self) -> None:
        # Signal everything first: each thread may be blocked in a socket
        # read, and sequential stop() would stall join-timeout per informer.
        for inf in self._informers:
            inf.signal_stop()
        for inf in self._informers:
            inf.stop()

    def wait_synced(self, timeout_s: float = 30.0) -> bool:
        return all(inf.synced.wait(timeout_s) for inf in self._informers)
