"""Reconciler cores: pool, model, endpoint membership.

Each reconciler owns one slice of datastore state and is driven by a watch
source (``filewatch.ConfigWatcher`` locally, a k8s informer on GKE).  The
semantics mirror the reference reconcilers line by line; citations inline.
"""

from __future__ import annotations

import logging
from dataclasses import dataclass

from llm_instance_gateway_tpu.api.v1alpha1 import InferenceModel, InferencePool
from llm_instance_gateway_tpu.gateway.datastore import Datastore
from llm_instance_gateway_tpu.gateway.types import Pod

logger = logging.getLogger(__name__)


class InferencePoolReconciler:
    """inferencepool_reconciler.go:28-50: copy the watched pool into the
    datastore, gated on name/namespace and ResourceVersion change."""

    def __init__(self, datastore: Datastore, pool_name: str,
                 namespace: str = "default", on_update=None):
        self.datastore = datastore
        self.pool_name = pool_name
        self.namespace = namespace
        # Called with the new pool after every accepted update — lets the
        # bootstrap propagate pool-carried settings (scheduler thresholds)
        # into live components on hot reload.
        self.on_update = on_update

    def reconcile(self, pool: InferencePool) -> bool:
        if pool.name != self.pool_name or pool.namespace != self.namespace:
            return False
        try:
            current = self.datastore.get_pool()
            if current.resource_version == pool.resource_version:
                return False  # ResourceVersion gate (:45-50)
        except LookupError:
            pass
        self.datastore.set_pool(pool)
        logger.info("updated InferencePool %s (rv %s)", pool.name, pool.resource_version)
        if self.on_update is not None:
            try:
                self.on_update(pool)
            except Exception:
                logger.exception("pool on_update hook failed")
        return True


class InferenceModelReconciler:
    """inferencemodel_reconciler.go:23-55: store models whose PoolRef targets
    our pool, delete those that stop targeting it (keyed by ModelName)."""

    def __init__(self, datastore: Datastore, pool_name: str,
                 namespace: str = "default", default_pool: str | None = None):
        self.datastore = datastore
        self.pool_name = pool_name
        self.namespace = namespace
        # A model WITHOUT a poolRef binds to the deployment's default
        # (first) pool — the same semantics ``_check_models_unambiguous``
        # assumes at build time.  Single-pool gateways pass their own name,
        # so poolRef-less models serve instead of silently 404ing.
        self.default_pool = default_pool if default_pool is not None else pool_name

    def _targets_us(self, model: InferenceModel) -> bool:
        ref = (model.spec.pool_ref.name if model.spec.pool_ref is not None
               else self.default_pool)
        return ref == self.pool_name

    def reconcile(self, model: InferenceModel, deleted: bool = False) -> None:
        if model.namespace != self.namespace:
            return
        if deleted or not self._targets_us(model):
            # updateDatastore deletes when PoolRef moved away (:45-55).
            self.datastore.delete_model(model.spec.model_name)
            return
        self.datastore.store_model(model)

    def resync(self, models: list[InferenceModel]) -> None:
        """Full-state reconcile for file sources (k8s gives us events; a file
        gives us the whole desired state, so compute deletions by diff)."""
        desired = {
            m.spec.model_name: m
            for m in models
            if m.namespace == self.namespace and self._targets_us(m)
        }
        existing = {m.spec.model_name for m in self.datastore.all_models()}
        for name in existing - set(desired):
            self.datastore.delete_model(name)
        for model in desired.values():
            self.datastore.store_model(model)


@dataclass
class Endpoint:
    """One replica endpoint (the EndpointSlice entry equivalent)."""

    name: str
    address: str  # host only or host:port; port filled from pool if absent
    ready: bool = True
    zone: str = ""
    role: str = "collocated"  # disaggregation role (gateway/types.py)


class EndpointsReconciler:
    """endpointslice_reconciler.go:33-111 equivalent: Ready (+zone-matching)
    endpoints become scheduler pods at the pool's target port; stale pods are
    removed.  Gated on pool availability (predicates :81-105)."""

    def __init__(self, datastore: Datastore, zone: str = ""):
        self.datastore = datastore
        self.zone = zone

    def _valid(self, ep: Endpoint) -> bool:
        # validPod (:107-111): Ready, and zone-matching when a zone is set.
        return ep.ready and (not self.zone or ep.zone == self.zone)

    def reconcile(self, endpoints: list[Endpoint]) -> None:
        if not self.datastore.has_synced_pool():
            return  # pool gate (:41-48)
        port = self.datastore.get_pool().spec.target_port_number
        desired: dict[str, Pod] = {}
        for ep in endpoints:
            if not self._valid(ep):
                continue
            address = ep.address if ":" in ep.address else f"{ep.address}:{port}"
            desired[ep.name] = Pod(name=ep.name, address=address,
                                   role=getattr(ep, "role", "collocated"))
        for name in self.datastore.pod_names() - set(desired):
            self.datastore.delete_pod(name)  # remove stale (:64-79)
        for pod in desired.values():
            self.datastore.store_pod(pod)
