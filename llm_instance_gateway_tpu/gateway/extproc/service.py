"""gRPC ExternalProcessor service speaking Envoy's real ext_proc v3 protocol.

Parity: reference ``pkg/ext-proc/main.go:131-158`` (gRPC server wiring +
health service) and ``handlers/server.go:51-121`` (the Process stream loop).
The wire surface is ``envoy.service.ext_proc.v3.ExternalProcessor`` and
``grpc.health.v1.Health`` with upstream message/field numbering
(``proto/``), so a stock Envoy Gateway (EnvoyExtensionPolicy ->
``deploy/gateway/``) and kubelet ``grpc:`` probes work against this server
unmodified.

grpc-python stub codegen (grpc_tools) is not available in this image, so the
services are registered through grpc's generic-handler API with protobuf
(de)serializers from the protoc-generated modules — functionally identical
to generated ``_pb2_grpc`` code.
"""

from __future__ import annotations

import logging
import time
from concurrent import futures as _futures

import grpc

from llm_instance_gateway_tpu.lockwitness import witness_lock
from llm_instance_gateway_tpu.gateway.extproc import envoy_base_pb2 as corepb
from llm_instance_gateway_tpu.gateway.extproc import envoy_http_status_pb2 as statuspb
from llm_instance_gateway_tpu.gateway.extproc import ext_proc_v3_pb2 as pb
from llm_instance_gateway_tpu.gateway.extproc import health_v1_pb2 as healthpb
from llm_instance_gateway_tpu.gateway.handlers.messages import (
    ProcessingResult,
    RequestBody,
    RequestHeaders,
    RequestTrailers,
    ResponseBody,
    ResponseHeaders,
    ResponseTrailers,
)
from llm_instance_gateway_tpu.gateway.handlers.server import (
    ProcessingError,
    RequestContext,
    Server,
)

logger = logging.getLogger(__name__)

SERVICE_NAME = "envoy.service.ext_proc.v3.ExternalProcessor"
HEALTH_SERVICE_NAME = "grpc.health.v1.Health"


def _headers_to_dict(header_map: corepb.HeaderMap) -> dict[str, str]:
    """Envoy populates either ``raw_value`` (bytes) or ``value`` per entry."""
    out: dict[str, str] = {}
    for h in header_map.headers:
        out[h.key] = (
            h.raw_value.decode("utf-8", "replace") if h.raw_value else h.value
        )
    return out


def _to_message(req: pb.ProcessingRequest):
    which = req.WhichOneof("request")
    if which == "request_headers":
        return RequestHeaders(
            headers=_headers_to_dict(req.request_headers.headers))
    if which == "request_body":
        return RequestBody(body=req.request_body.body)
    if which == "response_headers":
        return ResponseHeaders(
            headers=_headers_to_dict(req.response_headers.headers))
    if which == "response_body":
        return ResponseBody(
            body=req.response_body.body,
            end_of_stream=req.response_body.end_of_stream,
        )
    if which == "request_trailers":
        return RequestTrailers(
            headers=_headers_to_dict(req.request_trailers.trailers))
    if which == "response_trailers":
        return ResponseTrailers(
            headers=_headers_to_dict(req.response_trailers.trailers))
    return None


def _to_proto(result: ProcessingResult) -> pb.ProcessingResponse:
    if result.immediate_status is not None:
        # server.go:100-109: shed -> ImmediateResponse{429}.  StatusCode
        # values are the HTTP codes themselves on the wire.
        return pb.ProcessingResponse(
            immediate_response=pb.ImmediateResponse(
                status=statuspb.HttpStatus(code=result.immediate_status),
                details="dropping request due to limited backend resources",
            )
        )
    if result.phase == "request_trailers":
        return pb.ProcessingResponse(request_trailers=pb.TrailersResponse())
    if result.phase == "response_trailers":
        return pb.ProcessingResponse(response_trailers=pb.TrailersResponse())
    common = pb.CommonResponse(clear_route_cache=result.clear_route_cache)
    for key, value in result.set_headers.items():
        # request.go:82-97: mutations carry HeaderValueOption{Header:
        # {Key, RawValue}}.  append_action is set explicitly: the proto
        # default (APPEND_IF_EXISTS_OR_ADD) would make Envoy append a second
        # Content-Length to a client request that already carries one,
        # mis-framing the mutated body.
        common.header_mutation.set_headers.append(
            corepb.HeaderValueOption(
                header=corepb.HeaderValue(key=key, raw_value=value.encode()),
                append_action=(
                    corepb.HeaderValueOption.OVERWRITE_IF_EXISTS_OR_ADD),
            )
        )
    if result.body is not None:
        common.body_mutation.body = result.body
    if result.phase == "request_headers":
        return pb.ProcessingResponse(
            request_headers=pb.HeadersResponse(response=common))
    if result.phase == "request_body":
        return pb.ProcessingResponse(
            request_body=pb.BodyResponse(response=common))
    if result.phase == "response_headers":
        return pb.ProcessingResponse(
            response_headers=pb.HeadersResponse(response=common))
    return pb.ProcessingResponse(
        response_body=pb.BodyResponse(response=common))


class ExtProcService:
    """Bidirectional Process stream: one RequestContext per stream."""

    def __init__(self, server: Server):
        self._server = server

    def process(self, request_iterator, context: grpc.ServicerContext):
        req_ctx = RequestContext()
        for req in request_iterator:
            msg = _to_message(req)
            if msg is None:
                context.abort(grpc.StatusCode.UNKNOWN, "unknown request type")
            try:
                result = self._server.process(req_ctx, msg)
            except ProcessingError as e:
                # server.go:110-112: non-shed errors terminate the stream.
                context.abort(
                    grpc.StatusCode.UNKNOWN, f"failed to handle request: {e}")
            yield _to_proto(result)


class HealthService:
    """grpc.health.v1: SERVING once the InferencePool has synced
    (main.go:43-52)."""

    # Each live Watch stream pins one executor worker (sync gRPC); cap them
    # so health watchers can never starve the Process data path out of the
    # shared pool.  Excess watchers get the current status once and a clean
    # stream end — spec-conforming clients re-subscribe.
    MAX_WATCHERS = 4

    def __init__(self, datastore):
        self._datastore = datastore
        self._watchers = 0
        self._watchers_lock = witness_lock("HealthService._watchers_lock")

    def _status(self) -> int:
        if self._datastore.has_synced_pool():
            return healthpb.HealthCheckResponse.SERVING
        return healthpb.HealthCheckResponse.NOT_SERVING

    def check(self, request: healthpb.HealthCheckRequest,
              context) -> healthpb.HealthCheckResponse:
        return healthpb.HealthCheckResponse(status=self._status())

    def watch(self, request: healthpb.HealthCheckRequest, context):
        """Stream the current status, then updates on change (1s poll)."""
        with self._watchers_lock:
            admit = self._watchers < self.MAX_WATCHERS
            if admit:
                self._watchers += 1
        if not admit:
            yield healthpb.HealthCheckResponse(status=self._status())
            return
        try:
            last = None
            while context.is_active():
                status = self._status()
                if status != last:
                    last = status
                    yield healthpb.HealthCheckResponse(status=status)
                time.sleep(1.0)
        finally:
            with self._watchers_lock:
                self._watchers -= 1


def build_grpc_server(
    handler_server: Server,
    datastore,
    port: int = 9002,
    max_workers: int = 16,
) -> grpc.Server:
    """Assemble the gRPC server (main.go:131-158); caller starts/stops it."""
    ext = ExtProcService(handler_server)
    health = HealthService(datastore)
    server = grpc.server(_futures.ThreadPoolExecutor(max_workers=max_workers))
    server.add_generic_rpc_handlers(
        (
            grpc.method_handlers_generic_handler(
                SERVICE_NAME,
                {
                    "Process": grpc.stream_stream_rpc_method_handler(
                        ext.process,
                        request_deserializer=pb.ProcessingRequest.FromString,
                        response_serializer=(
                            pb.ProcessingResponse.SerializeToString),
                    )
                },
            ),
            grpc.method_handlers_generic_handler(
                HEALTH_SERVICE_NAME,
                {
                    "Check": grpc.unary_unary_rpc_method_handler(
                        health.check,
                        request_deserializer=(
                            healthpb.HealthCheckRequest.FromString),
                        response_serializer=(
                            healthpb.HealthCheckResponse.SerializeToString),
                    ),
                    "Watch": grpc.unary_stream_rpc_method_handler(
                        health.watch,
                        request_deserializer=(
                            healthpb.HealthCheckRequest.FromString),
                        response_serializer=(
                            healthpb.HealthCheckResponse.SerializeToString),
                    ),
                },
            ),
        )
    )
    server.add_insecure_port(f"[::]:{port}")
    return server


def make_process_stub(channel: grpc.Channel):
    """Client-side Process stream callable (for tests and the load rig)."""
    return channel.stream_stream(
        f"/{SERVICE_NAME}/Process",
        request_serializer=pb.ProcessingRequest.SerializeToString,
        response_deserializer=pb.ProcessingResponse.FromString,
    )


def make_health_stub(channel: grpc.Channel):
    return channel.unary_unary(
        f"/{HEALTH_SERVICE_NAME}/Check",
        request_serializer=healthpb.HealthCheckRequest.SerializeToString,
        response_deserializer=healthpb.HealthCheckResponse.FromString,
    )
