"""gRPC ExternalProcessor service over the tpu.extproc.v1 wire protocol.

Parity: reference ``pkg/ext-proc/main.go:131-158`` (gRPC server wiring +
health service) and ``handlers/server.go:51-121`` (the Process stream loop).

grpc-python stub codegen (grpc_tools) is not available in this image, so the
service is registered through grpc's generic-handler API with protobuf
(de)serializers from the protoc-generated ``extproc_pb2`` — functionally
identical to generated ``_pb2_grpc`` code.
"""

from __future__ import annotations

import logging
from concurrent import futures as _futures

import grpc

from llm_instance_gateway_tpu.gateway.extproc import extproc_pb2 as pb
from llm_instance_gateway_tpu.gateway.handlers.messages import (
    ProcessingResult,
    RequestBody,
    RequestHeaders,
    ResponseBody,
    ResponseHeaders,
)
from llm_instance_gateway_tpu.gateway.handlers.server import (
    ProcessingError,
    RequestContext,
    Server,
)

logger = logging.getLogger(__name__)

SERVICE_NAME = "tpu.extproc.v1.ExternalProcessor"
HEALTH_SERVICE_NAME = "tpu.extproc.v1.Health"


def _to_message(req: pb.ProcessingRequest):
    which = req.WhichOneof("request")
    if which == "request_headers":
        return RequestHeaders(
            headers={h.key: h.raw_value.decode("utf-8", "replace")
                     for h in req.request_headers.headers.headers}
        )
    if which == "request_body":
        return RequestBody(body=req.request_body.body)
    if which == "response_headers":
        return ResponseHeaders(
            headers={h.key: h.raw_value.decode("utf-8", "replace")
                     for h in req.response_headers.headers.headers}
        )
    if which == "response_body":
        return ResponseBody(
            body=req.response_body.body,
            end_of_stream=req.response_body.end_of_stream,
        )
    return None


def _to_proto(result: ProcessingResult) -> pb.ProcessingResponse:
    if result.immediate_status is not None:
        return pb.ProcessingResponse(
            immediate_response=pb.ImmediateResponse(
                status_code=result.immediate_status,
                details="dropping request due to limited backend resources",
            )
        )
    common = pb.CommonResponse(clear_route_cache=result.clear_route_cache)
    for key, value in result.set_headers.items():
        common.header_mutation.set_headers.append(
            pb.HeaderValue(key=key, raw_value=value.encode())
        )
    if result.body is not None:
        common.body_mutation.body = result.body
    if result.phase == "request_headers":
        return pb.ProcessingResponse(
            request_headers=pb.HeadersResponse(response=common)
        )
    if result.phase == "request_body":
        return pb.ProcessingResponse(request_body=pb.BodyResponse(response=common))
    if result.phase == "response_headers":
        return pb.ProcessingResponse(
            response_headers=pb.HeadersResponse(response=common)
        )
    return pb.ProcessingResponse(response_body=pb.BodyResponse(response=common))


class ExtProcService:
    """Bidirectional Process stream: one RequestContext per stream."""

    def __init__(self, server: Server):
        self._server = server

    def process(self, request_iterator, context: grpc.ServicerContext):
        req_ctx = RequestContext()
        for req in request_iterator:
            msg = _to_message(req)
            if msg is None:
                context.abort(grpc.StatusCode.UNKNOWN, "unknown request type")
            try:
                result = self._server.process(req_ctx, msg)
            except ProcessingError as e:
                # server.go:110-112: non-shed errors terminate the stream.
                context.abort(grpc.StatusCode.UNKNOWN, f"failed to handle request: {e}")
            yield _to_proto(result)


class HealthService:
    """main.go:43-52: SERVING once the InferencePool has synced."""

    def __init__(self, datastore):
        self._datastore = datastore

    def check(self, request: pb.HealthCheckRequest, context) -> pb.HealthCheckResponse:
        if self._datastore.has_synced_pool():
            status = pb.HealthCheckResponse.SERVING
        else:
            status = pb.HealthCheckResponse.NOT_SERVING
        return pb.HealthCheckResponse(status=status)


def build_grpc_server(
    handler_server: Server,
    datastore,
    port: int = 9002,
    max_workers: int = 16,
) -> grpc.Server:
    """Assemble the gRPC server (main.go:131-158); caller starts/stops it."""
    ext = ExtProcService(handler_server)
    health = HealthService(datastore)
    server = grpc.server(_futures.ThreadPoolExecutor(max_workers=max_workers))
    server.add_generic_rpc_handlers(
        (
            grpc.method_handlers_generic_handler(
                SERVICE_NAME,
                {
                    "Process": grpc.stream_stream_rpc_method_handler(
                        ext.process,
                        request_deserializer=pb.ProcessingRequest.FromString,
                        response_serializer=pb.ProcessingResponse.SerializeToString,
                    )
                },
            ),
            grpc.method_handlers_generic_handler(
                HEALTH_SERVICE_NAME,
                {
                    "Check": grpc.unary_unary_rpc_method_handler(
                        health.check,
                        request_deserializer=pb.HealthCheckRequest.FromString,
                        response_serializer=pb.HealthCheckResponse.SerializeToString,
                    )
                },
            ),
        )
    )
    server.add_insecure_port(f"[::]:{port}")
    return server


def make_process_stub(channel: grpc.Channel):
    """Client-side Process stream callable (for tests and the load rig)."""
    return channel.stream_stream(
        f"/{SERVICE_NAME}/Process",
        request_serializer=pb.ProcessingRequest.SerializeToString,
        response_deserializer=pb.ProcessingResponse.FromString,
    )


def make_health_stub(channel: grpc.Channel):
    return channel.unary_unary(
        f"/{HEALTH_SERVICE_NAME}/Check",
        request_serializer=pb.HealthCheckRequest.SerializeToString,
        response_deserializer=pb.HealthCheckResponse.FromString,
    )
