#!/usr/bin/env bash
# Regenerate the flattened *_pb2.py modules from proto/ sources.
#
# Output modules are flattened into this directory (no envoy/ or grpc/
# python package nesting — a local `grpc/` dir would shadow site-packages
# grpc) and imports are rewritten to package-absolute.  The serialized
# descriptors keep their canonical proto paths (envoy/config/core/v3/...),
# so cross-file type resolution in the descriptor pool is unaffected.
#
# CONSTRAINT: these register the canonical file paths AND symbol names
# (envoy.*, grpc.health.v1.*) in the process-wide default descriptor pool —
# deliberate, since wire/package parity with stock Envoy is the point.  If
# the real grpcio-health-checking or Envoy proto packages are ever installed
# in the same process, imports would collide; this image ships neither.
set -euo pipefail
cd "$(dirname "$0")"

TMP=$(mktemp -d)
trap 'rm -rf "$TMP"' EXIT

protoc -I proto \
  --python_out="$TMP" \
  proto/envoy/config/core/v3/base.proto \
  proto/envoy/type/v3/http_status.proto \
  proto/envoy/service/ext_proc/v3/external_processor.proto \
  proto/grpc/health/v1/health.proto

PKG=llm_instance_gateway_tpu.gateway.extproc
cp "$TMP"/envoy/config/core/v3/base_pb2.py envoy_base_pb2.py
cp "$TMP"/envoy/type/v3/http_status_pb2.py envoy_http_status_pb2.py
cp "$TMP"/envoy/service/ext_proc/v3/external_processor_pb2.py ext_proc_v3_pb2.py
cp "$TMP"/grpc/health/v1/health_pb2.py health_v1_pb2.py

sed -i \
  -e "s/^from envoy\.config\.core\.v3 import base_pb2/from $PKG import envoy_base_pb2/" \
  -e "s/^from envoy\.type\.v3 import http_status_pb2/from $PKG import envoy_http_status_pb2/" \
  ext_proc_v3_pb2.py

echo "regenerated: envoy_base_pb2.py envoy_http_status_pb2.py ext_proc_v3_pb2.py health_v1_pb2.py"
