"""gRPC ext-proc gateway entrypoint (the Envoy-sidecar deployment mode).

Parity with the reference EPP binary (``pkg/ext-proc/main.go:59-158``): serve
the ExternalProcessor + Health gRPC services over the same
datastore/provider/scheduler assembly the standalone proxy uses.

Run:  python -m llm_instance_gateway_tpu.gateway.extproc \
        --config pool.yaml --port 9002 --discover-dns my-pool --probe-endpoints
"""

from __future__ import annotations

import argparse
import logging
import os
import signal
import threading

from llm_instance_gateway_tpu.gateway import bootstrap
from llm_instance_gateway_tpu.gateway.extproc.service import build_grpc_server

logger = logging.getLogger(__name__)


def main(argv: list[str] | None = None) -> None:
    parser = argparse.ArgumentParser(description="TPU-native ext-proc endpoint picker")
    parser.add_argument("--port", type=int, default=9002)  # main.go:33 default
    parser.add_argument("--grpc-workers", type=int, default=16)
    bootstrap.add_common_args(parser)
    bootstrap.add_fairness_args(parser)
    args = parser.parse_args(argv)

    comps = bootstrap.components_from_args(args)
    stop = threading.Event()
    # Fairness/quota plane (gateway/fairness.py): the admit() gate lives in
    # the handler core this transport shares with the HTTP proxy, but the
    # proxy's observability loop isn't running here — build the usage
    # rollup + policy and tick them on a daemon thread, or a pool
    # document's fairnessPolicy section would parse and then sit dead.
    from llm_instance_gateway_tpu.gateway import fairness as fairness_mod
    from llm_instance_gateway_tpu.gateway import usage as usage_mod

    rollup = usage_mod.UsageRollup(comps.provider)
    fairness = fairness_mod.FairnessPolicy(
        rollup, cfg=getattr(comps.scheduler.cfg, "fairness", None),
        provider=comps.provider,
        cli_overrides=bootstrap.fairness_from_args(args))
    if hasattr(comps.handler_server, "fairness"):
        comps.handler_server.fairness = fairness
    elif fairness.mode != fairness_mod.LOG_ONLY:
        # Multi-pool front: no fairness seams on the wrapper (per-pool
        # wiring is future work) — refuse to leave the config silently
        # dead.
        logger.warning(
            "fairness mode=%s configured but %s has no fairness seams — "
            "enforcement is INACTIVE (single-pool deployments only)",
            fairness.mode, type(comps.handler_server).__name__)
    inner = getattr(comps.scheduler, "_scheduler", comps.scheduler)
    if hasattr(inner, "usage_advisor"):
        inner.usage_advisor = fairness  # pick deprioritization seam
    if hasattr(comps.scheduler, "fairness"):
        comps.scheduler.fairness = fairness  # pool-doc hot-reload push
    tick_s = float(os.environ.get("LIG_SLO_TICK_S", "5"))

    def _fairness_tick() -> None:
        while not stop.wait(tick_s):
            try:
                rollup.tick()
                fairness.tick()
            except Exception:
                logger.exception("usage/fairness tick failed")

    threading.Thread(target=_fairness_tick, daemon=True,
                     name="lig-fairness-tick").start()
    # Admission queueing parks requests ON their handler threads (bounded by
    # maxDepth x maxWaitSeconds); the worker pool must cover the full parked
    # depth on top of the active-stream workers, or parked non-critical
    # traffic starves Critical requests at the transport.  The controller is
    # ALSO told the transport's park budget, so a hot-reload that enables
    # (or deepens) the queue later can never park more waiters than the
    # already-sized pool absorbs — half the base workers stay free for
    # non-parked traffic no matter what the pool document says.
    workers = args.grpc_workers
    admission = comps.scheduler.cfg.admission
    if admission.enabled:
        workers = args.grpc_workers + admission.max_depth
        logger.info(
            "admission queue enabled: gRPC workers %d -> %d "
            "(+maxDepth)", args.grpc_workers, workers)
    comps.scheduler.set_park_budget(workers - max(4, args.grpc_workers // 2))
    server = build_grpc_server(
        comps.handler_server, comps.datastore,
        port=args.port, max_workers=workers,
    )
    server.start()
    logger.info("ext-proc gRPC server listening on :%d", args.port)

    for sig in (signal.SIGTERM, signal.SIGINT):  # main.go SIGTERM handling
        signal.signal(sig, lambda *a: stop.set())
    try:
        stop.wait()
    finally:
        server.stop(grace=5).wait(10)
        comps.stop()


if __name__ == "__main__":
    main()
