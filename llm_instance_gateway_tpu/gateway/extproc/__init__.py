"""gRPC ext-proc transport: wire proto + streaming service."""
