"""Distributed execution: device meshes, GSPMD shardings, ring attention.

The reference's only parallelism is request-level DP across replica pods
(SURVEY.md §2.5); everything intra-model was delegated to vLLM.  This package
owns that layer for TPU: a named-axis mesh (data/fsdp/tensor/expert/sequence),
PartitionSpecs for every model family, XLA-collective-based sequence
parallelism (ring attention) for long context, and multi-host initialization
over ICI/DCN.
"""

from llm_instance_gateway_tpu.parallel.mesh import MeshConfig, make_mesh

__all__ = ["MeshConfig", "make_mesh"]
