"""Ring attention: sequence/context parallelism over the ``sequence`` mesh axis.

Long-context prefill can exceed one device's HBM and FLOP budget; ring
attention shards the sequence across devices and rotates K/V blocks around
the ring with ``ppermute`` (ICI neighbor exchanges — the cheapest collective
pattern on a TPU torus), accumulating attention with the online-softmax
recurrence so no device ever materializes the full [S, S] score matrix.

Causality is enforced with *global* positions reconstructed from
``axis_index``: block b of the ring holds tokens [b*S_loc, (b+1)*S_loc), so
a device can mask exactly which rotated keys its queries may attend to —
no wasted compute is skipped (each step still runs; skipping would need
data-dependent control flow that XLA can't pipeline), but masked blocks
contribute zeros through the softmax correction.

Reference pattern: Liu et al., "Ring Attention with Blockwise Transformers"
(PAPERS.md retrieval); implementation is shard_map + lax.fori_loop +
ppermute, fully jittable and differentiable.
"""

from __future__ import annotations

import functools
from typing import Sequence

import jax
import jax.numpy as jnp
from jax import shard_map
from jax.sharding import Mesh, PartitionSpec as P

NEG_INF = -1e30


def _ring_attention_local(
    q: jax.Array,  # [B, S_loc, H, hd] (this device's query block)
    k: jax.Array,  # [B, S_loc, K, hd]
    v: jax.Array,  # [B, S_loc, K, hd]
    *,
    axis_name: str,
    axis_size: int,
    causal: bool,
    varying_axes: tuple[str, ...] = (),
) -> jax.Array:
    b, s_loc, n_heads, hd = q.shape
    n_kv = k.shape[2]
    g = n_heads // n_kv
    qg = q.reshape(b, s_loc, n_kv, g, hd)
    scale = 1.0 / jnp.sqrt(hd).astype(jnp.float32)

    my_idx = jax.lax.axis_index(axis_name)
    local_pos = jnp.arange(s_loc)
    q_pos = my_idx * s_loc + local_pos  # global positions of my queries

    # Online-softmax accumulators (f32).  They start as constants but the
    # loop body mixes in device-varying data, so mark them varying over the
    # manual axes up front or the fori_loop carry types won't match (JAX
    # varying-axes typing for shard_map).
    m = jnp.full((b, n_kv, g, s_loc), NEG_INF, jnp.float32)
    l = jnp.zeros((b, n_kv, g, s_loc), jnp.float32)
    o = jnp.zeros((b, n_kv, g, s_loc, hd), jnp.float32)
    if varying_axes and hasattr(jax.lax, "pvary"):
        m, l, o = (jax.lax.pvary(x, varying_axes) for x in (m, l, o))

    perm = [(i, (i + 1) % axis_size) for i in range(axis_size)]

    def body(step, carry):
        m, l, o, k_cur, v_cur = carry
        # After `step` rotations I hold the block originally on (my_idx - step).
        src = (my_idx - step) % axis_size
        k_pos = src * s_loc + local_pos
        s = jnp.einsum(
            "bikgh,bjkh->bkgij", qg, k_cur, preferred_element_type=jnp.float32
        ) * scale  # [B,K,G,Sq,Sk]
        if causal:
            mask = q_pos[:, None] >= k_pos[None, :]  # [Sq, Sk] global causality
            s = jnp.where(mask[None, None, None], s, NEG_INF)
        m_new = jnp.maximum(m, s.max(axis=-1))
        p = jnp.exp(s - m_new[..., None])
        corr = jnp.exp(m - m_new)
        l = l * corr + p.sum(axis=-1)
        o = o * corr[..., None] + jnp.einsum(
            "bkgij,bjkh->bkgih", p, v_cur.astype(jnp.float32)
        )
        k_nxt = jax.lax.ppermute(k_cur, axis_name, perm)
        v_nxt = jax.lax.ppermute(v_cur, axis_name, perm)
        return m_new, l, o, k_nxt, v_nxt

    m, l, o, _, _ = jax.lax.fori_loop(0, axis_size, body, (m, l, o, k, v))
    out = o / jnp.maximum(l[..., None], 1e-30)
    # [B,K,G,S,hd] -> [B,S,H,hd]
    out = out.transpose(0, 3, 1, 2, 4).reshape(b, s_loc, n_heads, hd)
    return out.astype(q.dtype)


def ring_attention(
    q: jax.Array,  # [B, S, H, hd] globally, S sharded over "sequence"
    k: jax.Array,
    v: jax.Array,
    mesh: Mesh,
    causal: bool = True,
    axis_name: str = "sequence",
    batch_axes: Sequence[str] = ("data",),
) -> jax.Array:
    """Sequence-parallel attention over a named mesh axis (jit-compatible)."""
    axis_size = mesh.shape[axis_name]
    spec = P(tuple(batch_axes), axis_name, None, None)
    fn = shard_map(
        functools.partial(
            _ring_attention_local,
            axis_name=axis_name,
            axis_size=axis_size,
            causal=causal,
            varying_axes=tuple(batch_axes) + (axis_name,),
        ),
        mesh=mesh,
        in_specs=(spec, spec, spec),
        out_specs=spec,
    )
    return fn(q, k, v)
