"""Long-context prefill: the full model forward with ring attention.

For prompts beyond one device's HBM/FLOP budget, the sequence axis shards
across the mesh: activations are [B, S/seq_shards, ...] per device, MLP and
projections are embarrassingly parallel in S, and attention rotates K/V
blocks around the ring (``parallel.ring_attention``).  This is the
"long-context is a model-server concern" half of SURVEY.md §5 — the gateway
half (token-aware routing on KV headroom) already exists in the scheduler.

Usage:
    fn = make_sharded_prefill(cfg, mesh)
    logits, k, v = fn(params, tokens, positions)   # jitted, sharded

Constraints: right-padded batches (ring attention is causal-only), sequence
length divisible by the mesh's ``sequence`` axis.  The returned prompt KV is
sharded over sequence too — for serving, ``gather_kv`` pulls it together for
insertion into a replicated decode cache (decode itself is latency-bound and
runs data/tensor-parallel, not sequence-parallel).
"""

from __future__ import annotations

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from llm_instance_gateway_tpu.models import transformer
from llm_instance_gateway_tpu.models.configs import ModelConfig
from llm_instance_gateway_tpu.parallel.ring_attention import ring_attention


def make_sharded_prefill(cfg: ModelConfig, mesh: Mesh):
    """Jitted sequence-parallel prefill over ``mesh``."""

    def attention_fn(q, k, v, positions):
        # positions are unused: ring attention reconstructs global causality
        # from block indices (right-padded batches only).
        return ring_attention(q, k, v, mesh)

    def fn(params, tokens, positions, lora_bufs=None, slot_ids=None):
        return transformer.prefill(
            cfg, params, tokens, positions,
            lora_bufs=lora_bufs, slot_ids=slot_ids,
            attention_fn=attention_fn,
        )

    # Inputs arrive pre-sharded (shard_inputs / sharding.shard_pytree); jit
    # reads their placements, so no in_shardings pytree is needed here.
    return jax.jit(fn)


def shard_inputs(mesh: Mesh, tokens, positions):
    s = NamedSharding(mesh, P("data", "sequence"))
    return jax.device_put(tokens, s), jax.device_put(positions, s)


def gather_kv(k, v):
    """Materialize sequence-sharded prompt KV contiguously (for cache insert)."""
    return jax.device_get(k), jax.device_get(v)
