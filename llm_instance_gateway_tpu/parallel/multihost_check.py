"""Two-process serving check: the Engine decodes over a mesh spanning hosts.

The reference's unit of scheduling is a pod; the SURVEY maps that to a
slice-backed replica where one model server spans multiple HOSTS (a v5e-16
slice is 4 hosts x 4 chips — SURVEY §2.5).  `tests/test_multihost.py` proved
two OS processes can TRAIN over one mesh; serving is harder because the
engine is a host-driven loop: every process must issue the identical
sequence of jitted calls (multi-controller SPMD), and every host-read value
must be fully replicated.

This check runs the REAL `server.engine.Engine` in two coordinated
processes over a `tensor=8` mesh (4 virtual CPU devices per process — the
tensor axis, and with it every per-layer attention/MLP psum, crosses the
process boundary exactly where DCN sits on a multi-host slice):

- determinism: all requests are submitted BEFORE `start()`, slots >=
  requests, equal budgets, greedy sampling, a fixed engine seed — so both
  loops admit, prefill, and decode in lockstep with no timing-dependent
  branch;
- replication: with no `data` axis the batch dimension is unsharded, so
  sampled tokens (and the prefill's first token) come back fully
  replicated and `np.asarray` on them is legal in every process.

Used by `tests/test_multihost.py` (serving parity assertion) and
`__graft_entry__.dryrun_multichip` (the driver's multi-chip certification,
which reports the multi-host serve result in its tail line).
"""

from __future__ import annotations

import os
import socket
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

SERVE_WORKER = r"""
import os, sys
import jax
jax.config.update("jax_platforms", "cpu")
sys.path.insert(0, os.environ["GRAFT_REPO"])

from llm_instance_gateway_tpu.parallel.mesh import (
    MeshConfig, initialize_distributed, make_mesh,
)

initialize_distributed()
assert jax.process_count() == 2, jax.process_count()
assert jax.device_count() == 8, jax.device_count()

import dataclasses
import jax.numpy as jnp

from llm_instance_gateway_tpu.models import transformer
from llm_instance_gateway_tpu.models.configs import LLAMA3_8B
from llm_instance_gateway_tpu.server.engine import (
    Engine, EngineConfig, Request, SamplingParams,
)

cfg = dataclasses.replace(
    LLAMA3_8B, name="multihost-serve", vocab_size=256, d_model=64,
    n_layers=2, n_heads=8, n_kv_heads=8, d_ff=128, head_dim=8,
    max_seq_len=64, use_flash_attention=False, use_pallas_decode=False,
)
mesh = make_mesh(MeshConfig(tensor=8))
params = transformer.init_params(cfg, jax.random.PRNGKey(0), dtype=jnp.float32)
engine = Engine(
    cfg, params,
    EngineConfig(decode_slots=2, max_seq_len=64, prefill_buckets=(16,)),
    eos_id=None, dtype=jnp.float32, seed=0, mesh=mesh,
)
reqs = [
    Request(prompt_tokens=[5, 6, 7], max_new_tokens=6,
            sampling=SamplingParams(temperature=0.0)),
    Request(prompt_tokens=[9, 10, 11, 12], max_new_tokens=6,
            sampling=SamplingParams(temperature=0.0)),
]
# Submit BEFORE start: both processes' loops see the same full queue on
# their first admission pass — no timing-dependent divergence.
for r in reqs:
    engine.submit(r)
engine.start()
try:
    for r in reqs:
        assert r.done.wait(240), "request hung"
        assert r.error is None, r.error
finally:
    engine.stop()
toks = ";".join(",".join(map(str, r.output_tokens)) for r in reqs)
print(f"MULTIHOST SERVE OK pid={jax.process_index()} tokens={toks}",
      flush=True)
"""


def free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def run_two_process(worker_src: str, n_local: int = 4,
                    timeout_s: float = 300.0) -> list[str]:
    """Launch ``worker_src`` in 2 coordinated processes (``n_local``
    virtual CPU devices each) under the env contract the GKE manifests set
    (TPU_GATEWAY_COORDINATOR/_PROCESS_ID/_NUM_PROCESSES).  Returns both
    processes' combined stdout/stderr; raises RuntimeError on a non-zero
    exit.  The single launch scaffold for every two-process check (train
    and serve) — the coordination contract lives here only."""
    import tempfile
    import time

    port = free_port()
    procs = []
    files = []
    timed_out = False
    try:
        for pid in (0, 1):
            env = dict(os.environ)
            env.pop("JAX_PLATFORMS", None)
            env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
            env["GRAFT_REPO"] = REPO
            env["XLA_FLAGS"] = (
                f"--xla_force_host_platform_device_count={n_local}")
            env["TPU_GATEWAY_COORDINATOR"] = f"127.0.0.1:{port}"
            env["TPU_GATEWAY_PROCESS_ID"] = str(pid)
            env["TPU_GATEWAY_NUM_PROCESSES"] = "2"
            # Temp FILES, not pipes: a worker blocked writing a full 64KiB
            # pipe while its peer waits in a cross-process collective would
            # deadlock the pair (nobody drains until communicate()).
            f = tempfile.TemporaryFile(mode="w+", encoding="utf-8",
                                       errors="replace")
            files.append(f)
            procs.append(subprocess.Popen(
                [sys.executable, "-c", worker_src], env=env,
                stdout=f, stderr=subprocess.STDOUT, text=True,
            ))
        deadline = time.monotonic() + timeout_s
        for p in procs:
            remaining = deadline - time.monotonic()
            try:
                p.wait(timeout=max(0.1, remaining))
            except subprocess.TimeoutExpired:
                timed_out = True
                break
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
        for p in procs:
            try:
                p.wait(timeout=10)
            except subprocess.TimeoutExpired:
                pass
        outs = []
        for f in files:
            f.seek(0)
            outs.append(f.read())
            f.close()
    if timed_out:
        raise RuntimeError(
            "two-process worker timed out:\n"
            + "\n---\n".join(o[-2000:] for o in outs))
    for p, out in zip(procs, outs):
        if p.returncode != 0:
            raise RuntimeError(f"two-process worker failed:\n{out[-3000:]}")
    return outs


def run_two_process_serve(timeout_s: float = 300.0) -> list[str]:
    """Serving check: returns the per-process token strings (len 2) — the
    caller asserts they match.  Raises RuntimeError on any failure."""
    outs = run_two_process(SERVE_WORKER, timeout_s=timeout_s)
    tokens = []
    for out in outs:
        ok = [l for l in out.splitlines() if l.startswith("MULTIHOST SERVE OK")]
        if not ok:
            raise RuntimeError(f"no OK line:\n{out[-3000:]}")
        tokens.append(ok[0].rsplit("tokens=", 1)[1])
    return tokens
