"""PartitionSpecs for model params, KV caches, LoRA buffers, and activations.

The GSPMD recipe (scaling-book style): annotate shardings on the jit
boundary, let XLA insert the collectives.  Megatron-style tensor parallelism
for the decoder: column-shard the up-projections (heads / ffn columns),
row-shard the down-projections, so each layer needs exactly one
reduce(-scatter) on the attention output and one on the MLP output — both
riding ICI.

Weights additionally shard over ``fsdp`` on their non-tensor dim (zero-cost
when fsdp=1).  KV caches shard heads over ``tensor`` and batch over ``data``.
LoRA buffers shard ``b`` (rank -> d_out) over ``tensor`` on d_out and keep
``a`` replicated (rank dims are tiny); the delta then composes with the
column-sharded base projection without extra collectives.
"""

from __future__ import annotations

from typing import Any

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from llm_instance_gateway_tpu.models import lora as lora_lib
from llm_instance_gateway_tpu.models.configs import ModelConfig


def param_specs(cfg: ModelConfig) -> dict[str, Any]:
    """PartitionSpec pytree matching ``transformer.init_params`` layout."""
    layers: dict[str, Any] = {
        "attn_norm": P(None, None),
        "mlp_norm": P(None, None),
        # [L, D, H*hd]: column-shard heads over tensor, D over fsdp.
        "wq": P(None, "fsdp", "tensor"),
        "wk": P(None, "fsdp", "tensor"),
        "wv": P(None, "fsdp", "tensor"),
        # [L, H*hd, D]: row-shard (same tensor axis contracts away).
        "wo": P(None, "tensor", "fsdp"),
    }
    if cfg.attention_bias:
        # [L, H*hd] biases shard with their projection's output columns.
        layers["wq_b"] = P(None, "tensor")
        layers["wk_b"] = P(None, "tensor")
        layers["wv_b"] = P(None, "tensor")
    if cfg.n_experts:
        layers.update(
            {
                "router": P(None, None, None),
                # [L, E, D, F]: experts over expert axis, ffn over tensor.
                "w_gate": P(None, "expert", "fsdp", "tensor"),
                "w_up": P(None, "expert", "fsdp", "tensor"),
                "w_down": P(None, "expert", "tensor", "fsdp"),
            }
        )
    else:
        layers.update(
            {
                "w_gate": P(None, "fsdp", "tensor"),
                "w_up": P(None, "fsdp", "tensor"),
                "w_down": P(None, "tensor", "fsdp"),
            }
        )
    specs: dict[str, Any] = {
        # [V, D]: vocab over tensor (embedding lookups all-gather a slice;
        # the final projection contracts D and psums over tensor).
        "embed": P("tensor", None),
        "layers": layers,
        "final_norm": P(None),
    }
    if not cfg.tie_embeddings:
        specs["lm_head"] = P("fsdp", "tensor")
    return specs


def cache_specs(cfg: ModelConfig | None = None, mesh: Mesh | None = None,
                quantized: bool = False) -> dict[str, Any]:
    """Decode cache [L, B, S, K, hd]: batch over data, KV heads over tensor.

    MQA/GQA caches whose kv-head count doesn't divide the tensor axis (e.g.
    Gemma-2B's single KV head on a tensor=4 mesh) replicate the head dim —
    the attention einsums then read the replicated cache and XLA partitions
    on the query heads instead.  ``quantized`` adds the int8 cache's
    per-(position, kv-head) scale arrays, sharded like K/V minus head_dim.
    """
    head_axis: str | None = "tensor"
    if cfg is not None and mesh is not None:
        if cfg.n_kv_heads % mesh.shape["tensor"] != 0:
            head_axis = None
    kv = P(None, "data", None, head_axis, None)
    specs = {"k": kv, "v": kv, "length": P("data")}
    if quantized:
        specs["k_scale"] = specs["v_scale"] = P(None, "data", None, head_axis)
    return specs


def paged_cache_specs(cfg: ModelConfig | None = None,
                      mesh: Mesh | None = None,
                      quantized: bool = False) -> dict[str, Any]:
    """Paged pool [L, n_blocks, block, Kh, hd]: KV heads over tensor (the
    Megatron split — attention reads stay shard-local, the psum lives in
    wo), everything else replicated.  The block-pool dim belongs to no mesh
    axis: rows of one pool serve whichever requests the host allocator
    assigns, so the batch/data axis must be 1 (tensor-parallel paged
    serving — the big-model case; data-parallel replicas are separate
    engine processes, which is how the gateway scales them anyway).
    ``quantized`` adds the int8 pool's scale arrays, sharded like K/V
    minus head_dim."""
    head_axis: str | None = "tensor"
    if cfg is not None and mesh is not None:
        if cfg.n_kv_heads % mesh.shape["tensor"] != 0:
            head_axis = None
    kv = P(None, None, None, head_axis, None)
    specs = {"k": kv, "v": kv, "tables": P(), "length": P()}
    if quantized:
        specs["k_scale"] = specs["v_scale"] = P(None, None, None, head_axis)
    return specs


def lora_specs(cfg: ModelConfig) -> dict[str, Any]:
    specs: dict[str, Any] = {"scale": P(None)}
    for t in lora_lib.TARGETS:
        # a: [L, S, d_in, r] replicated (tiny); b: [L, S, r, d_out] column-
        # sharded to match the base projection's output sharding.
        specs[f"{t}_a"] = P(None, None, None, None)
        specs[f"{t}_b"] = P(None, None, None, "tensor")
    # Row-sharded targets contract d_out == D over fsdp instead.
    specs["o_b"] = P(None, None, None, "fsdp")
    specs["down_b"] = P(None, None, None, "fsdp")
    return specs


def activation_specs() -> dict[str, Any]:
    return {
        "tokens_2d": P("data", "sequence"),   # [B, S]
        "tokens_1d": P("data"),               # [B]
        "logits_prefill": P("data", "sequence", "tensor"),
        "logits_decode": P("data", "tensor"),
    }


def shard_pytree(tree: Any, specs: Any, mesh: Mesh) -> Any:
    """device_put a pytree with NamedShardings from a matching spec pytree.

    Weight-only-int8 leaves (``ops.quant`` ``{"q", "s"}`` dicts) carry ONE
    spec for the original dense array: ``q`` takes it verbatim and the
    per-output-channel scale takes the spec minus its contracted
    (second-to-last) axis — so ``--quantize int8`` composes with serve
    meshes for dense AND expert-stack weights."""
    from llm_instance_gateway_tpu.ops.quant import is_quantized

    def place(x, s):
        if is_quantized(x):
            axes = tuple(s)
            scale_spec = P(*(axes[:-2] + axes[-1:])) if len(axes) >= 2 else s
            return {
                "q": jax.device_put(x["q"], NamedSharding(mesh, s)),
                "s": jax.device_put(x["s"],
                                    NamedSharding(mesh, scale_spec)),
            }
        return jax.device_put(x, NamedSharding(mesh, s))

    return jax.tree.map(place, tree, specs, is_leaf=is_quantized)


def named(mesh: Mesh, spec: P) -> NamedSharding:
    return NamedSharding(mesh, spec)
