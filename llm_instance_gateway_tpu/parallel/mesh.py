"""Device mesh construction with named axes.

Axis vocabulary (the scaling-book convention, sized per pool topology):

- ``data``     request/batch data parallelism (maps across slices/DCN)
- ``fsdp``     parameter sharding for training / large models (ICI)
- ``pipe``     pipeline parallelism over layer-stack stages (DCN-tolerant:
               one activation transfer per microbatch per step)
- ``tensor``   tensor parallelism inside a layer: heads / ffn columns (ICI)
- ``expert``   MoE expert parallelism (Mixtral pools)
- ``sequence`` context parallelism for long sequences (ring attention, ICI)

Axes of size 1 cost nothing — every jitted function is written against the
full six-axis mesh, and a v5e-8 pool simply instantiates e.g.
``{"data": 1, "fsdp": 1, "pipe": 1, "tensor": 8, "expert": 1, "sequence": 1}``.

Multi-host: ``initialize_distributed()`` wires ``jax.distributed`` from env
vars (GKE TPU pod env or explicit addresses), after which ``make_mesh`` sees
all hosts' devices — the DCN/ICI split is expressed by putting ``data``
outermost (DCN-friendly collectives) and the ICI-bound axes innermost,
mirroring how ``mesh_utils.create_device_mesh`` orders physical links.
"""

from __future__ import annotations

import logging
import os
from dataclasses import dataclass

import jax
import numpy as np
from jax.experimental import mesh_utils
from jax.sharding import Mesh

logger = logging.getLogger(__name__)

AXES = ("data", "fsdp", "pipe", "tensor", "expert", "sequence")


@dataclass(frozen=True)
class MeshConfig:
    data: int = 1
    fsdp: int = 1
    pipe: int = 1
    tensor: int = 1
    expert: int = 1
    sequence: int = 1

    @property
    def shape(self) -> tuple[int, ...]:
        return (self.data, self.fsdp, self.pipe, self.tensor, self.expert,
                self.sequence)

    @property
    def total(self) -> int:
        return int(np.prod(self.shape))

    @staticmethod
    def for_devices(n: int, tensor: int | None = None, sequence: int = 1,
                    expert: int = 1) -> "MeshConfig":
        """Sensible inference default: fill ``tensor`` with what's left."""
        if tensor is None:
            tensor = max(1, n // (sequence * expert))
        data = n // (tensor * sequence * expert)
        return MeshConfig(data=data, tensor=tensor, expert=expert, sequence=sequence)


def make_mesh(cfg: MeshConfig, devices=None) -> Mesh:
    devices = devices if devices is not None else jax.devices()
    if cfg.total != len(devices):
        raise ValueError(
            f"mesh shape {cfg.shape} needs {cfg.total} devices, have {len(devices)}"
        )
    try:
        dev_array = mesh_utils.create_device_mesh(cfg.shape, devices=devices)
    except (ValueError, AssertionError):
        # Virtual/CPU devices without topology info: plain reshape.
        dev_array = np.asarray(devices).reshape(cfg.shape)
    return Mesh(dev_array, AXES)


def single_device_mesh() -> Mesh:
    return make_mesh(MeshConfig(), devices=jax.devices()[:1])


def initialize_distributed() -> None:
    """Multi-host init from environment (idempotent, no-op single-host).

    GKE TPU pods inject coordinator/process env; explicit override via
    ``TPU_GATEWAY_COORDINATOR`` / ``TPU_GATEWAY_PROCESS_ID`` /
    ``TPU_GATEWAY_NUM_PROCESSES`` for bare-metal DCN clusters.
    """
    coord = os.environ.get("TPU_GATEWAY_COORDINATOR")
    # TPU_WORKER_HOSTNAMES with a single entry is a one-host slice (some
    # single-chip images set it to "localhost") — multi-host init there
    # either fails or hangs waiting for peers.
    hosts = [h for h in os.environ.get("TPU_WORKER_HOSTNAMES", "").split(",") if h]
    multi_host_env = bool(
        os.environ.get("MEGASCALE_COORDINATOR_ADDRESS")) or len(hosts) > 1
    if jax.distributed.is_initialized():
        return  # idempotent
    # Genuine multi-host init failures (unreachable coordinator, peer
    # timeout) propagate: serving on a partial world is worse than a
    # crash-and-restart.
    if coord:
        jax.distributed.initialize(
            coordinator_address=coord,
            num_processes=int(os.environ["TPU_GATEWAY_NUM_PROCESSES"]),
            process_id=int(os.environ["TPU_GATEWAY_PROCESS_ID"]),
        )
        logger.info(
            "jax.distributed initialized: process %s/%s via %s",
            os.environ["TPU_GATEWAY_PROCESS_ID"],
            os.environ["TPU_GATEWAY_NUM_PROCESSES"], coord,
        )
    elif multi_host_env:
        jax.distributed.initialize()  # GKE/TPU-pod auto-config
        logger.info("jax.distributed initialized from TPU pod environment")
