"""SPMD pipeline parallelism over the ``pipe`` mesh axis.

The reference scales out by adding vLLM replicas behind the gateway; models
that outgrow one replica's memory are out of scope there.  Here the model
server owns the chips, so when a model outgrows ``tensor``+``fsdp`` on one
ICI domain the layer stack itself must span domains — pipeline parallelism
(SURVEY.md §2.5 maps this to the pp axis of the serving mesh).

TPU-first formulation — a *collective* pipeline, not a multi-controller one:

- The stacked layer params ``[L, ...]`` are reshaped to ``[pp, L/pp, ...]``
  and sharded ``P("pipe", ...)``: stage ``i``'s slice lives on the devices
  whose ``pipe`` coordinate is ``i``.
- The batch is split into M microbatches.  A rotation buffer of shape
  ``[pp, mb, S, D]`` (axis 0 sharded over ``pipe``) holds the activation
  each stage is working on.  One ``lax.scan`` step = every stage applies
  its L/pp layers to its slot (a ``vmap`` over the stage axis that XLA
  partitions across ``pipe``), then the buffer rotates one stage forward —
  ``jnp.roll`` on a pipe-sharded axis lowers to a single
  ``collective-permute`` riding ICI/DCN.
- GPipe schedule: microbatch j enters at step j, exits at step j + pp - 1;
  total steps M + pp - 1, bubble fraction (pp-1)/(M+pp-1).

Everything is one jitted program: XLA sees the whole schedule, overlaps the
permute with the next stage's compute, and the backward pass falls out of
differentiating the scan — no hand-written send/recv state machine, which
is how a CUDA framework would build this.

The per-layer math is ``transformer.prefill_layer`` — the same block the
non-pipelined forward scans, so parity is structural, not re-implemented.
"""

from __future__ import annotations

from typing import Any

import jax
import jax.numpy as jnp
import optax
from jax.sharding import Mesh, PartitionSpec as P

from llm_instance_gateway_tpu.models import transformer
from llm_instance_gateway_tpu.models.configs import ModelConfig
from llm_instance_gateway_tpu.ops.layers import rms_norm
from llm_instance_gateway_tpu.ops.quant import matmul as q_matmul

Params = dict[str, Any]


def stage_params(cfg: ModelConfig, params: Params, pipe: int) -> Params:
    """Reshape stacked layer leaves [L, ...] -> [pp, L/pp, ...].

    Stage i holds layers [i*L/pp, (i+1)*L/pp) — contiguous assignment, the
    standard pipeline layout.  Non-layer params (embed, final_norm, lm_head)
    pass through; they run outside the pipelined region.
    """
    if cfg.n_layers % pipe != 0:
        raise ValueError(
            f"n_layers={cfg.n_layers} not divisible by pipe={pipe}")
    per = cfg.n_layers // pipe
    staged = jax.tree.map(
        lambda x: x.reshape((pipe, per) + x.shape[1:]), params["layers"])
    return {**params, "layers": staged}


def stage_param_specs(cfg: ModelConfig, base_specs: dict) -> dict:
    """PartitionSpecs for the staged layout: prepend ``pipe`` on the stage
    axis of every layer leaf (the L axis of ``sharding.param_specs`` is
    unsharded, so the staged spec is P("pipe", None, *rest))."""
    staged = jax.tree.map(
        lambda s: P("pipe", *s), base_specs["layers"],
        is_leaf=lambda s: isinstance(s, P))
    return {**base_specs, "layers": staged}


def pipeline_forward(
    cfg: ModelConfig,
    params: Params,          # staged: layers leaves [pp, L/pp, ...]
    tokens: jax.Array,       # [B, S] int32
    positions: jax.Array,    # [B, S] int32
    pipe: int,
    n_microbatches: int,
    mesh: Mesh | None = None,
) -> jax.Array:
    """Pipelined full-prompt forward.  Returns logits [B, S, V] f32.

    B must divide into ``n_microbatches`` equal microbatches; with
    ``pipe == 1`` this degenerates to the plain layer scan (one stage, no
    rotation) and matches ``transformer.prefill`` logits exactly.
    """
    b, s = tokens.shape
    m = n_microbatches
    if b % m != 0:
        raise ValueError(f"batch {b} not divisible by n_microbatches={m}")
    mb = b // m

    h = params["embed"][tokens]
    if cfg.embedding_scale:
        h = h * jnp.sqrt(cfg.d_model).astype(h.dtype)
    d = h.shape[-1]

    # Microbatch stream, padded with pp-1 drain steps.
    h_mb = h.reshape(m, mb, s, d)
    pos_mb = positions.reshape(m, mb, s)
    pad_h = jnp.zeros((pipe - 1, mb, s, d), h.dtype)
    pad_pos = jnp.zeros((pipe - 1, mb, s), positions.dtype)
    xs_h = jnp.concatenate([h_mb, pad_h], axis=0)
    xs_pos = jnp.concatenate([pos_mb, pad_pos], axis=0)

    def stage_apply(stage_layers, h_one, pos_one):
        def body(h_c, lp):
            h_c, _ = transformer.prefill_layer(cfg, lp, h_c, pos_one)
            return h_c, None

        out, _ = jax.lax.scan(body, h_one, stage_layers)
        return out

    if mesh is None:
        pin = lambda x: x
    else:
        pin = lambda x: jax.lax.with_sharding_constraint(
            x, jax.sharding.NamedSharding(
                mesh, P("pipe", "data", *([None] * (x.ndim - 2)))))

    def step(carry, xs):
        buf_h, buf_pos = carry
        in_h, in_pos = xs
        # Fresh microbatch enters stage 0; stages 1..pp-1 keep what the
        # rotation delivered last step.
        buf_h = pin(buf_h.at[0].set(in_h))
        buf_pos = buf_pos.at[0].set(in_pos)
        out = pin(jax.vmap(stage_apply)(params["layers"], buf_h, buf_pos))
        # Microbatch finishing the last stage exits this step.
        y = (out[pipe - 1], buf_pos[pipe - 1])
        # Rotate stage i -> i+1 (a collective-permute over ``pipe``); the
        # wrapped-around slot 0 is dead and overwritten next step.
        buf_h = pin(jnp.roll(out, 1, axis=0))
        buf_pos = jnp.roll(buf_pos, 1, axis=0)
        return (buf_h, buf_pos), y

    buf0 = (
        pin(jnp.zeros((pipe, mb, s, d), h.dtype)),
        jnp.zeros((pipe, mb, s), positions.dtype),
    )
    _, (ys_h, _) = jax.lax.scan(step, buf0, (xs_h, xs_pos))

    # Microbatch j exits at step j + pp - 1: drop the pp-1 warm-up outputs.
    h_out = ys_h[pipe - 1:].reshape(b, s, d)

    h_out = rms_norm(h_out, params["final_norm"], cfg.norm_eps,
                     plus_one=cfg.norm_plus_one)
    head = params["embed"].T if cfg.tie_embeddings else params["lm_head"]
    return q_matmul(h_out, head).astype(jnp.float32)


def pipeline_lm_loss(cfg: ModelConfig, params: Params, tokens, positions,
                     pipe: int, n_microbatches: int,
                     mesh: Mesh | None = None) -> jax.Array:
    """``train.causal_lm_loss`` with the pipelined forward plugged in."""
    from llm_instance_gateway_tpu.training import train

    return train.causal_lm_loss(
        cfg, params, tokens, positions,
        logits_fn=lambda p, t, pos: pipeline_forward(
            cfg, p, t, pos, pipe, n_microbatches, mesh=mesh))


def make_pipeline_train_step(cfg: ModelConfig, optimizer, pipe: int,
                             n_microbatches: int, mesh: Mesh | None = None):
    """Full-parameter train step over staged params (caller jits + shards).

    Gradients flow through the scanned schedule — XLA derives the 1F1B-ish
    interleaving from the scan transpose; optimizer state mirrors the staged
    param tree.
    """

    def step(params, opt_state, tokens, positions):
        loss, grads = jax.value_and_grad(
            lambda p: pipeline_lm_loss(
                cfg, p, tokens, positions, pipe, n_microbatches, mesh=mesh)
        )(params)
        updates, opt_state = optimizer.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        return params, opt_state, loss

    return step
