"""Benchmark: multiplexed-LoRA serving throughput vs single-tenant baseline.

The BASELINE.json north star: route multiplexed LoRA'd InferenceModels at
>= 90% of single-tenant tokens/sec.  This bench measures exactly that ratio
on the real chip, through the real engine:

- Phase A (baseline): N greedy requests against the base model.
- Phase B (multiplexed): same workload round-robined across 4 resident LoRA
  adapters (rank 8) — per-row adapter deltas in every decode batch.

Prints ONE JSON line:
  {"metric": "multiplexed_lora_tokens_per_sec", "value": <tok/s>,
   "unit": "tok/s", "vs_baseline": <multiplexed / single-tenant>}
"""

from __future__ import annotations

import dataclasses
import json
import os
import sys
import threading
import time

_T0 = time.monotonic()
_REPO = os.path.dirname(os.path.abspath(__file__))
sys.path.insert(0, _REPO)

import jax

# Hermetic runs: the image's sitecustomize imports jax with the TPU platform
# already captured, so the JAX_PLATFORMS env var alone does NOT keep this
# process off the (possibly wedged) chip — pin the config directly, the same
# mechanism tests/conftest.py and __graft_entry__ uses.
if os.environ.get("JAX_PLATFORMS", "").strip() == "cpu":
    jax.config.update("jax_platforms", "cpu")

# Persistent compilation cache: in-round bench/sweep runs warm it, so the
# driver's end-of-round run (same shapes, same code) skips the 20-40s/program
# XLA compiles and fits comfortably inside the wall-clock governor below.
# TPU-only: XLA:CPU AOT cache entries are machine-feature-pinned and reload
# on a different host with a "could lead to SIGILL" warning — not a risk the
# hermetic fallback path should carry for a pure optimization.
if os.environ.get("JAX_PLATFORMS", "").strip() != "cpu":
    try:
        jax.config.update("jax_compilation_cache_dir",
                          os.path.join(_REPO, ".jax_cache"))
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
    except Exception:
        pass  # older jax: cache is an optimization, never a requirement

# ---------------------------------------------------------------------------
# Wall-clock governor.  BENCH_r03 was rc=124 with EMPTY output: the probe
# budget (40 min) exceeded the driver's own kill timeout, so the process died
# having printed nothing.  The driver's patience is unknown but bounded below
# by round 2's observed ~22 min of completed probing; this governor guarantees
# ONE JSON line on stdout strictly before a 19-minute deadline, whatever else
# happens: phases record partial results as they land, and a daemon watchdog
# prints best-available (or sentinel) JSON and exits if the main path hasn't.
# ---------------------------------------------------------------------------
TOTAL_BUDGET_S = float(os.environ.get("BENCH_TOTAL_BUDGET_S", "1140"))
# Conservative estimate of warm-cache claim->result time; the probe loop
# gets whatever the governor budget leaves after reserving this.
RUN_ESTIMATE_S = float(os.environ.get("BENCH_RUN_ESTIMATE_S", "420"))

_emit_lock = threading.Lock()
_emitted = False
_partial: dict = {}
# Device-independent metrics (the handoff/disaggregation phase) merged into
# EVERY emission path — sentinel errors included — so they land in the BENCH
# trajectory even when the TPU probe never succeeds.
_EXTRA: dict = {}


def _deadline() -> float:
    return _T0 + TOTAL_BUDGET_S


def _emit(result: dict, blocking: bool = True) -> bool:
    """Print the one JSON result line exactly once, process-wide.

    ``blocking=False`` is for the SIGTERM handler: it runs ON the main
    thread, so blocking on a lock the interrupted frame holds (mid-print
    inside _emit) would deadlock — if the lock is busy, an emit is already
    in flight and the handler can simply proceed to exit.
    """
    global _emitted
    if not _emit_lock.acquire(blocking=blocking):
        return False
    try:
        if _emitted:
            return False
        _emitted = True
        merged = dict(result)
        for k, v in _EXTRA.items():
            merged.setdefault(k, v)
        print(json.dumps(merged), flush=True)
        return True
    finally:
        _emit_lock.release()


def _emit_best_effort(note: str, blocking: bool = True) -> None:
    """Watchdog/SIGTERM path: emit whatever partial result exists."""
    if _partial.get("value"):
        _emit({**_partial, "truncated": note}, blocking=blocking)
    else:
        _emit({
            "metric": "multiplexed_lora_tokens_per_sec",
            "value": 0.0,
            "unit": "tok/s",
            "vs_baseline": 0.0,
            "error": note,
        }, blocking=blocking)


def _install_governor() -> None:
    def watch():
        remain = _deadline() - 15.0 - time.monotonic()
        if remain > 0:
            time.sleep(remain)
        if not _emitted:
            _emit_best_effort(
                f"governor deadline ({TOTAL_BUDGET_S:.0f}s) reached")
            # Hard exit: the main thread may be blocked inside PJRT where
            # no Python exception can reach it.
            os._exit(2)

    threading.Thread(target=watch, daemon=True).start()

import jax.numpy as jnp
import numpy as np


def bench_model_cfg():
    from llm_instance_gateway_tpu.models.configs import LLAMA3_8B

    if jax.default_backend() == "cpu":  # hermetic fallback: tiny shapes
        return dataclasses.replace(
            LLAMA3_8B, name="bench-cpu", vocab_size=512, d_model=128,
            n_layers=2, n_heads=4, n_kv_heads=2, d_ff=256, head_dim=32,
            max_seq_len=512, max_lora_slots=4, max_lora_rank=8,
        )
    # ~1.1B-param Llama-3-shaped model: fits v5e-1 HBM in bf16 with a
    # 16-slot x 512-token KV cache and 4 adapter slots.
    return dataclasses.replace(
        LLAMA3_8B, name="bench-llama-1b", vocab_size=32_000, d_model=2048,
        n_layers=16, n_heads=16, n_kv_heads=8, d_ff=8192, head_dim=128,
        max_seq_len=512, max_lora_slots=4, max_lora_rank=8,
        use_flash_attention=True,
    )


def make_adapter_weights(cfg, rank, seed):
    from llm_instance_gateway_tpu.models.lora import target_dims

    dims = target_dims(cfg)
    rng = np.random.RandomState(seed)
    return {
        t: {
            "a": (rng.randn(cfg.n_layers, dims[t][0], rank) * 0.01).astype(np.float32),
            "b": (rng.randn(cfg.n_layers, rank, dims[t][1]) * 0.01).astype(np.float32),
        }
        for t in ("q", "k", "v", "o")
    }


def run_phase(engine, n_requests, prompt_len, max_new, adapters, seed=0):
    from llm_instance_gateway_tpu.server.engine import Request, SamplingParams

    rng = np.random.RandomState(seed)
    reqs = []
    for i in range(n_requests):
        adapter = adapters[i % len(adapters)] if adapters else None
        reqs.append(
            Request(
                prompt_tokens=list(rng.randint(1, 250, size=prompt_len)),
                max_new_tokens=max_new,
                sampling=SamplingParams(temperature=0.0),
                adapter=adapter,
            )
        )
    t0 = time.perf_counter()
    for r in reqs:
        engine.submit(r)
    for r in reqs:
        if not r.done.wait(1800):
            raise RuntimeError("bench request timed out")
    wall = time.perf_counter() - t0
    tokens = sum(len(r.output_tokens) for r in reqs)
    ttfts = sorted(r.ttft_s for r in reqs)
    return {
        "tokens": tokens,
        "wall_s": wall,
        "tok_per_s": tokens / wall,
        "ttft_p50_ms": ttfts[len(ttfts) // 2] * 1e3,
        "ttft_p99_ms": ttfts[min(len(ttfts) - 1, int(0.99 * len(ttfts)))] * 1e3,
    }


def install_sigterm_cleanup() -> None:
    """Convert SIGTERM into SystemExit so ``finally: engine.stop()`` blocks
    run and the chip grant releases cleanly (round-2 verdict: end-of-round
    chip hygiene is a deliverable — a TERM-killed TPU process that skips
    cleanup can wedge the relay grant for the NEXT process for 10+ min).
    SIGKILL is unhandleable; this covers the common ``timeout``/driver path.
    """
    import signal

    def _term(signum, frame):
        # Non-blocking: the handler runs on the main thread and must not
        # wait on a lock an interrupted _emit frame is holding.
        _emit_best_effort("SIGTERM", blocking=False)
        raise SystemExit(143)

    try:
        signal.signal(signal.SIGTERM, _term)
    except ValueError:
        pass  # not the main thread: caller manages its own lifecycle


def run_handoff_microbench() -> dict:
    """Disaggregation phase: device-independent (CPU backend, tiny model).

    Two measurements:

    - **Handoff plane throughput**: N requests through the full
      cross-engine path — ``prefill_only`` on a prefill-role engine,
      serialize, deserialize, ``attach_prefilled`` on a decode-role engine
      (paged pool) — reported as KV blocks/s exported+attached and wire
      MB/s.  This is the metric the acceptance bar pins to the BENCH
      trajectory even when the TPU relay is wedged.

    - **Decode interference A/B (TTFT/TPOT split)**: short decode-heavy
      requests measured once on a COLLOCATED engine that is concurrently
      admitting long prefills (the interference disaggregation removes),
      and once on a decode-role engine fed attaches while the long
      prefills run on the SEPARATE prefill engine.  TPOT = per-request
      (t_done - t_first_token)/(tokens-1).
    """
    from llm_instance_gateway_tpu.models import transformer
    from llm_instance_gateway_tpu.models.configs import LLAMA3_8B
    from llm_instance_gateway_tpu.server.engine import (
        Engine, EngineConfig, Request, SamplingParams,
    )
    from llm_instance_gateway_tpu.server.kv_transfer import PrefillHandoff

    cfg = dataclasses.replace(
        LLAMA3_8B, name="handoff-cpu", vocab_size=512, d_model=128,
        n_layers=2, n_heads=4, n_kv_heads=2, d_ff=256, head_dim=32,
        max_seq_len=256,
    )
    params = transformer.init_params(cfg, jax.random.PRNGKey(0),
                                     dtype=jnp.float32)
    block = 16
    ecfg = dict(decode_slots=4, max_seq_len=256,
                prefill_buckets=(32, 64, 128))

    def engine(**kw):
        e = Engine(cfg, params, EngineConfig(**ecfg, **kw), eos_id=None,
                   dtype=jnp.float32)
        e.start()
        return e

    rng = np.random.RandomState(0)

    def req(prompt_len, max_new):
        return Request(
            prompt_tokens=list(rng.randint(1, 500, size=prompt_len)),
            max_new_tokens=max_new, sampling=SamplingParams(temperature=0.0))

    pre = engine(role="prefill")
    dec = engine(role="decode", paged_kv_block=block)
    coll = engine(paged_kv_block=block)
    out: dict = {}
    try:
        # Warm the compiled-shape set out of the measurement.
        warm = dec.attach_prefilled(PrefillHandoff.from_bytes(
            pre.prefill_only(req(64, 2), timeout_s=300).to_bytes()))
        warm.done.wait(300)
        coll.generate(req(64, 2), timeout_s=300)
        coll.generate(req(120, 2), timeout_s=300)

        # --- handoff plane throughput (+ per-phase trace collection) ---
        n_req, prompt_len = 8, 64
        wire_bytes = 0
        traces = []  # the /debug/traces shape tools/trace_report.py reads
        t0 = time.perf_counter()
        for i in range(n_req):
            pr = req(prompt_len, 2)
            h = pre.prefill_only(pr, timeout_s=300)
            t_s0 = time.time()
            wire = h.to_bytes()
            t_s1 = time.time()
            wire_bytes += len(wire)
            t_d0 = time.time()
            handoff2 = PrefillHandoff.from_bytes(wire)
            t_d1 = time.time()
            ar = dec.attach_prefilled(handoff2)
            t_att = time.time()
            if not ar.done.wait(300):
                raise RuntimeError("attach timed out")
            spans = [
                {"name": "engine.queue_wait", "start": pr.t_submit,
                 "end": pr.t_prefill_start},
                {"name": "engine.prefill", "start": pr.t_prefill_start,
                 "end": pr.t_first_token},
                {"name": "handoff.serialize", "start": t_s0, "end": t_s1},
                {"name": "handoff.deserialize", "start": t_d0, "end": t_d1},
                {"name": "handoff.attach", "start": t_d1, "end": t_att},
                {"name": "engine.decode", "start": t_att, "end": ar.t_done},
            ]
            traces.append({"trace_id": f"bench-{i}", "spans": spans})
        wall = time.perf_counter() - t0
        blocks = n_req * (-(-prompt_len // block))
        out["handoff_blocks_per_s"] = round(blocks / wall, 1)
        out["handoff_wire_mb_s"] = round(wire_bytes / wall / 1e6, 2)

        # Per-phase latency table (tools/trace_report.py smoke invocation):
        # the same code path the CLI uses, so the BENCH trajectory carries
        # the phase breakdown the tracing subsystem exists to answer.
        try:
            from tools import trace_report

            rows = trace_report.phase_table(
                trace_report.phase_samples({"traces": traces}))
            out["phase_latency_ms"] = {
                r["phase"]: {"p50": r["p50_ms"], "p95": r["p95_ms"],
                             "p99": r["p99_ms"]}
                for r in rows}
        except Exception as e:  # additive: never block the throughput metric
            out["phase_latency_error"] = str(e)[:200]

        # --- decode interference A/B ---
        def tpot_ms(r):
            steps = max(1, len(r.output_tokens) - 1)
            return (r.t_done - r.t_first_token) * 1e3 / steps

        # Collocated: decode-heavy requests share the engine with long
        # prefill admissions — each prefill program stalls every active
        # decode slot for its duration (the interference under test).
        decoders = [req(16, 24) for _ in range(4)]
        for r in decoders:
            coll.submit(r)
        longs = [coll.submit(req(120, 2)) for _ in range(4)]
        for r in decoders + longs:
            if not r.done.wait(300):
                raise RuntimeError("collocated request timed out")
        vals = sorted(tpot_ms(r) for r in decoders)
        out["colloc_decode_tpot_p50_ms"] = round(vals[len(vals) // 2], 2)
        out["colloc_decode_tpot_max_ms"] = round(vals[-1], 2)

        # Disaggregated: decoders attach on dec; long prefills hand off on
        # pre (their KV never enters dec's decode loop as prefill work).
        decoders = []
        for _ in range(4):
            decoders.append(dec.attach_prefilled(PrefillHandoff.from_bytes(
                pre.prefill_only(req(16, 24), timeout_s=300).to_bytes())))
        longs = [pre.submit(Request(
            prompt_tokens=list(rng.randint(1, 500, size=120)),
            max_new_tokens=2, sampling=SamplingParams(temperature=0.0)))
            for _ in range(4)]
        for r in decoders:
            if not r.done.wait(300):
                raise RuntimeError("disagg decode request timed out")
        for r in longs:
            r.done.wait(300)
        vals = sorted(tpot_ms(r) for r in decoders)
        out["disagg_decode_tpot_p50_ms"] = round(vals[len(vals) // 2], 2)
        out["disagg_decode_tpot_max_ms"] = round(vals[-1], 2)
        out["disagg_decode_ttft_p50_ms"] = round(sorted(
            r.ttft_s for r in decoders)[len(decoders) // 2] * 1e3, 2)

        # --- usage-attribution overhead A/B ---
        # Same engine/workload with the capacity-attribution tracker ON
        # (the default) vs OFF: decode-heavy requests so the per-dispatch
        # charge path dominates the delta.  Acceptance bar (observability
        # PR): usage_attribution_ratio <= 1.05 — attribution costs < 5%
        # of decode-step cost.  Interleaved rounds, MIN per side (the
        # PR-2/PR-4 microbench precedent: contended cores swing single
        # runs 2x).
        off_engine = engine(paged_kv_block=block, usage_attribution=False)
        try:
            def decode_wall(e) -> float:
                rs = [req(16, 24) for _ in range(4)]
                t0 = time.perf_counter()
                for r in rs:
                    e.submit(r)
                for r in rs:
                    if not r.done.wait(300):
                        raise RuntimeError("usage A/B request timed out")
                return time.perf_counter() - t0

            decode_wall(coll), decode_wall(off_engine)  # warmup pair
            on_best = off_best = float("inf")
            for _ in range(3):
                off_best = min(off_best, decode_wall(off_engine))
                on_best = min(on_best, decode_wall(coll))
            out["usage_attribution_on_s"] = round(on_best, 4)
            out["usage_attribution_off_s"] = round(off_best, 4)
            out["usage_attribution_ratio"] = round(on_best / off_best, 4)
        finally:
            off_engine.stop()
        if jax.default_backend() == "cpu":
            # Both engines share this host's cores, so cross-engine CPU
            # contention inflates the disagg numbers; on separate TPU
            # replicas the interference signal is the COLLOCATED max/p50
            # spread (decode stalls during co-resident prefill programs).
            out["handoff_note"] = "cpu-backend: engines share host cores"
    finally:
        pre.stop()
        dec.stop()
        coll.stop()
    return out


def run_pick_microbench(n: int = 4000, n_pods: int = 64,
                        n_models: int = 128) -> dict:
    """Scheduler pick microbench with a tracing-overhead A/B.

    Device-independent: a real Python filter-tree scheduler over a static
    fake fleet, run through the SAME per-pick instrumentation the proxy
    executes per request (trace-id mint for the echo contract, admission
    span record, pick-latency histogram observe) — measured once with the
    tracer DISABLED (LIG_TRACE=0 equivalent: record() short-circuits) and
    once ENABLED at default sampling.  The acceptance bar is
    ``pick_traced_ratio`` <= 1.05: turning tracing on costs < 5% of a pick.
    Each side reports its MIN over interleaved runs — this container's
    cores are contended and single-run ratios swing 2x from noise alone.
    """
    from llm_instance_gateway_tpu import tracing
    from llm_instance_gateway_tpu.gateway.scheduling.scheduler import Scheduler
    from llm_instance_gateway_tpu.gateway.scheduling.types import LLMRequest
    from llm_instance_gateway_tpu.gateway.telemetry import GatewayMetrics
    from llm_instance_gateway_tpu.gateway.testing import (
        fake_metrics, fake_pod, static_provider,
    )

    pods = {
        fake_pod(i): fake_metrics(
            queue=i % 5, kv=(i % 10) / 10.0,
            adapters={f"adapter-{i * 2 + j}": 0 for j in range(2)},
            max_adapters=4)
        for i in range(n_pods)
    }
    scheduler = Scheduler(static_provider(pods))
    reqs = [
        LLMRequest(model=f"adapter-{i % n_models}",
                   resolved_target_model=f"adapter-{i % n_models}",
                   critical=True, prompt_tokens=25, criticality="Critical")
        for i in range(64)
    ]

    def loop(tracer) -> float:
        gm = GatewayMetrics()
        t0 = time.perf_counter()
        for i in range(n):
            trace_id = tracing.new_trace_id()  # echo contract: always minted
            t_req = time.time()
            tp0 = time.perf_counter()
            pod = scheduler.schedule(reqs[i % len(reqs)])
            pick_s = time.perf_counter() - tp0
            gm.record_pick(pod.name, pick_s, False)
            tracer.record(trace_id, "gateway.admission", t_req, time.time(),
                          pod=pod.name, pick_s=round(pick_s, 6))
        return time.perf_counter() - t0

    # Interleaved A/B pairs (warm-up pair discarded), MIN per side: this
    # container's cores are contended and single-pair ratios swing 2x from
    # scheduler-side noise alone — each side's minimum is its uncontended
    # cost, which is the quantity the <5% bound is about.
    off, on = tracing.Tracer(enabled=False), tracing.Tracer()
    loop(off), loop(on)
    base_best = traced_best = float("inf")
    for _ in range(12):
        base_best = min(base_best, loop(off))
        traced_best = min(traced_best, loop(on))
    return {
        "pick_us": round(base_best / n * 1e6, 2),
        "pick_traced_us": round(traced_best / n * 1e6, 2),
        "pick_traced_ratio": round(traced_best / base_best, 4),
    }


def run_policy_microbench(n: int = 4000, n_pods: int = 64) -> dict:
    """Health-policy enforcement cost A/B (robustness PR acceptance bar:
    ``pick_policy_ratio`` <= 1.05 — enforcing ``health_policy=avoid``
    costs < 5% of a pick vs ``log_only``).

    Same harness shape as ``run_pick_microbench``: a real Python
    filter-tree scheduler over a static fleet, with a REAL ResiliencePlane
    advisor attached on both sides — log_only pays only the note_pick
    count, avoid additionally runs ``filter_by_policy`` over the survivor
    set (one degraded pod in the fleet so the filter actually filters).
    Interleaved runs, MIN per side (contended cores swing single runs 2x).
    """
    import random as random_mod

    from llm_instance_gateway_tpu.gateway import health, resilience
    from llm_instance_gateway_tpu.gateway.provider import StaticProvider
    from llm_instance_gateway_tpu.gateway.scheduling.scheduler import Scheduler
    from llm_instance_gateway_tpu.gateway.scheduling.types import LLMRequest
    from llm_instance_gateway_tpu.gateway.testing import (
        fake_metrics, fake_pod,
    )
    from llm_instance_gateway_tpu.gateway.types import PodMetrics

    provider = StaticProvider([
        PodMetrics(pod=fake_pod(i),
                   metrics=fake_metrics(queue=i % 5, kv=(i % 10) / 10.0))
        for i in range(n_pods)
    ])
    req = LLMRequest(model="m", resolved_target_model="m", critical=True,
                     prompt_tokens=25, criticality="Critical")

    def make_side(policy: str):
        plane = resilience.ResiliencePlane(
            health.HealthScorer(provider=provider),
            cfg=resilience.ResilienceConfig(health_policy=policy))
        plane.health.update()
        # Degrade ONE pod so avoid-mode filtering does real work.
        for _ in range(8):
            plane.health.record_upstream("pod-0", ok=False)
        plane.health.update()
        plane.health.update()
        sched = Scheduler(provider, prefix_aware=False,
                          rng=random_mod.Random(0))
        sched.health_advisor = plane
        return sched

    log_only, avoid = make_side("log_only"), make_side("avoid")

    def loop(sched) -> float:
        t0 = time.perf_counter()
        for _ in range(n):
            sched.schedule(req)
        return time.perf_counter() - t0

    loop(log_only), loop(avoid)  # warmup pair
    base_best = avoid_best = float("inf")
    for _ in range(12):
        base_best = min(base_best, loop(log_only))
        avoid_best = min(avoid_best, loop(avoid))
    return {
        "pick_policy_log_only_us": round(base_best / n * 1e6, 2),
        "pick_policy_avoid_us": round(avoid_best / n * 1e6, 2),
        "pick_policy_ratio": round(avoid_best / base_best, 4),
    }


def run_pick_ledger_microbench(n: int = 4000, n_pods: int = 64) -> dict:
    """Decision-ledger overhead A/B (explainability PR acceptance bar:
    ``pick_ledger_ratio`` <= 1.05 — sampled decision records + the
    counterfactual replays, amortized at the default sample_every=8,
    cost < 5% of a pick vs no ledger).

    Same harness shape as ``run_policy_microbench``: a real Python
    filter-tree scheduler over a static fleet with ALL THREE advisor
    planes attached on both sides (the ledger's counterfactual replays
    exercise every seam); the ON side additionally wires a real
    ``PickLedger``.  Interleaved runs, MIN per side.
    """
    import random as random_mod

    from llm_instance_gateway_tpu.gateway import fairness as fairness_mod
    from llm_instance_gateway_tpu.gateway import health, resilience
    from llm_instance_gateway_tpu.gateway import pickledger
    from llm_instance_gateway_tpu.gateway import placement as placement_mod
    from llm_instance_gateway_tpu.gateway import usage as usage_mod
    from llm_instance_gateway_tpu.gateway.provider import StaticProvider
    from llm_instance_gateway_tpu.gateway.scheduling.scheduler import Scheduler
    from llm_instance_gateway_tpu.gateway.scheduling.types import LLMRequest
    from llm_instance_gateway_tpu.gateway.testing import (
        fake_metrics, fake_pod,
    )
    from llm_instance_gateway_tpu.gateway.types import PodMetrics

    provider = StaticProvider([
        PodMetrics(pod=fake_pod(i),
                   metrics=fake_metrics(queue=i % 5, kv=(i % 10) / 10.0))
        for i in range(n_pods)
    ])
    req = LLMRequest(model="m", resolved_target_model="m", critical=True,
                     prompt_tokens=25, criticality="Critical")

    def make_side(with_ledger: bool):
        plane = resilience.ResiliencePlane(
            health.HealthScorer(provider=provider))
        plane.health.update()
        rollup = usage_mod.UsageRollup(provider)
        fair = fairness_mod.FairnessPolicy(rollup, provider=provider)
        planner = placement_mod.PlacementPlanner(provider, usage=rollup)
        sched = Scheduler(provider, prefix_aware=False,
                          rng=random_mod.Random(0))
        sched.health_advisor = plane
        sched.usage_advisor = fair
        sched.placement_advisor = planner
        if with_ledger:
            sched.pick_ledger = pickledger.PickLedger(
                cfg=pickledger.PickLedgerConfig(sample_every=8))
        return sched

    off, on = make_side(False), make_side(True)

    def loop(sched) -> float:
        t0 = time.perf_counter()
        for _ in range(n):
            sched.schedule(req)
        return time.perf_counter() - t0

    loop(off), loop(on)  # warmup pair
    # Median of PAIRED per-round ratios, not MIN per side: each round
    # times off then on back-to-back so CPU-frequency drift cancels
    # within the pair — a MIN-per-side comparison can attribute a
    # machine-wide slow phase entirely to whichever side it landed on.
    offs, ons, ratios = [], [], []
    for _ in range(12):
        o, w = loop(off), loop(on)
        offs.append(o)
        ons.append(w)
        ratios.append(w / o)
    ratios.sort()
    mid = len(ratios) // 2
    ratio = (ratios[mid] if len(ratios) % 2
             else (ratios[mid - 1] + ratios[mid]) / 2)
    return {
        "pick_ledger_off_us": round(min(offs) / n * 1e6, 2),
        "pick_ledger_on_us": round(min(ons) / n * 1e6, 2),
        "pick_ledger_ratio": round(ratio, 4),
    }


def run_fairness_microbench(n: int = 4000, n_pods: int = 64) -> dict:
    """Fairness pick-deprioritization cost A/B (fairness PR acceptance
    bar: ``pick_fairness_ratio`` <= 1.05 — ``mode=enforce`` costs < 5% of
    a pick vs the policy OFF).

    Same harness shape as ``run_policy_microbench``: a real Python
    filter-tree scheduler over a static fleet, with a REAL FairnessPolicy
    (over a rollup carrying one flagged-noisy adapter resident on part of
    the fleet, so ``filter_by_fairness`` does real narrowing work on every
    pick) vs no advisor at all.  Interleaved runs, MIN per side.
    """
    import random as random_mod

    from llm_instance_gateway_tpu.gateway import fairness as fairness_mod
    from llm_instance_gateway_tpu.gateway import usage as usage_mod
    from llm_instance_gateway_tpu.gateway.provider import StaticProvider
    from llm_instance_gateway_tpu.gateway.scheduling.scheduler import Scheduler
    from llm_instance_gateway_tpu.gateway.scheduling.types import LLMRequest
    from llm_instance_gateway_tpu.gateway.testing import (
        fake_metrics, fake_pod,
    )
    from llm_instance_gateway_tpu.gateway.types import PodMetrics

    # A quarter of the fleet hosts the flagged adapter: quiet picks narrow
    # past it every time (the enforcing path's real work).
    provider = StaticProvider([
        PodMetrics(pod=fake_pod(i),
                   metrics=fake_metrics(
                       queue=i % 5, kv=(i % 10) / 10.0,
                       adapters={"hog": 0} if i % 4 == 0 else {},
                       max_adapters=2))
        for i in range(n_pods)
    ])
    req = LLMRequest(model="m", resolved_target_model="m", critical=True,
                     prompt_tokens=25, criticality="Critical")

    rollup = usage_mod.UsageRollup(provider)
    # Flag "hog" directly (the microbench measures pick cost, not
    # detection); seed_noisy keeps the coupled flag tables consistent.
    rollup.seed_noisy("base", "hog")
    plane = fairness_mod.FairnessPolicy(
        rollup, cfg=fairness_mod.FairnessConfig(mode="enforce"),
        provider=provider)

    off = Scheduler(provider, prefix_aware=False, rng=random_mod.Random(0))
    enforce = Scheduler(provider, prefix_aware=False,
                        rng=random_mod.Random(0))
    enforce.usage_advisor = plane

    def loop(sched) -> float:
        t0 = time.perf_counter()
        for _ in range(n):
            sched.schedule(req)
        return time.perf_counter() - t0

    loop(off), loop(enforce)  # warmup pair
    base_best = enforce_best = float("inf")
    for _ in range(12):
        base_best = min(base_best, loop(off))
        enforce_best = min(enforce_best, loop(enforce))
    return {
        "pick_fairness_off_us": round(base_best / n * 1e6, 2),
        "pick_fairness_enforce_us": round(enforce_best / n * 1e6, 2),
        "pick_fairness_ratio": round(enforce_best / base_best, 4),
    }


def run_placement_microbench(n: int = 4000, n_pods: int = 64) -> dict:
    """Placement pick-steering cost A/B (placement PR acceptance bar:
    ``pick_placement_ratio`` <= 1.05 — ``prefer_resident`` costs < 5% of
    a pick vs no placement advisor).

    Same harness shape as ``run_fairness_microbench``: a real Python
    filter-tree scheduler over a static fleet whose pods export residency
    tiers (a quarter slot-resident, a quarter host-resident for the
    request's adapter, so ``filter_by_placement`` does real two-level
    narrowing on every pick) with a REAL ticked PlacementPlanner, vs no
    advisor at all.  Interleaved runs, MIN per side.
    """
    import random as random_mod

    from llm_instance_gateway_tpu.gateway import placement as placement_mod
    from llm_instance_gateway_tpu.gateway.provider import StaticProvider
    from llm_instance_gateway_tpu.gateway.scheduling.scheduler import Scheduler
    from llm_instance_gateway_tpu.gateway.scheduling.types import LLMRequest
    from llm_instance_gateway_tpu.gateway.testing import (
        fake_metrics, fake_pod,
    )
    from llm_instance_gateway_tpu.gateway.types import PodMetrics

    provider = StaticProvider([
        PodMetrics(pod=fake_pod(i),
                   metrics=fake_metrics(
                       queue=i % 5, kv=(i % 10) / 10.0,
                       adapters={"hot": 0} if i % 4 == 0 else {},
                       max_adapters=2,
                       adapter_tiers=({"hot": "slot"} if i % 4 == 0
                                      else {"hot": "host"} if i % 4 == 1
                                      else {})))
        for i in range(n_pods)
    ])
    req = LLMRequest(model="hot", resolved_target_model="hot",
                     critical=True, prompt_tokens=25,
                     criticality="Critical")
    planner = placement_mod.PlacementPlanner(
        provider, cfg=placement_mod.PlacementConfig(mode="prefer_resident"))
    planner.tick()

    off = Scheduler(provider, prefix_aware=False, rng=random_mod.Random(0))
    steered = Scheduler(provider, prefix_aware=False,
                        rng=random_mod.Random(0))
    steered.placement_advisor = planner

    def loop(sched) -> float:
        t0 = time.perf_counter()
        for _ in range(n):
            sched.schedule(req)
        return time.perf_counter() - t0

    loop(off), loop(steered)  # warmup pair
    base_best = steer_best = float("inf")
    for _ in range(12):
        base_best = min(base_best, loop(off))
        steer_best = min(steer_best, loop(steered))
    return {
        "pick_placement_off_us": round(base_best / n * 1e6, 2),
        "pick_placement_resident_us": round(steer_best / n * 1e6, 2),
        "pick_placement_ratio": round(steer_best / base_best, 4),
    }


def run_witness_microbench(n: int = 4000, n_pods: int = 64) -> dict:
    """Lock-witness overhead A/B (concurrency-contract PR acceptance bar:
    ``pick_witness_ratio`` <= 1.05 — running with LIG_LOCK_WITNESS armed
    costs < 5% of a pick vs plain locks, so the whole test suite can stay
    witnessed without taxing anything).

    Same harness shape as ``run_policy_microbench``: a real Python
    filter-tree scheduler + ResiliencePlane advisor + GatewayMetrics
    recording, so each pick crosses the three hot-path locks the witness
    wraps (health note_pick, breaker note_pick, pick-latency record).  The
    witness arms at LOCK CONSTRUCTION time, so each side builds its whole
    stack under its own env setting.  Interleaved runs, MIN per side.
    """
    import os as os_mod
    import random as random_mod

    from llm_instance_gateway_tpu import lockwitness
    from llm_instance_gateway_tpu.gateway import health, resilience
    from llm_instance_gateway_tpu.gateway.provider import StaticProvider
    from llm_instance_gateway_tpu.gateway.scheduling.scheduler import Scheduler
    from llm_instance_gateway_tpu.gateway.scheduling.types import LLMRequest
    from llm_instance_gateway_tpu.gateway.telemetry import GatewayMetrics
    from llm_instance_gateway_tpu.gateway.testing import (
        fake_metrics, fake_pod,
    )
    from llm_instance_gateway_tpu.gateway.types import PodMetrics

    req = LLMRequest(model="m", resolved_target_model="m", critical=True,
                     prompt_tokens=25, criticality="Critical")

    def make_side(armed: bool):
        prev = os_mod.environ.get(lockwitness.ENV)
        os_mod.environ[lockwitness.ENV] = "1" if armed else "0"
        try:
            provider = StaticProvider([
                PodMetrics(pod=fake_pod(i),
                           metrics=fake_metrics(queue=i % 5,
                                                kv=(i % 10) / 10.0))
                for i in range(n_pods)
            ])
            plane = resilience.ResiliencePlane(
                health.HealthScorer(provider=provider))
            plane.health.update()
            gm = GatewayMetrics()
            sched = Scheduler(provider, prefix_aware=False,
                              rng=random_mod.Random(0))
            sched.health_advisor = plane
        finally:
            if prev is None:
                os_mod.environ.pop(lockwitness.ENV, None)
            else:
                os_mod.environ[lockwitness.ENV] = prev
        return sched, gm

    plain, armed = make_side(False), make_side(True)

    def loop(side) -> float:
        sched, gm = side
        t0 = time.perf_counter()
        for _ in range(n):
            pod = sched.schedule(req)
            gm.record_pick(pod.name, 0.0, False)
        return time.perf_counter() - t0

    loop(plain), loop(armed)  # warmup pair
    off_best = on_best = float("inf")
    for _ in range(12):
        off_best = min(off_best, loop(plain))
        on_best = min(on_best, loop(armed))
    return {
        "pick_witness_off_us": round(off_best / n * 1e6, 2),
        "pick_witness_on_us": round(on_best / n * 1e6, 2),
        "pick_witness_ratio": round(on_best / off_best, 4),
    }


def run_decode_lever_microbench(emit_lanes: bool = False) -> dict:
    """Decode fast-path lever family (CPU-deterministic; ROADMAP item 2).

    Three A/Bs over one micro model (so per-dispatch host overhead, the
    thing multi-step fusion amortizes, is a visible share of the wall):

    - **adaptive multi-step dispatch**: decode tok/s at the seed settings
      (steps=1, host stops) vs the fast path (``adaptive_steps=8`` +
      device-side stops).  ``decode_adaptive_speedup`` is the PR's pinned
      >= 2x acceptance bar, gated absolutely by tools/bench_check.py.
    - **device-side stop strings**: wall with stop sequences riding the
      device automaton vs the host oracle (stops present, never matching)
      — bounds the automaton's overhead (``device_stops_ratio``).
    - **concurrent chunk-stream lanes**: a long prompt ahead of a shorter
      long prompt plus short decode traffic, 1 lane vs 2: the second
      prompt's TTFT no longer serializes behind the first
      (``stream_second_ttft_ratio``), with the lane-occupancy histogram
      (``emit_lanes=True``) as the committed evidence artifact.

    MIN over interleaved rounds per side, the suite convention.
    """
    from llm_instance_gateway_tpu.models import transformer
    from llm_instance_gateway_tpu.models.configs import LLAMA3_8B
    from llm_instance_gateway_tpu.server.engine import (
        Engine, EngineConfig, Request, SamplingParams,
    )

    # Micro model: small enough that the per-dispatch host tax dominates a
    # single step — the regime every remote-TPU tunnel lives in.
    cfg = dataclasses.replace(
        LLAMA3_8B, name="lever-cpu", vocab_size=128, d_model=64,
        n_layers=1, n_heads=2, n_kv_heads=1, d_ff=128, head_dim=32,
        max_seq_len=512,
        # XLA paths: the Pallas kernels run interpreted off-TPU and would
        # time the interpreter, not the engine.
        use_flash_attention=False, use_pallas_decode=False,
    )
    params = transformer.init_params(cfg, jax.random.PRNGKey(0),
                                     dtype=jnp.float32)
    base = dict(decode_slots=4, max_seq_len=512, prefill_buckets=(16,))
    rng = np.random.RandomState(0)

    def engine(**kw):
        e = Engine(cfg, params, EngineConfig(**base, **kw), eos_id=None,
                   dtype=jnp.float32)
        e.start()
        return e

    def reqs(n, prompt_len, max_new, stops=()):
        return [
            Request(prompt_tokens=list(rng.randint(1, 120, size=prompt_len)),
                    max_new_tokens=max_new,
                    sampling=SamplingParams(temperature=0.0),
                    stop_sequences=tuple(tuple(s) for s in stops))
            for _ in range(n)
        ]

    def decode_wall(e, stops=()) -> tuple[float, int]:
        rs = reqs(4, 16, 64, stops=stops)
        t0 = time.perf_counter()
        for r in rs:
            e.submit(r)
        for r in rs:
            if not r.done.wait(300):
                raise RuntimeError("decode-lever request timed out")
        wall = time.perf_counter() - t0
        return wall, sum(len(r.output_tokens) for r in rs)

    out: dict = {}
    seed_e = engine(decode_steps_per_sync=1, device_stops=False)
    fast_e = engine(adaptive_steps=8, device_stops=True)
    try:
        decode_wall(seed_e), decode_wall(fast_e)  # warmup/compile pair
        seed_best = fast_best = float("inf")
        toks = 0
        for _ in range(3):
            w, toks = decode_wall(seed_e)
            seed_best = min(seed_best, w)
            w, _ = decode_wall(fast_e)
            fast_best = min(fast_best, w)
        out["decode_step1_tok_s"] = round(toks / seed_best, 1)
        out["decode_adaptive_tok_s"] = round(toks / fast_best, 1)
        out["decode_adaptive_speedup"] = round(seed_best / fast_best, 4)

        # Device automaton overhead: stops present, never matching (token
        # 127 is excluded from the random prompts and unlikely greedy; a
        # match would only shorten both sides identically anyway).
        stops = [(127, 126, 125), (124, 123)]
        host_e = engine(adaptive_steps=8, device_stops=False)
        try:
            decode_wall(fast_e, stops), decode_wall(host_e, stops)
            on_best = off_best = float("inf")
            for _ in range(3):
                off_best = min(off_best, decode_wall(host_e, stops)[0])
                on_best = min(on_best, decode_wall(fast_e, stops)[0])
            out["device_stops_on_s"] = round(on_best, 4)
            out["device_stops_off_s"] = round(off_best, 4)
            out["device_stops_ratio"] = round(on_best / off_best, 4)
        finally:
            host_e.stop()
    finally:
        seed_e.stop()
        fast_e.stop()

    # -- chunk-stream lanes: head-of-line A/B ------------------------------
    long_a = list(rng.randint(1, 120, size=160))   # 10 chunks of 16
    long_b = list(rng.randint(1, 120, size=48))    # 3 chunks: the victim
    shorts = [list(rng.randint(1, 120, size=8)) for _ in range(2)]

    def lane_run(e):
        occupancy: dict[int, int] = {}
        ra = Request(prompt_tokens=long_a, max_new_tokens=8,
                     sampling=SamplingParams(temperature=0.0))
        rb = Request(prompt_tokens=long_b, max_new_tokens=8,
                     sampling=SamplingParams(temperature=0.0))
        rs = [Request(prompt_tokens=p, max_new_tokens=8,
                      sampling=SamplingParams(temperature=0.0))
              for p in shorts]
        t0 = time.perf_counter()
        for r in (ra, rb, *rs):
            e.submit(r)
        while not all(r.done.is_set() for r in (ra, rb, *rs)):
            n = len(e._streams)
            occupancy[n] = occupancy.get(n, 0) + 1
            time.sleep(0.0002)
        wall = time.perf_counter() - t0
        for r in (ra, rb, *rs):
            if r.error:
                raise RuntimeError(f"lane bench request failed: {r.error}")
        return wall, rb.ttft_s, occupancy

    # One engine per side, warmed with a throwaway pass so the chunk /
    # decode programs compile OUTSIDE the measured window (each Engine
    # owns fresh jit objects), then MIN TTFT over rounds.
    one_e = engine(stream_lanes=1)
    two_e = engine(stream_lanes=2)
    # Occupancy accumulates across EVERY round (warmup included): the
    # per-round samples come from a polling thread, so any single round
    # can miss the overlap window — but the stream_lanes_max_active gate
    # (== 2) only needs the overlap observed ONCE across the whole run.
    occ_all_1: dict[int, int] = {}
    occ_all_2: dict[int, int] = {}

    def merge(dst: dict, src: dict) -> None:
        for k, v in src.items():
            dst[k] = dst.get(k, 0) + v
    try:
        merge(occ_all_1, lane_run(one_e)[2])  # warmup/compile pair
        merge(occ_all_2, lane_run(two_e)[2])
        wall_1 = ttft_b_1 = wall_2 = ttft_b_2 = float("inf")
        for _ in range(3):
            w, t, o = lane_run(one_e)
            merge(occ_all_1, o)
            if t < ttft_b_1:
                wall_1, ttft_b_1 = w, t
            w, t, o = lane_run(two_e)
            merge(occ_all_2, o)
            if t < ttft_b_2:
                wall_2, ttft_b_2 = w, t
    finally:
        one_e.stop()
        two_e.stop()
    out["stream_serialized_wall_s"] = round(wall_1, 4)
    out["stream_dual_wall_s"] = round(wall_2, 4)
    out["stream_second_ttft_1lane_ms"] = round(ttft_b_1 * 1e3, 2)
    out["stream_second_ttft_2lane_ms"] = round(ttft_b_2 * 1e3, 2)
    out["stream_second_ttft_ratio"] = round(
        ttft_b_1 / ttft_b_2, 4) if ttft_b_2 > 0 else 0.0
    out["stream_lanes_max_active"] = max(occ_all_2) if occ_all_2 else 0
    if emit_lanes:
        out["lane_occupancy"] = {
            "one_lane_samples": {str(k): v
                                 for k, v in sorted(occ_all_1.items())},
            "two_lane_samples": {str(k): v
                                 for k, v in sorted(occ_all_2.items())},
        }
    return out


def run_profiler_microbench(emit_profile: bool = False,
                            fast_path: bool = False) -> dict:
    """Step-timeline-profiler overhead A/B (fleet-observability PR
    acceptance bar: ``step_profile_ratio`` <= 1.05 — profiling every
    dispatch costs < 5% of step-loop wall).

    Two tiny CPU engines run the same decode-heavy workload, profiler ON
    (the default) vs ``step_profile=False``; interleaved rounds, MIN per
    side (the usage-attribution A/B precedent — contended cores swing
    single runs 2x).  ``emit_profile=True`` additionally returns the ON
    engine's profiler snapshot — the deterministic run committed as
    ``PROFILE_BASELINE.json`` (the dispatch/host-sync/idle attribution
    baseline every ROADMAP item-2 lever is measured against).
    ``fast_path=True`` runs both engines with the decode levers on
    (adaptive fused dispatch + device-side stops) — the post-lever
    attribution the refreshed baseline commits, whose host-sync share
    must sit strictly below the pre-lever baseline's.
    """
    from llm_instance_gateway_tpu.models import transformer
    from llm_instance_gateway_tpu.models.configs import LLAMA3_8B
    from llm_instance_gateway_tpu.server.engine import (
        Engine, EngineConfig, Request, SamplingParams,
    )

    cfg = dataclasses.replace(
        LLAMA3_8B, name="profiler-cpu", vocab_size=512, d_model=128,
        n_layers=2, n_heads=4, n_kv_heads=2, d_ff=256, head_dim=32,
        max_seq_len=256,
    )
    params = transformer.init_params(cfg, jax.random.PRNGKey(0),
                                     dtype=jnp.float32)
    ecfg = dict(decode_slots=4, max_seq_len=256,
                prefill_buckets=(32, 64, 128))
    if fast_path:
        ecfg["adaptive_steps"] = 8
    rng = np.random.RandomState(0)

    def engine(**kw):
        e = Engine(cfg, params, EngineConfig(**ecfg, **kw), eos_id=None,
                   dtype=jnp.float32)
        e.start()
        return e

    def req(prompt_len, max_new):
        return Request(
            prompt_tokens=list(rng.randint(1, 500, size=prompt_len)),
            max_new_tokens=max_new,
            sampling=SamplingParams(temperature=0.0))

    def decode_wall(e) -> float:
        rs = [req(16, 24) for _ in range(4)]
        t0 = time.perf_counter()
        for r in rs:
            e.submit(r)
        for r in rs:
            if not r.done.wait(300):
                raise RuntimeError("profiler A/B request timed out")
        return time.perf_counter() - t0

    on_engine = engine()
    off_engine = engine(step_profile=False)
    try:
        decode_wall(on_engine), decode_wall(off_engine)  # warmup pair
        on_best = off_best = float("inf")
        for _ in range(3):
            off_best = min(off_best, decode_wall(off_engine))
            on_best = min(on_best, decode_wall(on_engine))
        out = {
            "step_profile_on_s": round(on_best, 4),
            "step_profile_off_s": round(off_best, 4),
            "step_profile_ratio": round(on_best / off_best, 4),
        }
        if emit_profile:
            out["profile"] = on_engine.profiler.snapshot()
    finally:
        on_engine.stop()
        off_engine.stop()
    return out


def run_kv_ledger_microbench() -> dict:
    """KV block-lifecycle ledger overhead A/B (KV-economy PR acceptance
    bar: ``kv_ledger_ratio`` < 1.05 — charging every alloc/reuse/release
    plus the per-scrape state recount costs < 5% of paged-engine wall).

    Two tiny paged-KV CPU engines run the same shared-prefix workload
    (the reuse path is the hottest ledger charge site), ledger ON (the
    default) vs ``kv_ledger=False``; interleaved rounds, MIN per side
    (the step-profiler A/B precedent).  Each round also scrapes
    ``metrics_snapshot()`` once per request batch, so the ledger's
    snapshot/render cost is inside the measured wall, as in production.
    """
    from llm_instance_gateway_tpu.models import transformer
    from llm_instance_gateway_tpu.models.configs import LLAMA3_8B
    from llm_instance_gateway_tpu.server.engine import (
        Engine, EngineConfig, Request, SamplingParams,
    )

    cfg = dataclasses.replace(
        LLAMA3_8B, name="kvledger-cpu", vocab_size=512, d_model=128,
        n_layers=2, n_heads=4, n_kv_heads=2, d_ff=256, head_dim=32,
        max_seq_len=256,
    )
    params = transformer.init_params(cfg, jax.random.PRNGKey(0),
                                     dtype=jnp.float32)
    ecfg = dict(decode_slots=4, max_seq_len=256,
                prefill_buckets=(32, 64), paged_kv_block=8,
                prefix_cache=True)
    rng = np.random.RandomState(0)
    shared = list(rng.randint(1, 500, size=16))  # two full shared blocks

    def engine(**kw):
        e = Engine(cfg, params, EngineConfig(**ecfg, **kw), eos_id=None,
                   dtype=jnp.float32)
        e.start()
        return e

    def wall(e) -> float:
        rs = [Request(
            prompt_tokens=shared + list(rng.randint(1, 500, size=8)),
            max_new_tokens=16,
            sampling=SamplingParams(temperature=0.0)) for _ in range(4)]
        t0 = time.perf_counter()
        for r in rs:
            e.submit(r)
        for r in rs:
            if not r.done.wait(300):
                raise RuntimeError("kv ledger A/B request timed out")
        e.metrics_snapshot()  # the scrape rides the measured wall
        return time.perf_counter() - t0

    on_engine = engine()
    off_engine = engine(kv_ledger=False)
    try:
        wall(on_engine), wall(off_engine)  # warmup pair
        on_best = off_best = float("inf")
        for _ in range(3):
            off_best = min(off_best, wall(off_engine))
            on_best = min(on_best, wall(on_engine))
        return {
            "kv_ledger_on_s": round(on_best, 4),
            "kv_ledger_off_s": round(off_best, 4),
            "kv_ledger_ratio": round(on_best / off_best, 4),
        }
    finally:
        on_engine.stop()
        off_engine.stop()


def run_capacity_microbench(n_pods: int = 16, n_ticks: int = 192) -> dict:
    """Capacity-plane tick overhead A/B (capacity-twin PR acceptance bar:
    ``capacity_tick_ratio`` < 1.05 — enabling ``CapacityPlanner`` on the
    observability cadence costs < 5% of the control-tick composite the
    proxy already runs every period).

    Both sides drive the REAL composite — the full advisor stack
    (health/breaker, usage, kvobs, fairness, placement, pickledger), the
    SLO engine, and the statebus snapshot/apply, exactly
    ``GatewayProxy.control_tick``'s synchronous pass — over an identical
    deterministic schedule of advancing pod accumulators; the ON side
    flips ``CapacityConfig.enabled``.  The planner's clock is pinned to
    a virtual 5s-per-tick clock (the default obs cadence) so the
    ``min_window_s`` window floor folds on its production duty cycle —
    one fold per 6 ticks, clock-compare early-returns between — instead
    of collapsing to a single fold at bench speed.  192 ticks per round
    = 32 folds = exactly one ``refit_every_ticks`` self-calibration, so
    the refit spike lands once per round instead of jittering the
    per-round ratios.  Self-calibration
    refits ride the measured wall (they amortize at
    ``refit_every_ticks``, as in production) but the DES knee probes are
    excluded: their cadence is a config knob whose cost ``make
    sim-check`` pins, not a per-tick tax.  The workload advance runs
    OUTSIDE the timed region (it is load synthesis, not observability
    work — leaving it in would pad both sides and flatter the ratio).
    The two sides interleave per tick (off-tick then on-tick, same
    virtual instant) and each tick index is timed separately; the
    reported ratio compares per-side sums of PER-TICK-INDEX medians
    across rounds.  An OS or GC hiccup lands in one tick of one round
    and that tick's cross-round median rejects it, while structural
    cost — including the refit tick — survives because it recurs at
    the same tick index every round.
    """
    import random as random_mod

    from llm_instance_gateway_tpu.gateway.advisors import AdvisorStack
    from llm_instance_gateway_tpu.gateway.capacity import CapacityConfig
    from llm_instance_gateway_tpu.gateway.provider import StaticProvider
    from llm_instance_gateway_tpu.gateway.slo import SLOEngine
    from llm_instance_gateway_tpu.gateway.statebus import StateBus
    from llm_instance_gateway_tpu.gateway.telemetry import GatewayMetrics
    from llm_instance_gateway_tpu.gateway.testing import fake_metrics, fake_pod
    from llm_instance_gateway_tpu.gateway.types import PodMetrics

    rng = random_mod.Random(0)
    # Per-pod per-tick accumulator increments, precomputed once so both
    # sides (and every round) replay identical scrape content.
    plan = [[(0.02 * (1 + rng.random()), 20.0,
              1.5 * (1 + rng.random()), 3000.0,
              5 * 0.25 * (1 + rng.random()), 5.0,
              20.0 * rng.randint(120, 260), 20.0 * rng.randint(130, 170),
              # KV free varies independently of batch so calibration
              # windows stay full-rank: the twin actually FITS and the
              # ON side pays the real steady-state path (drift
              # predictions + amortized refits), not the degenerate
              # fit-rejected one.
              200000 - rng.randint(20000, 160000))
             for _ in range(n_pods)]
            for _ in range(n_ticks)]

    def make_side(enabled: bool):
        pods = [PodMetrics(pod=fake_pod(i),
                           metrics=fake_metrics(
                               queue=i % 5, kv=(i % 10) / 10.0,
                               adapters={f"adapter-{i}-{j}": 0
                                         for j in range(4)}))
                for i in range(n_pods)]
        for pm in pods:
            pm.metrics.kv_tokens_capacity = 200000
            pm.metrics.kv_tokens_free = 180000
            pm.metrics.running_queue_size = 4
        gw_metrics = GatewayMetrics()
        stack = AdvisorStack(
            "pool", StaticProvider(pods), metrics=gw_metrics,
            capacity_cfg=CapacityConfig(enabled=enabled,
                                        forecast_every_ticks=10 ** 9))
        slo = SLOEngine(gw_metrics)
        bus = StateBus({"pool": stack})
        clock = [1000.0]
        stack.capacity._clock = lambda: clock[0]

        def advance(tick_i: int) -> None:
            # Production-shaped load: 4 models on the SLO engine, one
            # token-attribution entry per {adapter, phase} per pod — the
            # multi-tenant tables the usage plane exists to roll up, not
            # a single-model toy that would understate the base.
            clock[0] += 5.0
            for j in range(4):
                gw_metrics.record_request("m%d" % j)
                gw_metrics.record_phase("m%d" % j, "/v1/completions",
                                        ttft_s=0.05, tpot_s=0.02,
                                        e2e_s=3.0)
            for i, (pm, inc) in enumerate(zip(pods,
                                              plan[tick_i % n_ticks])):
                m = pm.metrics
                m.prefill_seconds_sum += inc[0]
                m.prefill_seconds_count += inc[1]
                m.decode_step_seconds_sum += inc[2]
                m.decode_step_seconds_count += inc[3]
                m.decode_batch_occupancy_sum += inc[4]
                m.decode_batch_occupancy_count += inc[5]
                m.kv_tokens_free = inc[8]
                at = m.adapter_tokens
                for j in range(4):
                    for value, phase in ((inc[6] / 4.0, "prefill"),
                                         (inc[7] / 4.0, "decode")):
                        k = ("m%d" % j, "adapter-%d-%d" % (i, j), phase)
                        at[k] = at.get(k, 0.0) + value
        return stack, slo, bus, advance

    off_side, on_side = make_side(False), make_side(True)
    perf = time.perf_counter

    def timed_tick(side, i: int) -> float:
        stack, slo, bus, advance = side
        advance(i)
        t0 = perf()
        stack.tick()
        slo.tick()
        bus.tick()
        return perf() - t0

    n_rounds = 16
    # off_t[r][i] / on_t[r][i]: wall of tick i in round r.
    off_t = [[0.0] * n_ticks for _ in range(n_rounds)]
    on_t = [[0.0] * n_ticks for _ in range(n_rounds)]
    for i in range(n_ticks):  # warmup round (untimed)
        timed_tick(off_side, i), timed_tick(on_side, i)
    for r in range(n_rounds):
        for i in range(n_ticks):
            off_t[r][i] = timed_tick(off_side, i)
            on_t[r][i] = timed_tick(on_side, i)

    def col(rows: list, i: int) -> list:
        return sorted(rows[r][i] for r in range(n_rounds))

    total_off = total_on = min_off = min_on = 0.0
    mid = n_rounds // 2
    for i in range(n_ticks):
        o, w = col(off_t, i), col(on_t, i)
        total_off += (o[mid - 1] + o[mid]) / 2
        total_on += (w[mid - 1] + w[mid]) / 2
        min_off += o[0]
        min_on += w[0]
    return {
        "capacity_tick_off_us": round(min_off / n_ticks * 1e6, 2),
        "capacity_tick_on_us": round(min_on / n_ticks * 1e6, 2),
        "capacity_tick_ratio": round(total_on / total_off, 4),
    }


def run_native_pick_microbench(n: int = 4000, n_pods: int = 200,
                               n_models: int = 1000,
                               batch: int = 64) -> dict:
    """Snapshot-resident native pick cost (the data-plane fast path).

    200 pods x 1000 adapters — the LOADGEN fixture scale — over a REAL
    versioned ``Provider`` so the resident state marshals once and every
    pick crosses the FFI with request scalars only.  Three measurements,
    MIN over interleaved runs (contended-core precedent from the other
    microbenches):

    - ``pick_native_us``: one ``schedule()`` = one ``lig_pick`` crossing.
    - ``pick_many_us``: per-pick cost with ``batch`` requests amortized
      into ONE ``lig_pick_many`` crossing.
    - ``pick_python_us``: the Python oracle on the SAME fixture, and
      ``pick_native_speedup`` = python/native — the compute-only gap the
      e2e loadgen ratio is chasing.
    """
    import random as random_mod

    from llm_instance_gateway_tpu.gateway.scheduling import native
    from llm_instance_gateway_tpu.gateway.scheduling.scheduler import Scheduler
    from llm_instance_gateway_tpu.gateway.scheduling.types import LLMRequest
    from llm_instance_gateway_tpu.gateway.testing import (
        build_handler_server, fake_metrics, fake_pod, make_model,
    )

    if not native.available():
        return {"native_pick_error": "libligsched.so unavailable"}
    per_pod = max(1, n_models // n_pods)
    pods = {
        fake_pod(i): fake_metrics(
            queue=i % 5, kv=(i % 10) / 10.0,
            adapters={f"adapter-{i * per_pod + j}": 0
                      for j in range(per_pod)},
            max_adapters=per_pod + 1)
        for i in range(n_pods)
    }
    models = [make_model(f"adapter-{i}") for i in range(n_models)]
    # build_handler_server gives a versioned Provider (snapshot cache key).
    provider = build_handler_server(pods, models).scheduler._provider
    nat = native.NativeScheduler(provider, rng=random_mod.Random(0))
    py = Scheduler(provider, rng=random_mod.Random(0), prefix_aware=False)
    reqs = [
        LLMRequest(model=f"adapter-{i % n_models}",
                   resolved_target_model=f"adapter-{i % n_models}",
                   critical=True, prompt_tokens=25, criticality="Critical")
        for i in range(256)
    ]

    def loop_single(sched) -> float:
        t0 = time.perf_counter()
        for i in range(n):
            sched.schedule(reqs[i % len(reqs)])
        return time.perf_counter() - t0

    def loop_many() -> float:
        t0 = time.perf_counter()
        done = 0
        while done < n:
            take = min(batch, n - done)
            nat.pick_many([reqs[(done + k) % len(reqs)]
                           for k in range(take)])
            done += take
        return time.perf_counter() - t0

    loop_single(nat), loop_many(), loop_single(py)  # warmup
    nat_best = many_best = py_best = float("inf")
    for _ in range(8):
        nat_best = min(nat_best, loop_single(nat))
        many_best = min(many_best, loop_many())
        py_best = min(py_best, loop_single(py))
    return {
        "pick_native_us": round(nat_best / n * 1e6, 2),
        "pick_many_us": round(many_best / n * 1e6, 2),
        "pick_python_us": round(py_best / n * 1e6, 2),
        "pick_native_speedup": round(py_best / nat_best, 2),
        "native_picks_per_s": round(n / nat_best, 1),
    }


def run_relay_microbench(n_chunks: int = 256, chunk_bytes: int = 160,
                         rounds: int = 6) -> dict:
    """Zero-copy relay A/B: chunks/s through the REAL proxy relay loop,
    fast (verbatim write + tail references) vs slow (per-chunk line
    re-framing) — same upstream script, same sockets, interleaved rounds
    with MAX throughput per side (the µbench the regression gate rides).
    """
    import asyncio

    from aiohttp import web
    from aiohttp.test_utils import TestClient, TestServer

    from llm_instance_gateway_tpu.api.v1alpha1 import InferencePool
    from llm_instance_gateway_tpu.gateway import resilience
    from llm_instance_gateway_tpu.gateway.datastore import Datastore
    from llm_instance_gateway_tpu.gateway.handlers.server import Server
    from llm_instance_gateway_tpu.gateway.provider import StaticProvider
    from llm_instance_gateway_tpu.gateway.proxy import GatewayProxy
    from llm_instance_gateway_tpu.gateway.scheduling.scheduler import Scheduler
    from llm_instance_gateway_tpu.gateway.testing import (
        fake_metrics, make_model,
    )
    from llm_instance_gateway_tpu.gateway.types import Pod, PodMetrics

    filler = b'data: {"choices": [{"index": 0, "text": "' + \
        b"x" * max(1, chunk_bytes - 60) + b'"}]}\n\n'
    final = (b'data: {"choices": [{"index": 0, "text": "."}], '
             b'"usage": {"prompt_tokens": 7, "completion_tokens": 3}}\n\n')

    async def measure() -> dict:
        async def completions(request: web.Request) -> web.StreamResponse:
            resp = web.StreamResponse(
                status=200, headers={"Content-Type": "text/event-stream"})
            await resp.prepare(request)
            for _ in range(n_chunks - 2):
                await resp.write(filler)
            await resp.write(final)
            await resp.write(b"data: [DONE]\n\n")
            return resp

        app = web.Application()
        app.router.add_post("/v1/completions", completions)
        up = TestServer(app)
        await up.start_server()

        async def one_side(fast: bool):
            pods = {Pod("p", f"127.0.0.1:{up.port}"): fake_metrics()}
            ds = Datastore(pods=list(pods))
            ds.set_pool(InferencePool(name="pool"))
            ds.store_model(make_model("m"))
            provider = StaticProvider(
                [PodMetrics(pod=p, metrics=m) for p, m in pods.items()])
            proxy = GatewayProxy(
                Server(Scheduler(provider, token_aware=False,
                                 prefill_aware=False, prefix_aware=False),
                       ds),
                provider, ds,
                resilience_cfg=resilience.ResilienceConfig(),
                fast_relay=fast)
            client = TestClient(TestServer(proxy.build_app()))
            await client.start_server()

            async def one_round() -> float:
                t0 = time.perf_counter()
                resp = await client.post(
                    "/v1/completions",
                    json={"model": "m", "prompt": "x", "stream": True})
                raw = await resp.read()
                wall = time.perf_counter() - t0
                assert resp.status == 200 and raw.endswith(
                    b"data: [DONE]\n\n")
                return wall

            return client, one_round

        fast_client, fast_round = await one_side(True)
        slow_client, slow_round = await one_side(False)
        try:
            await fast_round(), await slow_round()  # warmup pair
            fast_best = slow_best = float("inf")
            for _ in range(rounds):
                fast_best = min(fast_best, await fast_round())
                slow_best = min(slow_best, await slow_round())
        finally:
            await fast_client.close()
            await slow_client.close()
            await up.close()
        return {
            "relay_fast_chunks_per_s": round(n_chunks / fast_best, 1),
            "relay_slow_chunks_per_s": round(n_chunks / slow_best, 1),
            # >= 1.0: verbatim relay at least matches the line scanner.
            "relay_fast_ratio": round(slow_best / fast_best, 4),
        }

    return asyncio.run(measure())


def _collect_handoff_metrics(timeout_s: float = 300.0) -> None:
    """Run the disaggregation phase in a CPU subprocess BEFORE the device
    claim (it must not touch — or wait for — the TPU relay) and merge its
    metrics into every emission path, sentinels included."""
    import subprocess

    env = dict(os.environ, JAX_PLATFORMS="cpu")
    try:
        r = subprocess.run(
            [sys.executable, os.path.abspath(__file__),
             "--handoff-microbench"],
            capture_output=True, text=True, timeout=timeout_s, env=env)
        stdout, rc = r.stdout, r.returncode
    except subprocess.TimeoutExpired as e:
        # The child prints the handoff line BEFORE the pick phase: salvage
        # whatever JSON made it out before the deadline.
        stdout = (e.stdout.decode() if isinstance(e.stdout, bytes)
                  else e.stdout) or ""
        rc = "timeout"
        _EXTRA["handoff_error"] = f"subprocess deadline ({timeout_s:.0f}s)"
    except Exception as e:  # the phase is additive; never block the ratio
        _EXTRA["handoff_error"] = str(e)[:200]
        return
    lines = [ln for ln in (stdout or "").splitlines() if ln.startswith("{")]
    if lines:
        _EXTRA.update(json.loads(lines[-1]))
    elif "handoff_error" not in _EXTRA:
        _EXTRA["handoff_error"] = f"no output (rc={rc})"


# v5e (per chip): 819 GB/s HBM bandwidth, 197 TFLOP/s bf16 on the MXU.
V5E_HBM_BYTES_PER_S = 819e9
V5E_BF16_FLOPS = 197e12


def _param_bytes(params) -> int:
    """Total bytes the decode step streams from HBM for weights (int8
    weight-only quant counts 1 byte/param + f32 scales)."""
    total = 0
    for leaf in jax.tree.leaves(params):
        total += leaf.size * leaf.dtype.itemsize
    return total


def _roofline_probes(engine, cfg, params, b_slots: int) -> dict:
    """Measure decode HBM-roofline fraction and prefill MFU (VERDICT r2 #2).

    - Decode probe: exactly ``b_slots`` short-prompt/long-output requests so
      every decode step runs full-batch; achieved HBM bytes/s = (weight
      bytes + mean KV-read bytes per step) x steps/s vs the v5e peak.
    - Prefill probe: bucket-sized prompts, 1 new token each; MFU = dense
      forward FLOPs (2 x params x tokens) / wall vs bf16 peak.

    Both are conservative: they ignore activation traffic (decode) and
    attention FLOPs (prefill), so the reported fractions are lower bounds
    on hardware utilization.
    """
    hd = cfg.resolved_head_dim
    # Counts EVERY leaf (embeddings and quant scales included): 2*N*T is an
    # approximation of dense forward FLOPs and the extra leaves overstate it
    # by a few percent at these shapes — acceptable for a roofline FRACTION.
    n_params = sum(l.size for l in jax.tree.leaves(params))
    w_bytes = _param_bytes(params)
    kv_itemsize = jax.tree.leaves(engine.cache)[0].dtype.itemsize

    # --- decode probe ---
    prompt, new = 16, 96
    r = run_phase(engine, b_slots, prompt, new, adapters=[])
    steps_per_s = r["tok_per_s"] / b_slots
    mean_len = prompt + new / 2
    kv_bytes_per_step = (
        b_slots * cfg.n_layers * 2 * mean_len * cfg.n_kv_heads * hd
        * kv_itemsize)
    decode_hbm_frac = (
        (w_bytes + kv_bytes_per_step) * steps_per_s / V5E_HBM_BYTES_PER_S)

    # --- prefill probe ---
    pf_prompt = 256
    n_pf = 16
    t0 = time.perf_counter()
    rp = run_phase(engine, n_pf, pf_prompt, 1, adapters=[])
    pf_wall = time.perf_counter() - t0
    pf_flops = 2.0 * n_params * n_pf * pf_prompt
    prefill_mfu = pf_flops / pf_wall / V5E_BF16_FLOPS

    return {
        "decode_tok_per_s_fullbatch": round(r["tok_per_s"], 1),
        "decode_hbm_frac": round(decode_hbm_frac, 4),
        "prefill_mfu": round(prefill_mfu, 4),
        "ttft_p50_ms": round(rp["ttft_p50_ms"], 1),
        "ttft_p99_ms": round(rp["ttft_p99_ms"], 1),
    }


def _bench_error(msg: str) -> None:
    _emit({
        "metric": "multiplexed_lora_tokens_per_sec",
        "value": 0.0,
        "unit": "tok/s",
        "vs_baseline": 0.0,
        "error": msg,
    })


def _bench_skip(reason: str, probe_log: list) -> None:
    """Structured SKIP emission (r05 lesson: the multichip bench wedged 12
    minutes and then emitted only an opaque error STRING).  ``skipped`` +
    ``probe_log`` let trajectory tooling distinguish "device never became
    available" (an environment skip) from a real perf regression, and show
    exactly how the probe budget was spent."""
    _emit({
        "metric": "multiplexed_lora_tokens_per_sec",
        "value": 0.0,
        "unit": "tok/s",
        "vs_baseline": 0.0,
        "skipped": reason,
        "probe_log": probe_log,
    })


def _claim_device_with_retry(probe_timeout_s: float = 120.0) -> None:
    """Adaptive retry-with-backoff on the device grant, BEFORE backend init.

    The single chip is granted to one process at a time; a stale grant (e.g.
    after another process was killed mid-run) clears on its own on minute
    scales sometimes — observed wedges have taken 10+ minutes.  Probing from
    a short-lived subprocess lets this process retry — once OUR backend init
    starts it blocks uninterruptibly inside PJRT, so the probe must come
    first.  Killing the probe is safe: it is blocked *waiting* for the
    grant, it never holds the chip.

    The schedule is a BUDGET derived from the wall-clock governor: the probe
    loop gets what remains of TOTAL_BUDGET_S after reserving RUN_ESTIMATE_S
    for the measured run itself (round-3 lesson: a probe budget longer than
    the driver's kill timeout means dying with NOTHING on stdout — rc=124,
    empty tail).  Budget exhausted -> sentinel JSON + exit 2 so the driver
    records a structured failure instead of hanging.
    """
    import subprocess

    if (os.environ.get("JAX_PLATFORMS", "") == "cpu"
            or getattr(jax.config, "jax_platforms", None) == "cpu"):
        return  # hermetic run: no relay involved
    budget_s = min(
        float(os.environ.get("BENCH_PROBE_BUDGET_S", "1e9")),
        max(60.0, _deadline() - RUN_ESTIMATE_S - time.monotonic()),
    )
    deadline = time.monotonic() + budget_s
    # The probe enforces its own deadline (daemon watchdog + os._exit) so it
    # exits BEFORE the outer SIGKILL backstop: a probe killed externally in
    # the instant after the grant lands would itself wedge the relay.
    code = (
        "import os, threading, jax, jax.numpy as jnp\n"
        f"threading.Timer({probe_timeout_s}, lambda: os._exit(3)).start()\n"
        "jnp.zeros((8,)).block_until_ready()\n"
        "print('CLAIM_OK', jax.default_backend(), flush=True)\n"
        "os._exit(0)\n"
    )
    backoff = 30.0  # dense early: most observed wedges clear in minutes
    attempts = 0
    t_loop0 = time.monotonic()
    # Per-probe structured log: emitted with the skip sentinel so the
    # trajectory record shows HOW the budget was spent (outcomes:
    # ok / claimed_cpu / probe_timeout / rc=N).
    probe_log: list[dict] = []
    while True:
        attempts += 1
        t_p0 = time.monotonic()
        outcome = "probe_timeout"
        try:
            r = subprocess.run(
                [sys.executable, "-c", code], timeout=probe_timeout_s + 30,
                capture_output=True, text=True,
            )
            out = r.stdout or ""
            # Require a real accelerator claim: this image lists platforms
            # 'axon,cpu', so a fast-failing relay would otherwise fall back
            # to CPU and publish a tiny-CPU number as the TPU result.
            if "CLAIM_OK" in out and "CLAIM_OK cpu" not in out:
                outcome = "ok"
            elif "CLAIM_OK cpu" in out:
                outcome = "claimed_cpu"
            else:
                outcome = f"rc={r.returncode}"
        except subprocess.TimeoutExpired:
            pass
        probe_log.append({
            "attempt": attempts,
            "t_s": round(t_p0 - t_loop0, 1),
            "probe_s": round(time.monotonic() - t_p0, 1),
            "outcome": outcome,
        })
        if outcome == "ok":
            return
        if time.monotonic() + backoff + probe_timeout_s > deadline:
            break
        time.sleep(backoff)
        backoff = min(backoff * 2, 180.0)
    _bench_skip("device_unavailable", probe_log)
    sys.exit(2)


def _device_watchdog(timeout_s: float = 180.0) -> None:
    """Fail fast if the chip can't be claimed (wedged relay grant).

    Backend init blocks uninterruptibly inside PJRT when the single chip's
    pool-side grant is stuck (observed after a killed TPU process) — without
    this guard the bench hangs forever instead of reporting.  A watcher
    thread hard-exits with a sentinel JSON line if a trivial device op
    doesn't complete in time.
    """
    import threading

    done = threading.Event()

    def watch():
        if not done.wait(timeout_s):
            _bench_skip("device_unavailable",
                        [{"outcome": f"watchdog_timeout_{timeout_s:.0f}s"}])
            os._exit(2)

    threading.Thread(target=watch, daemon=True).start()
    jnp.zeros((8,)).block_until_ready()  # forces backend init + one op
    done.set()


def main() -> None:
    from llm_instance_gateway_tpu.models import transformer
    from llm_instance_gateway_tpu.server.engine import Engine, EngineConfig
    from llm_instance_gateway_tpu.server.lora_manager import LoRAManager

    install_sigterm_cleanup()
    _install_governor()
    # Disaggregation phase FIRST (CPU subprocess): its metrics merge into
    # every later emission, so they survive a wedged TPU relay.
    _collect_handoff_metrics()
    _claim_device_with_retry()
    _device_watchdog()
    cfg = bench_model_cfg()
    on_cpu = jax.default_backend() == "cpu"
    dtype = jnp.float32 if on_cpu else jnp.bfloat16
    n_requests = 8 if on_cpu else 48
    prompt_len = 16 if on_cpu else 100
    max_new = 8 if on_cpu else 64

    params = transformer.init_params(cfg, jax.random.PRNGKey(0), dtype=dtype)
    if not on_cpu:
        from llm_instance_gateway_tpu.ops.quant import quantize_params

        # Weight-only int8: halves the HBM weight traffic decode is bound by.
        # Applied to BOTH phases, so the north-star ratio stays apples-to-apples.
        params = quantize_params(params)
    engine_cfg = EngineConfig(
        decode_slots=4 if on_cpu else 16,
        max_seq_len=cfg.max_seq_len,
        prefill_buckets=(32, 128) if on_cpu else (128, 256),
        # Amortize per-dispatch latency (the device->host token readback
        # costs ~77ms through the remote-TPU relay; measured end-to-end:
        # sync K=8 -> 271, K=32 -> 511; pipelined K=8 -> 1046 tok/s).
        decode_steps_per_sync=1 if on_cpu else 32,
        # Hide the readback entirely: block N+1 dispatches from the device
        # carry while block N's tokens transfer.
        pipeline_decode=not on_cpu,
        # Burst admission: all 48 requests arrive at once and share buckets,
        # so grouped prefill collapses the admission phase from ~48
        # dispatches to ~12 (applies identically to both phases — the
        # north-star ratio stays apples-to-apples).
        prefill_batch=1 if on_cpu else 4,
    )

    # Two engines over SHARED params: the TRUE single-tenant baseline
    # (lora_manager=None compiles a delta-free program — the honest
    # denominator) and the multiplexed engine with 4 resident adapters.
    # Throughput through the remote-TPU relay drifts tens of percent between
    # runs, so phases are INTERLEAVED (A B A B ...) and each side reports its
    # best sample — phase-order bias and slow windows can't skew the ratio.
    baseline_engine = Engine(cfg, params, engine_cfg, lora_manager=None,
                             eos_id=None, dtype=dtype)
    lora = LoRAManager(cfg, dtype=dtype)
    multi_engine = Engine(cfg, params, engine_cfg, lora_manager=lora,
                          eos_id=None, dtype=dtype)
    baseline_engine.start()
    multi_engine.start()
    try:
        adapter_names = []
        for i in range(cfg.max_lora_slots):
            name = f"bench-adapter-{i}"
            lora.load(name, weights=make_adapter_weights(cfg, rank=8, seed=i),
                      alpha=16.0, rank=8)
            adapter_names.append(name)
        run_phase(baseline_engine, 2, prompt_len, 4, adapters=[])  # warm-up A
        run_phase(multi_engine, 2, prompt_len, 4, adapters=adapter_names)  # warm-up B
        # Relay throughput drifts on minute scales, so the ratio is estimated
        # from ADJACENT sample pairs (drift cancels within a pair), with the
        # pair order alternating to kill order bias, and the median taken
        # across pairs to shrug off one bad window.
        samples = 1 if on_cpu else 3
        # Relay slow-windows happen: never let extra samples push the run
        # past the governor's patience (leave room for roofline + emit).
        budget_deadline = min(time.monotonic() + 300, _deadline() - 120)
        multis, ratios = [], []
        best_multi_stats = None
        for s in range(samples):
            if multis and time.monotonic() > budget_deadline:
                break
            def sample_single():
                return run_phase(baseline_engine, n_requests, prompt_len,
                                 max_new, adapters=[])["tok_per_s"]

            def sample_multi():
                return run_phase(multi_engine, n_requests, prompt_len,
                                 max_new, adapters=adapter_names)

            if s % 2 == 0:
                a, bs = sample_single(), sample_multi()
            else:
                bs, a = sample_multi(), sample_single()
            multis.append(bs["tok_per_s"])
            if bs["tok_per_s"] == max(multis):
                best_multi_stats = bs
            ratios.append(bs["tok_per_s"] / a)
            # Keep the governor's best-effort emission current: from the
            # first completed pair on, a watchdog fire reports a REAL
            # (truncated) measurement instead of a zero sentinel.
            _partial.update({
                "metric": "multiplexed_lora_tokens_per_sec",
                "value": round(max(multis), 2),
                "unit": "tok/s",
                "vs_baseline": round(sorted(ratios)[(len(ratios) - 1) // 2], 4),
            })

        # Efficiency, not just a ratio (VERDICT r2 #2): where the measured
        # throughput sits against the v5e HBM/MXU rooflines.  Skipped when
        # the governor is nearly out of budget — ratio first, roofline extra.
        roofline = {}
        if not on_cpu and time.monotonic() < _deadline() - 90:
            roofline = _roofline_probes(
                baseline_engine, cfg, params, engine_cfg.decode_slots)
    finally:
        baseline_engine.stop()
        multi_engine.stop()

    ratios.sort()
    # Lower median: with an even sample count (deadline-truncated runs) this
    # picks the smaller middle ratio — conservative exactly when degraded.
    vs_baseline = ratios[(len(ratios) - 1) // 2]
    result = {
        "metric": "multiplexed_lora_tokens_per_sec",
        "value": round(max(multis), 2),
        "unit": "tok/s",
        "vs_baseline": round(vs_baseline, 4),
        **({"multiplexed_ttft_p50_ms": round(best_multi_stats["ttft_p50_ms"], 1),
            "multiplexed_ttft_p99_ms": round(best_multi_stats["ttft_p99_ms"], 1)}
           if best_multi_stats else {}),
        **roofline,
    }
    _emit(result)


if __name__ == "__main__":
    if "--handoff-microbench" in sys.argv:
        results = run_handoff_microbench()
        # Emit the handoff metrics IMMEDIATELY: if the pick phase below
        # hangs past the parent's subprocess deadline, the parent still
        # salvages this line (it parses the LAST JSON line it received).
        print(json.dumps(results), flush=True)
        try:
            results.update(run_pick_microbench())
        except Exception as e:  # additive phase: never block the handoff line
            results["pick_error"] = str(e)[:200]
        try:
            # Resilience microbench (robustness PR): enforcement cost of
            # health_policy=avoid vs log_only rides every emission.
            results.update(run_policy_microbench())
        except Exception as e:
            results["pick_policy_error"] = str(e)[:200]
        try:
            # Fairness microbench (fairness PR): enforcement cost of
            # mode=enforce pick deprioritization vs policy off.
            results.update(run_fairness_microbench())
        except Exception as e:
            results["pick_fairness_error"] = str(e)[:200]
        try:
            # Placement microbench (placement PR): steering cost of
            # placement_mode=prefer_resident vs no advisor.
            results.update(run_placement_microbench())
        except Exception as e:
            results["pick_placement_error"] = str(e)[:200]
        try:
            # Data-plane fast path (perf PR 6): snapshot-resident native
            # pick + batched pick_many cost at the loadgen fixture scale.
            results.update(run_native_pick_microbench())
        except Exception as e:
            results["native_pick_error"] = str(e)[:200]
        try:
            # Zero-copy relay A/B rides every emission too.
            results.update(run_relay_microbench())
        except Exception as e:
            results["relay_error"] = str(e)[:200]
        try:
            # Step-profiler overhead A/B (fleet-observability PR): the
            # <5% step_profile_ratio bound rides every emission.
            results.update(run_profiler_microbench())
        except Exception as e:
            results["profiler_error"] = str(e)[:200]
        try:
            # Lock-witness overhead A/B (concurrency-contract PR): the
            # <5% pick_witness_ratio bound rides every emission so the
            # test suite can stay witness-armed.
            results.update(run_witness_microbench())
        except Exception as e:
            results["witness_error"] = str(e)[:200]
        try:
            # KV ledger overhead A/B (KV-economy PR): the <5%
            # kv_ledger_ratio bound rides every emission so the ledger
            # can stay on by default.
            results.update(run_kv_ledger_microbench())
        except Exception as e:
            results["kv_ledger_error"] = str(e)[:200]
        try:
            # Decision-ledger overhead A/B (explainability PR): the <5%
            # pick_ledger_ratio bound rides every emission so the ledger
            # can stay on by default.
            results.update(run_pick_ledger_microbench())
        except Exception as e:
            results["pick_ledger_error"] = str(e)[:200]
        try:
            # Capacity-plane overhead A/B (capacity-twin PR): the <5%
            # capacity_tick_ratio bound rides every emission so the
            # headroom forecasts can stay on by default.
            results.update(run_capacity_microbench())
        except Exception as e:
            results["capacity_error"] = str(e)[:200]
        print(json.dumps(results), flush=True)
    else:
        main()
