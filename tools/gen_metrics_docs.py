#!/usr/bin/env python
"""Generate docs/METRICS.md from the metric registry.

Usage: ``python tools/gen_metrics_docs.py [output-path]`` (default
``docs/METRICS.md``; ``make metrics-docs`` is the canonical entry point).
``tests/test_metrics_docs.py`` asserts the committed file matches the
registry, so adding a metric family means updating
``llm_instance_gateway_tpu/metrics_registry.py`` and re-running this.
"""

from __future__ import annotations

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from llm_instance_gateway_tpu.metrics_registry import render_markdown  # noqa: E402


def main(argv: list[str] | None = None) -> int:
    argv = sys.argv[1:] if argv is None else argv
    out = argv[0] if argv else "docs/METRICS.md"
    os.makedirs(os.path.dirname(out) or ".", exist_ok=True)
    with open(out, "w", encoding="utf-8") as f:
        f.write(render_markdown())
    print(f"wrote {out}")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
