"""On-chip (real TPU) validation + timing of the Pallas kernels.

Runs the compiled (non-interpret) flash-prefill and cached-decode kernels
against the XLA references at serving-realistic shapes, reports max abs
error and wall time.  This is the round-2 gate for flipping
``use_flash_attention`` / ``use_pallas_decode`` defaults on TPU
(VERDICT.md "Next round" item 6).
"""

from __future__ import annotations

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp

from llm_instance_gateway_tpu.ops import attention as xla_att
from llm_instance_gateway_tpu.ops import pallas_attention as flash
from llm_instance_gateway_tpu.ops import pallas_decode_attention as pdec


def _time(fn, *args, iters=20):
    """Time `fn` with a chained on-device loop: one dispatch, `iters` real
    evaluations (the remote-tunnel per-call latency would otherwise drown
    sub-ms kernels).  The output is fed back into the first arg's low bits
    so XLA can't hoist or dedupe the iterations."""
    out = fn(*args)  # also the parity-check value
    jax.block_until_ready(out)

    import functools

    @functools.partial(jax.jit, static_argnums=0)
    def loop(n, out0, *args):
        def body(_, carry):
            a, prev = carry
            o = fn(a, *args[1:])
            # fold a data dependency the compiler can't fold away: ×(1+eps·o)
            # is numerically identity in bf16 but not statically foldable.
            a = a * (1 + o.reshape(-1)[0] * 1e-30).astype(a.dtype)
            return a, o
        a, o = jax.lax.fori_loop(0, n, body, (args[0], out0))
        return o

    def run(n):
        r = loop(n, out, *args)
        jax.block_until_ready(r)
        t0 = time.perf_counter()
        r = loop(n, out, *args)
        jax.block_until_ready(r)
        return time.perf_counter() - t0

    t_n, t_2n = run(iters), run(2 * iters)
    # Differencing cancels the (large, variable) tunnel dispatch overhead.
    return out, max(t_2n - t_n, 1e-9) / iters * 1e3


def check_flash(b=2, h=8, n_kv=2, s=2048, hd=128, dtype=jnp.bfloat16):
    kq, kk, kv = jax.random.split(jax.random.PRNGKey(0), 3)
    q = jax.random.normal(kq, (b, s, h, hd), dtype)
    k = jax.random.normal(kk, (b, s, n_kv, hd), dtype)
    v = jax.random.normal(kv, (b, s, n_kv, hd), dtype)

    ref_fn = jax.jit(xla_att.prefill_attention)
    ker_fn = jax.jit(lambda q, k, v: flash.flash_attention(q, k, v))
    ref, t_ref = _time(ref_fn, q, k, v)
    out, t_ker = _time(ker_fn, q, k, v)
    err = float(jnp.max(jnp.abs(out.astype(jnp.float32) - ref.astype(jnp.float32))))
    print(f"flash  b={b} h={h} kv={n_kv} s={s} hd={hd} {dtype.__name__}: "
          f"max_err={err:.4f} xla={t_ref:.2f}ms pallas={t_ker:.2f}ms "
          f"speedup={t_ref / t_ker:.2f}x")
    return err, t_ref, t_ker


def check_decode(b=8, h=32, n_kv=8, s_max=2048, hd=128, dtype=jnp.bfloat16):
    kq, kk, kv = jax.random.split(jax.random.PRNGKey(1), 3)
    q = jax.random.normal(kq, (b, h, hd), dtype)
    k_cache = jax.random.normal(kk, (b, s_max, n_kv, hd), dtype)
    v_cache = jax.random.normal(kv, (b, s_max, n_kv, hd), dtype)
    lengths = jnp.array([s_max // 2 + 17 * i for i in range(b)], jnp.int32) % s_max
    lengths = jnp.maximum(lengths, 1)

    ref_fn = jax.jit(xla_att.decode_attention)
    ker_fn = jax.jit(lambda q, kc, vc, l: pdec.decode_attention(q, kc, vc, l))
    ref, t_ref = _time(ref_fn, q, k_cache, v_cache, lengths, iters=50)
    out, t_ker = _time(ker_fn, q, k_cache, v_cache, lengths, iters=50)
    err = float(jnp.max(jnp.abs(out.astype(jnp.float32) - ref.astype(jnp.float32))))
    print(f"decode b={b} h={h} kv={n_kv} smax={s_max} hd={hd} {dtype.__name__}: "
          f"max_err={err:.4f} xla={t_ref:.3f}ms pallas={t_ker:.3f}ms "
          f"speedup={t_ref / t_ker:.2f}x")
    return err, t_ref, t_ker


def check_decode_quant(b=8, h=32, n_kv=8, s_max=2048, hd=128,
                       dtype=jnp.bfloat16):
    """int8-KV kernel vs dequantize-then-XLA: parity + the bandwidth win
    (half the HBM bytes per step vs the bf16 kernel)."""
    from llm_instance_gateway_tpu.models.transformer import (
        _kv_dequantize, _kv_quantize)

    kq, kk, kv = jax.random.split(jax.random.PRNGKey(2), 3)
    q = jax.random.normal(kq, (b, h, hd), dtype)
    kf = jax.random.normal(kk, (b, s_max, n_kv, hd), jnp.float32)
    vf = jax.random.normal(kv, (b, s_max, n_kv, hd), jnp.float32)
    k_int8, k_s = _kv_quantize(kf)
    v_int8, v_s = _kv_quantize(vf)
    lengths = jnp.array([s_max // 2 + 17 * i for i in range(b)], jnp.int32) % s_max
    lengths = jnp.maximum(lengths, 1)

    ref_fn = jax.jit(lambda q, kc, vc, ks, vs, l: xla_att.decode_attention(
        q, _kv_dequantize(kc, ks, q.dtype), _kv_dequantize(vc, vs, q.dtype), l))
    ker_fn = jax.jit(pdec.decode_attention_quant)
    ref, t_ref = _time(ref_fn, q, k_int8, v_int8, k_s, v_s, lengths, iters=50)
    out, t_ker = _time(ker_fn, q, k_int8, v_int8, k_s, v_s, lengths, iters=50)
    err = float(jnp.max(jnp.abs(out.astype(jnp.float32) - ref.astype(jnp.float32))))
    print(f"decode-int8 b={b} h={h} kv={n_kv} smax={s_max} hd={hd}: "
          f"max_err={err:.4f} xla-deq={t_ref:.3f}ms pallas-int8={t_ker:.3f}ms "
          f"speedup={t_ref / t_ker:.2f}x")
    return err, t_ref, t_ker


def check_paged_decode(b=8, h=32, n_kv=8, hd=128, block=64, m=32,
                       quant=False, dtype=jnp.bfloat16):
    """Direct paged kernel (block-table indirection via scalar prefetch)
    vs gather-then-attend: parity + the materialization win (the gather
    path writes AND reads a contiguous copy of the live cache per step).
    ``quant`` runs the int8-pool variant (scales on the same indirection).
    """
    import numpy as np

    from llm_instance_gateway_tpu.models.transformer import _kv_quantize

    s_max = block * m
    kq, kk, kv = jax.random.split(jax.random.PRNGKey(3), 3)
    q = jax.random.normal(kq, (b, h, hd), dtype)
    n_blocks = b * m
    kf = jax.random.normal(kk, (n_blocks + 1, block, n_kv, hd), jnp.float32)
    vf = jax.random.normal(kv, (n_blocks + 1, block, n_kv, hd), jnp.float32)
    rng = np.random.RandomState(11)
    tables = jnp.asarray(
        (rng.permutation(n_blocks) + 1).reshape(b, m), jnp.int32)
    lengths = jnp.asarray(
        [max(1, (s_max // 2 + 97 * i) % s_max) for i in range(b)], jnp.int32)

    if quant:
        k_pool, k_s = _kv_quantize(kf)
        v_pool, v_s = _kv_quantize(vf)
        scales = (k_s, v_s)
    else:
        k_pool, v_pool = kf.astype(dtype), vf.astype(dtype)
        scales = ()

    def gather_path(q, kp, vp, tabs, lens, *sc):
        from llm_instance_gateway_tpu.ops.attention import gather_pool_rows

        def rows(pool):
            return gather_pool_rows(pool, tabs)
        if sc:
            return pdec.decode_attention_quant(
                q, rows(kp), rows(vp), rows(sc[0]), rows(sc[1]), lens)
        return pdec.decode_attention(q, rows(kp), rows(vp), lens)

    ref_fn = jax.jit(gather_path)
    ker_fn = jax.jit(pdec.paged_decode_attention_pallas)
    ref, t_ref = _time(ref_fn, q, k_pool, v_pool, tables, lengths, *scales,
                       iters=50)
    out, t_ker = _time(ker_fn, q, k_pool, v_pool, tables, lengths, *scales,
                       iters=50)
    err = float(jnp.max(jnp.abs(out.astype(jnp.float32) - ref.astype(jnp.float32))))
    tag = "int8" if quant else "bf16"
    print(f"paged-decode-{tag} b={b} h={h} kv={n_kv} block={block} m={m} "
          f"smax={s_max}: max_err={err:.4f} gather+kernel={t_ref:.3f}ms "
          f"direct={t_ker:.3f}ms speedup={t_ref / t_ker:.2f}x")
    return err, t_ref, t_ker


def check_chunk(c=512, s_max=8192, h=32, n_kv=8, hd=128, start=4096,
                dtype=jnp.bfloat16):
    """Chunk-stream attend: flash-style kernel vs the XLA reference's
    [C, S_max] logits materialization — the long-context TTFT hot op."""
    from llm_instance_gateway_tpu.ops.attention import xla_chunk_attention
    from llm_instance_gateway_tpu.ops.pallas_attention import (
        chunk_attention_pallas,
    )

    ks = jax.random.split(jax.random.PRNGKey(4), 3)
    q = jax.random.normal(ks[0], (1, c, h, hd), dtype)
    kc = jax.random.normal(ks[1], (1, s_max, n_kv, hd), dtype)
    vc = jax.random.normal(ks[2], (1, s_max, n_kv, hd), dtype)
    off = jnp.int32(start)
    ref_fn = jax.jit(xla_chunk_attention)
    ker_fn = jax.jit(chunk_attention_pallas)
    ref, t_ref = _time(ref_fn, q, kc, vc, off, iters=20)
    out, t_ker = _time(ker_fn, q, kc, vc, off, iters=20)
    err = float(jnp.max(jnp.abs(out.astype(jnp.float32) - ref.astype(jnp.float32))))
    print(f"chunk-attend c={c} smax={s_max} start={start} h={h}: "
          f"max_err={err:.4f} xla={t_ref:.3f}ms pallas={t_ker:.3f}ms "
          f"speedup={t_ref / t_ker:.2f}x")
    return err, t_ref, t_ker


if __name__ == "__main__":
    print("devices:", jax.devices())
    for s in (512, 2048, 8192):
        check_flash(s=s)
    for s_max in (1024, 2048, 8192):
        check_decode(s_max=s_max)
    for s_max in (1024, 2048, 8192):
        check_decode_quant(s_max=s_max)
    for quant in (False, True):
        for m in (16, 64):
            check_paged_decode(m=m, quant=quant)
    for start in (0, 4096):
        check_chunk(start=start)
