"""Generate golden Envoy ext_proc wire transcripts for replay testing.

A stock Envoy configured with ``deploy/gateway/envoy.yaml``'s
``EnvoyExtensionPolicy`` (processingMode: request {body: Buffered},
response {body: Buffered} — reference parity:
``/root/reference/pkg/manifests/ext_proc.yaml:84-111``) drives the EPP
with this message sequence per HTTP request:

    1. ProcessingRequest{request_headers}   (full request header map)
    2. ProcessingRequest{request_body}      (whole body, end_of_stream=true)
    3. ProcessingRequest{response_headers}  (upstream's response headers)
    4. ProcessingRequest{response_body}     (whole body, end_of_stream=true)

This tool serializes that sequence — realistic Envoy header sets
(pseudo-headers, x-request-id, x-forwarded-proto, content-length) included —
into length-prefixed binary transcripts under ``tests/golden/``.

PROVENANCE CAVEAT: the transcripts are SYNTHESIZED from the ext_proc spec
and this repo's own vendored pb2 modules.  They encode the author's belief
about Envoy's phase sequence; no real Envoy has produced or validated
these bytes.  They pin byte stability against regression — they do not
certify Envoy conformance.  The first time a real Envoy is available,
regenerate them from a packet capture of the live stream.  The
replay suite (``tests/test_envoy_golden_replay.py``) streams the COMMITTED
BYTES through a real gRPC channel to the real EPP, so any drift in the
vendored proto subset or the server's phase handling breaks loudly against
bytes fixed in git.

Why transcripts instead of a live Envoy: this build image has no Envoy
binary, no container runtime, and no network egress to fetch either, so
the reference's kind-based e2e (``test/e2e/e2e_test.go:32-122``) cannot
run here.  The protocol surface is pinned three ways instead: upstream
field numbers (test_extproc_hermetic.py::TestWireCompat), live-stub
integration (the rest of that suite), and these byte-frozen transcripts.

Frame format: repeated [u32 big-endian length][ProcessingRequest bytes].

Usage: python tools/make_envoy_golden.py  (regenerates tests/golden/*.bin)
"""

from __future__ import annotations

import json
import os
import struct
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from llm_instance_gateway_tpu.gateway.extproc import envoy_base_pb2 as corepb
from llm_instance_gateway_tpu.gateway.extproc import ext_proc_v3_pb2 as pb

GOLDEN_DIR = os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    "tests", "golden",
)


def _headers(pairs: list[tuple[str, bytes]]) -> pb.HttpHeaders:
    # Envoy >= 1.27 populates raw_value (bytes), not value — the replay
    # covers the modern encoding; the hermetic suite covers value=.
    return pb.HttpHeaders(
        headers=corepb.HeaderMap(headers=[
            corepb.HeaderValue(key=k, raw_value=v) for k, v in pairs
        ])
    )


def _request_headers(body: bytes, authority: str, req_id: str) -> pb.ProcessingRequest:
    return pb.ProcessingRequest(request_headers=_headers([
        (":authority", authority.encode()),
        (":method", b"POST"),
        (":path", b"/v1/completions"),
        (":scheme", b"http"),
        ("content-type", b"application/json"),
        ("content-length", str(len(body)).encode()),
        ("user-agent", b"envoy-golden-replay/1"),
        ("x-forwarded-proto", b"http"),
        ("x-request-id", req_id.encode()),
    ]))


def _response_headers(body: bytes) -> pb.ProcessingRequest:
    return pb.ProcessingRequest(response_headers=_headers([
        (":status", b"200"),
        ("content-type", b"application/json"),
        ("content-length", str(len(body)).encode()),
    ]))


def completion_transcript() -> list[pb.ProcessingRequest]:
    """One full /v1/completions round-trip for the hermetic fixture's
    ``sql-lora`` model (traffic-split target sql-lora-v1, pod affinity)."""
    req_body = json.dumps({
        "model": "sql-lora",
        "prompt": "golden replay prompt",
        "max_tokens": 100,
        "temperature": 0,
    }).encode()
    resp_body = json.dumps({
        "id": "cmpl-golden", "object": "text_completion",
        "choices": [{"index": 0, "text": " ok", "finish_reason": "length"}],
        "usage": {"prompt_tokens": 5, "completion_tokens": 10,
                  "total_tokens": 15},
    }).encode()
    return [
        _request_headers(req_body, "tpu-inference-gateway", "golden-req-1"),
        pb.ProcessingRequest(
            request_body=pb.HttpBody(body=req_body, end_of_stream=True)),
        _response_headers(resp_body),
        pb.ProcessingRequest(
            response_body=pb.HttpBody(body=resp_body, end_of_stream=True)),
    ]


def shed_transcript() -> list[pb.ProcessingRequest]:
    """A sheddable-model request against a saturated pool: the EPP must
    answer the body phase with an immediate 429 (no upstream phases —
    Envoy short-circuits on immediate_response)."""
    req_body = json.dumps({
        "model": "batch",
        "prompt": "golden shed prompt",
        "max_tokens": 100,
        "temperature": 0,
    }).encode()
    return [
        _request_headers(req_body, "tpu-inference-gateway", "golden-req-2"),
        pb.ProcessingRequest(
            request_body=pb.HttpBody(body=req_body, end_of_stream=True)),
    ]


def write(path: str, msgs: list[pb.ProcessingRequest]) -> None:
    with open(path, "wb") as f:
        for m in msgs:
            blob = m.SerializeToString()
            f.write(struct.pack(">I", len(blob)))
            f.write(blob)
    print(f"wrote {path} ({len(msgs)} frames)")


def main() -> None:
    os.makedirs(GOLDEN_DIR, exist_ok=True)
    write(os.path.join(GOLDEN_DIR, "envoy_extproc_completion.bin"),
          completion_transcript())
    write(os.path.join(GOLDEN_DIR, "envoy_extproc_shed429.bin"),
          shed_transcript())


if __name__ == "__main__":
    main()
