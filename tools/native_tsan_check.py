#!/usr/bin/env python3
"""Thread-sanitized native build gate (`make native-tsan`).

The ASan gate (tools/native_asan_check.py) proves the library safe against
hostile INPUTS; this gate proves it safe against hostile SCHEDULES.  The
fuzz harness's threaded stages drive the two concurrency contracts the
gateway's data-plane fast path rests on:

1. **The _call_lock protocol suffices**: picker threads calling
   ``lig_pick_many`` race an updater thread swapping whole snapshots via
   ``lig_state_update`` on ONE state handle, every call serialized by a
   mutex mirroring ``NativeScheduler._call_lock``.  The Python-side lock
   is only correct if the library hides no unsynchronized global state
   behind it — TSan checks the library's real memory accesses, not our
   beliefs about them.
2. **Picks are const**: threads call ``lig_pick``/``lig_pick_many``
   concurrently with NO lock and no writer.  The candidate computation
   must read the snapshot and write only caller buffers; a hidden mutable
   cache inside ``State`` would race here.  This property is why the
   gateway may copy candidates out and run the prefix/RNG/note_* finish
   seams outside the lock (the PR-6 lock discipline).

Exit 0 with ``NATIVE-TSAN PASS`` on success; exit 0 with a loud
``NATIVE-TSAN SKIPPED: <why>`` when the toolchain or the TSan runtime is
absent (the pytest wrapper converts that into a visible skip); exit 1 on
any failure or sanitizer report.
"""

from __future__ import annotations

import os
import shutil
import subprocess
import sys
import tempfile

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
NATIVE_DIR = os.path.join(REPO, "llm_instance_gateway_tpu", "native")
FUZZ_BIN = os.path.join(NATIVE_DIR, "ligsched_tsan_fuzz")


def skip(why: str) -> int:
    print(f"NATIVE-TSAN SKIPPED: {why}", flush=True)
    return 0


def _tsan_runtime_available(cxx: str) -> bool:
    """Probe-compile a trivial program with -fsanitize=thread: some hosts
    ship g++ but not libtsan, and that must be a loud skip, not a
    confusing build error."""
    with tempfile.TemporaryDirectory() as tmp:
        src = os.path.join(tmp, "probe.cc")
        out = os.path.join(tmp, "probe")
        with open(src, "w") as fh:
            fh.write("int main() { return 0; }\n")
        try:
            rc = subprocess.run(
                [cxx, "-fsanitize=thread", "-pthread", src, "-o", out],
                capture_output=True, text=True, timeout=60)
        except (OSError, subprocess.SubprocessError):
            return False
        return rc.returncode == 0


def main() -> int:
    cxx = os.environ.get("CXX", "g++")
    if shutil.which(cxx) is None or shutil.which("make") is None:
        return skip(f"no C++ toolchain ({cxx}/make not found) — the "
                    f"thread-sanitized scheduler build cannot run on "
                    f"this host")
    if not _tsan_runtime_available(cxx):
        return skip("libtsan not available (probe compile with "
                    "-fsanitize=thread failed)")
    build = subprocess.run(["make", "-C", NATIVE_DIR, "tsan"],
                           capture_output=True, text=True)
    if build.returncode != 0:
        print(build.stdout + build.stderr)
        print("NATIVE-TSAN FAIL: thread-sanitized build failed")
        return 1
    env = dict(os.environ,
               TSAN_OPTIONS="halt_on_error=1:second_deadlock_stack=1")
    print("[1/1] threaded pick/update fuzz under TSan", flush=True)
    fuzz = subprocess.run([FUZZ_BIN], env=env, capture_output=True,
                          text=True, timeout=600)
    print(fuzz.stdout, end="")
    if fuzz.returncode != 0:
        print(fuzz.stderr)
        print("NATIVE-TSAN FAIL: threaded fuzz reported errors")
        return 1
    print("NATIVE-TSAN PASS")
    return 0


if __name__ == "__main__":
    sys.exit(main())
