"""CPU-deterministic microbench regression gate (``make bench-check``).

ROADMAP item 5, first slice: the bench.py microbench suite — pick latency
(Python, native snapshot-resident, batched pick_many), handoff blocks/s,
the tracing/policy overhead ratios, and the zero-copy relay A/B — gets a
COMMITTED baseline (``BASELINE_BENCH.json``) and a gate that fails on >20%
regression against it, plus the absolute ratio bounds the PRs' acceptance
bars pinned (``pick_traced_ratio``/``pick_policy_ratio`` < 1.05).

Run:    make bench-check            # compare against BASELINE_BENCH.json
        python tools/bench_check.py --update   # re-baseline (new rig)
        python tools/bench_check.py --skip-handoff   # quick gate

Every measurement uses the MIN-over-interleaved-runs convention from
bench.py, so single-run container noise mostly cancels; the 20% tolerance
absorbs what remains.  Baselines are rig-specific: re-run ``--update``
when the hardware changes, never to paper over a regression.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)

BASELINE_PATH = os.path.join(_REPO, "BASELINE_BENCH.json")

# metric -> ("higher"|"lower", relative tolerance).  "lower" = smaller is
# better (latency); "higher" = bigger is better (throughput).
GATED = {
    "pick_us": ("lower", 0.20),
    "pick_native_us": ("lower", 0.20),
    "pick_many_us": ("lower", 0.20),
    "handoff_blocks_per_s": ("higher", 0.20),
    "relay_fast_chunks_per_s": ("higher", 0.20),
    "decode_adaptive_tok_s": ("higher", 0.20),
}
# Absolute bounds that hold regardless of the baseline (the PR acceptance
# bars: tracing/policy enforcement each cost < 5% of a pick; the stop
# automaton < 15% of a fused decode wall on the micro model).
ABSOLUTE_MAX = {
    "pick_traced_ratio": 1.05,
    "pick_policy_ratio": 1.05,
    "pick_fairness_ratio": 1.05,
    "pick_placement_ratio": 1.05,
    "step_profile_ratio": 1.05,
    "pick_witness_ratio": 1.05,
    "kv_ledger_ratio": 1.05,
    "pick_ledger_ratio": 1.05,
    "capacity_tick_ratio": 1.05,
    "device_stops_ratio": 1.15,
}
# Absolute floors.  relay_fast_ratio (slow wall / fast wall) hovers around
# 1.0 on a socket-bound rig, so a baseline-relative gate would only measure
# noise; the invariant worth pinning is that the zero-copy path never gets
# MEANINGFULLY slower than the line-scanning oracle.
# decode_adaptive_speedup >= 2.0 is the decode-lever PR's pinned
# acceptance bar (adaptive fused dispatch + device-side stops vs the
# steps=1 host-stop seed settings); stream_lanes_max_active == 2 pins the
# head-of-line fix (a second long prompt streams CONCURRENTLY — sampled
# across every round, so one missed polling window can't flake the gate);
# the TTFT ratio floor only pins "a second lane never makes the second
# prompt SLOWER" (the improvement itself swings 1.1-1.4x with host
# timing, so a tighter floor would gate noise).
ABSOLUTE_MIN = {
    "relay_fast_ratio": 0.80,
    "decode_adaptive_speedup": 2.0,
    "stream_lanes_max_active": 2,
    "stream_second_ttft_ratio": 1.0,
}


# ratio-bound metric -> the bench family that produces it, for the
# retry-on-over-bound pass in collect_families().
_RATIO_SOURCES = {
    "pick_traced_ratio": "pick",
    "pick_policy_ratio": "policy",
    "pick_fairness_ratio": "fairness",
    "pick_placement_ratio": "placement",
    "step_profile_ratio": "profiler",
    "pick_witness_ratio": "witness",
    "kv_ledger_ratio": "kvledger",
    "pick_ledger_ratio": "pickledger",
    "capacity_tick_ratio": "capacity",
    "device_stops_ratio": "decode",
}

# family -> (primary metric, direction) used to choose the conservative
# run in the --update --runs merge.  Whole families come from ONE run so
# sibling metrics (e.g. relay_fast/relay_slow chunks/s and their ratio)
# stay internally consistent in the committed baseline.
_FAMILY_PRIMARY = {
    "pick": ("pick_us", "lower"),
    "policy": ("pick_policy_ratio", "lower"),
    "fairness": ("pick_fairness_ratio", "lower"),
    "placement": ("pick_placement_ratio", "lower"),
    "profiler": ("step_profile_ratio", "lower"),
    "witness": ("pick_witness_ratio", "lower"),
    "kvledger": ("kv_ledger_ratio", "lower"),
    "pickledger": ("pick_ledger_ratio", "lower"),
    "capacity": ("capacity_tick_ratio", "lower"),
    "native": ("pick_native_us", "lower"),
    "relay": ("relay_fast_chunks_per_s", "higher"),
    "handoff": ("handoff_blocks_per_s", "higher"),
    "decode": ("decode_adaptive_speedup", "higher"),
}


def collect_families(skip_handoff: bool = False) -> dict[str, dict]:
    """Run the CPU-deterministic suite in-process; returns metric dicts
    keyed by microbench family (each family from one coherent run)."""
    import bench

    fams: dict[str, dict] = {
        "pick": bench.run_pick_microbench(),
        "policy": bench.run_policy_microbench(),
        "fairness": bench.run_fairness_microbench(),
        "placement": bench.run_placement_microbench(),
        "profiler": bench.run_profiler_microbench(),
        "witness": bench.run_witness_microbench(),
        "kvledger": bench.run_kv_ledger_microbench(),
        "pickledger": bench.run_pick_ledger_microbench(),
        "capacity": bench.run_capacity_microbench(),
        "native": bench.run_native_pick_microbench(),
        "relay": bench.run_relay_microbench(n_chunks=512, chunk_bytes=2048),
        "decode": bench.run_decode_lever_microbench(),
    }
    # The <5% overhead bounds are MIN-ratio estimates (12 interleaved
    # rounds per side inside each microbench), but one collect() pass on a
    # phase-shifting container can still catch the two A/B sides in
    # different host phases and report a spuriously high ratio.  Retry
    # just the offending microbench and keep the better attempt: a retry
    # only tightens toward the true uncontended overhead — if the ratio
    # is GENUINELY above the bound, every retry stays above it and the
    # gate still fails.
    _RATIO_FNS = {"pick": bench.run_pick_microbench,
                  "policy": bench.run_policy_microbench,
                  "fairness": bench.run_fairness_microbench,
                  "placement": bench.run_placement_microbench,
                  "profiler": bench.run_profiler_microbench,
                  "witness": bench.run_witness_microbench,
                  "kvledger": bench.run_kv_ledger_microbench,
                  "pickledger": bench.run_pick_ledger_microbench,
                  "capacity": bench.run_capacity_microbench,
                  "decode": bench.run_decode_lever_microbench}
    for metric, fam in _RATIO_SOURCES.items():
        for _ in range(2):
            if fams[fam].get(metric, 0.0) <= ABSOLUTE_MAX[metric]:
                break
            redo = _RATIO_FNS[fam]()
            if redo[metric] < fams[fam][metric]:
                fams[fam] = redo  # whole family: keep the µs coherent
    if not skip_handoff:
        handoff = bench.run_handoff_microbench()
        # Only the scalar plane metrics belong in the gate file.
        fams["handoff"] = {
            key: handoff[key]
            for key in ("handoff_blocks_per_s", "handoff_wire_mb_s",
                        "usage_attribution_ratio") if key in handoff
        }
    return fams


def collect(skip_handoff: bool = False) -> dict:
    """Flat metric dict the gate consumes."""
    out: dict = {}
    for fam in collect_families(skip_handoff).values():
        out.update(fam)
    return out


def compare(baseline: dict, current: dict,
            require_all: bool = True) -> list[str]:
    """Gate ``current`` against ``baseline``; returns failure strings
    (empty = green).  ``require_all=False`` restricts the check to the
    metrics present in ``current`` (the --skip-handoff quick mode)."""
    failures = []
    for metric, (direction, tol) in GATED.items():
        base = baseline.get(metric)
        if base is None:
            continue  # baseline predates the metric: nothing to gate yet
        cur = current.get(metric)
        if cur is None:
            if require_all:
                failures.append(f"{metric}: missing from current run "
                                f"(baseline {base})")
            continue
        if direction == "lower":
            limit = base * (1 + tol)
            if cur > limit:
                failures.append(
                    f"{metric}: {cur} > {limit:.4g} "
                    f"(baseline {base}, +{tol:.0%} tolerance)")
        else:
            limit = base * (1 - tol)
            if cur < limit:
                failures.append(
                    f"{metric}: {cur} < {limit:.4g} "
                    f"(baseline {base}, -{tol:.0%} tolerance)")
    for metric, bound in ABSOLUTE_MAX.items():
        cur = current.get(metric)
        if cur is None:
            if require_all and metric in baseline:
                failures.append(f"{metric}: missing from current run")
            continue
        if cur > bound:
            failures.append(f"{metric}: {cur} > absolute bound {bound}")
    for metric, bound in ABSOLUTE_MIN.items():
        cur = current.get(metric)
        if cur is None:
            if require_all and metric in baseline:
                failures.append(f"{metric}: missing from current run")
            continue
        if cur < bound:
            failures.append(f"{metric}: {cur} < absolute floor {bound}")
    return failures


def render_table(baseline: dict, current: dict) -> str:
    rows = ["metric                        baseline      current"]
    for metric in sorted(set(GATED) | set(ABSOLUTE_MAX) | set(ABSOLUTE_MIN)):
        if metric in baseline or metric in current:
            rows.append(f"{metric:<28}  {baseline.get(metric, '-')!s:>10}  "
                        f"{current.get(metric, '-')!s:>10}")
    return "\n".join(rows)


def main(argv: list[str] | None = None) -> int:
    parser = argparse.ArgumentParser(
        description="microbench regression gate vs BASELINE_BENCH.json")
    parser.add_argument("--update", action="store_true",
                        help="re-baseline: write the collected metrics to "
                             "BASELINE_BENCH.json instead of gating")
    parser.add_argument("--skip-handoff", action="store_true",
                        help="skip the engine handoff phase (~20s): gate "
                             "only the scheduler/relay microbenches")
    parser.add_argument("--runs", type=int, default=1,
                        help="with --update: collect N times and keep the "
                             "CONSERVATIVE edge per metric (max for "
                             "latencies, min for throughputs) so the gate "
                             "tolerance absorbs normal run-to-run noise")
    args = parser.parse_args(argv)

    if args.update and args.runs > 1:
        # Conservative-edge merge at FAMILY granularity: per family, keep
        # the run whose primary gated metric is worst (max latency / min
        # throughput) so the gate tolerance absorbs run-to-run noise —
        # but never mix metrics from different runs inside a family, or
        # the committed siblings (e.g. relay chunks/s vs relay ratio)
        # contradict each other.
        best = collect_families(skip_handoff=args.skip_handoff)
        for _ in range(args.runs - 1):
            nxt = collect_families(skip_handoff=args.skip_handoff)
            for fam, (metric, direction) in _FAMILY_PRIMARY.items():
                if fam not in nxt or fam not in best:
                    continue
                worse = (nxt[fam].get(metric, 0)
                         > best[fam].get(metric, 0))
                if worse == (direction == "lower"):
                    best[fam] = nxt[fam]
        current = {}
        for fam in best.values():
            current.update(fam)
    else:
        current = collect(skip_handoff=args.skip_handoff)
    if args.update:
        if args.skip_handoff and os.path.exists(BASELINE_PATH):
            # Partial update keeps the existing handoff numbers.
            with open(BASELINE_PATH) as f:
                merged = json.load(f).get("metrics", {})
        else:
            merged = {}
        merged.update(current)
        payload = {
            "note": ("CPU-deterministic microbench baselines "
                     "(tools/bench_check.py --update; min over interleaved "
                     "runs, rig-specific)"),
            "gates": {m: {"direction": d, "tolerance": t}
                      for m, (d, t) in GATED.items()},
            "absolute_max": ABSOLUTE_MAX,
            "absolute_min": ABSOLUTE_MIN,
            "metrics": merged,
        }
        with open(BASELINE_PATH, "w") as f:
            json.dump(payload, f, indent=1)
            f.write("\n")
        print(f"baseline written: {BASELINE_PATH}")
        print(render_table(merged, current))
        return 0

    if not os.path.exists(BASELINE_PATH):
        print(f"no baseline at {BASELINE_PATH}; run with --update first",
              file=sys.stderr)
        return 2
    with open(BASELINE_PATH) as f:
        baseline = json.load(f)["metrics"]
    failures = compare(baseline, current,
                       require_all=not args.skip_handoff)
    print(render_table(baseline, current))
    if failures:
        print("\nBENCH-CHECK FAILED:", file=sys.stderr)
        for line in failures:
            print(f"  {line}", file=sys.stderr)
        return 1
    print("\nbench-check green")
    return 0


if __name__ == "__main__":
    sys.exit(main())
