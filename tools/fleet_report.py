"""Fleet-wide observability report: one request across N replicas.

Renders a gateway's ``/debug/fleet`` payload — or builds one locally from
several replicas' ``/debug/traces`` documents — into the three tables an
operator scaling past one gateway actually needs:

- **fleet phase table**: per-phase p50/p95/p99 over the STITCHED
  cross-replica timelines (a two-hop disagg request contributes its
  prefill replica's spans, its decode replica's spans, and its gateway's
  hop spans to the same rows);
- **slowest-trace exemplars**: the worst end-to-end traces with their
  per-span breakdown and source replicas — the "which replica ate the
  time" answer;
- **per-replica divergence**: each source's per-phase p50 against the
  fleet p50 (ratio >1 = this replica is slower than the fleet on that
  phase), plus the fleet SLO rollup and source health when the input is
  a /debug/fleet payload.

Usage:
  python tools/fleet_report.py http://gw-1:8081/debug/fleet
  python tools/fleet_report.py --replicas http://gw-1:8081,http://gw-2:8082
  python tools/fleet_report.py fleet.json --json
"""

from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from llm_instance_gateway_tpu.gateway import fleetobs  # noqa: E402
from tools.trace_report import (  # noqa: E402 — one loader, no drift
    format_table,
    load,
    percentile,
    phase_table,
)


def collect_replicas(bases: list[str]) -> dict:
    """Build a fleet-shaped payload client-side from several replicas'
    debug surfaces (the same stitcher /debug/fleet runs server-side)."""
    trace_sources = []
    slo_payloads = {}
    sources = []
    for base in bases:
        base = base.rstrip("/")
        row = {"name": base, "kind": "gateway", "url": base, "ok": True,
               "error": ""}
        try:
            trace_sources.append(
                (base, load(f"{base}/debug/traces?limit=256")))
            try:
                slo_payloads[base] = load(f"{base}/debug/slo")
            except Exception:  # pods have no /debug/slo
                row["kind"] = "pod"
        except Exception as e:  # noqa: BLE001 — a dead replica is a marker
            row["ok"], row["error"] = False, str(e)[:200]
        sources.append(row)
    return {
        "replica": "(client-side collect)",
        "sources": sources,
        "traces": fleetobs.stitch_traces(trace_sources),
        "slo": fleetobs.fleet_slo(slo_payloads),
        "health": {},
        "events": [],
    }


def phase_samples_by_source(traces: list[dict]) -> tuple[dict, dict]:
    """(fleet phase->samples, source->phase->samples) off stitched spans."""
    fleet: dict[str, list[float]] = {}
    per_source: dict[str, dict[str, list[float]]] = {}
    for trace in traces or []:
        for span in trace.get("spans") or []:
            try:
                d = max(0.0, float(span["end"]) - float(span["start"]))
            except (KeyError, TypeError, ValueError):
                continue
            name = str(span.get("name", "?"))
            fleet.setdefault(name, []).append(d)
            src = str(span.get("source", "?"))
            per_source.setdefault(src, {}).setdefault(name, []).append(d)
    return fleet, per_source


def slowest_traces(traces: list[dict], n: int = 3) -> list[dict]:
    rows = []
    for t in traces or []:
        spans = t.get("spans") or []
        if not spans:
            continue
        dur = max(float(s["end"]) for s in spans) - min(
            float(s["start"]) for s in spans)
        rows.append({
            "trace_id": t.get("trace_id", "?"),
            "model": t.get("model", ""),
            "path": t.get("path", ""),
            "status": t.get("status", ""),
            "total_ms": round(dur * 1e3, 3),
            "sources": t.get("sources", []),
            "skew": t.get("skew", {}),
            "spans": [
                {"name": s["name"], "source": s.get("source", "?"),
                 "ms": round((float(s["end"]) - float(s["start"])) * 1e3, 3)}
                for s in spans],
        })
    rows.sort(key=lambda r: -r["total_ms"])
    return rows[:n]


def divergence_rows(fleet: dict, per_source: dict) -> list[dict]:
    """Per (source, phase): source p50 / fleet p50 — who is slow where."""
    rows = []
    for src in sorted(per_source):
        for phase, xs in sorted(per_source[src].items()):
            if not xs or not fleet.get(phase):
                continue
            src_p50 = percentile(sorted(xs), 0.50)
            fleet_p50 = percentile(sorted(fleet[phase]), 0.50)
            rows.append({
                "source": src,
                "phase": phase,
                "n": len(xs),
                "p50_ms": round(src_p50 * 1e3, 3),
                "vs_fleet": (round(src_p50 / fleet_p50, 3)
                             if fleet_p50 > 0 else None),
            })
    return rows


def render_report(payload: dict) -> str:
    traces = payload.get("traces") or []
    fleet, per_source = phase_samples_by_source(traces)
    out = [
        "=" * 72,
        f"FLEET OBSERVABILITY REPORT (collected by "
        f"{payload.get('replica', '?')}; {len(traces)} stitched traces)",
        "=" * 72,
        "",
        "Sources:",
    ]
    for s in payload.get("sources") or []:
        status = "ok" if s.get("ok") else f"ERROR {s.get('error', '')}"
        out.append(f"  {s.get('kind', '?'):<8} {s.get('name', '?'):<40}"
                   f" {status}")
    out += ["", "Fleet per-phase latency (stitched spans):",
            format_table(phase_table(fleet))]
    slo = payload.get("slo") or {}
    if slo.get("models"):
        out += ["", "Fleet SLO rollup:"]
        for model in sorted(slo["models"]):
            for objective, agg in sorted(slo["models"][model].items()):
                states = ",".join(
                    f"{r}={s}" for r, s in sorted(
                        (agg.get("states") or {}).items()))
                out.append(
                    f"  {model}/{objective:<11}"
                    f" compliance={agg.get('compliance')}"
                    f" good/total={agg.get('good')}/{agg.get('total')}"
                    f" worst_burn={agg.get('worst_burn')}"
                    f"@{agg.get('worst_burn_replica')} [{states}]")
    exemplars = slowest_traces(traces)
    if exemplars:
        out += ["", "Slowest traces:"]
        for r in exemplars:
            skew = (f" skew={r['skew']}" if r["skew"] else "")
            out.append(f"  {r['trace_id']} model={r['model']} "
                       f"path={r['path']} total={r['total_ms']}ms "
                       f"sources={','.join(r['sources'])}{skew}")
            for s in r["spans"]:
                out.append(f"    {s['name']:<22} {s['ms']:>10.3f}ms  "
                           f"[{s['source']}]")
    div = divergence_rows(fleet, per_source)
    if div:
        out += ["", "Per-replica divergence (p50 vs fleet p50):",
                format_table([{k: r[k] for k in
                               ("source", "phase", "n", "p50_ms",
                                "vs_fleet")} for r in div])]
    out.append("")
    return "\n".join(out)


def main(argv: list[str] | None = None) -> int:
    parser = argparse.ArgumentParser(
        description="fleet-wide stitched-trace report from /debug/fleet "
                    "or several replicas' /debug/traces")
    parser.add_argument("source", nargs="?",
                        help="/debug/fleet URL, file path, or - for stdin")
    parser.add_argument("--replicas",
                        help="CSV of replica base URLs to collect and "
                             "stitch client-side (instead of a "
                             "/debug/fleet source)")
    parser.add_argument("--json", action="store_true",
                        help="emit the computed tables as one JSON doc")
    args = parser.parse_args(argv)
    if args.replicas:
        payload = collect_replicas(
            [u.strip() for u in args.replicas.split(",") if u.strip()])
    elif args.source:
        payload = load(args.source)
    else:
        parser.error("need a source or --replicas")
    if args.json:
        fleet, per_source = phase_samples_by_source(
            payload.get("traces") or [])
        print(json.dumps({
            "phases": phase_table(fleet),
            "slowest": slowest_traces(payload.get("traces") or []),
            "divergence": divergence_rows(fleet, per_source),
            "slo": payload.get("slo"),
        }))
    else:
        print(render_report(payload))
    return 0


if __name__ == "__main__":
    sys.exit(main())
