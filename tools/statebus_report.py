#!/usr/bin/env python
"""Statebus divergence report: merged-vs-local state per gateway replica.

Reads a gateway's ``/debug/statebus`` payload (live URL or a saved JSON
file) and renders, per pool, how the replica's LOCAL tick-derived state
differs from the MERGED fleet view its advisors currently wear — the
first question when debugging a multi-gateway front ("why does gw-2
still route to the hog's replica?" -> its merged view is stale/diverged).

Sections:

- **replicas**: every replica the gateway knows, with snapshot seq, age,
  and freshness (stale replicas are excluded from the merged view).
- **per-pool divergence**: for each key family (noisy flags, avoid set,
  resident map) the entries only-local vs only-merged.  An empty table
  means the fleet agrees; ``statebus stale — local-only enforcement``
  is called out loudly.

Usage::

    python tools/statebus_report.py --url http://localhost:8081 --once
    python tools/statebus_report.py --from-file /tmp/statebus.json --once

``--once`` renders a single report and exits (CI-friendly); ``--watch``
re-renders every N seconds.  ``--json`` dumps the raw payload instead.
"""

from __future__ import annotations

import argparse
import json
import sys
import time
import urllib.request


def fetch_payload(url: str) -> dict:
    with urllib.request.urlopen(f"{url.rstrip('/')}/debug/statebus",
                                timeout=5.0) as resp:
        return json.loads(resp.read().decode())


def _fmt_set(items) -> str:
    items = sorted(items)
    if not items:
        return "-"
    body = ", ".join(str(i) for i in items[:6])
    return body + (f" (+{len(items) - 6} more)" if len(items) > 6 else "")


def _resident_sets(resident: dict) -> set:
    """Flatten a resident map into comparable (adapter, tier, pod)
    triples."""
    out = set()
    for adapter, tiers in (resident or {}).items():
        slot, host = (tiers + [[], []])[:2] if isinstance(tiers, list) \
            else tiers
        out |= {(adapter, "slot", p) for p in slot}
        out |= {(adapter, "host", p) for p in host}
    return out


def render_report(payload: dict) -> str:
    """The human-readable report (pure function of the /debug/statebus
    payload — tested offline)."""
    lines: list[str] = []
    replica = payload.get("replica", "?")
    lines.append(f"statebus @ {replica}  seq={payload.get('seq')}  "
                 f"live_replicas={payload.get('live_replicas')}  "
                 f"quota_scale={payload.get('quota_scale')}")
    if payload.get("stale"):
        lines.append("  !! STALE: every peer aged out — LOCAL-ONLY "
                     "enforcement (statebus_stale journaled)")
    lines.append("")
    lines.append("  %-28s %8s %10s %s" % ("replica", "seq", "age_s",
                                          "fresh"))
    for rid, row in sorted(payload.get("replicas", {}).items()):
        lines.append("  %-28s %8s %10.3f %s"
                     % (rid, row.get("seq"), row.get("age_s", 0.0),
                        "yes" if row.get("fresh") else "NO (stale)"))
    local = payload.get("local", {})
    merged = payload.get("merged", {})
    for pool in sorted(set(local) | set(merged)):
        lp = local.get(pool, {})
        mp = merged.get(pool, {})
        lines.append("")
        lines.append(f"  pool {pool}:")
        l_noisy = set(lp.get("noisy", {}))
        m_noisy = set(mp.get("noisy", {}))
        l_avoid = set(lp.get("avoid", []))
        m_avoid = set(mp.get("avoid", []))
        l_res = _resident_sets(lp.get("resident", {}))
        m_res = _resident_sets(mp.get("resident", {}))
        rows = [
            ("noisy", l_noisy, m_noisy),
            ("avoid", l_avoid, m_avoid),
            ("resident", l_res, m_res),
        ]
        lines.append("    %-10s %-34s %s" % ("family", "only-local",
                                             "only-merged(peers)"))
        diverged = False
        for family, lset, mset in rows:
            only_l, only_m = lset - mset, mset - lset
            if only_l or only_m:
                diverged = True
            lines.append("    %-10s %-34s %s"
                         % (family, _fmt_set(only_l), _fmt_set(only_m)))
        lines.append("    (fleet agrees)" if not diverged
                     else "    => diverged: merged view adds/lacks the "
                          "entries above vs this replica's own state")
        shares = [s for s in lp.get("shares", [])
                  if isinstance(s, (list, tuple)) and len(s) == 3]
        if shares:
            top = sorted(shares, key=lambda s: -s[2])[:5]
            lines.append("    top local shares: " + ", ".join(
                f"{m}/{a}={v}" for m, a, v in top))
    fleet = payload.get("fleet_buckets", {})
    for pool in sorted(fleet):
        rows: dict[tuple, dict] = {}
        for rid, buckets in fleet[pool].items():
            for entry in buckets:
                if isinstance(entry, (list, tuple)) and len(entry) == 3:
                    model, adapter, tokens = entry
                    rows.setdefault((model, adapter), {})[rid] = tokens
        if not rows:
            continue
        lines.append("")
        lines.append(f"  pool {pool} fleet quota buckets "
                     "(tokens remaining per replica partition):")
        for (model, adapter), per_rep in sorted(rows.items()):
            spread = "  ".join(f"{rid}={tok}" for rid, tok
                               in sorted(per_rep.items()))
            lines.append(f"    {model}/{adapter}: {spread}  "
                         f"(fleet total {round(sum(per_rep.values()), 3)})")
    counters = payload.get("counters", {})
    lines.append("")
    lines.append(f"  stale_fallbacks_total="
                 f"{counters.get('stale_fallbacks_total', 0)}  "
                 f"exchanges={counters.get('exchanges', {})}")
    return "\n".join(lines)


def main(argv: list[str] | None = None) -> int:
    parser = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    parser.add_argument("--url", default="http://localhost:8081",
                        help="gateway base URL serving /debug/statebus")
    parser.add_argument("--from-file", default=None, metavar="PATH",
                        help="render a saved /debug/statebus payload "
                             "instead of fetching (offline debugging)")
    parser.add_argument("--once", action="store_true",
                        help="render one report and exit (CI-friendly)")
    parser.add_argument("--watch", type=float, default=0.0, metavar="S",
                        help="re-render every S seconds (0 = once)")
    parser.add_argument("--json", action="store_true",
                        help="dump the raw payload instead of the report")
    args = parser.parse_args(argv)
    while True:
        if args.from_file:
            with open(args.from_file) as f:
                payload = json.load(f)
        else:
            payload = fetch_payload(args.url)
        print(json.dumps(payload, indent=2) if args.json
              else render_report(payload))
        if args.once or args.watch <= 0:
            return 0
        time.sleep(args.watch)


if __name__ == "__main__":
    sys.exit(main())
