"""Post-mortem timeline from a black-box dump.

The gateway writes a dump (``slo.write_blackbox``) the moment any SLO
objective enters fast burn: flight-recorder events, recent traces, the SLO
and health debug payloads, and the raw /metrics text, all in one JSON file.
This tool renders it into the narrative an on-caller actually reads —
"what was the system doing in the 30 seconds before the breach?":

- the breach reason (model, objective, burn rates per window),
- SLO compliance/state per model-objective at dump time,
- per-replica health scores, states, and streaks,
- the replicated state bus's view (PR 10): merged-vs-local divergence,
  peer snapshot ages, quota scale — was this replica enforcing alone
  when it burned?,
- the pool pods' step-profiler attribution (server/profiler.py):
  dispatch / host-sync / idle shares per pod at the breach,
- the KV economy at dump time (gateway/kvobs.py + per-pod /debug/kv):
  reuse efficiency, parked-KV share, the fleet duplication headline, and
  each pod's raw block-state ledger (unreachable pods marked UNAVAILABLE),
- the capacity twin at dump time (gateway/capacity.py): saturation
  indices, the headroom/time-to-breach forecast and whether it was
  trusted — was this breach forecast, and did anyone get to see it?,
- a merged chronological timeline of journal events and trace spans
  leading up to the dump (``--window`` seconds, default 60).

Usage:
  python tools/blackbox_report.py /tmp/lig-blackbox/blackbox-*.json
  python tools/blackbox_report.py dump.json --window 30
  python tools/blackbox_report.py dump.json --json   # machine-readable
"""

from __future__ import annotations

import argparse
import json
import sys
import time


# Payload sections added after the dump format shipped.  A dump written
# by an older gateway simply lacks the key — render an explicit marker
# (not a silent skip, and never a stack trace) so the on-caller knows the
# data was never captured rather than captured-empty.
_VERSIONED_SECTIONS = (
    ("statebus", "State bus"),
    ("profile", "Engine step-timeline"),
    ("kv", "KV economy"),
    ("picks", "Routing decisions"),
    ("capacity", "Capacity twin"),
)


def _predates(dump: dict, key: str) -> bool:
    """True when the dump was written before this payload section
    existed (key absent entirely — distinct from present-but-empty)."""
    return key not in dump


def _funnel(stages: list) -> str:
    return "->".join(str(s.get("survivors", "?")) for s in stages or [])


def _fmt_ts(ts: float, t0: float) -> str:
    """Absolute clock + offset relative to the dump instant (negative =
    before the breach)."""
    clock = time.strftime("%H:%M:%S", time.gmtime(ts))
    return f"{clock} ({ts - t0:+7.2f}s)"


def _event_line(e: dict, t0: float) -> str:
    attrs = e.get("attrs") or {}
    detail = " ".join(f"{k}={attrs[k]}" for k in sorted(attrs))
    trace = f" trace={e['trace_id']}" if e.get("trace_id") else ""
    return (f"  {_fmt_ts(e['ts'], t0)}  EVENT {e['kind']:<18}"
            f"{trace}  {detail}".rstrip())


def _span_rows(traces: list, t0: float, window_s: float) -> list[tuple]:
    rows = []
    for t in traces or []:
        for span in t.get("spans", []):
            if span["end"] < t0 - window_s:
                continue
            rows.append((span["start"],
                         f"  {_fmt_ts(span['start'], t0)}  SPAN  "
                         f"{span['name']:<18} trace={t['trace_id']} "
                         f"dur={1e3 * (span['end'] - span['start']):.1f}ms "
                         f"status={t.get('status', '')}"))
    return rows


def timeline(dump: dict, window_s: float = 60.0) -> list[str]:
    """Merged event+span rows inside the pre-breach window, oldest first."""
    t0 = float(dump.get("written_at") or 0.0)
    rows: list[tuple] = []
    events = (dump.get("events") or {}).get("events", [])
    for e in events:
        if e["ts"] >= t0 - window_s:
            rows.append((e["ts"], _event_line(e, t0)))
    rows += _span_rows(dump.get("traces"), t0, window_s)
    rows.sort(key=lambda r: r[0])
    return [line for _, line in rows]


def render_report(dump: dict, window_s: float = 60.0) -> str:
    reason = dump.get("reason") or {}
    lines = [
        "=" * 72,
        "BLACK-BOX POST-MORTEM "
        f"(written {time.strftime('%Y-%m-%d %H:%M:%SZ', time.gmtime(float(dump.get('written_at') or 0)))})",
        "=" * 72,
        "",
        f"Trigger : {reason.get('trigger', '?')} on "
        f"model={reason.get('model', '?')} "
        f"objective={reason.get('objective', '?')}",
        f"Burns   : {json.dumps(reason.get('burns', {}))}",
        "",
    ]
    slo = dump.get("slo") or {}
    if slo.get("models"):
        lines.append("SLO state at dump time:")
        for model in sorted(slo["models"]):
            for objective, o in sorted(slo["models"][model].items()):
                burns = {k: v for k, v in
                         (o.get("burn_rates") or {}).items()
                         if v is not None}
                lines.append(
                    f"  {model}/{objective:<11} state={o.get('state'):<10}"
                    f" compliance={o.get('compliance')}"
                    f" good/total={o.get('good')}/{o.get('total')}"
                    f" burns={json.dumps(burns)}")
        lines.append("")
    health = dump.get("health") or {}
    if health.get("pods"):
        lines.append("Replica health at dump time:")
        for pod in sorted(health["pods"]):
            p = health["pods"][pod]
            lines.append(
                f"  {pod:<20} score={p.get('score')} "
                f"state={p.get('state'):<10}"
                f" err_streak={p.get('upstream_error_streak', 0)}"
                f" handoff_streak={p.get('handoff_failure_streak', 0)}"
                f" would_avoid={p.get('would_avoid', 0)}")
        wa = health.get("would_avoid_total")
        if wa is not None:
            lines.append(f"  would-avoid picks (log-only): {wa}")
        lines.append("")
    statebus = dump.get("statebus") or {}
    if _predates(dump, "statebus"):
        lines.append("State bus: UNAVAILABLE "
                     "(dump predates this payload section)")
        lines.append("")
    elif statebus:
        lines.append("State bus at dump time:")
        lines.append(
            f"  replica={statebus.get('replica')} "
            f"stale={statebus.get('stale')} "
            f"live_replicas={statebus.get('live_replicas')} "
            f"quota_scale={statebus.get('quota_scale')}")
        for rid, r in sorted((statebus.get("replicas") or {}).items()):
            lines.append(
                f"  peer {rid:<20} seq={r.get('seq')} "
                f"age={r.get('age_s')}s "
                f"{'fresh' if r.get('fresh') else 'STALE'}")
        merged = statebus.get("merged") or {}
        local = statebus.get("local") or {}
        for pool in sorted(merged):
            m, loc = merged[pool], local.get(pool) or {}
            lines.append(
                f"  pool {pool}: merged noisy={sorted(m.get('noisy') or {})}"
                f" avoid={m.get('avoid') or []} | local "
                f"noisy={sorted(loc.get('noisy') or {})}"
                f" avoid={loc.get('avoid') or []}")
        lines.append("")
    profiles = dump.get("profile") or {}
    if _predates(dump, "profile"):
        lines.append("Engine step-timeline: UNAVAILABLE "
                     "(dump predates this payload section)")
        lines.append("")
    elif profiles:
        lines.append("Engine step-timeline at dump time "
                     "(dispatch/host-sync/idle shares):")
        for pod in sorted(profiles):
            p = profiles[pod]
            if "error" in p:
                lines.append(f"  {pod:<20} UNAVAILABLE: {p['error']}")
                continue
            att = p.get("attribution") or {}
            shares = att.get("shares") or {}
            lines.append(
                f"  {pod:<20} dispatch={shares.get('dispatch', 0):.1%}"
                f" host_sync={shares.get('host_sync', 0):.1%}"
                f" idle={shares.get('idle', 0):.1%}"
                f" over {att.get('dispatches', 0)} dispatches"
                f" ({att.get('tracked_seconds', 0)}s tracked)")
        lines.append("")
    kv = dump.get("kv") or {}
    if _predates(dump, "kv"):
        lines.append("KV economy: UNAVAILABLE "
                     "(dump predates this payload section)")
        lines.append("")
    elif kv:
        lines.append("KV economy at dump time:")
        gw = kv.get("gateway") or {}
        for pod, view in sorted((gw.get("pods") or {}).items()):
            lines.append(
                f"  {pod:<20} usage={view.get('usage', 0):.1%}"
                f" parked={view.get('parked_share', 0):.1%}"
                f" reuse_eff={view.get('reuse_efficiency', 0):.1%}"
                f" saved={view.get('saved_tokens_per_s', 0)}tok/s")
        dup = gw.get("duplication") or {}
        lines.append(
            f"  duplication: {dup.get('duplicated_prefixes', 0)} prefixes"
            f" / {dup.get('duplicated_blocks', 0)} blocks on >=2 replicas"
            f" ({dup.get('dedup_tokens_saved_per_s', 0)}tok/s servable by"
            " a shared copy)")
        # Per-pod raw ledger fetches: unreachable pods (exactly when
        # dumps fire) degrade to markers, mirroring the profiler section.
        for pod, snap in sorted((kv.get("pods") or {}).items()):
            if isinstance(snap, dict) and "error" in snap:
                lines.append(f"  {pod:<20} UNAVAILABLE: {snap['error']}")
            elif isinstance(snap, dict):
                states = snap.get("states") or {}
                lines.append(
                    f"  {pod:<20} ledger: " + " ".join(
                        f"{s}={states.get(s, 0)}"
                        for s in ("free", "active", "prefix_resident",
                                  "parked"))
                    + f" (of {snap.get('blocks_total', 0)})")
        lines.append("")
    picks = dump.get("picks") or {}
    if _predates(dump, "picks"):
        lines.append("Routing decisions: UNAVAILABLE "
                     "(dump predates this payload section)")
        lines.append("")
    elif picks:
        lines.append("Routing decisions at dump time "
                     "(sampled; gateway/pickledger.py):")
        for pool, p in sorted(picks.items()):
            if not isinstance(p, dict):
                continue
            decisive = p.get("decisive") or {}
            escapes = p.get("escapes") or {}
            lines.append(
                f"  pool {pool}: picks={p.get('picks', 0)}"
                f" samples={p.get('samples', 0)}"
                f" decisive={json.dumps(decisive, sort_keys=True)}"
                f" escapes={json.dumps(escapes, sort_keys=True)}")
            for r in (p.get("records") or [])[-3:]:
                lines.append(
                    f"    {r.get('hop', '?'):<7} winner={r.get('winner')}"
                    f" decisive={r.get('decisive')}"
                    f" funnel={_funnel(r.get('stages'))}"
                    f" trace={r.get('trace_id', '')}")
        lines.append("")
    capacity = dump.get("capacity") or {}
    if _predates(dump, "capacity"):
        lines.append("Capacity twin: UNAVAILABLE "
                     "(dump predates this payload section)")
        lines.append("")
    elif capacity:
        # Was the breach forecast, and was the forecast trusted when it
        # mattered?  (gateway/capacity.py; tools/capacity_report.py
        # renders the full table from the same section.)
        fc = capacity.get("forecast") or {}
        twin = capacity.get("twin") or {}
        sat = capacity.get("saturation") or {}
        ttb = fc.get("time_to_breach_s", -1.0)
        lines.append("Capacity twin at dump time:")
        lines.append(
            f"  forecast: offered={fc.get('offered_rps', 0.0)}rps"
            f" knee={fc.get('knee_rps', 0.0)}rps"
            f" headroom={fc.get('headroom_ratio', 0.0):.1%}"
            f" time_to_breach="
            + ("none" if ttb is None or ttb < 0 else f"{ttb:.0f}s")
            + f" breach_alarm={bool(fc.get('breach_alarm'))}"
            f" trusted={bool(fc.get('trusted'))}")
        lines.append(
            "  saturation: " + (" ".join(
                f"{k}={sat[k]:.2f}" for k in sorted(sat)) or "(none)"))
        drift = twin.get("drift") or {}
        lines.append(
            f"  twin: source={(twin.get('model') or {}).get('source', '?')}"
            f" state={twin.get('state', '?')}"
            + ("  drift: " + " ".join(
                f"{k}={drift[k]}" for k in sorted(drift)) if drift else ""))
        if not fc.get("trusted"):
            lines.append("  NOTE: forecasts were UNTRUSTED at the breach "
                         "— the twin had drifted or never calibrated")
        lines.append("")
    counts = (dump.get("events") or {}).get("counts") or {}
    if counts:
        lines.append("Event counts (cumulative): " + ", ".join(
            f"{k}={counts[k]}" for k in sorted(counts)))
        lines.append("")
    lines.append(f"Timeline (last {window_s:.0f}s before the dump):")
    rows = timeline(dump, window_s)
    lines += rows if rows else ["  (no events or spans in the window)"]
    lines.append("")
    return "\n".join(lines)


def main(argv: list[str] | None = None) -> int:
    parser = argparse.ArgumentParser(
        description="Render a post-mortem timeline from a black-box dump")
    parser.add_argument("dump", help="dump file path, or - for stdin")
    parser.add_argument("--window", type=float, default=60.0,
                        help="seconds of pre-breach timeline to show")
    parser.add_argument("--json", action="store_true",
                        help="emit the merged timeline as JSON rows")
    args = parser.parse_args(argv)
    if args.dump == "-":
        dump = json.load(sys.stdin)
    else:
        with open(args.dump) as f:
            dump = json.load(f)
    if args.json:
        print(json.dumps({"reason": dump.get("reason"),
                          "timeline": timeline(dump, args.window)}, indent=1))
    else:
        print(render_report(dump, args.window))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
