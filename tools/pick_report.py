"""Routing decision report: pick funnel, seam steering, exemplar picks.

Reads a ``/debug/picks`` payload (URL, file path, or ``-`` for stdin)
from the gateway's routing decision ledger (``gateway/pickledger.py``),
or the ``picks`` section of a black-box dump (one payload per pool), and
renders the operator view of "why did my request land on pod X?":

- the narrowing funnel (mean surviving candidates per pick stage across
  sampled picks: pool -> role partition -> filter tree -> health/circuit
  -> fairness -> placement -> prefix tie-break -> RNG);
- per-seam steering shares (what fraction of sampled picks each advisor
  seam changed, per the counterfactual replay) and the decisive-seam
  distribution;
- the top steered-away pods (who keeps getting removed, by which stage);
- exemplar decision records, newest first, with their trace ids (join
  against ``tools/trace_report.py`` / the fleet's stitched traces).

Usage:
  python tools/pick_report.py http://localhost:8081/debug/picks
  python tools/pick_report.py http://localhost:8081/debug/picks --once
  python tools/pick_report.py dump.json        # black-box picks section
  python tools/pick_report.py - --json < picks.json
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from tools.trace_report import load  # noqa: E402 — one loader, no drift

# Canonical funnel order (pickledger.STAGES; re-declared so the report
# renders old payloads without importing gateway code).
STAGE_ORDER = ("pool", "role_partition", "filter_tree", "health/circuit",
               "fairness", "placement", "prefix_affinity", "rng")


# ---------------------------------------------------------------------------
# Payload extraction
# ---------------------------------------------------------------------------


def extract_picks(doc: dict) -> dict[str, dict]:
    """Normalize any accepted source to ``{pool_name: ledger_payload}``.

    A ``/debug/picks`` body is one payload (pool "default"; its optional
    ``pools`` section overrides per-pool); a black-box dump carries
    ``picks`` as a per-pool mapping already."""
    if not isinstance(doc, dict):
        raise ValueError("payload is not a JSON object")
    if isinstance(doc.get("picks"), dict) and "samples" not in doc:
        # Black-box dump (or a wrapper): {"picks": {pool: payload}}.
        inner = doc["picks"]
        if inner and all(isinstance(v, dict) for v in inner.values()):
            return dict(inner)
    if "samples" in doc and "rollup" in doc:
        pools = doc.get("pools")
        if isinstance(pools, dict) and pools:
            return dict(pools)
        return {"default": doc}
    raise ValueError("no pick-ledger payload found (expected a gateway "
                     "/debug/picks body or a dump's 'picks' section)")


# ---------------------------------------------------------------------------
# Rows (pure — the testable core)
# ---------------------------------------------------------------------------


def funnel_rows(payload: dict) -> list[dict]:
    means = (payload.get("rollup") or {}).get("mean_survivors") or {}
    extra = sorted(set(means) - set(STAGE_ORDER))
    return [{"stage": stage, "mean_survivors": means.get(stage, 0.0)}
            for stage in (*STAGE_ORDER, *extra) if stage in means]


def steering_rows(payload: dict) -> list[dict]:
    """Per-seam steering share over sampled picks, joined with the
    decisive counts and escape-hatch fires."""
    rollup = payload.get("rollup") or {}
    steered = rollup.get("steered") or {}
    decisive = payload.get("decisive") or rollup.get("decisive") or {}
    escapes = payload.get("escapes") or rollup.get("escapes") or {}
    samples = int(payload.get("samples") or rollup.get("samples") or 0)
    seams = sorted(set(steered) | set(decisive) | set(escapes))
    rows = []
    for seam in seams:
        n = int(steered.get(seam, 0))
        rows.append({
            "seam": seam,
            "steered": n,
            "steered_pct": round(100.0 * n / samples, 1) if samples else 0.0,
            "decisive": int(decisive.get(seam, 0)),
            "escapes": int(escapes.get(seam, 0)),
        })
    rows.sort(key=lambda r: (-r["steered"], -r["decisive"], r["seam"]))
    return rows


def steered_away_rows(payload: dict, top: int = 8) -> list[dict]:
    away = (payload.get("rollup") or {}).get("steered_away") or {}
    rows = [{"pod": pod, "removals": int(n)} for pod, n in away.items()]
    rows.sort(key=lambda r: (-r["removals"], r["pod"]))
    return rows[:top]


def exemplar_rows(payload: dict, top: int = 5) -> list[dict]:
    """Newest sampled decisions, compacted to one row each."""
    rows = []
    for r in (payload.get("records") or [])[-top:][::-1]:
        funnel = "->".join(str(s.get("survivors", "?"))
                           for s in r.get("stages") or [])
        rows.append({
            "seq": r.get("seq", 0),
            "hop": r.get("hop", "?"),
            "path": r.get("path", "?"),
            "winner": r.get("winner", "?"),
            "decisive": r.get("decisive", "?"),
            "steered": ",".join(r.get("steered") or []) or "-",
            "escapes": ",".join(r.get("escapes") or []) or "-",
            "funnel": funnel,
            "trace": r.get("trace_id") or "-",
        })
    return rows


def _table(rows: list[dict], headers: tuple) -> str:
    if not rows:
        return "(no samples)"
    widths = [max(len(h), *(len(str(r[h])) for r in rows)) for h in headers]

    def fmt(vals):
        return "  ".join(str(v).rjust(w) if i else str(v).ljust(w)
                         for i, (v, w) in enumerate(zip(vals, widths)))

    lines = [fmt(headers), fmt(["-" * w for w in widths])]
    lines += [fmt([r[h] for h in headers]) for r in rows]
    return "\n".join(lines)


def render_pool(name: str, payload: dict) -> str:
    rollup = payload.get("rollup") or {}
    mismatch = rollup.get("shadow_mismatch", 0)
    out = [
        f"ROUTING DECISIONS — pool {name} "
        f"(picks={payload.get('picks', 0)}, "
        f"samples={payload.get('samples', 0)}, "
        f"sample_every={(payload.get('config') or {}).get('sample_every')})",
        "",
        "Narrowing funnel (mean survivors per stage):",
        _table(funnel_rows(payload), ("stage", "mean_survivors")),
        "",
        "Seam steering (counterfactual: picks the seam changed):",
        _table(steering_rows(payload),
               ("seam", "steered", "steered_pct", "decisive", "escapes")),
        "",
        "Top steered-away pods:",
        _table(steered_away_rows(payload), ("pod", "removals")),
        "",
        "Exemplar decisions (newest first):",
        _table(exemplar_rows(payload),
               ("seq", "hop", "path", "winner", "decisive", "steered",
                "escapes", "funnel", "trace")),
    ]
    if mismatch:
        out += ["", f"WARNING: {mismatch} native shadow-replay "
                    "mismatch(es) — oracle drifted from the native path"]
    return "\n".join(out)


def render(doc: dict) -> str:
    pools = extract_picks(doc)
    return "\n\n".join(render_pool(name, payload)
                       for name, payload in sorted(pools.items()))


# ---------------------------------------------------------------------------
# CLI
# ---------------------------------------------------------------------------


def main(argv: list[str] | None = None) -> int:
    parser = argparse.ArgumentParser(
        description="Routing decision report: pick funnel, seam steering, "
                    "exemplars (from /debug/picks)")
    parser.add_argument("source",
                        help="file path, http(s) URL, or - for stdin")
    parser.add_argument("--once", action="store_true",
                        help="render one report and exit (CI mode; URL "
                             "sources otherwise refresh every --interval)")
    parser.add_argument("--interval", type=float, default=5.0,
                        help="watch-mode refresh seconds (URL sources)")
    parser.add_argument("--json", action="store_true",
                        help="emit the extracted rows as JSON")
    args = parser.parse_args(argv)

    watch = (not args.once and not args.json
             and args.source.startswith(("http://", "https://")))
    while True:
        try:
            doc = load(args.source)
            pools = extract_picks(doc)
        except ValueError as e:
            print(f"error: {e}", file=sys.stderr)
            return 2
        if args.json:
            print(json.dumps({
                name: {"funnel": funnel_rows(p),
                       "steering": steering_rows(p),
                       "steered_away": steered_away_rows(p),
                       "exemplars": exemplar_rows(p)}
                for name, p in sorted(pools.items())}, indent=1))
            return 0
        if watch:
            print("\x1b[2J\x1b[H", end="")
        print(render(doc))
        if not watch:
            return 0
        time.sleep(max(0.5, args.interval))


if __name__ == "__main__":
    sys.exit(main())
