"""Capacity report: saturation, headroom forecast, twin drift.

Reads a ``/debug/capacity`` payload (URL, file path, or ``-`` for stdin)
from the gateway's capacity plane (``gateway/capacity.py``) — or the
``capacity`` section of a fast-burn black-box dump — and renders the
operator view:

- the per-pod per-resource saturation table (KV, decode slots, queue,
  prefill compute) with the pool's weakest-link indices;
- the headroom forecast: offered load vs the calibrated twin's knee
  rate, headroom-at-SLO, time-to-breach on the current trend, and
  whether a breach alarm is standing;
- the twin itself: calibration source (committed artifact vs live
  self-fit), fit residuals, per-observable drift EMAs against the
  ``--twin-drift-threshold``, and the trust state — an UNTRUSTED
  banner when drift has disarmed the forecasts.

Usage:
  python tools/capacity_report.py http://localhost:8081/debug/capacity
  python tools/capacity_report.py http://localhost:8081/debug/capacity --once
  python tools/capacity_report.py dump.json          # black-box dump
  python tools/capacity_report.py - --json < payload.json
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from tools.trace_report import load  # noqa: E402 — one loader, no drift

# Render order mirrors gateway/capacity.py (import-free on purpose: the
# report must open dumps from gateways whose package isn't importable).
RESOURCES = ("kv", "decode_slots", "queue", "prefill_compute")
DRIFT_OBSERVABLES = ("prefill_s", "decode_step_s", "occupancy")


# ---------------------------------------------------------------------------
# Payload extraction
# ---------------------------------------------------------------------------


def extract_capacity(doc: dict) -> dict:
    """Accept a raw ``/debug/capacity`` body, a black-box dump (its
    ``capacity`` section), or a fleet payload row holding one."""
    if not isinstance(doc, dict):
        raise ValueError("payload is not a JSON object")
    if "forecast" in doc and "saturation" in doc:
        return doc
    inner = doc.get("capacity")
    if isinstance(inner, dict):
        return extract_capacity(inner)
    raise ValueError("no capacity payload found (expected a gateway "
                     "/debug/capacity body or a dump's 'capacity' section)")


# ---------------------------------------------------------------------------
# Rows (pure — the testable core)
# ---------------------------------------------------------------------------


def saturation_rows(payload: dict) -> list[dict]:
    rows = []
    for name, view in sorted((payload.get("pods") or {}).items()):
        sat = view.get("saturation") or {}
        rows.append({
            "pod": name,
            **{r: f"{100.0 * sat.get(r, 0.0):.1f}%" for r in RESOURCES},
            "index": f"{100.0 * view.get('saturation_index', 0.0):.1f}%",
        })
    pool = payload.get("saturation") or {}
    if pool:
        rows.append({
            "pod": "POOL(max)",
            **{r: f"{100.0 * pool.get(r, 0.0):.1f}%" for r in RESOURCES},
            "index": f"{100.0 * max(pool.values(), default=0.0):.1f}%",
        })
    return rows


def drift_rows(payload: dict) -> list[dict]:
    twin = payload.get("twin") or {}
    drift = twin.get("drift") or {}
    threshold = (payload.get("config") or {}).get("drift_threshold", 0.5)
    rows = []
    for obs in DRIFT_OBSERVABLES:
        if obs not in drift:
            continue
        rows.append({"observable": obs, "ema": round(drift[obs], 4),
                     "threshold": threshold,
                     "over": "YES" if drift[obs] > threshold else "no"})
    return rows


def forecast_summary(payload: dict) -> dict:
    fc = payload.get("forecast") or {}
    ttb = fc.get("time_to_breach_s", -1.0)
    return {
        "offered_rps": fc.get("offered_rps", 0.0),
        "knee_rps": fc.get("knee_rps", 0.0),
        "headroom_pct": round(100.0 * fc.get("headroom_ratio", 0.0), 1),
        "time_to_breach": ("none" if ttb is None or ttb < 0
                           else "NOW" if ttb == 0 else f"{ttb:.0f}s"),
        "trusted": bool(fc.get("trusted")),
        "breach_alarm": bool(fc.get("breach_alarm")),
    }


def _table(rows: list[dict], headers: tuple) -> str:
    if not rows:
        return "(no samples)"
    widths = [max(len(h), *(len(str(r[h])) for r in rows)) for h in headers]

    def fmt(vals):
        return "  ".join(str(v).rjust(w) if i else str(v).ljust(w)
                         for i, (v, w) in enumerate(zip(vals, widths)))

    lines = [fmt(headers), fmt(["-" * w for w in widths])]
    lines += [fmt([r[h] for h in headers]) for r in rows]
    return "\n".join(lines)


def render(payload: dict) -> str:
    fc = forecast_summary(payload)
    twin = payload.get("twin") or {}
    model = twin.get("model") or {}
    residuals = model.get("residuals") or {}
    out = [
        "CAPACITY & SATURATION "
        f"(ticks={payload.get('ticks', 0)}, "
        f"pods={len(payload.get('pods') or {})})",
        "",
        _table(saturation_rows(payload), ("pod",) + RESOURCES + ("index",)),
        "",
        f"Headroom forecast: offered={fc['offered_rps']}rps "
        f"knee={fc['knee_rps']}rps headroom={fc['headroom_pct']}% "
        f"time_to_breach={fc['time_to_breach']}"
        + (" [BREACH ALARM]" if fc["breach_alarm"] else ""),
    ]
    if not fc["trusted"]:
        out.append("*** FORECAST UNTRUSTED — twin state "
                   f"'{twin.get('state', '?')}' (drift or no calibration); "
                   "numbers exported but not alarmed on ***")
    src = model.get("source", "none")
    res_txt = " ".join(f"{k}={residuals[k]}" for k in sorted(residuals))
    out += [
        "",
        f"Twin: source={src}"
        + (f" path={model.get('path')}" if model.get("path") else "")
        + (f" fit_tick={model.get('fit_tick')}"
           if model.get("fit_tick") else "")
        + f" fit_windows={twin.get('fit_windows', 0)}"
        + (f"  residuals: {res_txt}" if res_txt else ""),
    ]
    if model.get("source") == "error":
        out.append(f"  calibration artifact REJECTED: {model.get('error')}")
    if model.get("last_fit_error"):
        out.append("  last self-fit rejected: "
                   f"{model.get('last_fit_error')}")
    rows = drift_rows(payload)
    if rows:
        out += ["", "Twin drift (EMA of |predicted-observed|/observed):",
                _table(rows, ("observable", "ema", "threshold", "over"))]
    return "\n".join(out)


# ---------------------------------------------------------------------------
# CLI
# ---------------------------------------------------------------------------


def main(argv: list[str] | None = None) -> int:
    parser = argparse.ArgumentParser(
        description="Capacity report: saturation, headroom forecast, twin "
                    "drift (from /debug/capacity)")
    parser.add_argument("source",
                        help="file path, http(s) URL, or - for stdin")
    parser.add_argument("--once", action="store_true",
                        help="render one report and exit (CI mode; URL "
                             "sources otherwise refresh every --interval)")
    parser.add_argument("--interval", type=float, default=5.0,
                        help="watch-mode refresh seconds (URL sources)")
    parser.add_argument("--json", action="store_true",
                        help="emit the extracted rows as JSON")
    args = parser.parse_args(argv)

    watch = (not args.once and not args.json
             and args.source.startswith(("http://", "https://")))
    while True:
        try:
            payload = extract_capacity(load(args.source))
        except ValueError as e:
            print(f"error: {e}", file=sys.stderr)
            return 2
        if args.json:
            print(json.dumps({
                "saturation": saturation_rows(payload),
                "forecast": forecast_summary(payload),
                "drift": drift_rows(payload),
                "twin_state": (payload.get("twin") or {}).get("state"),
            }, indent=1))
            return 0
        if watch:
            print("\x1b[2J\x1b[H", end="")
        print(render(payload))
        if not watch:
            return 0
        time.sleep(max(0.5, args.interval))


if __name__ == "__main__":
    sys.exit(main())
