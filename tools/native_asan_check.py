#!/usr/bin/env python3
"""Sanitized native build gate (`make native-asan`).

Three stages, orchestrated so CI gets ONE entry point and a loud skip —
never a silent pass — when the toolchain is absent:

1. build ``native/libligsched_asan.so`` + ``native/ligsched_asan_fuzz``
   with ``-fsanitize=address,undefined -fno-omit-frame-pointer``;
2. run the hostile-snapshot FFI fuzzer (truncated CSR offsets,
   out-of-range adapter/pod ids, zero-pod pools, stale-ABI-shaped null
   calls — see native/fuzz_harness.cc);
3. re-exec this script with ``LD_PRELOAD=libasan`` +
   ``LIG_NATIVE_LIB=<asan .so>`` and run the Python-side parity fuzz
   (NativeScheduler vs the Python Scheduler oracle, same-seed RNG,
   schedule + pick_many) THROUGH the instrumented library, so the real
   ctypes marshal path — not just the C harness — runs under ASan/UBSan.

Exit 0 with ``NATIVE-ASAN PASS`` on success; exit 0 with a loud
``NATIVE-ASAN SKIPPED: <why>`` when g++/libasan are missing (the pytest
wrapper converts that into a visible skip); exit 1 on any failure or
sanitizer report.  jax is never imported — the scheduling package is
numpy-only, which keeps the ASan interposition surface small.
"""

from __future__ import annotations

import argparse
import os
import random
import shutil
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
NATIVE_DIR = os.path.join(REPO, "llm_instance_gateway_tpu", "native")
ASAN_LIB = os.path.join(NATIVE_DIR, "libligsched_asan.so")
FUZZ_BIN = os.path.join(NATIVE_DIR, "ligsched_asan_fuzz")
sys.path.insert(0, REPO)


def skip(why: str) -> int:
    print(f"NATIVE-ASAN SKIPPED: {why}", flush=True)
    return 0


def _find_libasan(cxx: str) -> str | None:
    try:
        out = subprocess.run([cxx, "-print-file-name=libasan.so"],
                             capture_output=True, text=True, timeout=30)
    except (OSError, subprocess.SubprocessError):
        return None
    path = out.stdout.strip()
    return path if path and os.path.sep in path and os.path.exists(path) \
        else None


def orchestrate() -> int:
    cxx = os.environ.get("CXX", "g++")
    if shutil.which(cxx) is None or shutil.which("make") is None:
        return skip(f"no C++ toolchain ({cxx}/make not found) — the "
                    f"sanitized scheduler build cannot run on this host")
    build = subprocess.run(["make", "-C", NATIVE_DIR, "asan"],
                           capture_output=True, text=True)
    if build.returncode != 0:
        print(build.stdout + build.stderr)
        print("NATIVE-ASAN FAIL: sanitized build failed")
        return 1
    env = dict(os.environ,
               ASAN_OPTIONS="abort_on_error=1",
               UBSAN_OPTIONS="halt_on_error=1:print_stacktrace=1")
    print("[1/2] hostile-snapshot FFI fuzz (C harness)", flush=True)
    fuzz = subprocess.run([FUZZ_BIN], env=env, capture_output=True,
                          text=True)
    print(fuzz.stdout, end="")
    if fuzz.returncode != 0:
        print(fuzz.stderr)
        print("NATIVE-ASAN FAIL: hostile-snapshot fuzz reported errors")
        return 1
    libasan = _find_libasan(cxx)
    if libasan is None:
        # The statically-linked C harness already ran; say so and stop
        # rather than pretend the Python stage happened.
        return skip("libasan.so not locatable for LD_PRELOAD — C harness "
                    "PASSED but the ctypes parity stage did not run")
    import importlib.util

    if importlib.util.find_spec("numpy") is None:
        # The parity stage drives the real marshal (numpy arrays); a bare
        # CI container without it must skip LOUDLY, not crash mid-stage.
        return skip("numpy not installed — C harness PASSED but the "
                    "ctypes parity stage did not run")
    print("[2/2] ctypes parity fuzz through the instrumented .so",
          flush=True)
    env = dict(os.environ,
               LD_PRELOAD=libasan,
               LIG_NATIVE_LIB=ASAN_LIB,
               # Python leaks by design at exit; leak checking would fail
               # every run on interpreter allocations, drowning real
               # reports.  ASan's memory-error detection stays fully on.
               ASAN_OPTIONS="detect_leaks=0:abort_on_error=1",
               UBSAN_OPTIONS="halt_on_error=1:print_stacktrace=1",
               PYTHONPATH=REPO + os.pathsep + os.environ.get(
                   "PYTHONPATH", ""))
    parity = subprocess.run(
        [sys.executable, os.path.abspath(__file__), "--parity-stage"],
        env=env, capture_output=True, text=True)
    print(parity.stdout, end="")
    if parity.returncode != 0:
        print(parity.stderr)
        print("NATIVE-ASAN FAIL: parity fuzz under ASan failed")
        return 1
    print("NATIVE-ASAN PASS")
    return 0


# ---------------------------------------------------------------------------
# Parity stage (runs in the LD_PRELOAD=libasan subprocess)
# ---------------------------------------------------------------------------


class _Advisor:
    """Minimal enforcing health advisor (avoid-set flavor)."""

    def __init__(self, policy: str, bad: frozenset):
        self.policy = policy
        self._bad = bad
        self.escapes = 0
        self.picks: list[str] = []

    def avoid_set(self) -> frozenset:
        return self._bad

    def should_avoid(self, name: str) -> bool:
        return name in self._bad

    def note_escape_hatch(self) -> None:
        self.escapes += 1

    def note_pick(self, name: str) -> None:
        self.picks.append(name)


def parity_stage() -> int:
    from llm_instance_gateway_tpu.gateway.provider import StaticProvider
    from llm_instance_gateway_tpu.gateway.scheduling import native
    from llm_instance_gateway_tpu.gateway.scheduling.config import (
        SchedulerConfig,
    )
    from llm_instance_gateway_tpu.gateway.scheduling.scheduler import (
        Scheduler,
        SchedulingError,
    )
    from llm_instance_gateway_tpu.gateway.scheduling.types import LLMRequest
    from llm_instance_gateway_tpu.gateway.types import (
        Metrics,
        Pod,
        PodMetrics,
    )

    assert os.environ.get("LIG_NATIVE_LIB"), "parity stage needs the override"
    if not native.available():
        print("parity stage: instrumented library did not load", flush=True)
        return 1

    adapters = ("a1", "a2", "a3")

    def random_pods(rng: random.Random, n: int) -> list[PodMetrics]:
        pods = []
        for i in range(n):
            resident = {a: 1 for a in adapters if rng.random() < 0.4}
            pods.append(PodMetrics(
                pod=Pod(f"p{i}", f"p{i}:8000"),
                metrics=Metrics(
                    waiting_queue_size=rng.randint(0, 60),
                    prefill_queue_size=rng.randint(0, 12),
                    kv_cache_usage_percent=round(rng.random(), 3),
                    kv_tokens_capacity=rng.choice([0, 44_448]),
                    kv_tokens_free=rng.randint(0, 44_448),
                    active_adapters=resident,
                    max_active_adapters=rng.choice([2, 4]),
                )))
        return pods

    cfg = SchedulerConfig()
    rng = random.Random(2026)
    trials = int(os.environ.get("LIG_ASAN_PARITY_TRIALS", "150"))
    for trial in range(trials):
        pods = random_pods(rng, rng.randint(1, 24))
        policy = rng.choice(["log_only", "avoid", "strict"])
        bad = frozenset(p.pod.name for p in pods if rng.random() < 0.3)
        reqs = [LLMRequest(
            model="m",
            resolved_target_model=rng.choice(list(adapters) + ["other"]),
            critical=rng.random() < 0.5,
            prompt_tokens=rng.choice([0, 100, 5000, 40_000]),
        ) for _ in range(rng.randint(1, 8))]
        seed = rng.getrandbits(32)
        picks: dict[str, list] = {}
        for kind in ("python", "native"):
            ctor = Scheduler if kind == "python" else native.NativeScheduler
            sched = ctor(StaticProvider([p.clone() for p in pods]), cfg,
                         rng=random.Random(seed))
            sched.health_advisor = _Advisor(policy, bad)
            out = []
            for req in reqs:
                try:
                    out.append(sched.schedule(req).name)
                except SchedulingError as e:
                    out.append(("shed", e.shed))
            picks[kind] = out
        if picks["python"] != picks["native"]:
            print(f"parity MISMATCH at trial {trial}: "
                  f"python={picks['python']} native={picks['native']}")
            return 1
        # Batched crossing: pick-for-pick identical to the loop above.
        sched = native.NativeScheduler(
            StaticProvider([p.clone() for p in pods]), cfg,
            rng=random.Random(seed))
        sched.health_advisor = _Advisor(policy, bad)
        try:
            many = [p.name for p in sched.pick_many(list(reqs))]
        except SchedulingError:
            many = None  # sheds raise at the first shedding request
        if many is not None and any(
                isinstance(p, tuple) for p in picks["native"]):
            print(f"parity MISMATCH at trial {trial}: pick_many served a "
                  f"batch the per-pick path shed")
            return 1
        if many is not None and many != picks["native"]:
            print(f"parity MISMATCH at trial {trial}: pick_many={many} "
                  f"schedule-loop={picks['native']}")
            return 1
    print(f"parity fuzz: {trials} trials clean through the instrumented "
          f"library", flush=True)
    return 0


def main() -> int:
    parser = argparse.ArgumentParser()
    parser.add_argument("--parity-stage", action="store_true",
                        help="(internal) run the in-process parity fuzz; "
                             "expects LIG_NATIVE_LIB + LD_PRELOAD set")
    args = parser.parse_args()
    if args.parity_stage:
        return parity_stage()
    return orchestrate()


if __name__ == "__main__":
    sys.exit(main())
