"""Dispatch-gap attribution table from the engine step-timeline profiler.

Reads a ``/debug/profile`` payload (URL, file path, or ``-`` for stdin —
including the committed ``PROFILE_BASELINE.json`` baseline run and the
``profile`` section of a black-box dump) and renders where the engine
thread's wall went:

- the attribution table — dispatch / host-sync / idle shares (they tile
  the tracked engine-thread timeline, so they sum to 100%);
- per-phase dispatch walls (prefill vs decode vs spec) with counts and
  mean wall per dispatch;
- a recent-dispatch summary from the record ring (mean batch occupancy,
  mean steps per dispatch, slot churn).

This is the evidence layer for the ROADMAP item-2 decode levers: every
"amortize the step loop" change must move the host-sync share DOWN on
this table versus the committed baseline, not just a throughput ratio.

Usage:
  python tools/profile_report.py http://localhost:8000/debug/profile
  python tools/profile_report.py PROFILE_BASELINE.json
  python tools/profile_report.py dump.json --json
"""

from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from tools.trace_report import load  # noqa: E402 — one loader, no drift


def extract_profile(doc: dict, pod: str | None = None) -> dict:
    """Accept a raw /debug/profile payload, a bench emission carrying
    ``profile``, or a black-box dump whose ``profile`` section maps pod
    name -> snapshot (slo.write_blackbox's shape; unreachable pods carry
    error markers).  ``pod`` selects one replica from a dump; without it
    the first pod (sorted) with a valid snapshot is used, with a note on
    stderr when several were available."""
    if "attribution" in doc:
        return doc
    inner = doc.get("profile")
    if isinstance(inner, dict):
        if "attribution" in inner:
            return inner
        # Black-box dump shape: pod name -> snapshot-or-error-marker.
        valid = {name: snap for name, snap in sorted(inner.items())
                 if isinstance(snap, dict) and "attribution" in snap}
        if pod is not None:
            if pod in valid:
                return valid[pod]
            raise ValueError(
                f"pod {pod!r} has no profiler snapshot in this dump "
                f"(pods with one: {sorted(valid) or 'none'})")
        if valid:
            name, snap = next(iter(valid.items()))
            if len(valid) > 1:
                print(f"note: dump holds {len(valid)} pod snapshots; "
                      f"showing {name!r} (pick one with --pod)",
                      file=sys.stderr)
            return snap
    raise ValueError("no profiler payload found (expected an 'attribution' "
                     "key or a 'profile' section)")


def attribution_rows(profile: dict) -> list[dict]:
    """One row per bucket: seconds + share of the tracked total."""
    att = profile.get("attribution") or {}
    shares = att.get("shares") or {}
    rows = []
    for bucket, key in (("dispatch", "dispatch_seconds"),
                        ("host_sync", "host_sync_seconds"),
                        ("idle", "idle_seconds")):
        rows.append({
            "bucket": bucket,
            "seconds": round(float(att.get(key, 0.0)), 6),
            "share_pct": round(100.0 * float(shares.get(bucket, 0.0)), 3),
        })
    return rows


def phase_rows(profile: dict) -> list[dict]:
    """Per-phase dispatch wall: total seconds, dispatch count, mean wall
    per dispatch (from the wall histogram's _sum/_count)."""
    rows = []
    for phase, state in sorted((profile.get("hist") or {}).get(
            "wall", {}).items()):
        n = int(state.get("count", 0))
        total = float(state.get("sum", 0.0))
        rows.append({
            "phase": phase,
            "dispatches": n,
            "wall_s": round(total, 6),
            "mean_ms": round(total / n * 1e3, 3) if n else 0.0,
        })
    return rows


def record_summary(profile: dict) -> dict:
    """Aggregate view of the recent per-dispatch record ring."""
    records = [r for r in profile.get("records") or []
               if r.get("phase") != "prefill"]
    if not records:
        return {}
    occ = [r["active"] / r["slots"] for r in records if r.get("slots")]
    gaps = [r.get("gap_s", 0.0) for r in records]
    return {
        "recent_dispatches": len(records),
        "mean_occupancy": round(sum(occ) / len(occ), 4) if occ else None,
        "mean_steps_per_dispatch": round(
            sum(r.get("n_steps", 1) for r in records) / len(records), 2),
        "mean_gap_ms": round(sum(gaps) / len(gaps) * 1e3, 4),
        "slot_churn_events": sum(1 for r in records if r.get("slot_churn")),
    }


def _table(rows: list[dict], headers: tuple) -> str:
    if not rows:
        return "(no samples)"
    widths = [max(len(h), *(len(str(r[h])) for r in rows)) for h in headers]

    def fmt(vals):
        return "  ".join(str(v).rjust(w) if i else str(v).ljust(w)
                         for i, (v, w) in enumerate(zip(vals, widths)))

    lines = [fmt(headers), fmt(["-" * w for w in widths])]
    lines += [fmt([r[h] for h in headers]) for r in rows]
    return "\n".join(lines)


def host_sync_delta(profile: dict, previous: dict | None) -> dict | None:
    """Host-sync-share movement vs a previous baseline's shares — the
    number every ROADMAP item-2 lever is judged by.  ``previous`` is
    either a ``{"shares": {...}}`` block (the refreshed
    PROFILE_BASELINE.json embeds the pre-lever shares under "previous")
    or a full profiler payload (--baseline FILE)."""
    if not previous:
        return None
    prev_shares = previous.get("shares")
    if prev_shares is None and "attribution" in previous:
        prev_shares = (previous.get("attribution") or {}).get("shares")
    if not prev_shares:
        return None
    cur = float(((profile.get("attribution") or {}).get("shares")
                 or {}).get("host_sync", 0.0))
    prev = float(prev_shares.get("host_sync", 0.0))
    return {
        "previous_pct": round(100.0 * prev, 4),
        "current_pct": round(100.0 * cur, 4),
        "delta_pp": round(100.0 * (cur - prev), 4),
        "improved": cur < prev,
    }


def render_report(profile: dict, previous: dict | None = None) -> str:
    att = attribution_rows(profile)
    out = [
        "ENGINE STEP-TIMELINE ATTRIBUTION "
        f"(tracked {profile.get('attribution', {}).get('tracked_seconds', 0)}s "
        f"over {profile.get('attribution', {}).get('dispatches', 0)} dispatches)",
        "",
        _table(att, ("bucket", "seconds", "share_pct")),
        "",
        "Per-phase dispatch wall:",
        _table(phase_rows(profile), ("phase", "dispatches", "wall_s",
                                     "mean_ms")),
    ]
    delta = host_sync_delta(profile, previous)
    if delta:
        out += ["", "Host-sync share vs previous baseline: "
                f"{delta['previous_pct']}% -> {delta['current_pct']}% "
                f"(delta {delta['delta_pp']:+}pp"
                f"{', improved' if delta['improved'] else ''})"]
    summary = record_summary(profile)
    if summary:
        out += ["", "Recent decode dispatches: " + ", ".join(
            f"{k}={v}" for k, v in summary.items())]
    padding = profile.get("padding_tokens")
    if padding:
        out += ["", f"Prefill padding tokens (cumulative): {padding}"]
    return "\n".join(out)


def main(argv: list[str] | None = None) -> int:
    parser = argparse.ArgumentParser(
        description="dispatch / host-sync / idle attribution table from a "
                    "/debug/profile payload")
    parser.add_argument("source",
                        help="file path, http(s) URL, or - for stdin")
    parser.add_argument("--pod",
                        help="which pod's snapshot to render when the "
                             "source is a black-box dump holding several")
    parser.add_argument("--baseline",
                        help="a previous profiler payload to diff the "
                             "host-sync share against (the committed "
                             "PROFILE_BASELINE.json embeds its "
                             "predecessor's shares, so the delta also "
                             "prints with no flag)")
    parser.add_argument("--json", action="store_true",
                        help="emit the attribution + phase rows as JSON")
    args = parser.parse_args(argv)
    try:
        doc = load(args.source)
        profile = extract_profile(doc, pod=args.pod)
        previous = doc.get("previous") if isinstance(doc, dict) else None
        if args.baseline:
            previous = extract_profile(load(args.baseline))
    except ValueError as e:
        print(f"error: {e}", file=sys.stderr)
        return 2
    if args.json:
        print(json.dumps({
            "attribution": attribution_rows(profile),
            "phases": phase_rows(profile),
            "summary": record_summary(profile),
            **({"host_sync_delta": host_sync_delta(profile, previous)}
               if previous else {}),
        }))
    else:
        print(render_report(profile, previous=previous))
    return 0


if __name__ == "__main__":
    sys.exit(main())
