"""Sweep decode (slots x K) on the live chip; print tok/s per config.

ROADMAP item 2: device-side stop removed the finish-lag waste that
previously penalized large K (a finished row freezes on-device instead of
decoding garbage until the next sync), so the old K=32 choice deserves a
re-sweep under an uncontended chip.

Method: the bench model + workload (bench.py) at each (decode_slots,
decode_steps_per_sync) over SHARED quantized params — engine construction
compiles per config, the measured phase excludes compile (warm-up first).
The grid runs in round-robin PASSES and each config reports its best pass:
throughput through the remote-TPU relay drifts tens of percent on minute
scales, and interleaving decorrelates that drift from the config order.

Run:  python tools/decode_sweep.py [--passes 2] [--slots 16 32] [--k 8 16 32 64]
Emits one JSON line per config plus a "best" line at the end.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp

import bench


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--passes", type=int, default=2)
    ap.add_argument("--slots", type=int, nargs="+", default=[16, 32])
    ap.add_argument("--k", type=int, nargs="+", default=[8, 16, 32, 64])
    ap.add_argument("--requests", type=int, default=32)
    ap.add_argument("--max-new", type=int, default=48)
    ap.add_argument("--prompt-len", type=int, default=100)
    args = ap.parse_args()

    bench.install_sigterm_cleanup()
    bench._claim_device_with_retry()
    bench._device_watchdog()
    cfg = bench.bench_model_cfg()
    on_cpu = jax.default_backend() == "cpu"
    dtype = jnp.float32 if on_cpu else jnp.bfloat16

    from llm_instance_gateway_tpu.models import transformer
    from llm_instance_gateway_tpu.server.engine import Engine, EngineConfig

    params = transformer.init_params(cfg, jax.random.PRNGKey(0), dtype=dtype)
    if not on_cpu:
        from llm_instance_gateway_tpu.ops.quant import quantize_params

        params = quantize_params(params)

    grid = [(s, k) for s in args.slots for k in args.k]
    results: dict[tuple[int, int], list[float]] = {g: [] for g in grid}
    engines: dict[tuple[int, int], Engine] = {}
    try:
        for slots, k in grid:
            engine = Engine(
                cfg, params,
                EngineConfig(
                    decode_slots=slots, max_seq_len=cfg.max_seq_len,
                    prefill_buckets=(128, 256),
                    decode_steps_per_sync=k, pipeline_decode=not on_cpu,
                ),
                lora_manager=None, eos_id=None, dtype=dtype,
            )
            engine.start()
            engines[(slots, k)] = engine
            # Warm-up: compile prefill buckets + decode program.
            bench.run_phase(engine, 2, args.prompt_len, 4, adapters=[])

        for p in range(args.passes):
            for slots, k in grid:
                r = bench.run_phase(
                    engines[(slots, k)], args.requests, args.prompt_len,
                    args.max_new, adapters=[])
                results[(slots, k)].append(r["tok_per_s"])
                print(json.dumps({
                    "slots": slots, "k": k, "pass": p,
                    "tok_per_s": round(r["tok_per_s"], 1),
                    "ttft_p50_ms": round(r["ttft_p50_ms"], 1),
                }), flush=True)
    finally:
        for engine in engines.values():
            engine.stop()

    summary = sorted(
        ((max(v), s, k) for (s, k), v in results.items() if v), reverse=True)
    for tok_s, s, k in summary:
        print(json.dumps({"slots": s, "k": k, "best_tok_per_s": round(tok_s, 1)}),
              flush=True)
    best = summary[0]
    print(json.dumps({"best": {"slots": best[1], "k": best[2],
                               "tok_per_s": round(best[0], 1)}}), flush=True)


if __name__ == "__main__":
    main()
