"""Per-phase latency report from tracing output.

Reads either shape and prints a per-phase p50/p95/p99 table:

- a ``/debug/traces`` JSON document (proxy or api_http; file path, URL, or
  ``-`` for stdin): ``{"traces": [{"spans": [{"name", "start", "end"}]}]}``
  — each span's duration is one sample of its phase;
- a loadgen ``--trace-out`` file: ``{"phases": {name: [seconds, ...]}}``.

Usage:
  python tools/trace_report.py http://localhost:8081/debug/traces
  python tools/trace_report.py traces.json --json
  python -m llm_instance_gateway_tpu.gateway.loadgen --requests 2000 \
      --trace-out /tmp/phases.json && python tools/trace_report.py /tmp/phases.json

bench.py invokes the same table-building functions on the handoff
microbench's requests, so every BENCH emission carries the per-phase
latency breakdown.
"""

from __future__ import annotations

import argparse
import json
import sys


def load(source: str) -> dict:
    """Load a traces/phases JSON document from a path, URL, or stdin."""
    if source == "-":
        return json.load(sys.stdin)
    if source.startswith(("http://", "https://")):
        import urllib.request

        with urllib.request.urlopen(source, timeout=10) as resp:
            return json.loads(resp.read().decode())
    with open(source) as f:
        return json.load(f)


def phase_samples(doc: dict) -> dict[str, list[float]]:
    """Phase name -> duration samples (seconds), from either input shape."""
    if "phases" in doc:
        return {str(k): [float(x) for x in v]
                for k, v in doc["phases"].items()}
    samples: dict[str, list[float]] = {}
    for trace in doc.get("traces", []):
        for span in trace.get("spans", []):
            try:
                d = float(span["end"]) - float(span["start"])
            except (KeyError, TypeError, ValueError):
                continue
            samples.setdefault(str(span.get("name", "?")), []).append(
                max(0.0, d))
    return samples


def percentile(xs: list[float], p: float) -> float:
    """Nearest-rank percentile over a SORTED sample list."""
    return xs[min(len(xs) - 1, int(p * len(xs)))]


def phase_table(samples: dict[str, list[float]]) -> list[dict]:
    """One row per phase: n, p50/p95/p99 and mean in milliseconds, sorted
    by p50 descending (the biggest time sinks lead)."""
    rows = []
    for name, xs in samples.items():
        if not xs:
            continue
        xs = sorted(xs)
        rows.append({
            "phase": name,
            "n": len(xs),
            "p50_ms": round(percentile(xs, 0.50) * 1e3, 3),
            "p95_ms": round(percentile(xs, 0.95) * 1e3, 3),
            "p99_ms": round(percentile(xs, 0.99) * 1e3, 3),
            "mean_ms": round(sum(xs) / len(xs) * 1e3, 3),
        })
    rows.sort(key=lambda r: (-r["p50_ms"], r["phase"]))
    return rows


def format_table(rows: list[dict]) -> str:
    if not rows:
        return "(no phase samples)"
    headers = ("phase", "n", "p50_ms", "p95_ms", "p99_ms", "mean_ms")
    widths = [max(len(h), *(len(str(r[h])) for r in rows)) for h in headers]
    def fmt(vals):
        return "  ".join(str(v).rjust(w) if i else str(v).ljust(w)
                         for i, (v, w) in enumerate(zip(vals, widths)))
    lines = [fmt(headers), fmt(["-" * w for w in widths])]
    lines += [fmt([r[h] for h in headers]) for r in rows]
    return "\n".join(lines)


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        description="per-phase latency table from /debug/traces JSON or "
                    "loadgen --trace-out output")
    parser.add_argument("source",
                        help="file path, http(s) URL, or - for stdin")
    parser.add_argument("--json", action="store_true",
                        help="emit the rows as one JSON line instead of a "
                             "table")
    args = parser.parse_args(argv)
    rows = phase_table(phase_samples(load(args.source)))
    if args.json:
        print(json.dumps(rows))
    else:
        print(format_table(rows))
    return 0 if rows else 1


if __name__ == "__main__":
    sys.exit(main())
