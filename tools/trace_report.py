"""Per-phase latency report from tracing output.

Reads either shape and prints a per-phase p50/p95/p99 table:

- a ``/debug/traces`` JSON document (proxy or api_http; file path, URL, or
  ``-`` for stdin): ``{"traces": [{"spans": [{"name", "start", "end"}]}]}``
  — each span's duration is one sample of its phase;
- a loadgen ``--trace-out`` file: ``{"phases": {name: [seconds, ...]}}``.

Usage:
  python tools/trace_report.py http://localhost:8081/debug/traces
  python tools/trace_report.py traces.json --json
  python -m llm_instance_gateway_tpu.gateway.loadgen --requests 2000 \
      --trace-out /tmp/phases.json && python tools/trace_report.py /tmp/phases.json

bench.py invokes the same table-building functions on the handoff
microbench's requests, so every BENCH emission carries the per-phase
latency breakdown.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

# Runnable as `python tools/trace_report.py` from anywhere: the fleet
# stitcher import (multi-replica mode) needs the repo root on the path.
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def load(source: str) -> dict:
    """Load a traces/phases JSON document from a path, URL, or stdin."""
    if source == "-":
        return json.load(sys.stdin)
    if source.startswith(("http://", "https://")):
        import urllib.request

        with urllib.request.urlopen(source, timeout=10) as resp:
            return json.loads(resp.read().decode())
    with open(source) as f:
        return json.load(f)


def phase_samples(doc: dict) -> dict[str, list[float]]:
    """Phase name -> duration samples (seconds), from either input shape."""
    if "phases" in doc:
        return {str(k): [float(x) for x in v]
                for k, v in doc["phases"].items()}
    samples: dict[str, list[float]] = {}
    for trace in doc.get("traces", []):
        for span in trace.get("spans", []):
            try:
                d = float(span["end"]) - float(span["start"])
            except (KeyError, TypeError, ValueError):
                continue
            samples.setdefault(str(span.get("name", "?")), []).append(
                max(0.0, d))
    return samples


def percentile(xs: list[float], p: float) -> float:
    """Nearest-rank percentile over a SORTED sample list."""
    return xs[min(len(xs) - 1, int(p * len(xs)))]


def phase_table(samples: dict[str, list[float]]) -> list[dict]:
    """One row per phase: n, p50/p95/p99 and mean in milliseconds, sorted
    by p50 descending (the biggest time sinks lead)."""
    rows = []
    for name, xs in samples.items():
        if not xs:
            continue
        xs = sorted(xs)
        rows.append({
            "phase": name,
            "n": len(xs),
            "p50_ms": round(percentile(xs, 0.50) * 1e3, 3),
            "p95_ms": round(percentile(xs, 0.95) * 1e3, 3),
            "p99_ms": round(percentile(xs, 0.99) * 1e3, 3),
            "mean_ms": round(sum(xs) / len(xs) * 1e3, 3),
        })
    rows.sort(key=lambda r: (-r["p50_ms"], r["phase"]))
    return rows


def format_table(rows: list[dict], headers: tuple | None = None) -> str:
    if not rows:
        return "(no phase samples)"
    headers = tuple(headers or rows[0].keys())
    widths = [max(len(h), *(len(str(r[h])) for r in rows)) for h in headers]
    def fmt(vals):
        return "  ".join(str(v).rjust(w) if i else str(v).ljust(w)
                         for i, (v, w) in enumerate(zip(vals, widths)))
    lines = [fmt(headers), fmt(["-" * w for w in widths])]
    lines += [fmt([r[h] for h in headers]) for r in rows]
    return "\n".join(lines)


def multi_replica_samples(sources: list[tuple[str, dict]]) -> dict:
    """Phase samples over SEVERAL replicas' /debug/traces payloads,
    merged through the fleet stitcher (gateway/fleetobs.py) — duplicate
    spans (the gateway's ``x-lig-spans`` copy of a server span) fold and
    per-source clock skew normalizes, so the table is the fleet truth
    instead of one replica's view reported as the whole story."""
    from llm_instance_gateway_tpu.gateway import fleetobs

    return phase_samples(
        {"traces": fleetobs.stitch_traces(sources, limit=1024)})


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        description="per-phase latency table from /debug/traces JSON or "
                    "loadgen --trace-out output")
    parser.add_argument("source", nargs="?",
                        help="file path, http(s) URL, or - for stdin")
    parser.add_argument("--url", action="append", default=[],
                        help="a replica's /debug/traces URL (repeatable: "
                             "multiple replicas merge through the fleet "
                             "stitcher instead of one view posing as the "
                             "whole story)")
    parser.add_argument("--replicas",
                        help="CSV of replica base URLs; each fetches "
                             "<base>/debug/traces and merges like --url")
    parser.add_argument("--json", action="store_true",
                        help="emit the rows as one JSON line instead of a "
                             "table")
    args = parser.parse_args(argv)
    urls = list(args.url)
    if args.replicas:
        urls += [u.strip().rstrip("/") + "/debug/traces"
                 for u in args.replicas.split(",") if u.strip()]
    if urls:
        sources = [(u, load(u)) for u in urls]
        if args.source:
            sources.append((args.source, load(args.source)))
        samples = multi_replica_samples(sources)
    elif args.source:
        samples = phase_samples(load(args.source))
    else:
        parser.error("need a source, --url, or --replicas")
    rows = phase_table(samples)
    if args.json:
        print(json.dumps(rows))
    else:
        print(format_table(rows))
    return 0 if rows else 1


if __name__ == "__main__":
    sys.exit(main())
