#!/usr/bin/env python
"""Deterministic chaos runner for the in-process gateway stack.

Builds the REAL proxy (scheduler + admission + health + resilience plane)
over fake chaos upstreams (``gateway/faultinject.py``), applies a seeded
fault schedule, drives load, and asserts recovery invariants per scenario:

====================  ====================================================
``blackhole``         faulted pod stops getting picks within 2 health
                      ticks (breaker + avoid policy); success rate > 99%
``brownout``          slow-TTFT pod: hedges fire and win; all requests ok
``midstream``         mid-stream upstream cut: clients get the error
                      event + [DONE]; the journal records it; stack lives
``scrape_flap``       scrape-plane-only failure steers routing off the
                      pod within 2 ticks with zero data-path errors
``handoff``           decode-hop failures fall back single-hop; an
                      abandoned attach triggers the KV release call
``noisy_neighbor``    one adapter floods long prompts: the usage rollup
                      flags it within 2 ticks, quiet adapters never flag
``adapter_flood``     fairness plane: the flooding hog is throttled AND
                      noisy-flagged within 2 ticks, zero critical sheds
``cold_start_storm``  placement plane: Zipf flood over a mostly-non-
                      resident universe; hot-set p99 TTFT within 2x the
                      all-resident baseline, zero wrong-tier picks in
                      prefer_resident mode
``replica_partition`` statebus plane: a replica partitioned off the bus
                      degrades to local-only enforcement with zero 5xx
                      (statebus_stale journaled) and rejoins within 2
                      ticks of the partition healing
``saturation_ramp``   capacity plane: a load ramp toward the pool knee —
                      the twin's capacity_forecast event leads the SLO
                      fast burn by >= 2 ticks, drift stays quiet on
                      honest counters, an injected model/pool mismatch
                      fires twin_drift and un-trusts forecasts
====================  ====================================================

Usage: ``python tools/chaos.py --seed 0 --scenario all`` (``make chaos``).
Exits non-zero when any scenario's invariant fails; prints one JSON report
line per scenario.  ``tests/test_resilience.py`` runs the same scenarios
as a ``slow``-marked pytest, so tier-1 stays fast.

Health ticks are driven EXPLICITLY (``proxy.resilience.tick()`` between
request rounds) instead of by the background task, so "within N ticks"
assertions are deterministic.
"""

from __future__ import annotations

import argparse
import asyncio
import json
import os
import random
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from aiohttp.test_utils import TestClient, TestServer  # noqa: E402

from llm_instance_gateway_tpu import events as events_mod  # noqa: E402
from llm_instance_gateway_tpu.api.v1alpha1 import InferencePool  # noqa: E402
from llm_instance_gateway_tpu.gateway import faultinject  # noqa: E402
from llm_instance_gateway_tpu.gateway.datastore import Datastore  # noqa: E402
from llm_instance_gateway_tpu.gateway.handlers.server import Server  # noqa: E402
from llm_instance_gateway_tpu.gateway.health import HealthConfig  # noqa: E402
from llm_instance_gateway_tpu.gateway.pickledger import (  # noqa: E402
    PickLedgerConfig,
)
from llm_instance_gateway_tpu.gateway.provider import StaticProvider  # noqa: E402
from llm_instance_gateway_tpu.gateway.proxy import GatewayProxy  # noqa: E402
from llm_instance_gateway_tpu.gateway.resilience import (  # noqa: E402
    ResilienceConfig,
)
from llm_instance_gateway_tpu.gateway.scheduling.scheduler import (  # noqa: E402
    Scheduler,
)
from llm_instance_gateway_tpu.gateway.testing import make_model  # noqa: E402
from llm_instance_gateway_tpu.gateway.types import (  # noqa: E402
    Metrics,
    Pod,
    PodMetrics,
)

GOOD, BAD = "pod-good", "pod-bad"


class ChaosStack:
    """One in-process gateway + N chaos upstreams, torn down together."""

    def __init__(self, schedule, seed: int, rcfg: ResilienceConfig,
                 roles: dict[str, str] | None = None,
                 provider_cls=StaticProvider,
                 models: tuple[str, ...] = ("m",),
                 model_tiers: dict[str, object] | None = None,
                 fairness_cfg=None, placement_cfg=None,
                 capacity_cfg=None, blackbox_dir: str | None = None):
        self.schedule = schedule
        self.seed = seed
        self.rcfg = rcfg
        self.roles = roles or {GOOD: "collocated", BAD: "collocated"}
        self.provider_cls = provider_cls
        self.models = models
        # model -> Criticality tier (default Critical, the historical
        # scenario shape); the fairness scenarios mix tiers.
        self.model_tiers = model_tiers or {}
        self.fairness_cfg = fairness_cfg
        self.placement_cfg = placement_cfg
        self.capacity_cfg = capacity_cfg
        self.blackbox_dir = blackbox_dir
        self.upstreams: dict[str, TestServer] = {}
        self.state: dict[str, dict] = {}
        self.client: TestClient | None = None
        self.proxy: GatewayProxy | None = None

    async def __aenter__(self) -> "ChaosStack":
        pods = []
        for name, role in self.roles.items():
            state: dict = {}
            server = TestServer(
                faultinject.make_chaos_app(name, self.schedule, state=state))
            await server.start_server()
            self.upstreams[name] = server
            self.state[name] = state
            pods.append(Pod(name, f"127.0.0.1:{server.port}", role=role))
        ds = Datastore(pods=pods)
        ds.set_pool(InferencePool(name="chaos-pool"))
        for model in self.models:
            tier = self.model_tiers.get(model)
            ds.store_model(make_model(model, tier) if tier is not None
                           else make_model(model))
        provider = self.provider_cls(
            [PodMetrics(pod=p, metrics=Metrics()) for p in pods])
        scheduler = Scheduler(provider, token_aware=False,
                              prefill_aware=False, prefix_aware=False,
                              rng=random.Random(self.seed))
        self.proxy = GatewayProxy(
            Server(scheduler, ds), provider, ds,
            resilience_cfg=self.rcfg,
            fairness_cfg=self.fairness_cfg,
            placement_cfg=self.placement_cfg,
            capacity_cfg=self.capacity_cfg,
            blackbox_dir=self.blackbox_dir,
            # Every pick recorded: the scenarios assert on the decision
            # ledger's counterfactual attribution, not a sample of it.
            pickledger_cfg=PickLedgerConfig(sample_every=1),
            # Fast hysteresis for harness time: 2-tick dwell is the
            # quantity the acceptance criterion counts.
            health_cfg=HealthConfig(dwell_ticks=2, error_streak_floor=3))
        self.proxy.obs_tick_s = 0  # ticks are driven explicitly
        self.client = TestClient(TestServer(self.proxy.build_app()))
        await self.client.start_server()
        self.schedule.arm()
        return self

    async def __aexit__(self, *exc) -> None:
        if self.client is not None:
            await self.client.close()
        for server in self.upstreams.values():
            await server.close()

    def tick(self) -> None:
        self.proxy.resilience.tick()

    async def request(self, stream: bool = False, model: str = "m",
                      prompt: str = "chaos") -> int:
        body = {"model": model, "prompt": prompt, "max_tokens": 4}
        if stream:
            body["stream"] = True
        resp = await self.client.post("/v1/completions", json=body)
        await resp.read()
        return resp.status

    def picks_by_round(self, events: list[dict]) -> list[str]:
        return [e["attrs"]["pod"] for e in events]


def _provider_factory(schedule):
    def build(pod_metrics):
        return faultinject.ChaosProvider(pod_metrics, schedule)

    return build


async def scenario_blackhole(seed: int) -> dict:
    """Acceptance-critical: with health_policy=avoid, a blackholed replica
    gets ZERO new picks within 2 health-evaluation ticks of the fault
    while overall success stays > 99% (retries absorb the in-window
    failures)."""
    schedule = faultinject.FaultSchedule([], seed=seed)
    rcfg = ResilienceConfig(
        health_policy="avoid", max_retries=3, retry_budget_min=32.0,
        trip_consecutive=3, open_cooldown_s=300.0,
        connect_timeout_s=2.0, ttft_timeout_s=0.25,
        stream_idle_timeout_s=2.0, backoff_base_s=0.005, backoff_cap_s=0.02)
    async with ChaosStack(schedule, seed, rcfg) as stack:
        statuses = []
        for _ in range(10):  # clean warmup: both pods in rotation
            statuses.append(await stack.request())
        stack.tick()
        warm_picks = stack.picks_by_round(
            stack.proxy.journal.events(kind=events_mod.PICK, limit=2048))
        assert BAD in warm_picks and GOOD in warm_picks, warm_picks

        schedule.inject_now(faultinject.BLACKHOLE, pod=BAD)
        pick_seq0 = stack.proxy.pickledger.seq
        round_picks: list[list[str]] = []
        for _ in range(6):  # 6 rounds == 6 health ticks under fault
            seq0 = stack.proxy.journal.seq
            for _ in range(5):
                statuses.append(await stack.request())
            stack.tick()
            round_picks.append(stack.picks_by_round(
                stack.proxy.journal.events(since=seq0, limit=2048,
                                           kind=events_mod.PICK)))

        ok = sum(1 for s in statuses if s == 200)
        success_rate = ok / len(statuses)
        bad_after_2_ticks = sum(p.count(BAD) for p in round_picks[2:])
        circuit = stack.proxy.resilience.breaker.state(BAD)
        # Explainability acceptance: the decision ledger's counterfactual
        # must ATTRIBUTE the reroute — during the outage, steered picks
        # are decisively steered by the health/circuit seam (disabling it
        # would have put the blackholed pod back in the survivor set).
        outage_recs = stack.proxy.pickledger.records(since=pick_seq0,
                                                     limit=2048)
        steered_recs = [r for r in outage_recs if r["steered"]]
        health_decisive = sum(1 for r in steered_recs
                              if r["decisive"] == "health/circuit")
        decisive_share = (health_decisive / len(steered_recs)
                          if steered_recs else 0.0)
        report = {
            "scenario": "blackhole", "requests": len(statuses),
            "success_rate": round(success_rate, 4),
            "bad_picks_per_round": [p.count(BAD) for p in round_picks],
            "bad_picks_after_2_ticks": bad_after_2_ticks,
            "circuit_state_bad": circuit,
            "retries": dict(stack.proxy.metrics.retries_total),
            "steered_picks": len(steered_recs),
            "decisive_health_share": round(decisive_share, 4),
        }
        assert success_rate > 0.99, report
        assert bad_after_2_ticks == 0, report
        assert circuit == "open", report
        assert sum(stack.proxy.metrics.retries_total.values()) >= 1, report
        assert steered_recs, report
        assert decisive_share >= 0.95, report
        return report


async def scenario_brownout(seed: int) -> dict:
    """Slow-TTFT replica: TTFT hedging masks the brownout — hedges fire,
    at least one wins, every request succeeds."""
    schedule = faultinject.FaultSchedule([], seed=seed)
    rcfg = ResilienceConfig(
        health_policy="avoid", max_retries=2, retry_budget_min=16.0,
        hedge_ttft_s=0.1, ttft_timeout_s=5.0, connect_timeout_s=2.0,
        stream_idle_timeout_s=5.0)
    async with ChaosStack(schedule, seed, rcfg) as stack:
        schedule.inject_now(faultinject.BROWNOUT, pod=BAD, delay_s=0.6)
        statuses = [await stack.request() for _ in range(20)]
        hedges = dict(stack.proxy.metrics.hedges_total)
        report = {"scenario": "brownout", "requests": len(statuses),
                  "success_rate": statuses.count(200) / len(statuses),
                  "hedges": hedges}
        assert all(s == 200 for s in statuses), report
        assert hedges.get("fired", 0) >= 1, report
        assert hedges.get("won", 0) >= 1, report
        return report


async def scenario_midstream(seed: int) -> dict:
    """Mid-stream upstream cut: the client's stream terminates with the
    error event + [DONE] (never a hung socket), the journal records the
    stream failure, and the stack keeps serving."""
    schedule = faultinject.FaultSchedule([], seed=seed)
    rcfg = ResilienceConfig(
        health_policy="avoid", max_retries=1, ttft_timeout_s=2.0,
        stream_idle_timeout_s=1.0, connect_timeout_s=2.0)
    async with ChaosStack(schedule, seed, rcfg) as stack:
        schedule.inject_now(faultinject.MIDSTREAM_DISCONNECT, pod=BAD,
                            after_chunks=2)
        cut = served = 0
        for _ in range(10):
            resp = await stack.client.post(
                "/v1/completions",
                json={"model": "m", "prompt": "x", "max_tokens": 4,
                      "stream": True})
            raw = (await resp.read()).decode()
            assert resp.status == 200
            assert raw.rstrip().endswith("data: [DONE]")
            if "upstream stream interrupted" in raw:
                cut += 1
            else:
                served += 1
        errs = stack.proxy.journal.events(kind=events_mod.UPSTREAM_ERROR,
                                          limit=2048)
        stream_errs = [e for e in errs if e["attrs"].get("stream")]
        # The faulted pod must have been hit at least once and every cut
        # stream must have closed cleanly for the client.
        report = {"scenario": "midstream", "cut_streams": cut,
                  "clean_streams": served,
                  "journaled_stream_errors": len(stream_errs)}
        assert cut >= 1 and served >= 1, report
        assert len(stream_errs) >= cut, report
        # Post-fault: the stack still serves non-streaming traffic.
        assert await stack.request() == 200
        return report


async def scenario_scrape_flap(seed: int) -> dict:
    """Scrape-plane-only failure (data path healthy): the health scorer's
    freshness component degrades the pod and avoid-policy steers routing
    off it within 2 ticks — with zero request failures throughout."""
    schedule = faultinject.FaultSchedule([], seed=seed)
    rcfg = ResilienceConfig(health_policy="avoid", max_retries=1,
                            ttft_timeout_s=2.0, connect_timeout_s=2.0,
                            stream_idle_timeout_s=2.0)
    async with ChaosStack(schedule, seed, rcfg,
                          provider_cls=_provider_factory(schedule)) as stack:
        statuses = [await stack.request() for _ in range(10)]
        stack.tick()
        schedule.inject_now(faultinject.SCRAPE_FLAP, pod=BAD)
        round_picks = []
        for _ in range(5):
            seq0 = stack.proxy.journal.seq
            for _ in range(5):
                statuses.append(await stack.request())
            stack.tick()
            round_picks.append(stack.picks_by_round(
                stack.proxy.journal.events(since=seq0, limit=2048,
                                           kind=events_mod.PICK)))
        report = {
            "scenario": "scrape_flap",
            "success_rate": statuses.count(200) / len(statuses),
            "bad_picks_per_round": [p.count(BAD) for p in round_picks],
            "bad_state": stack.proxy.health.state(BAD),
        }
        assert all(s == 200 for s in statuses), report
        assert sum(p.count(BAD) for p in round_picks[2:]) == 0, report
        return report


async def scenario_handoff(seed: int) -> dict:
    """Disaggregated pool, decode hop failing: every request degrades to
    single-hop (disagg_fallback journaled) and still succeeds; an
    abandoned attach (transport cut after the handoff was posted) fires
    the best-effort KV release at the decode replica."""
    schedule = faultinject.FaultSchedule([], seed=seed)
    rcfg = ResilienceConfig(health_policy="avoid", max_retries=1,
                            ttft_timeout_s=2.0, connect_timeout_s=2.0,
                            stream_idle_timeout_s=2.0)
    roles = {GOOD: "prefill", BAD: "decode"}
    async with ChaosStack(schedule, seed, rcfg, roles=roles) as stack:
        spec = schedule.inject_now(faultinject.HANDOFF_FAILURE, pod=BAD,
                                   mode="error")
        statuses = [await stack.request() for _ in range(5)]
        fallbacks = stack.proxy.journal.events(
            kind=events_mod.DISAGG_FALLBACK, limit=2048)
        assert all(s == 200 for s in statuses), statuses
        assert len(fallbacks) == 5, fallbacks

        # Phase 2: the attach DIES mid-flight -> abandoned work on the
        # decode replica -> the gateway fires /v1/prefill/release at it.
        schedule.faults.remove(spec)
        schedule.inject_now(faultinject.HANDOFF_FAILURE, pod=BAD,
                            mode="disconnect")
        statuses2 = [await stack.request() for _ in range(3)]
        await asyncio.sleep(0.2)  # let the fire-and-forget releases land
        released = list(stack.state[BAD]["released"])
        kv_events = stack.proxy.journal.events(kind=events_mod.KV_RELEASE,
                                               limit=2048)
        report = {"scenario": "handoff",
                  "fallbacks": len(fallbacks),
                  "phase2_success": statuses2.count(200) / len(statuses2),
                  "released_ids": released,
                  "kv_release_events": len(kv_events)}
        assert all(s == 200 for s in statuses2), report
        assert released, report
        assert kv_events and all(
            e["attrs"]["pod"] == BAD for e in kv_events), report
        return report


async def scenario_noisy_neighbor(seed: int) -> dict:
    """Capacity-attribution acceptance: one adapter floods long prompts
    (most of the pool's step-seconds on a modest traffic share) while two
    quiet adapters send ordinary traffic.  The usage rollup must flag the
    hog within 2 rollup ticks of the flood — and NEVER flag the quiet
    adapters (zero false positives).

    The gateway side is fully real: requests flow through the proxy (so
    admitted-traffic shares come from the live requests_total counters)
    and the REAL ``gateway/usage.py`` rollup scores them.  The replica
    side synthesizes the scraped ``tpu:adapter_step_seconds_total``
    counters each round — cumulative, proportional to the prompt tokens
    each adapter actually sent — exactly what a scrape of the engine's
    attribution tracker would return."""
    from llm_instance_gateway_tpu import events as ev

    schedule = faultinject.FaultSchedule([], seed=seed)
    rcfg = ResilienceConfig(health_policy="log_only", max_retries=1,
                            ttft_timeout_s=2.0, connect_timeout_s=2.0,
                            stream_idle_timeout_s=2.0)
    hog, quiet_a, quiet_b = "hog", "quiet-a", "quiet-b"
    models = (hog, quiet_a, quiet_b)
    # Long prompt for the hog: ~16x the quiet prompt, the prefill
    # step-second skew the synthetic counters mirror.
    long_prompt, short_prompt = "flood " * 160, "chaos"
    async with ChaosStack(schedule, seed, rcfg, models=models) as stack:
        usage = stack.proxy.usage
        provider = stack.proxy.provider
        step_totals = {m: 0.0 for m in models}

        def scrape(prompt_tokens: dict[str, int]) -> None:
            """One synthetic scrape round: step-seconds grow with the
            prompt tokens each adapter sent this round (1ms/token)."""
            for m, toks in prompt_tokens.items():
                step_totals[m] += toks * 1e-3
            for pm in provider.all_pod_metrics():
                pm.metrics.adapter_step_seconds = {
                    ("m", m, "prefill"): step_totals[m] / 2  # 2 pods
                    for m in models}

        async def round_(hog_requests: int) -> dict[str, int]:
            toks = {m: 0 for m in models}
            for _ in range(hog_requests):
                assert await stack.request(
                    model=hog, prompt=long_prompt) == 200
                toks[hog] += len(long_prompt.split())
            for m in (quiet_a, quiet_b):
                for _ in range(3):
                    assert await stack.request(
                        model=m, prompt=short_prompt) == 200
                    toks[m] += 1
            return toks

        # Clean warmup rounds: everyone quiet, shares settle.
        for _ in range(3):
            scrape(await round_(hog_requests=0))
            usage.tick()
        assert usage.noisy() == frozenset(), dict(usage._states)

        # Flood: the hog sends a few LONG-prompt requests per round —
        # small traffic share, dominant step-seconds share.
        flagged_after = None
        rounds = 6
        for i in range(1, rounds + 1):
            scrape(await round_(hog_requests=3))
            usage.tick()
            if flagged_after is None and hog in usage.noisy():
                flagged_after = i
        payload = usage.debug_payload()
        by_adapter = {r["adapter"]: r for r in payload["adapters"]}
        flags = stack.proxy.journal.events(kind=ev.NOISY_NEIGHBOR,
                                           limit=2048)
        report = {
            "scenario": "noisy_neighbor",
            "flagged_after_ticks": flagged_after,
            "hog_score": by_adapter[hog]["score"],
            "quiet_scores": {m: by_adapter[m]["score"]
                             for m in (quiet_a, quiet_b)},
            "noisy": payload["noisy"],
            "journaled_flags": [e["attrs"]["adapter"] for e in flags],
        }
        # Detection bar: the hog flags within 2 rollup ticks of the flood.
        assert flagged_after is not None and flagged_after <= 2, report
        assert payload["noisy"] == [hog], report
        # Zero false positives: quiet adapters stay quiet AND below the
        # score threshold for the whole run.
        cfg = usage.cfg
        for m in (quiet_a, quiet_b):
            assert by_adapter[m]["state"] == "quiet", report
            assert by_adapter[m]["score"] < cfg.noisy_ratio, report
        assert set(report["journaled_flags"]) == {hog}, report
        return report


async def scenario_adapter_flood(seed: int) -> dict:
    """Fairness-plane acceptance: one adapter floods long prompts under
    ``--criticality-mix``-shaped cotenant traffic with the fairness mode
    ENFORCING.  Within 2 observability ticks of the flood the hog must be
    throttled (over-quota: bucket-gated, demoted one tier) AND noisy-
    flagged (quiet tenants' picks steer off the replica hosting it); the
    quiet tenants' p99 stays within 1.2x of their pre-flood baseline, and
    ZERO critical requests are shed.

    Traffic shape: the same ``critical/default/sheddable`` tier mix the
    loadgen's ``--criticality-mix`` emits, so this scenario and future sim
    calibration share one mold.  The gateway side is fully real (requests
    flow through the proxy; the REAL UsageRollup + FairnessPolicy score
    and enforce); the replica side synthesizes the scraped attribution
    counters per round, like the noisy_neighbor scenario."""
    import time as time_mod

    from llm_instance_gateway_tpu.api.v1alpha1 import Criticality
    from llm_instance_gateway_tpu.gateway.fairness import FairnessConfig
    from llm_instance_gateway_tpu.gateway.loadgen import (
        parse_criticality_mix,
    )

    schedule = faultinject.FaultSchedule([], seed=seed)
    rcfg = ResilienceConfig(health_policy="log_only", max_retries=1,
                            ttft_timeout_s=2.0, connect_timeout_s=2.0,
                            stream_idle_timeout_s=2.0)
    # Tiny bucket so the flood exhausts it within a round; deprioritize +
    # quotas both ride mode=enforce (default over_ratio: a 60%-of-traffic
    # quiet tenant must NOT throttle, the flood must).
    fcfg = FairnessConfig(mode="enforce", quota_rps=0.5, quota_burst=1.0)
    mix = parse_criticality_mix("critical=0.1,default=0.6,sheddable=0.3")
    hog, quiet, crit, shed_m = "hog", "quiet-a", "crit", "shed-b"
    models = (hog, quiet, crit, shed_m)
    tiers = {hog: Criticality.DEFAULT, quiet: Criticality.DEFAULT,
             crit: Criticality.CRITICAL, shed_m: Criticality.SHEDDABLE}
    long_prompt, short_prompt = "flood " * 160, "chaos"
    async with ChaosStack(schedule, seed, rcfg, models=models,
                          model_tiers=tiers, fairness_cfg=fcfg) as stack:
        usage, fairness = stack.proxy.usage, stack.proxy.fairness
        provider = stack.proxy.provider
        # The hog adapter is RESIDENT on pod-bad only: once flagged, the
        # pick plane must steer quiet tenants off that replica.
        for pm in provider.all_pod_metrics():
            pm.metrics.active_adapters = (
                {hog: 0} if pm.pod.name == BAD else {quiet: 0})
        step_totals = {m: 0.0 for m in models}

        def scrape(prompt_tokens: dict[str, int]) -> None:
            for m, toks in prompt_tokens.items():
                step_totals[m] += toks * 1e-3
            for pm in provider.all_pod_metrics():
                pm.metrics.adapter_step_seconds = {
                    ("m", m, "prefill"): step_totals[m] / 2
                    for m in models}

        quiet_lat: dict[str, list[float]] = {"warm": [], "flood": []}
        crit_statuses: list[int] = []

        async def timed_quiet(bucket: str) -> None:
            t0 = time_mod.monotonic()
            status = await stack.request(model=quiet, prompt=short_prompt)
            quiet_lat[bucket].append(time_mod.monotonic() - t0)
            assert status == 200, status

        async def round_(hog_requests: int, bucket: str) -> dict[str, int]:
            """One traffic round in the shared criticality-mix shape:
            ~10% critical / 60% default / 30% sheddable cotenants, plus
            the flood."""
            toks = {m: 0 for m in models}
            for _ in range(hog_requests):
                assert await stack.request(
                    model=hog, prompt=long_prompt) == 200
                toks[hog] += len(long_prompt.split())
            n_quiet = max(1, round(6 * mix["Default"]))
            n_crit = max(1, round(6 * mix["Critical"]))
            n_shed = max(1, round(6 * mix["Sheddable"]))
            for _ in range(n_quiet):
                await timed_quiet(bucket)
                toks[quiet] += 1
            for _ in range(n_crit):
                crit_statuses.append(await stack.request(
                    model=crit, prompt=short_prompt))
                toks[crit] += 1
            for _ in range(n_shed):
                await stack.request(model=shed_m, prompt=short_prompt)
                toks[shed_m] += 1
            return toks

        def tick() -> None:
            usage.tick()
            fairness.tick()

        # Warmup: everyone modest; shares settle, baseline p99 collected.
        for _ in range(4):
            scrape(await round_(hog_requests=0, bucket="warm"))
            tick()
        assert fairness.throttled() == frozenset(), fairness.debug_payload()

        throttled_after = flagged_after = None
        for i in range(1, 7):
            seq0 = stack.proxy.journal.seq
            scrape(await round_(hog_requests=3, bucket="flood"))
            tick()
            if throttled_after is None and hog in fairness.throttled():
                throttled_after = i
            if flagged_after is None and hog in usage.noisy():
                flagged_after = i
            if i == 6:
                last_round_picks = [
                    e["attrs"] for e in stack.proxy.journal.events(
                        since=seq0, limit=2048, kind=events_mod.PICK)]

        def p99(vals: list[float]) -> float:
            vals = sorted(vals)
            return vals[min(len(vals) - 1, int(0.99 * len(vals)))]

        base_p99, flood_p99 = p99(quiet_lat["warm"]), p99(quiet_lat["flood"])
        fdbg = fairness.debug_payload()
        # Quiet-tenant picks on the hog-hosting replica in the LAST round
        # (well after the 2-tick bar): the deprioritization steady state.
        quiet_on_bad = sum(1 for a in last_round_picks
                           if a["model"] != hog and a["pod"] == BAD)
        report = {
            "scenario": "adapter_flood",
            "throttled_after_ticks": throttled_after,
            "flagged_after_ticks": flagged_after,
            "quota_throttles_total": fdbg["quota_throttles_total"],
            "fairness_demotions_total": fdbg["fairness_demotions_total"],
            "critical_sheds": sum(1 for s in crit_statuses if s == 429),
            "crit_requests": len(crit_statuses),
            "quiet_p99_base_ms": round(base_p99 * 1e3, 2),
            "quiet_p99_flood_ms": round(flood_p99 * 1e3, 2),
            "quiet_picks_on_hog_pod_last_round": quiet_on_bad,
            "throttled": sorted(fairness.throttled()),
        }
        # Detection bar: throttled within 2 ticks of the flood.
        assert throttled_after is not None and throttled_after <= 2, report
        assert flagged_after is not None, report
        # The quota actually bit: throttles counted, demotions journaled.
        assert fdbg["quota_throttles_total"] >= 1, report
        assert fdbg["fairness_demotions_total"] >= 1, report
        # Zero critical sheds, every critical request served.
        assert all(s == 200 for s in crit_statuses), report
        # Quiet-tenant p99 within 1.2x of baseline (50 ms absolute floor
        # absorbs in-process rig noise at sub-ms baselines).
        assert flood_p99 <= max(1.2 * base_p99, base_p99 + 0.05), report
        # Pick isolation converged: quiet tenants off the hog's replica.
        assert quiet_on_bad == 0, report
        return report


async def scenario_cold_start_storm(seed: int) -> dict:
    """Placement-plane acceptance: a seeded Zipf flood over a mostly-non-
    resident adapter universe with ``placement_mode=prefer_resident``.

    Two phases over the SAME stack and traffic shape:

    - ``all_resident`` baseline: every adapter slot-resident on every
      replica — no pick can ever pay a cold start.
    - ``storm``: only the Zipf head is RAM-resident (top slice slot-
      resident on a subset of replicas, next slice host-resident), the
      long tail is disk-only.  Each routed request's synthetic TTFT = a
      nominal prefill + the residency penalty of its PICKED replica
      (0 slot / host promote / full Orbax restore) — the same cost model
      the sim validates.  (The in-process rig's measured latency is pure
      harness noise at sub-ms pick costs, so it stays out of the TTFT;
      the routing is what this scenario tests, through the REAL proxy.)

    Bars: hot-set p99 TTFT within 2x the all-resident baseline, and ZERO
    wrong-tier picks (a request whose adapter is RAM-resident somewhere
    must never land on a non-resident replica; the planner's
    ``wrong_tier_picks_total`` counts exactly that).
    """
    from llm_instance_gateway_tpu.gateway.placement import PlacementConfig

    schedule = faultinject.FaultSchedule([], seed=seed)
    rcfg = ResilienceConfig(health_policy="log_only", max_retries=1,
                            ttft_timeout_s=2.0, connect_timeout_s=2.0,
                            stream_idle_timeout_s=2.0)
    universe = 30
    names = [f"zipf-{k:02d}" for k in range(universe)]
    weights = [1.0 / (k + 1) ** 1.1 for k in range(universe)]
    hot = set(names[:4])       # slot tier in the storm phase
    warm = set(names[4:10])    # host tier in the storm phase
    disk_load_s, host_promote_s, prefill_s = 0.5, 0.02, 0.02
    pods3 = {"pod-a": "collocated", "pod-b": "collocated",
             "pod-c": "collocated"}
    pcfg = PlacementConfig(mode="prefer_resident")
    async with ChaosStack(schedule, seed, rcfg, roles=pods3,
                          models=tuple(names),
                          placement_cfg=pcfg) as stack:
        provider, planner = stack.proxy.provider, stack.proxy.placement
        rng = random.Random(seed)

        def set_residency(tiers_of_pod) -> None:
            for pm in provider.all_pod_metrics():
                tiers = tiers_of_pod(pm.pod.name)
                pm.metrics.adapter_tiers = tiers
                pm.metrics.active_adapters = {
                    a: 0 for a, t in tiers.items() if t == "slot"}
                pm.metrics.max_active_adapters = universe + 1
            planner.tick()

        async def run_phase(n_requests: int, residency) -> dict[str, list]:
            """Fire seeded Zipf traffic; returns adapter -> synthetic
            TTFTs (nominal prefill + picked replica's residency penalty)."""
            ttfts: dict[str, list] = {}
            for _ in range(n_requests):
                adapter = rng.choices(names, weights=weights)[0]
                seq0 = stack.proxy.journal.seq
                status = await stack.request(model=adapter)
                assert status == 200, status
                picks = stack.proxy.journal.events(
                    since=seq0, kind=events_mod.PICK, limit=8)
                assert picks, "pick event missing"
                pod = picks[-1]["attrs"]["pod"]
                tier = residency(pod).get(adapter)
                penalty = (0.0 if tier == "slot"
                           else host_promote_s if tier == "host"
                           else disk_load_s)
                ttfts.setdefault(adapter, []).append(prefill_s + penalty)
            return ttfts

        def p99_of(ttfts: dict[str, list], subset) -> float:
            vals = sorted(v for a, lst in ttfts.items()
                          if a in subset for v in lst)
            return vals[min(len(vals) - 1, int(0.99 * len(vals)))] \
                if vals else 0.0

        # Phase 1: all-resident baseline.
        all_resident = {a: "slot" for a in names}
        set_residency(lambda pod: all_resident)
        base = await run_phase(80, lambda pod: all_resident)

        # Phase 2: the storm — head slot-resident on a SUBSET of
        # replicas, warm slice host-resident, long tail disk-only.
        storm_tiers = {
            "pod-a": {**{a: "slot" for a in list(hot)[:2]},
                      **{a: "host" for a in warm}},
            "pod-b": {**{a: "slot" for a in list(hot)[2:]},
                      **{a: "host" for a in warm}},
            "pod-c": {a: "host" for a in warm},
        }
        set_residency(lambda pod: storm_tiers[pod])
        planner.wrong_tier_total = 0  # phase boundary: count storm only
        storm = await run_phase(160, lambda pod: storm_tiers[pod])

        base_p99, storm_p99 = p99_of(base, hot), p99_of(storm, hot)
        # A disk-tier adapter with PARKED requests earns a prefetch
        # decision on the next planner tick (the sidecar would execute it
        # over the residency wire).
        for pm in provider.all_pod_metrics():
            if pm.pod.name == "pod-c":
                pm.metrics.waiting_adapters = frozenset({"zipf-20"})
        planner.tick()
        pdbg = planner.debug_payload()
        prefetches = [d for d in pdbg["decisions"]
                      if d["action"] == "prefetch"
                      and d["adapter"] == "zipf-20"]
        report = {
            "scenario": "cold_start_storm",
            "universe": universe,
            "hot_set": sorted(hot),
            "hot_p99_base_ms": round(base_p99 * 1e3, 2),
            "hot_p99_storm_ms": round(storm_p99 * 1e3, 2),
            "wrong_tier_picks": pdbg["counters"]["wrong_tier_picks_total"],
            "placement_escapes": pdbg["counters"]["escapes_total"],
            "decisions_total": pdbg["counters"]["decisions_total"],
            "waiting_prefetch_decisions": len(prefetches),
        }
        # Zero wrong-tier picks: every RAM-resident adapter's pick landed
        # on a replica actually holding it.
        assert report["wrong_tier_picks"] == 0, report
        # Hot-set p99 within 2x the all-resident baseline.
        assert storm_p99 <= 2.0 * base_p99, report
        # The planner actually planned: a parked (waiting) disk-tier
        # adapter earned a prefetch decision.
        assert prefetches, report
        return report


async def scenario_replica_partition(seed: int) -> dict:
    """Statebus acceptance: a gateway replica partitioned off the bus
    degrades to LOCAL-ONLY enforcement with ZERO 5xx and rejoins within
    2 ticks of the partition healing.

    Topology: replica A is a fully REAL proxy serving traffic; replica B
    is a peer gateway's control plane (advisor stack + statebus) that
    detected a noisy hog A has never seen locally.  One gossip round
    makes A enforce B's flag (quiet picks steer off the hog's replica,
    the tenant quota partitions 2 ways); cutting the bus past the
    staleness bound drops A to local-only (flag gone, full quota,
    ``statebus_stale`` journaled) while every request keeps succeeding;
    a fresh exchange restores merged enforcement (``statebus_rejoin``).
    """
    from llm_instance_gateway_tpu.gateway.advisors import AdvisorStack
    from llm_instance_gateway_tpu.gateway.fairness import FairnessConfig
    from llm_instance_gateway_tpu.gateway.statebus import (
        StateBus,
        StateBusConfig,
    )

    schedule = faultinject.FaultSchedule([], seed=seed)
    rcfg = ResilienceConfig(health_policy="log_only", max_retries=1,
                            ttft_timeout_s=2.0, connect_timeout_s=2.0,
                            stream_idle_timeout_s=2.0)
    fcfg = FairnessConfig(mode="deprioritize")
    hog, quiet = "hog", "m"
    async with ChaosStack(schedule, seed, rcfg, models=(quiet, hog),
                          fairness_cfg=fcfg) as stack:
        proxy = stack.proxy
        # The hog adapter is resident on pod-bad only: merged enforcement
        # must steer quiet picks off that replica.
        for pm in proxy.provider.all_pod_metrics():
            pm.metrics.active_adapters = (
                {hog: 0} if pm.pod.name == BAD else {})
        clock = [1000.0]
        pool = next(iter(proxy.stacks))
        bus_a = StateBus(proxy.stacks,
                         cfg=StateBusConfig(replica_id="gw-a",
                                            staleness_s=5.0),
                         journal=proxy.journal, clock=lambda: clock[0])
        proxy.statebus = bus_a
        # Replica B: a peer gateway's control plane over the same pool
        # membership (no data path needed — it contributes STATE).
        provider_b = StaticProvider([
            PodMetrics(pod=Pod(pm.pod.name, pm.pod.address),
                       metrics=Metrics(active_adapters=dict(
                           pm.metrics.active_adapters)))
            for pm in proxy.provider.all_pod_metrics()])
        stack_b = AdvisorStack(pool, provider_b)
        bus_b = StateBus({pool: stack_b},
                         cfg=StateBusConfig(replica_id="gw-b",
                                            staleness_s=5.0),
                         clock=lambda: clock[0])
        statuses: dict[str, list[int]] = {
            "joined": [], "partitioned": [], "rejoined": []}

        async def serve(phase: str, n: int) -> list[str]:
            seq0 = proxy.journal.seq
            for _ in range(n):
                statuses[phase].append(await stack.request(model=quiet))
            return [e["attrs"]["pod"] for e in proxy.journal.events(
                since=seq0, limit=2048, kind=events_mod.PICK)]

        # Phase 1: joined.  B detected the hog; one gossip round brings
        # the flag (and the 2-way quota partition) to A.
        stack_b.usage.seed_noisy(hog, hog)
        bus_b.tick()
        bus_a.tick()
        bus_a.exchange_with(bus_b)
        bus_a.apply()
        joined_flagged = hog in proxy.fairness.noisy()
        joined_scale = proxy.fairness.quota_scale
        joined_picks = await serve("joined", 20)

        # Phase 2: partition.  A's peer snapshots age past the staleness
        # bound; the next tick falls back to local-only enforcement.
        clock[0] += 10.0
        bus_a.tick()
        part_flagged = hog in proxy.fairness.noisy()
        part_scale = proxy.fairness.quota_scale
        part_picks = await serve("partitioned", 20)
        stale_events = proxy.journal.events(
            kind=events_mod.STATEBUS_STALE, limit=16)

        # Phase 3: rejoin.  B publishes a fresh snapshot; count the A
        # ticks until merged enforcement is back.
        bus_b.tick()
        bus_a.exchange_with(bus_b)
        rejoin_ticks = 0
        for _ in range(2):
            rejoin_ticks += 1
            bus_a.tick()
            if hog in proxy.fairness.noisy():
                break
        rejoin_events = proxy.journal.events(
            kind=events_mod.STATEBUS_REJOIN, limit=16)
        rejoin_picks = await serve("rejoined", 20)

        all_statuses = [s for phase in statuses.values() for s in phase]
        report = {
            "scenario": "replica_partition",
            "requests": len(all_statuses),
            "non_200": sum(1 for s in all_statuses if s != 200),
            "joined": {"flagged": joined_flagged,
                       "quota_scale": joined_scale,
                       "quiet_picks_on_hog_pod":
                           joined_picks.count(BAD)},
            "partitioned": {"flagged": part_flagged,
                            "quota_scale": part_scale,
                            "stale_events": len(stale_events),
                            "requests": len(statuses["partitioned"])},
            "rejoined": {"ticks_to_rejoin": rejoin_ticks,
                         "rejoin_events": len(rejoin_events),
                         "quiet_picks_on_hog_pod":
                             rejoin_picks.count(BAD)},
        }
        # Joined: the peer's flag enforces here — quiet traffic off the
        # hog replica, quota partitioned 2 ways.
        assert joined_flagged and joined_scale == 0.5, report
        assert report["joined"]["quiet_picks_on_hog_pod"] == 0, report
        # Partitioned: local-only (flag gone, full quota), journaled,
        # and ZERO 5xx — the replica keeps serving.
        assert not part_flagged and part_scale == 1.0, report
        assert len(stale_events) == 1, report
        assert report["non_200"] == 0, report
        # Rejoined within 2 ticks, journaled, enforcement restored.
        assert rejoin_ticks <= 2, report
        assert hog in proxy.fairness.noisy(), report
        assert len(rejoin_events) == 1, report
        assert report["rejoined"]["quiet_picks_on_hog_pod"] == 0, report
        return report


async def scenario_saturation_ramp(seed: int) -> dict:
    """Capacity-plane acceptance: a slow offered-load ramp toward the
    pool's knee.  Three bars, one stack:

    - **Forecast leads the page.**  The capacity plane's
      ``capacity_forecast`` event (time-to-breach entered the horizon)
      must fire at least 2 observability ticks BEFORE the SLO engine's
      fast-burn transition — the whole point of a digital twin is the
      alarm that arrives while there is still time to act.
    - **Drift stays quiet on honest traffic.**  The synthesized scrape
      counters are generated FROM a known ``LatencyModel`` (V5E), so the
      self-calibrated twin must track them: ZERO ``twin_drift`` events
      through warmup and ramp.
    - **A lying pool un-trusts the twin.**  After the burn, the replica
      counters flip to a 4x-slower reality (the injected mismatch): the
      drift detector must journal ``twin_drift`` within a few ticks,
      flip ``trusted`` off, and suppress the breach-forecast alarm.

    The gateway side is fully real (the REAL CapacityPlanner self-
    calibrates from the scraped windows, the REAL SLOEngine judges the
    recorded TTFTs, the fast-burn hook writes the REAL black-box dump —
    asserted to embed the twin state).  The replica side synthesizes the
    cumulative counters a scrape would return, Little's-law-consistent
    with the generating model below the knee.  Time is virtual: both
    planes tick with explicit ``now`` so every "within N ticks" bar is
    deterministic."""
    import tempfile

    from llm_instance_gateway_tpu.gateway.capacity import CapacityConfig
    from llm_instance_gateway_tpu.sim.core import V5E_DEFAULT

    schedule = faultinject.FaultSchedule([], seed=seed)
    rcfg = ResilienceConfig(health_policy="log_only", max_retries=1,
                            ttft_timeout_s=2.0, connect_timeout_s=2.0,
                            stream_idle_timeout_s=2.0)
    # Harness-speed cadences: fit from 4 windows, refit + forecast every
    # tick.  slo_ttft_s matches the SLO engine's default ttft threshold
    # (1.0s) so the knee the twin probes is the knee the page watches.
    ccfg = CapacityConfig(min_fit_windows=4, refit_every_ticks=1,
                          forecast_every_ticks=1, slo_ttft_s=1.0,
                          trend_window=8, breach_horizon_s=600.0,
                          min_window_s=0.0)
    dump_dir = tempfile.mkdtemp(prefix="lig-chaos-blackbox-")
    gen = V5E_DEFAULT
    rng = random.Random(seed)
    slots = float(ccfg.decode_slots)
    kv_capacity = 200_000.0
    dt, clock = 5.0, [1000.0]
    async with ChaosStack(schedule, seed, rcfg, capacity_cfg=ccfg,
                          blackbox_dir=dump_dir) as stack:
        proxy = stack.proxy
        cap = proxy.capacity
        # The planner timestamps its own on-demand passes (the fast-burn
        # hook's maybe_tick): pin it to the scenario's virtual clock so a
        # wall-clock read cannot fold a garbage mega-window into the fit.
        cap._clock = lambda: clock[0]
        cum = {pm.pod.name: {"prefill_s": 0.0, "prefills": 0.0,
                             "decode_s": 0.0, "steps": 0.0, "occ": 0.0,
                             "occs": 0.0, "ptoks": 0.0, "dtoks": 0.0}
               for pm in proxy.provider.all_pod_metrics()}
        n_pods = len(cum)

        def scrape(rate_rps: float, mismatch: float = 1.0) -> None:
            """One synthetic scrape round at pool rate ``rate_rps``:
            cumulative counters grown exactly as the generating model
            would (``mismatch`` scales the observed seconds — the
            injected model/pool divergence)."""
            prompt = rng.uniform(120.0, 260.0)
            output = rng.uniform(130.0, 170.0)
            kv_per_seq = rng.uniform(2000.0, 4500.0)
            per_pod = rate_rps / n_pods
            # Little's law twice: batch = per-pod concurrency at this
            # rate (one refinement pass resolves decode_s(batch)).
            batch = per_pod * (gen.prefill_s(prompt)
                               + output * gen.decode_s(kv_per_seq * 8, 8))
            kv = max(1.0, batch) * kv_per_seq
            service = (gen.prefill_s(prompt)
                       + output * gen.decode_s(kv, batch))
            batch = min(slots, max(0.5, per_pod * service))
            kv = batch * kv_per_seq
            overflow = max(0.0, per_pod * service - slots)
            for pm in proxy.provider.all_pod_metrics():
                c = cum[pm.pod.name]
                prefills = per_pod * dt
                steps = max(1.0, prefills * output / max(1.0, batch))
                c["prefills"] += prefills
                c["prefill_s"] += prefills * gen.prefill_s(prompt) * mismatch
                c["steps"] += steps
                c["decode_s"] += steps * gen.decode_s(kv, batch) * mismatch
                c["occ"] += steps * (batch / slots)
                c["occs"] += steps
                c["ptoks"] += prefills * prompt
                c["dtoks"] += prefills * output
                m = pm.metrics
                m.prefill_seconds_sum = c["prefill_s"]
                m.prefill_seconds_count = c["prefills"]
                m.decode_step_seconds_sum = c["decode_s"]
                m.decode_step_seconds_count = c["steps"]
                m.decode_batch_occupancy_sum = c["occ"]
                m.decode_batch_occupancy_count = c["occs"]
                m.adapter_tokens = {("m", "m", "prefill"): c["ptoks"],
                                    ("m", "m", "decode"): c["dtoks"]}
                m.kv_tokens_capacity = kv_capacity
                m.kv_tokens_free = kv_capacity - kv
                m.running_queue_size = int(round(batch))
                m.waiting_queue_size = int(round(overflow))

        def serve_slo(n: int, ttft_s: float) -> None:
            for _ in range(n):
                proxy.metrics.record_request("m")
                proxy.metrics.record_phase("m", "completions", ttft_s=ttft_s)

        def step(rate: float, ttft_s: float, n_req: int,
                 mismatch: float = 1.0) -> None:
            clock[0] += dt
            scrape(rate, mismatch=mismatch)
            cap.tick(now=clock[0])
            serve_slo(n_req, ttft_s)
            proxy.slo.tick(now=clock[0])

        def first_tick(kind: str) -> int | None:
            ev = proxy.journal.events(kind=kind, limit=4)
            return ev[0]["attrs"]["tick"] if ev else None

        # Phase A — steady warmup well below the knee: the twin self-
        # calibrates; constant rate = flat trend = no breach forecast.
        for _ in range(6):
            step(rate=4.0, ttft_s=0.05, n_req=10)
        payload = cap.debug_payload()
        assert payload["twin"]["model"]["source"] == "self", payload["twin"]
        assert first_tick(events_mod.TWIN_DRIFT) is None, payload["twin"]
        assert first_tick(events_mod.CAPACITY_FORECAST) is None, payload

        # Phase B — the ramp: +1.5 rps per tick toward the knee.  TTFT
        # stays good until offered crosses the twin's knee (that IS what
        # a knee means), then collapses; the rate holds just above the
        # knee while the SLO windows fill with bad requests.
        rate, fast_burn_i, forecast_i = 4.0, None, None
        for i in range(1, 41):
            knee = cap.debug_payload()["forecast"]["knee_rps"]
            over = knee > 0 and rate >= knee
            if not over:
                rate += 1.5
            step(rate=rate, ttft_s=1.8 if over else 0.05,
                 n_req=60 if over else 10)
            if forecast_i is None and first_tick(
                    events_mod.CAPACITY_FORECAST) is not None:
                forecast_i = i
            slo_evs = proxy.journal.events(
                kind=events_mod.SLO_TRANSITION, limit=64)
            if any(e["attrs"]["to"] == "fast_burn" for e in slo_evs):
                fast_burn_i = i
                break
        pre_burn = cap.debug_payload()
        forecast_ev = proxy.journal.events(
            kind=events_mod.CAPACITY_FORECAST, limit=4)[0]["attrs"]

        # The black-box dump the burn triggered must embed the twin
        # state (the write is dispatched to the executor; wait for it).
        dump = None
        for _ in range(100):
            dumps = proxy.journal.events(kind=events_mod.BREACH_DUMP,
                                         limit=4)
            if dumps:
                with open(dumps[0]["attrs"]["path"]) as f:
                    dump = json.load(f)
                break
            await asyncio.sleep(0.05)

        # Phase C — the injected mismatch: the pool turns 4x slower than
        # the twin's constants.  Drift must fire and un-trust forecasts.
        drift_after = None
        for i in range(1, 9):
            step(rate=6.0, ttft_s=0.05, n_req=10, mismatch=4.0)
            if drift_after is None and first_tick(
                    events_mod.TWIN_DRIFT) is not None:
                drift_after = i
        post = cap.debug_payload()

        report = {
            "scenario": "saturation_ramp",
            "knee_rps": pre_burn["forecast"]["knee_rps"],
            "forecast_tick": forecast_i,
            "fast_burn_tick": fast_burn_i,
            "lead_ticks": (fast_burn_i - forecast_i
                           if forecast_i and fast_burn_i else None),
            "forecast_event": forecast_ev,
            "drift_events_before_mismatch": 0 if drift_after else None,
            "dump_has_capacity": bool(dump and dump.get("capacity")),
            "drift_fired_after_ticks": drift_after,
            "post_mismatch_state": post["twin"]["state"],
            "post_mismatch_trusted": post["forecast"]["trusted"],
            "post_mismatch_breach_alarm": post["forecast"]["breach_alarm"],
        }
        # The forecast led the page by >= 2 ticks.
        assert forecast_i is not None and fast_burn_i is not None, report
        assert fast_burn_i - forecast_i >= 2, report
        # Honest traffic never drifted: the first twin_drift event (if
        # any) came from the mismatch phase, not the ramp.
        pre_mismatch_drift = [
            e for e in proxy.journal.events(kind=events_mod.TWIN_DRIFT,
                                            limit=16)
            if e["attrs"]["tick"] <= pre_burn["ticks"]]
        assert not pre_mismatch_drift, report
        assert pre_burn["forecast"]["trusted"], report
        # The dump landed and carries the twin state.
        assert report["dump_has_capacity"], report
        # The mismatch fired drift, flipped trust, muzzled the alarm.
        assert drift_after is not None, report
        assert post["twin"]["state"] == "drift", report
        assert not post["forecast"]["trusted"], report
        assert not post["forecast"]["breach_alarm"], report
        return report


SCENARIOS = {
    "blackhole": scenario_blackhole,
    "brownout": scenario_brownout,
    "midstream": scenario_midstream,
    "scrape_flap": scenario_scrape_flap,
    "handoff": scenario_handoff,
    "noisy_neighbor": scenario_noisy_neighbor,
    "adapter_flood": scenario_adapter_flood,
    "cold_start_storm": scenario_cold_start_storm,
    "replica_partition": scenario_replica_partition,
    "saturation_ramp": scenario_saturation_ramp,
}


def run_scenario(name: str, seed: int = 0) -> dict:
    """Run one scenario to completion (sync wrapper for pytest/CLI)."""
    return asyncio.run(SCENARIOS[name](seed))


def main(argv: list[str] | None = None) -> int:
    parser = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    parser.add_argument("--seed", type=int, default=0)
    parser.add_argument("--scenario", default="all",
                        choices=["all", *SCENARIOS])
    args = parser.parse_args(argv)
    names = list(SCENARIOS) if args.scenario == "all" else [args.scenario]
    failed = 0
    for name in names:
        try:
            report = run_scenario(name, seed=args.seed)
            report["ok"] = True
        except AssertionError as e:
            report = {"scenario": name, "ok": False, "detail": str(e)[:500]}
            failed += 1
        print(json.dumps(report))
    return 1 if failed else 0


if __name__ == "__main__":
    raise SystemExit(main())
