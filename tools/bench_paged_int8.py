"""On-chip A/B of the round-5 bandwidth composition: lane-bf16 vs
paged-bf16 vs paged-int8 (+prefix) serving throughput at long context.

Decode at long context is bound by streaming the KV cache from HBM; this
tool measures, on the real chip, what the two bandwidth features buy on
the same ~1.1B bench model `bench.py` uses:

- ``lane_bf16``      — the default contiguous-lane engine (baseline)
- ``paged_bf16``     — paged pool + direct paged kernel (no gathered copy)
- ``paged_int8``     — quantized pool + prefix cache (the production
                       long-context shape: paged + int8 + prefix)

One JSON line per engine config on stdout; the chip pipeline writes them
to ``PAGED_INT8_BENCH_r05.json``.  Reuses bench.py's model config, phase
runner, SIGTERM cleanup, and device-claim retry so it inherits the
relay-wedge hygiene.  Budgeted: respects BENCH_TOTAL_BUDGET_S like
bench.py (default here 600s) so it can never outstay a chip window.
"""

from __future__ import annotations

import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

os.environ.setdefault("BENCH_TOTAL_BUDGET_S", "600")

import bench  # noqa: E402  (repo-root bench.py: shared machinery)
import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402


def run_variant(name: str, cfg, ecfg_kwargs: dict, prompt_len: int,
                max_new: int, n_requests: int) -> dict:
    from llm_instance_gateway_tpu.models import transformer
    from llm_instance_gateway_tpu.server.engine import Engine, EngineConfig

    params = transformer.init_params(cfg, jax.random.PRNGKey(0),
                                     dtype=jnp.bfloat16)
    engine = Engine(cfg, params, EngineConfig(**ecfg_kwargs), eos_id=None,
                    dtype=jnp.bfloat16)
    engine.start()
    try:
        # Disjoint seeds: with the same stream, the prefix_cache variant
        # would serve measured prompts 0-1 straight from the warm phase's
        # cached blocks — a reuse win real traffic wouldn't grant — and the
        # A/B would conflate it with the bandwidth effect under test.
        warm = bench.run_phase(engine, n_requests=2, prompt_len=prompt_len,
                               max_new=8, adapters=[], seed=1)  # compile
        del warm
        stats = bench.run_phase(engine, n_requests=n_requests,
                                prompt_len=prompt_len, max_new=max_new,
                                adapters=[], seed=0)
    finally:
        engine.stop()
    row = {"variant": name, **{k: round(v, 2) for k, v in stats.items()}}
    print(json.dumps(row), flush=True)
    return row


def main() -> None:
    bench.install_sigterm_cleanup()
    bench._install_governor()
    bench._claim_device_with_retry()

    cfg = bench.bench_model_cfg()
    on_cpu = jax.default_backend() == "cpu"
    # Long-context shape: prompts near the cache limit so decode streams a
    # deep KV.  CPU fallback shrinks everything (hermetic smoke only).
    prompt_len = 48 if on_cpu else 384
    max_new = 16 if on_cpu else 96
    n_requests = 4 if on_cpu else 16
    slots = 4 if on_cpu else 16
    max_seq = 128 if on_cpu else 512
    block = 8 if on_cpu else 64
    common = dict(decode_slots=slots, max_seq_len=max_seq,
                  prefill_buckets=(64, 128) if on_cpu else (128, 256, 512),
                  decode_steps_per_sync=8, pipeline_decode=True)

    rows = [
        run_variant("lane_bf16", cfg, dict(common), prompt_len, max_new,
                    n_requests),
        run_variant("paged_bf16", cfg, dict(common, paged_kv_block=block),
                    prompt_len, max_new, n_requests),
        run_variant("paged_int8", cfg,
                    dict(common, paged_kv_block=block, kv_cache_quant="int8",
                         prefix_cache=True),
                    prompt_len, max_new, n_requests),
    ]
    base = rows[0]["tok_per_s"]
    print(json.dumps({
        "summary": "paged_int8_ab",
        "backend": jax.default_backend(),
        "model": cfg.name,
        "paged_vs_lane": round(rows[1]["tok_per_s"] / base, 3),
        "paged_int8_vs_lane": round(rows[2]["tok_per_s"] / base, 3),
    }), flush=True)


if __name__ == "__main__":
    main()
